"""Runnable colocation demo — the reference's ``examples/spark-jobs`` flow
(Spark executors co-located with prod services as best-effort batch pods)
on the TPU-native stack.

    python examples/colocation_demo.py

Walks the §3.3 feedback loop end to end and prints each stage: admission
mutation, batch-capacity computation, BE placement, the on-node cgroup
plan, and the load-spike reaction (batch shrink + suppression + victim
selection). The e2e test ``tests/test_e2e_colocation.py`` asserts the same
flow; this script narrates it.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import QoSClass
from koordinator_tpu.api.types import (
    ClusterColocationProfile,
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.descheduler.low_node_load import LowNodeLoad, LowNodeLoadArgs
from koordinator_tpu.koordlet import qosmanager, runtimehooks
from koordinator_tpu.manager.noderesource import (
    ColocationStrategy,
    NodeResourceController,
)
from koordinator_tpu.manager.profile import ProfileMutator
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

ALLOC_CPU, ALLOC_MEM = 64_000.0, 256 * 1024.0


def report(snap, node, util, now):
    usage = {ext.RES_CPU: ALLOC_CPU * util, ext.RES_MEMORY: ALLOC_MEM * util * 0.8}
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name=node),
            node_usage=ResourceMetric(usage=dict(usage)),
            prod_usage=ResourceMetric(usage=dict(usage)),
            update_time=now - 1,
        ),
        now=now,
    )


def main() -> None:
    snap = ClusterSnapshot()
    for i in range(8):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"node-{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: ALLOC_CPU, ext.RES_MEMORY: ALLOC_MEM}
                ),
            )
        )
        report(snap, f"node-{i}", 0.30, now=1000.0)

    print("== 1. admission: ClusterColocationProfile rewrites Spark pods to BE")
    mutator = ProfileMutator()
    mutator.upsert(
        ClusterColocationProfile(
            meta=ObjectMeta(name="colocation-spark"),
            selector={"koordinator.sh/enable-colocation": "true"},
            qos_class=QoSClass.BE,
            priority=5500,
            scheduler_name="koord-scheduler",
            resource_translation={
                ext.RES_CPU: ext.RES_BATCH_CPU,
                ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
            },
        )
    )
    pods = []
    for i in range(16):
        pod = Pod(
            meta=ObjectMeta(
                name=f"spark-executor-{i}",
                namespace="spark",
                labels={"koordinator.sh/enable-colocation": "true"},
            ),
            spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192}),
        )
        pods.append(mutator.mutate(pod))
    print(f"   {pods[0].meta.name}: qos={pods[0].qos.name} "
          f"priority={pods[0].spec.priority} requests={pods[0].spec.requests}")

    print("== 2. slo-controller: batch capacity from prod peak")
    ctrl = NodeResourceController(snap, ColocationStrategy(reserve_ratio=0.1))
    published = ctrl.reconcile()
    print(f"   node-0 publishes {published['node-0']}")

    print("== 3. scheduler: BE pods placed against batch resources (TPU solver)")
    sched = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=64)
    sched.extender.monitor.stop_background()
    out = sched.schedule(pods)
    spread = {}
    for p, n in out.bound:
        p.spec.node_name = n
        spread[n] = spread.get(n, 0) + 1
    print(f"   bound {len(out.bound)}/{len(pods)} across {len(spread)} nodes: {spread}")

    print("== 4. koordlet: cgroup plan for one bound BE pod")
    for path, cgroup, value in runtimehooks.pod_plan(out.bound[0][0])[:4]:
        print(f"   {cgroup}/{path} = {value}")

    print("== 5. prod load spike: batch shrinks, BE suppressed, victims picked")
    for i in range(2):
        report(snap, f"node-{i}", 0.85, now=2000.0)
    ctrl.reconcile()
    bc = snap.config.resources.index(ext.RES_BATCH_CPU)
    hot = snap.node_id("node-0")
    print(f"   node-0 batch-cpu now {snap.nodes.allocatable[hot, bc]:.0f}m")
    dec = qosmanager.cpu_suppress(
        node_allocatable_milli=ALLOC_CPU,
        node_used_milli=0.85 * ALLOC_CPU + 8000,
        be_used_milli=8000,
        threshold_percent=65.0,
    )
    print(f"   cpusuppress: BE allowance -> {dec.be_allowance_milli:.0f}m "
          f"({dec.be_cpuset_cpus} cpus)")
    lnl = LowNodeLoad(
        snap,
        LowNodeLoadArgs(
            high_thresholds={ext.RES_CPU: 70.0},
            low_thresholds={ext.RES_CPU: 45.0},
            anomaly_condition_count=2,
        ),
    )
    lnl.classify()
    lnl.classify()
    hot_pods = [p for p, n in out.bound if n in ("node-0", "node-1")]
    victims = lnl.select_victims(hot_pods)
    print(f"   descheduler victims: {[v.meta.name for v in victims]}")


if __name__ == "__main__":
    main()
