"""Narrated demo of the long-lived cross-component loop.

    python examples/longrun_loop.py [minutes]

The driver lives in the package (``koordinator_tpu.sim.longrun.run_loop``);
this script just runs it verbosely on CPU.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from koordinator_tpu.sim.longrun import run_loop  # noqa: E402

if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 30.0
    stats = run_loop(minutes=minutes, verbose=True)
    print("\nfinal:", stats)
    assert stats["bound"] > 0  # (completions need >2 simulated minutes)
    print(
        f"loop held for {stats['ticks']} ticks: {stats['bound']} pods bound, "
        f"{stats['completed']} completed, {stats['suppressions']} suppression "
        f"decisions, batch capacity breathed "
        f"{stats['min_batch_cap']:.0f}..{stats['max_batch_cap']:.0f} milli"
    )
