"""Headline benchmark: pods/sec scheduled at 10k simulated nodes.

BASELINE.md: the reference publishes no numbers, so the baseline is *measured*
here — a scalar per-pod sequential loop (``sim.golden.sequential_assign``)
that is architecture-faithful to the reference scheduler's one-pod-at-a-time
Filter→Score cycle over all nodes, run on this host's CPU. The TPU number is
the batched round solver over the same fixture.

Prints ONE JSON line:
  {"metric": ..., "value": pods/sec, "unit": "pods/s", "vs_baseline": ratio}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_NODES = 10_000
N_PODS = 98_304          # ~the BASELINE north-star scale (100k pending),
                         # solved in priority order batch by batch
BATCH = 512              # small batches ≈ sequential fidelity; the whole
                         # stream is one on-device scan, so batch count is
                         # free of host dispatch cost (see solve_stream)
MAX_ROUNDS = 12
PASSES = 3               # median-of-N to tame tunnel jitter
BASELINE_PODS = 512      # scalar loop sample size (extrapolated to pods/sec)
THRESHOLDS = (65.0, 95.0)


def build_fixture(seed: int = 0):
    rng = np.random.default_rng(seed)
    shapes = np.array([[32_000, 128 * 1024], [64_000, 256 * 1024], [96_000, 384 * 1024]])
    alloc = shapes[rng.integers(0, 3, N_NODES)].astype(np.float32)
    util = rng.uniform(0.1, 0.55, (N_NODES, 1)).astype(np.float32)
    est_used = alloc * util
    req_cpu = rng.choice([500, 1000, 2000, 4000], N_PODS, p=[0.4, 0.3, 0.2, 0.1])
    req_mem = req_cpu * rng.choice([2, 4, 8], N_PODS)
    req = np.stack([req_cpu, req_mem], 1).astype(np.float32)
    est = (req * np.array([0.85, 0.70], np.float32)).astype(np.float32)
    prio = rng.integers(5000, 9999, N_PODS).astype(np.int32)
    return dict(
        alloc=alloc,
        est_used=est_used,
        prod_used=est_used * 0.6,
        req=req,
        est=est,
        prio=prio,
        is_prod=prio >= 9000,
    )


def bench_solver(fix, tracer=None) -> tuple[float, list[float]]:
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.obs import NULL_TRACER
    from koordinator_tpu.ops.solver import (
        NodeState,
        PodBatch,
        SolverParams,
        solve_stream,
    )

    tracer = tracer or NULL_TRACER

    nodes = NodeState.create(
        allocatable=fix["alloc"],
        estimated_used=fix["est_used"],
        prod_used=fix["prod_used"],
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray(THRESHOLDS, jnp.float32),
        prod_thresholds=jnp.zeros(2, jnp.float32),
        score_weights=jnp.ones(2, jnp.float32),
    )
    n_batches = N_PODS // BATCH
    stacked = PodBatch.create(
        requests=fix["req"],
        estimate=fix["est"],
        priority=fix["prio"],
        is_prod=fix["is_prod"],
    )
    stacked = jax.tree.map(
        lambda a: a.reshape((n_batches, BATCH) + a.shape[1:]), stacked
    )

    def run_pass(span_name: str = "solve_pass") -> tuple[int, float]:
        with tracer.span(span_name, cat="bench", pods=N_PODS):
            t0 = time.perf_counter()
            _, _, placed, _ = solve_stream(
                stacked,
                nodes,
                params,
                max_rounds=MAX_ROUNDS,
                approx_topk=True,
            )
            placed_total = int(np.asarray(placed).sum())  # forces device sync
            return placed_total, time.perf_counter() - t0

    # warmup pass covers compile + first host->device transfer; measured
    # passes then pay exactly one dispatch + one sync through the tunnel.
    run_pass("compile_warmup")

    times = []
    placed = 0
    for _ in range(PASSES):
        placed, elapsed = run_pass()
        times.append(elapsed)
    if placed < 0.5 * N_PODS:
        print(f"warning: only {placed}/{N_PODS} pods placed", file=sys.stderr)
    # every pass goes into the artifact — regression vs. tunnel variance
    # must be distinguishable from the committed numbers alone (VERDICT r2)
    return (
        N_PODS / sorted(times)[len(times) // 2],
        [round(N_PODS / t, 1) for t in times],
    )


def bench_baseline(fix) -> float:
    from koordinator_tpu.sim import golden

    sl = slice(0, BASELINE_PODS)
    t0 = time.perf_counter()
    golden.sequential_assign(
        pod_req=fix["req"][sl],
        pod_estimate=fix["est"][sl],
        pod_priority=fix["prio"][sl],
        pod_is_prod=fix["is_prod"][sl],
        allocatable=fix["alloc"],
        requested0=np.zeros_like(fix["alloc"]),
        estimated_used0=fix["est_used"],
        prod_used0=fix["prod_used"],
        metric_fresh=np.ones(N_NODES, bool),
        schedulable=np.ones(N_NODES, bool),
        usage_thresholds=np.asarray(THRESHOLDS, np.float32),
        prod_thresholds=np.zeros(2, np.float32),
        score_weights=np.ones(2, np.float32),
    )
    return BASELINE_PODS / (time.perf_counter() - t0)


def main(argv=None) -> None:
    import argparse

    from koordinator_tpu.obs import Tracer

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--trace",
        nargs="?",
        const="bench_trace.json",
        default=None,
        metavar="PATH",
        help="write a Chrome trace_event JSON of the run (open in "
        "Perfetto / chrome://tracing); default path bench_trace.json",
    )
    ap.add_argument(
        "--stage-report",
        action="store_true",
        help="print a per-stage total/p50/p99 table to stderr and embed "
        "stage_breakdown_ms in the JSON (with --scenario, the suite "
        "scenarios' BENCH_SUITE.json entries gain the breakdown too)",
    )
    ap.add_argument(
        "--scenario",
        action="append",
        default=None,
        metavar="NAME",
        help="run bench_suite scenario(s) (loadaware / numa / device_gang "
        "/ quota_tree / latency_stream / stream_pipelined) instead of the "
        "headline metric, honoring --stage-report/--trace; results merge "
        "into BENCH_SUITE.json",
    )
    args = ap.parse_args(argv)
    if args.scenario:
        import bench_suite

        bench_suite.run_scenarios(
            args.scenario, stage_report=args.stage_report, trace=args.trace
        )
        return
    tracer = Tracer(enabled=args.trace is not None or args.stage_report)
    with tracer.span("fixture", cat="bench"):
        fix = build_fixture()
    with tracer.span("baseline", cat="bench", pods=BASELINE_PODS):
        baseline_pps = bench_baseline(fix)
    solver_pps, passes = bench_solver(fix, tracer=tracer)
    out = {
        "metric": "sched_pods_per_sec_10k_nodes",
        "value": round(solver_pps, 1),
        "unit": "pods/s",
        "vs_baseline": round(solver_pps / baseline_pps, 2),
        "passes": passes,
        "baseline_pods_per_sec": round(baseline_pps, 1),
    }
    if args.trace is not None or args.stage_report:
        # per-stage wall breakdown (where the benchmark's time went —
        # fixture build vs. XLA compile vs. measured solve passes) rides
        # the bench JSON so perf PRs can show WHERE a win landed
        out["stage_breakdown_ms"] = {
            name: round(total * 1000.0, 2)
            for name, total in sorted(tracer.stage_totals().items())
        }
    if args.stage_report:
        import bench_suite

        bench_suite._print_stage_table(
            "headline", bench_suite._stage_stats(tracer.records())
        )
    if args.trace is not None:
        with open(args.trace, "w") as f:
            json.dump(tracer.to_chrome_trace(), f)
        out["trace_file"] = args.trace
    print(json.dumps(out))


if __name__ == "__main__":
    main()
