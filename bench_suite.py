"""Extended benchmark suite: the BASELINE.md measurement configs beyond the
headline metric (which stays in ``bench.py`` — the driver contract is ONE
JSON line there).

Scenarios (BASELINE.md "Numbers to measure"):
  2. loadaware    — 10k nodes / 32k pods, cpu+mem dims, end-to-end host
                    pipeline AND raw solver stream (the headline).
  3. numa         — 2-socket nodes, LSR whole-core pods, cpuset-aware
                    placement through the NUMA manager.
  4. device_gang  — 8-GPU nodes, 4-GPU all-or-nothing gang pods.
  5. quota_tree   — 3-level quota hierarchy, admission along the chain.

Each prints one JSON line: pods/sec plus p50/p99 per-solver-batch latency
(the per-pod scheduling-latency proxy: a pod's wait is at most one batch).
Run: ``python bench_suite.py [scenario ...]``; results land in stdout and
``BENCH_SUITE.json``.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _percentiles(samples):
    if not samples:
        return 0.0, 0.0
    arr = np.asarray(samples) * 1e3
    return float(np.percentile(arr, 50)), float(np.percentile(arr, 99))


#: --stage-report / --trace state (set by run_scenarios): when on,
#: _measure runs one extra TRACED drain per scenario and embeds the
#: per-stage wall breakdown into the scenario's BENCH json entry, so
#: stage regressions show in the perf trajectory without a Chrome trace
STAGE_REPORT = False
TRACE_PATH = None


def _stage_stats(records):
    """Per-span-name totals + p50/p99 (ms) from tracer records."""
    per = {}
    for s in records:
        per.setdefault(s.name, []).append(s.dur * 1e3)
    out = {}
    for name, durs in sorted(per.items()):
        arr = np.asarray(durs)
        out[name] = {
            "total_ms": round(float(arr.sum()), 2),
            "count": len(durs),
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
        }
    return out


def _print_stage_table(scenario: str, stats) -> None:
    print(f"--- stage report: {scenario} ---", file=sys.stderr)
    print(
        f"{'stage':<32} {'total_ms':>10} {'count':>6} {'p50_ms':>9} {'p99_ms':>9}",
        file=sys.stderr,
    )
    for name, row in sorted(
        stats.items(), key=lambda kv: -kv[1]["total_ms"]
    ):
        print(
            f"{name:<32} {row['total_ms']:>10.2f} {row['count']:>6} "
            f"{row['p50_ms']:>9.3f} {row['p99_ms']:>9.3f}",
            file=sys.stderr,
        )


def _stage_report_pass(build, chunk, name, result, dp=None) -> None:
    """One extra drain with the scheduler's tracer ON (runs for
    --stage-report AND/OR --trace): per-stage totals land in the scenario
    entry (``stage_breakdown_ms``), the p50/p99 table goes to stderr
    (stage-report only), and --trace dumps the Chrome trace. Runs after
    the measured passes so tracing overhead never lands in them; the jit
    caches are already warm, so no compile time pollutes the stages.

    With a solver observatory (``dp``, shared with the warmup/measured
    builds so the scenario's cold compiles were ledgered), the traced
    pass runs inside an armed CAPTURE window: every solver dispatch is
    fenced and recorded on the device lane, and the scenario entry gains
    ``solve_breakdown_ms`` — the solve residual decomposed into compile
    (scenario-wide jit wall, warmups included) vs fenced device-compute
    vs host↔device transfer — plus the per-entry-point compile ledger.
    The Chrome trace gains the ``device`` lane so device ops line up
    under their host stage spans."""
    sched, pods = build()
    sched.extender.monitor.stop_background()
    tracer = sched.extender.tracer
    tracer.enabled = True
    if dp is not None:
        if sched.devprof is None:
            # the measured-pass builds run unobserved (see _measure);
            # the traced pass's own scheduler wires the observatory back
            sched.attach_devprof(dp)
        dp.capture(1 << 30)  # the whole traced drain
    _run_scheduler(sched, pods, chunk=chunk)
    if dp is not None:
        dp.capture(0)
        result["solve_breakdown_ms"] = dp.breakdown_ms()
        result["compiles"] = {
            fn: {
                "traces": row["traces"],
                "compile_s": round(row["compile_seconds"], 3),
            }
            for fn, row in dp.ledger.report()["functions"].items()
        }
        result["solve_breakdown_note"] = (
            "compile_ms is the scenario's total jit wall (warmup passes "
            "included — the measured passes exclude it by the warmup "
            "discipline); device_compute_ms/transfer_ms are fenced "
            "dispatch windows from the traced pass only"
        )
    stats = _stage_stats(tracer.records())
    result["stage_breakdown_ms"] = {
        k: v["total_ms"] for k, v in stats.items()
    }
    result["stage_p50_p99_ms"] = {
        k: [v["p50_ms"], v["p99_ms"]] for k, v in stats.items()
    }
    if STAGE_REPORT:
        _print_stage_table(name, stats)
    if TRACE_PATH:
        path = f"{TRACE_PATH.removesuffix('.json')}_{name}.json"
        doc = tracer.to_chrome_trace()
        if dp is not None:
            dp.extend_chrome(doc, tracer.epoch)
        with open(path, "w") as f:
            json.dump(doc, f)
        result["trace_file"] = path


def _run_scheduler(sched, pods, chunk=4096):
    """Drive the host pipeline in chunks; returns (bound, total, batch_times)."""
    times = []
    bound = 0
    for start in range(0, len(pods), chunk):
        t0 = time.perf_counter()
        out = sched.schedule(pods[start : start + chunk])
        times.append(time.perf_counter() - t0)
        bound += len(out.bound)
    return bound, times


def _golden_baseline(build, sample: int = 2048) -> float:
    """Scalar per-pod sequential baseline (``sim.golden.sequential_assign``)
    on the scenario's own node/pod population — the measured stand-in for
    stock koord-scheduler (BASELINE.md: no published numbers). Runs the
    first ``sample`` pods and extrapolates to pods/sec, mirroring
    bench.py's BASELINE_PODS discipline."""
    from koordinator_tpu.sim import golden

    sched, pods = build()
    sched.extender.monitor.stop_background()
    snap = sched.snapshot
    n = min(len(pods), sample)
    arrays = snap.build_pods(list(pods[:n]))
    est = np.floor(arrays.requests * sched._scales[None, :] + 0.5)
    na = snap.nodes
    n_real = snap.node_count
    from koordinator_tpu.api import extension as ext

    is_prod = arrays.prio_class == int(ext.PriorityClass.PROD)
    est_used = (
        np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
    )[:n_real]
    t0 = time.perf_counter()
    golden.sequential_assign(
        pod_req=arrays.requests[:n],
        pod_estimate=est[:n],
        pod_priority=arrays.priority[:n],
        pod_is_prod=is_prod[:n],
        allocatable=na.allocatable[:n_real],
        requested0=na.requested[:n_real].copy(),
        estimated_used0=est_used,
        prod_used0=(na.prod_usage + na.assigned_pending_prod)[:n_real],
        metric_fresh=na.metric_fresh[:n_real],
        schedulable=na.schedulable[:n_real],
        usage_thresholds=np.asarray(sched._params.usage_thresholds),
        prod_thresholds=np.asarray(sched._params.prod_thresholds),
        score_weights=np.asarray(sched._params.score_weights),
    )
    return n / (time.perf_counter() - t0)


def _measure(build, chunk, name, passes: int = 3):
    """Warmup passes on throwaway instances (fills the jit cache for both
    the per-chunk and the pipelined specializations), then measure on
    fresh state — mirrors bench.py's warmup-pass discipline so compile
    time never lands in the p99.

    Latency (p50/p99) comes from one-chunk-per-call scheduling — the wait
    an individual pod's batch experiences. Throughput comes from draining
    the whole backlog in one call, which pipelines all chunk solves
    on-device (chained capacity) and overlaps host commits with them.
    Every throughput pass lands in the artifact (tunnel variance must be
    distinguishable from regression, VERDICT r2), along with the host
    commit's own per-chunk p50/p99 (CPU-side cost, tunnel-independent)
    and the scenario's measured scalar baseline."""
    dp = None
    if STAGE_REPORT or TRACE_PATH:
        # solver observatory shared between the WARMUP builds (their
        # cold compiles land in one ledger, with watch signatures for
        # attribution) and the traced pass's own build — never the
        # measured or latency builds: a per-cycle census + per-dispatch
        # watch inside the measured passes would make their recorded
        # pods_per_sec incomparable to a plain run, exactly the drift
        # bench_regress exists to catch
        from koordinator_tpu.obs.devprof import DevProf

        dp = DevProf()
        _inner_build = build
        _build_count = {"n": 0}

        def build():
            sched, pods = _inner_build()
            _build_count["n"] += 1
            if _build_count["n"] <= 2:  # the two warmup builds only
                sched.attach_devprof(dp)
            return sched, pods

    sched, pods = build()
    # first solve of a new jit specialization can exceed the 30 s watchdog;
    # that's the monitor doing its job, but it's noise here — silence it
    sched.extender.monitor.stop_background()
    _run_scheduler(sched, pods, chunk=chunk)
    sched, pods = build()
    sched.extender.monitor.stop_background()
    _run_scheduler(sched, pods, chunk=len(pods))

    sched, pods = build()
    sched.extender.monitor.stop_background()
    _, times = _run_scheduler(sched, pods, chunk=chunk)
    p50, p99 = _percentiles(times)

    pass_pps = []
    bound = 0
    commit_times: list = []
    pod_lat: list = []
    for p in range(passes):
        sched, pods = build()
        sched.extender.monitor.stop_background()
        if p == 0:
            # host-commit cost per chunk, measured once (CPU-side work —
            # independent of tunnel round-trip noise), together with
            # per-pod enqueue→bind latencies for the drain
            orig = sched._commit
            marks: list = []

            def timed(*a, _o=orig, **kw):
                c0 = time.perf_counter()
                b, u = _o(*a, **kw)
                c1 = time.perf_counter()
                commit_times.append(c1 - c0)
                marks.append((len(b) + len(u), c1))
                return b, u

            sched._commit = timed
        t0 = time.perf_counter()
        bound, _ = _run_scheduler(sched, pods, chunk=len(pods))
        elapsed = time.perf_counter() - t0
        if p == 0:
            for n_p, t_end in marks:
                pod_lat.extend([(t_end - t0) * 1e3] * n_p)
        pass_pps.append(round(len(pods) / elapsed, 1))
    commit_p50, commit_p99 = _percentiles(commit_times)
    baseline_pps = _golden_baseline(build)
    median_pps = sorted(pass_pps)[len(pass_pps) // 2]
    pod_arr = np.asarray(pod_lat) if pod_lat else np.zeros(1)
    result = {
        "scenario": name,
        "pods_per_sec": median_pps,
        "passes": pass_pps,
        "placed": bound,
        "total": len(pods),
        "batch_p50_ms": round(p50, 2),
        "batch_p99_ms": round(p99, 2),
        "commit_p50_ms": round(commit_p50, 2),
        "commit_p99_ms": round(commit_p99, 2),
        # per-pod enqueue→bind percentiles for the throughput drain (all
        # pods enqueue at t0, so these are dominated by drain position —
        # the latency OPERATING POINT is the latency_stream scenario)
        "pod_p50_ms": round(float(np.percentile(pod_arr, 50)), 2),
        "pod_p99_ms": round(float(np.percentile(pod_arr, 99)), 2),
        "baseline_pods_per_sec": round(baseline_pps, 1),
        "vs_baseline": round(median_pps / baseline_pps, 2),
    }
    if STAGE_REPORT or TRACE_PATH:
        try:
            _stage_report_pass(build, chunk, name, result, dp=dp)
        finally:
            dp.uninstall()
    return result


def bench_loadaware():
    import jax.numpy as jnp

    import bench as headline
    from koordinator_tpu.ops.solver import (
        NodeState,
        PodBatch,
        SolverParams,
        solve_stream,
    )

    fix = headline.build_fixture()
    nodes = NodeState.create(
        allocatable=fix["alloc"],
        estimated_used=fix["est_used"],
        prod_used=fix["prod_used"],
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray(headline.THRESHOLDS, jnp.float32),
        prod_thresholds=jnp.zeros(2, jnp.float32),
        score_weights=jnp.ones(2, jnp.float32),
    )
    import jax

    p = 512
    b = headline.N_PODS // p
    stacked = PodBatch.create(
        requests=fix["req"], estimate=fix["est"],
        priority=fix["prio"], is_prod=fix["is_prod"],
    )
    stacked = jax.tree.map(lambda a: a.reshape((b, p) + a.shape[1:]), stacked)
    solve_stream(stacked, nodes, params, max_rounds=12, approx_topk=True)
    # per-batch latency: single 512-pod assign against the live table
    from koordinator_tpu.ops.solver import assign

    single = jax.tree.map(lambda a: a[0], stacked)
    r = assign(single, nodes, params, max_rounds=12, approx_topk=True)
    np.asarray(r.assignment)   # compile warmup for the single-batch shape
    lat = []
    for _ in range(20):
        t0 = time.perf_counter()
        r = assign(single, nodes, params, max_rounds=12, approx_topk=True)
        np.asarray(r.assignment)
        lat.append(time.perf_counter() - t0)
    pass_pps = []
    total_placed = 0
    for _ in range(3):
        t0 = time.perf_counter()
        _, _, placed, _ = solve_stream(
            stacked, nodes, params, max_rounds=12, approx_topk=True
        )
        total_placed = int(np.asarray(placed).sum())
        pass_pps.append(round(headline.N_PODS / (time.perf_counter() - t0), 1))
    p50, p99 = _percentiles(lat)
    median_pps = sorted(pass_pps)[len(pass_pps) // 2]
    baseline_pps = headline.bench_baseline(fix)
    return {
        "scenario": "loadaware_10k_nodes",
        "pods_per_sec": median_pps,
        "passes": pass_pps,
        "placed": total_placed,
        "total": headline.N_PODS,
        "batch_p50_ms": round(p50, 2),
        "batch_p99_ms": round(p99, 2),
        "baseline_pods_per_sec": round(baseline_pps, 1),
        "vs_baseline": round(median_pps / baseline_pps, 2),
    }


def bench_loadaware_100k():
    """Region-scale raw-solver stream: the columnar fleet generator
    (``sim.cluster_gen.gen_fleet_arrays``) at 100k heterogeneous nodes
    across 8 region cohorts, drained with the same ``solve_stream``
    discipline as ``loadaware_10k_nodes``. ``approx_topk`` + a shorter
    round budget keep the top-k sort tractable at this node count."""
    import jax
    import jax.numpy as jnp

    from koordinator_tpu.ops.solver import (
        PodBatch,
        SolverParams,
        assign,
        solve_stream,
    )
    from koordinator_tpu.sim.cluster_gen import (
        FLEET_SHAPES,
        FleetConfig,
        fleet_node_state,
        gen_fleet_pod_arrays,
    )

    cfg = FleetConfig(n_nodes=100_000)
    nodes = fleet_node_state(cfg)
    n_pods = 4096
    fix = gen_fleet_pod_arrays(cfg, n_pods)
    params = SolverParams(
        # PERCENT scale, like bench.THRESHOLDS — fractional thresholds
        # silently place nothing
        usage_thresholds=jnp.asarray((65.0, 95.0), jnp.float32),
        prod_thresholds=jnp.zeros(2, jnp.float32),
        score_weights=jnp.ones(2, jnp.float32),
    )
    p = 512
    b = n_pods // p
    stacked = PodBatch.create(
        requests=fix["requests"], estimate=fix["estimate"],
        priority=fix["priority"], is_prod=fix["is_prod"],
    )
    stacked = jax.tree.map(lambda a: a.reshape((b, p) + a.shape[1:]), stacked)
    solve_stream(stacked, nodes, params, max_rounds=8, approx_topk=True)
    single = jax.tree.map(lambda a: a[0], stacked)
    r = assign(single, nodes, params, max_rounds=8, approx_topk=True)
    np.asarray(r.assignment)
    lat = []
    for _ in range(8):
        t0 = time.perf_counter()
        r = assign(single, nodes, params, max_rounds=8, approx_topk=True)
        np.asarray(r.assignment)
        lat.append(time.perf_counter() - t0)
    pass_pps = []
    total_placed = 0
    for _ in range(3):
        t0 = time.perf_counter()
        _, _, placed, _ = solve_stream(
            stacked, nodes, params, max_rounds=8, approx_topk=True
        )
        total_placed = int(np.asarray(placed).sum())
        pass_pps.append(round(n_pods / (time.perf_counter() - t0), 1))
    p50, p99 = _percentiles(lat)
    return {
        "scenario": "loadaware_100k_nodes",
        "pods_per_sec": sorted(pass_pps)[len(pass_pps) // 2],
        "passes": pass_pps,
        "placed": total_placed,
        "total": n_pods,
        "n_nodes": cfg.n_nodes,
        "n_regions": cfg.n_regions,
        "n_node_shapes": len(FLEET_SHAPES),
        "batch_p50_ms": round(p50, 2),
        "batch_p99_ms": round(p99, 2),
        "measurement_note": (
            "100k-node fleet on ONE CPU container: the [100k, 2] node "
            "tables and their top-k reductions exceed host cache, so "
            "wall clock here measures memory bandwidth of a single "
            "shared host, not accelerator solve throughput; the "
            "scenario exists to keep the region-scale shapes compiling "
            "and placing — real fleet-scale numbers need real HBM"
        ),
    }


def bench_loadaware_multichip():
    """Pods/s-vs-device-count curve over the production mesh path
    (S = 1/2/4/8 virtual CPU devices). Delegates to the
    ``tools.bench_multichip`` driver — each arm needs its own process
    to set the XLA device-count flag — which also writes the canonical
    ``MULTICHIP_rNN.json`` artifact with the embedded curve."""
    from tools.bench_multichip import run_curve

    return run_curve()


def _build_numa(n_nodes=2000, n_pods=16000, **sched_kw):
    """2-socket nodes + LSR whole-core pods; shared by the drain bench
    and the latency stream (the cpuset host commit sits on BOTH paths)."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
        NUMAPolicy,
    )

    topo = CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=16)
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    for i in range(n_nodes):
        name = f"n{i:04d}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
                ),
            )
        )
        numa.register_node(
            name, topo, NUMAPolicy.SINGLE_NUMA_NODE, memory_per_zone_mib=131072
        )
    pods = [
        Pod(
            meta=ObjectMeta(
                name=f"p{i:05d}",
                labels={ext.LABEL_POD_QOS: "LSR"},
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                priority=9500,
            ),
        )
        for i in range(n_pods)
    ]
    sched = BatchScheduler(snap, LoadAwareArgs(), numa=numa, **sched_kw)
    return sched, pods


def bench_numa():
    # r4: 2000 nodes / 16k pods (was 500/4000) — constrained scenarios
    # now measure steady-state throughput at a node scale where the
    # reference's per-pod × per-node Filter/Score scan actually hurts
    # (north star is 10k nodes); the scalar baseline below is re-measured
    # on this same config, so the ratio stays apples-to-apples.
    # bucket 2048: with GC deferred out of the cycle the per-chunk host
    # commit stays well under the 50 ms p99 bound, and fewer chunks
    # amortize the per-chunk dispatch cost better
    def build():
        return _build_numa(batch_bucket=2048)

    result = _measure(build, 2048, "numa_binpack_2socket")
    # open-the-gates PR: the NUMA carry A/B — speculation through the
    # opened gate, engagement + per-gate evidence embedded in the entry
    result["pipelined_ab"] = _pipelined_ab(build, max_batch=2048)
    return result


def _build_device_nodes(n_nodes):
    """8-GPU nodes (4 per NUMA domain) with a DeviceManager inventory."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Device, DeviceInfo, Node, NodeStatus, ObjectMeta
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager

    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    for i in range(n_nodes):
        name = f"g{i:04d}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 128000, ext.RES_MEMORY: 1 << 20}
                ),
            )
        )
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g, numa_node=g // 4)
                    for g in range(8)
                ],
            )
        )
    return snap, dm


def _build_device_gang(n_nodes=4000, n_gangs=4000, **sched_kw):
    """One gang (2 members × 4 GPUs) fills one 8-GPU node, so gangs ==
    nodes keeps the workload exactly satisfiable."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

    snap, dm = _build_device_nodes(n_nodes)
    pods = []
    for g in range(n_gangs):
        for m in range(2):
            pods.append(
                Pod(
                    meta=ObjectMeta(
                        name=f"gang{g:04d}-{m}",
                        labels={
                            ext.LABEL_GANG_NAME: f"gang-{g}",
                            ext.LABEL_GANG_MIN_AVAILABLE: "2",
                        },
                    ),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: 16000,
                            ext.RES_MEMORY: 65536,
                            ext.RES_GPU: 4,
                        },
                        priority=9000,
                    ),
                )
            )
    sched = BatchScheduler(snap, LoadAwareArgs(), devices=dm, **sched_kw)
    return sched, pods


def _build_device_stream(n_nodes=2000, n_pods=8000, **sched_kw):
    """Non-gang GPU pods (whole 1/2/4 + fractional 30/50%) for the
    latency stream: the exact per-minor device commit sits on the
    latency path for every pod."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

    snap, dm = _build_device_nodes(n_nodes)
    rng = np.random.default_rng(11)
    pods = []
    for i in range(n_pods):
        kind = rng.integers(0, 5)
        req = {ext.RES_CPU: 4000, ext.RES_MEMORY: 16384}
        if kind == 0:
            req[ext.RES_GPU] = 4
        elif kind == 1:
            req[ext.RES_GPU] = 2
        elif kind == 2:
            req[ext.RES_GPU] = 1
        elif kind == 3:
            req[ext.RES_GPU_MEMORY_RATIO] = 50
        else:
            req[ext.RES_GPU_MEMORY_RATIO] = 30
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"d{i:05d}"),
                spec=PodSpec(requests=req, priority=9000),
            )
        )
    sched = BatchScheduler(snap, LoadAwareArgs(), devices=dm, **sched_kw)
    return sched, pods


def bench_device_gang():
    # r4: 4000 nodes / 4000 gangs (8k pods, was 1000/1000) — steady-state
    # throughput at north-star-adjacent node scale; the scalar baseline is
    # re-measured on this same config (see bench_numa note).
    # bucket 1024: the device commit's per-chunk cost stays well under
    # the 50 ms p99 bound even on a contended host slice
    def build():
        return _build_device_gang(batch_bucket=1024)

    # latency at 1024-pod batches (a gang pair never splits); throughput
    # drains all 8k pods in ONE pipelined call
    result = _measure(build, 1024, "device_gang_8gpu")
    # open-the-gates PR: device + warm-gang carry A/B
    result["pipelined_ab"] = _pipelined_ab(build, max_batch=1024)
    return result


def _build_quota(n_nodes=4000, n_pods=32_768, oversubscribed=True, **sched_kw):
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ElasticQuota, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
    from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager
    from koordinator_tpu.sim.cluster_gen import GenConfig, gen_nodes

    cfg = GenConfig(n_nodes=n_nodes, n_pods=0, seed=5)
    nodes, metrics = gen_nodes(cfg)
    snap = ClusterSnapshot()
    for n in nodes:
        snap.upsert_node(n)
    for m in metrics:
        snap.set_node_metric(m, now=m.update_time + 1 if m.update_time else 1.0)
    gqm = GroupQuotaManager(snap.config)
    # 3-level tree: root -> 4 orgs -> 4 teams each. The drain bench keeps
    # the tree oversubscribed (admission + preemption under pressure);
    # the latency stream measures a healthy cluster (limits rarely bind,
    # so the cycle cost is the admission machinery, not a sustained
    # preemption storm)
    scale = 1 if oversubscribed else 8
    for org in range(4):
        gqm.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name=f"org-{org}"),
                min={
                    ext.RES_CPU: 2_000_000 * scale,
                    ext.RES_MEMORY: (8 << 20) * scale,
                },
                max={
                    ext.RES_CPU: 16_000_000 * scale,
                    ext.RES_MEMORY: (64 << 20) * scale,
                },
                is_parent=True,
            )
        )
        for team in range(4):
            gqm.upsert_quota(
                ElasticQuota(
                    meta=ObjectMeta(name=f"org-{org}-team-{team}"),
                    min={
                        ext.RES_CPU: 400_000 * scale,
                        ext.RES_MEMORY: (2 << 20) * scale,
                    },
                    max={
                        ext.RES_CPU: 8_000_000 * scale,
                        ext.RES_MEMORY: (32 << 20) * scale,
                    },
                    parent=f"org-{org}",
                )
            )
    rng = np.random.default_rng(9)
    pods = []
    for i in range(n_pods):
        org, team = rng.integers(0, 4), rng.integers(0, 4)
        cpu = int(rng.choice([500, 1000, 2000]))
        pods.append(
            Pod(
                meta=ObjectMeta(
                    name=f"q{i:05d}",
                    labels={ext.LABEL_QUOTA_NAME: f"org-{org}-team-{team}"},
                ),
                spec=PodSpec(
                    requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu * 2},
                    priority=int(rng.integers(5000, 9999)),
                ),
            )
        )
    sched = BatchScheduler(snap, LoadAwareArgs(), quotas=gqm, **sched_kw)
    return sched, pods


def bench_quota_tree():
    # r4: 4000 nodes / 32k pods (was 2000/16k) — see bench_numa note
    def build():
        return _build_quota(batch_bucket=4096)

    result = _measure(build, 4096, "quota_tree_3level")
    # open-the-gates PR: quota-table chaining A/B
    result["pipelined_ab"] = _pipelined_ab(build, max_batch=4096)
    return result


def _build_loadaware_stream(n_pods, **sched_kw):
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
    from koordinator_tpu.sim.cluster_gen import GenConfig, gen_nodes, gen_pods

    cfg = GenConfig(n_nodes=10_000, n_pods=n_pods, seed=7)
    nodes, metrics = gen_nodes(cfg)
    pods = gen_pods(cfg)
    snap = ClusterSnapshot()
    for n in nodes:
        snap.upsert_node(n)
    for m in metrics:
        snap.set_node_metric(m, now=m.update_time + 1 if m.update_time else 1.0)
    return BatchScheduler(snap, LoadAwareArgs(), **sched_kw), pods


def _latency_stream_run(
    backend_device, rate, build=None, n_target=6000, max_batch=256
):
    """One latency-mode run: Poisson arrivals at ``rate`` pods/s into a
    StreamScheduler with adaptive batches + upstream node sampling
    (PercentageOfNodesToScore=0 → the kube-scheduler adaptive default).
    ``build(batch_bucket=, max_rounds=, percentage_of_nodes_to_score=)``
    returns (sched, pods) — default is the 10k-node loadaware cluster;
    the constrained scenarios pass their own builders so the NUMA cpuset
    / device-minor / quota host commits sit ON the latency path. Returns
    per-pod enqueue→bind latencies (ms) for bound pods plus end backlog."""
    import jax

    from koordinator_tpu.scheduler.stream import StreamScheduler

    if build is None:
        build = _build_loadaware_stream
    with jax.default_device(backend_device):
        sched, pods = build(
            n_pods=n_target + 2_048,
            batch_bucket=max_batch,
            max_rounds=8,
            percentage_of_nodes_to_score=0,
        )
        sched.extender.monitor.stop_background()
        # warm the adaptive-batch shapes (full bucket + two partials)
        sched.schedule(pods[:max_batch])
        sched.schedule(pods[max_batch : max_batch + 100])
        sched.schedule(pods[max_batch + 100 : max_batch + 130])
        stream = StreamScheduler(sched, max_batch=max_batch)
        rng = np.random.default_rng(3)
        lat: list = []
        i = max_batch + 130
        t0 = time.perf_counter()
        next_arr = 0.0
        while len(lat) < n_target and i < len(pods):
            now = time.perf_counter() - t0
            while next_arr <= now and i < len(pods):
                stream.submit(pods[i], now=t0 + next_arr)
                i += 1
                next_arr += rng.exponential(1.0 / rate)
            res = stream.pump()
            for _pod, node, l in res:
                if node is not None:
                    lat.append(l * 1e3)
            if not res:
                time.sleep(0.0005)
    return lat, stream.backlog()


def bench_latency_stream():
    """The north star's latency clause (VERDICT r3 #2, extended per
    VERDICT r4 #2): per-pod enqueue→bind p50/p99 under continuous
    admission — the 10k-node loadaware cluster AND the constrained
    scenarios (numa cpuset / device minors / quota chain), whose host
    commits sit ON the latency path.

    Two backends are recorded for loadaware: the real TPU behind this
    environment's tunnel (every device→host fetch pays a fixed
    ~100-200 ms round trip — the hard floor of THIS wire, not of the
    design), and the in-process CPU backend as the co-located proxy
    (dispatch without the wire). Constrained runs use the co-located
    proxy. The throughput cost of the latency operating point is stated
    against the loadaware drain number."""
    import jax

    out = {"scenario": "latency_stream_10k"}
    runs = []
    cpu_dev = jax.devices("cpu")[0]
    # co-located proxy: 3000 pods/s sustained
    lat, backlog = _latency_stream_run(cpu_dev, rate=3000.0)
    p50, p99 = _percentiles([l / 1e3 for l in lat])
    runs.append(
        {
            "backend": "cpu_colocated_proxy",
            "rate_pods_per_sec": 3000,
            "bound": len(lat),
            "pod_p50_ms": round(p50, 2),
            "pod_p99_ms": round(p99, 2),
            "end_backlog": backlog,
        }
    )
    # constrained scenarios at their stated sustainable rates: the host
    # commit (cpuset slots / device minors / quota charges) is part of
    # every cycle, so these p99s include it
    import functools

    for name, build, rate in (
        ("numa_stream", _build_numa, 2000.0),
        ("device_stream", _build_device_stream, 1500.0),
        (
            "quota_stream",
            functools.partial(_build_quota, oversubscribed=False),
            1500.0,
        ),
    ):
        lat, backlog = _latency_stream_run(
            cpu_dev, rate=rate, build=build, n_target=4000
        )
        p50, p99 = _percentiles([l / 1e3 for l in lat])
        runs.append(
            {
                "backend": "cpu_colocated_proxy",
                "scenario": name,
                "rate_pods_per_sec": rate,
                "bound": len(lat),
                "pod_p50_ms": round(p50, 2),
                "pod_p99_ms": round(p99, 2),
                "end_backlog": backlog,
            }
        )
    # the tunneled TPU: sustainable rate is bounded by the fixed
    # round-trip per cycle; recorded for honesty, floor documented
    try:
        tpu = [d for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        tpu = []
    if tpu:
        lat, backlog = _latency_stream_run(
            tpu[0], rate=1200.0, n_target=2500
        )
        p50, p99 = _percentiles([l / 1e3 for l in lat])
        runs.append(
            {
                "backend": "tpu_via_tunnel",
                "rate_pods_per_sec": 1200,
                "bound": len(lat),
                "pod_p50_ms": round(p50, 2),
                "pod_p99_ms": round(p99, 2),
                "end_backlog": backlog,
                "note": (
                    "every cycle pays the tunnel's fixed ~100-200 ms "
                    "device-to-host round trip; co-located dispatch has "
                    "no such wire (see cpu_colocated_proxy)"
                ),
            }
        )
    out["runs"] = runs
    # throughput cost: latency mode schedules at most max_batch pods per
    # cycle over a 5% node window vs the drain's bucketed pipeline
    out["throughput_cost_note"] = (
        "latency mode sustains ~3k pods/s per scheduler at p99 below the "
        "50 ms north-star bound (co-located); the drain mode's 300k-400k "
        "pods/s headline remains the throughput operating point"
    )
    return out


def _drain_stream(sched, pods, pipelined, max_batch=512, depth=1, info=None):
    """Drain ``pods`` through a StreamScheduler in ``max_batch`` waves;
    returns (decided, bound, elapsed_s). ``depth`` selects the pipeline
    depth (open-the-gates PR); pass a dict as ``info`` to receive the
    live ``/debug/pipeline`` payload before the stream closes."""
    from koordinator_tpu.scheduler.stream import StreamScheduler

    stream = StreamScheduler(
        sched, max_batch=max_batch, pipelined=pipelined,
        pipeline_depth=depth,
    )
    try:
        for p in pods:
            stream.submit(p)
        decided = 0
        bound = 0
        t0 = time.perf_counter()
        while stream.backlog() or (pipelined and stream._pipe.inflight):
            for _pod, node, _lat in stream.pump():
                decided += 1
                bound += node is not None
        for _pod, node, _lat in stream.flush():
            decided += 1
            bound += node is not None
        elapsed = time.perf_counter() - t0
        if info is not None and pipelined:
            info.update(stream._pipe.gate_info())
    finally:
        stream.close()
    return decided, bound, elapsed


def _pipelined_ab(build, max_batch, depth=2, passes=3):
    """Same-backend serial-vs-pipelined A/B for one CONSTRAINED scenario
    (open-the-gates PR acceptance): the same cluster drained through the
    StreamScheduler twice, with the speculative path now riding the
    opened quota/NUMA/device/gang gates at ``depth`` in-flight solves.
    The entry embeds the engagement evidence — speculation kept >
    0, per-gate closed counts (the opened gates must read 0), the live
    ``/debug/pipeline`` payload — plus a retrace-free steady-state check
    over the measured passes (PR 8 standing rule: a perf claim must
    cite compile-ledger evidence, not just wall clock)."""
    from koordinator_tpu.obs.devprof import CompileLedger

    out = {"max_batch": max_batch, "depth": depth}
    # warm both jit specializations on throwaway instances — FULL drains,
    # because the retry tail's bucket ladder (odd-sized re-batches of
    # unschedulable pods) is part of the steady shape set and must not
    # read as a measured-pass retrace
    for pipelined in (False, True):
        sched, pods = build()
        sched.extender.monitor.stop_background()
        _drain_stream(
            sched, pods, pipelined=pipelined,
            max_batch=max_batch, depth=depth,
        )
    ledger = CompileLedger().install()
    ledger.mark_steady()
    try:
        for mode, pipelined in (("serial", False), ("pipelined", True)):
            rates = []
            kept = disc = 0.0
            gate_closed: dict = {}
            mismatches: dict = {}
            info: dict = {}
            for _ in range(passes):
                sched, pods = build()
                sched.extender.monitor.stop_background()
                info = {}
                decided, _bound, elapsed = _drain_stream(
                    sched, pods, pipelined=pipelined,
                    max_batch=max_batch, depth=depth, info=info,
                )
                rates.append(round(decided / elapsed, 1))
                if pipelined:
                    # aggregate the engagement counters over EVERY
                    # measured pass — each pass builds a fresh scheduler
                    # and last-pass-only evidence would under-report a
                    # transient gate closure or carry mismatch
                    reg = sched.extender.registry
                    spec_c = reg.get("pipeline_speculation_total")
                    kept += spec_c.value(outcome="kept")
                    disc += spec_c.value(outcome="discarded")
                    gc = reg.get("pipeline_gate_closed_total")
                    for key, s in gc._series.items():
                        gate_closed[key[0]] = (
                            gate_closed.get(key[0], 0.0) + s.value
                        )
                    cm = reg.get("pipeline_carry_mismatch_total")
                    for key, s in cm._series.items():
                        mismatches[key[0]] = (
                            mismatches.get(key[0], 0.0) + s.value
                        )
            out[f"{mode}_pods_per_sec"] = sorted(rates)[len(rates) // 2]
            out[f"{mode}_passes"] = rates
            if pipelined:
                out["speculation_kept"] = kept
                out["speculation_discarded"] = disc
                out["gate_closed"] = gate_closed
                out["carry_mismatches"] = mismatches
                out["debug_pipeline"] = info
    finally:
        out["steady_retraces"] = ledger.steady_retraces()
        ledger.uninstall()
    out["speedup"] = round(
        out["pipelined_pods_per_sec"]
        / max(out["serial_pods_per_sec"], 1e-9),
        3,
    )
    try:
        import jax

        accel = [d for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        accel = []
    if not accel:
        out["measurement_note"] = (
            "CPU-only backend: the 'device' solve, the prepare worker "
            "and the trailing commit all contend for the same host "
            "cores, so the overlap's wall effect sits inside "
            "measurement noise (often below 1.0x) — the engagement "
            "evidence (speculation kept, opened-gate closed-counts 0, "
            "retrace-free steady state) is the structural claim here; "
            "the wall win belongs to accelerator backends where host "
            "Reserve and device solve are different silicon"
        )
    return out


def _build_reservation_fastpath(
    n_nodes=512, n_resv=384, n_owner=1024, n_plain=3072
):
    """Reservation-bearing constrained scenario (open the last gates
    PR): a population of Available reservations whose owner pods bind
    through the fast path, interleaved with plain solver pods. With the
    ``reservations`` gate open, the pipelined stream PREDICTS each
    cycle's fast-path binds at dispatch and validates them by value at
    consume — the serial/pipelined A/B proves engagement (kept > 0,
    zero reservations-gate closures) on exactly this shape."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        ElasticQuota,
        ObjectMeta,
        Pod,
        PodSpec,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        GroupQuotaManager,
    )
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
    )
    from koordinator_tpu.sim.cluster_gen import GenConfig, gen_nodes

    cfg = GenConfig(n_nodes=n_nodes, n_pods=0, seed=11)
    nodes, metrics = gen_nodes(cfg)
    snap = ClusterSnapshot()
    for n in nodes:
        snap.upsert_node(n)
    for m in metrics:
        snap.set_node_metric(
            m, now=m.update_time + 1 if m.update_time else 1.0
        )
    gqm = GroupQuotaManager(snap.config)
    # allow_lent_resource=False: the min stays reserved regardless of
    # propagated demand, so the fast path's headroom check admits the
    # labeled owners (a demand-driven runtime trails it by one cycle)
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="resv-team"),
            min={ext.RES_CPU: 4_000_000, ext.RES_MEMORY: 16 << 20},
            max={ext.RES_CPU: 8_000_000, ext.RES_MEMORY: 32 << 20},
            allow_lent_resource=False,
        )
    )
    sched = BatchScheduler(
        snap, LoadAwareArgs(), quotas=gqm, batch_bucket=512
    )
    rm = ReservationManager(sched)
    for k in range(n_resv):
        rm.add(
            Reservation(
                meta=ObjectMeta(name=f"resv-{k:04d}"),
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                owners=[
                    ReservationOwner(label_selector={"app": "resv-owner"})
                ],
                allocate_once=(k % 2 == 0),
            )
        )
    assert rm.schedule_pending() == n_resv
    rng = np.random.default_rng(13)
    from koordinator_tpu.api import extension as _e

    owners = [
        Pod(
            meta=ObjectMeta(
                name=f"own{i:05d}",
                labels={
                    "app": "resv-owner",
                    _e.LABEL_QUOTA_NAME: "resv-team",
                },
            ),
            spec=PodSpec(
                requests={_e.RES_CPU: 2000, _e.RES_MEMORY: 4096},
                priority=9100,
            ),
        )
        for i in range(n_owner)
    ]
    plain = [
        Pod(
            meta=ObjectMeta(name=f"pl{i:05d}"),
            spec=PodSpec(
                requests={
                    _e.RES_CPU: int(rng.choice([500, 1000, 2000])),
                    _e.RES_MEMORY: 2048,
                },
                priority=int(rng.integers(5000, 9000)),
            ),
        )
        for i in range(n_plain)
    ]
    # interleave so fast-path binds spread across every pump
    pods = []
    oi = pi = 0
    while oi < len(owners) or pi < len(plain):
        if oi < len(owners):
            pods.append(owners[oi])
            oi += 1
        for _ in range(3):
            if pi < len(plain):
                pods.append(plain[pi])
                pi += 1
    return sched, pods


def bench_reservation_fastpath():
    def build():
        return _build_reservation_fastpath()

    # engagement probe (serial, outside the measured passes): the fast
    # path must actually consume reservations under this fixture, or
    # the A/B proves nothing about the reservation carry
    sched, pods = build()
    sched.extender.monitor.stop_background()
    _decided, bound, _el = _drain_stream(
        sched, pods, pipelined=False, max_batch=256
    )
    consumed = sum(
        1
        for r in sched.reservations.list()
        if r.current_owners or r.phase.value == "Succeeded"
    )
    assert consumed > 0, "fixture never exercised the fast path"
    out = {
        "scenario": "reservation_fastpath",
        "total": len(pods),
        "placed_serial_probe": bound,
        "reservations_consumed": consumed,
        "measurement_note_scenario": (
            "with hundreds of simultaneously-Available reservations the "
            "fast path is HOST match-bound (the per-pod nomination scan "
            "dominates the serial drain too — profiled ~90% of its "
            "wall); the dispatch-side preview necessarily runs that "
            "scan a second time, which a 2-core CPU container pays "
            "serially but an accelerator hides under the device solve "
            "(prepare-worker overlap). The engagement evidence "
            "(kept>0, zero reservation-gate closures, zero reservation "
            "carry mismatches, retrace-free) is the structural claim "
            "of this CPU round; vectorizing the nomination scan is the "
            "follow-on that lifts BOTH paths"
        ),
    }
    out.update(_pipelined_ab(build, max_batch=256, depth=2))
    return out


def _build_preempt_priority(n_nodes=256, n_low=1024, n_high=256):
    """Priority-preemption constrained scenario (open the last gates
    PR): low-priority filler saturates the cluster, then high-priority
    arrivals can only place by evicting it — the PostFilter preemption
    pass fires exactly in this overloaded regime, and with the
    ``preemption`` gate open the non-preempting cycles still speculate
    (an eager eviction discards only the downstream chain at its own
    commit)."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    # uniform SMALL nodes so the low-priority wave exactly saturates
    # the cluster (heterogeneous gen_nodes shapes leave too much slack
    # for preemption to ever fire): n_low * 4000 cpu == n_nodes * 16000
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"node-{i:05d}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: 16_000,
                        ext.RES_MEMORY: 65_536,
                    }
                ),
            )
        )
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=256,
        enable_priority_preemption=True,
    )
    low = [
        Pod(
            meta=ObjectMeta(name=f"low{i:05d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                priority=4000 + (i % 7),
            ),
        )
        for i in range(n_low)
    ]
    high = [
        Pod(
            meta=ObjectMeta(name=f"high{i:04d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384},
                priority=9500,
            ),
        )
        for i in range(n_high)
    ]
    return sched, low + high


def bench_preempt_priority():
    def build():
        return _build_preempt_priority()

    # engagement probe: evictions really happen (bound decisions whose
    # pods are no longer assumed at the end ARE the victims)
    sched, pods = build()
    sched.extender.monitor.stop_background()
    _decided, bound, _el = _drain_stream(
        sched, pods, pipelined=False, max_batch=128
    )
    evicted = bound - len(sched.snapshot._assumed)
    assert evicted > 0, "fixture never triggered priority preemption"
    out = {
        "scenario": "preempt_priority",
        "total": len(pods),
        "placed_serial_probe": bound,
        "preempted": evicted,
    }
    out.update(_pipelined_ab(build, max_batch=128, depth=2))
    return out


def bench_stream_pipelined():
    """Same-backend A/B of the cross-cycle solve pipeline (perf PR 4):
    one loadaware cluster drained through the StreamScheduler twice —
    serial pump vs pipelined pump (prepare worker + speculative chained
    dispatch + trailing commit). Decisions are identical (tested in
    tier-1); this measures the wall-clock effect of the overlap. Both
    modes get a traced pass: the serial stage table shows
    prepare+commit ADDITIVE with the solve inside each cycle, the
    pipelined one shows them overlapped (prepare rides the worker while
    the previous solve is in flight; the ``solve`` stage pays only the
    residual fence time of a solve dispatched before the trailing
    commit; the ``overlap`` span covers dispatch→consume).

    The fixture is sized so the HOST share of a cycle is material (2048
    nodes, 512-pod batches): the overlap's upper bound is the
    prepare+commit share, and at 10k+ nodes a CPU backend is so
    solve-bound (~97%) that the effect drowns in host noise — on a TPU
    backend the host share grows (device solve shrinks, host Reserve
    doesn't), which is where the pipeline is aimed."""
    n_pods = 6144
    max_batch = 512

    def build():
        from koordinator_tpu.core.snapshot import ClusterSnapshot
        from koordinator_tpu.scheduler.batch_solver import (
            BatchScheduler,
            LoadAwareArgs,
        )
        from koordinator_tpu.sim.cluster_gen import (
            GenConfig,
            gen_nodes,
            gen_pods,
        )

        cfg = GenConfig(n_nodes=2048, n_pods=n_pods, seed=11)
        nodes, metrics = gen_nodes(cfg)
        pods = gen_pods(cfg)
        snap = ClusterSnapshot()
        for n in nodes:
            snap.upsert_node(n)
        for m in metrics:
            snap.set_node_metric(
                m, now=m.update_time + 1 if m.update_time else 1.0
            )
        sched = BatchScheduler(
            snap, LoadAwareArgs(), batch_bucket=max_batch, max_rounds=8
        )
        return sched, pods

    # warm both jit specializations on throwaway instances
    sched, pods = build()
    sched.extender.monitor.stop_background()
    _drain_stream(sched, pods[: 2 * max_batch], pipelined=False)
    sched, pods = build()
    sched.extender.monitor.stop_background()
    _drain_stream(sched, pods[: 2 * max_batch], pipelined=True)

    out = {"scenario": "stream_pipelined", "total": n_pods}
    for mode, pipelined in (("serial", False), ("pipelined", True)):
        passes = []
        bound = decided = 0
        for _ in range(3):
            sched, pods = build()
            sched.extender.monitor.stop_background()
            decided, bound, elapsed = _drain_stream(
                sched, pods, pipelined=pipelined, max_batch=max_batch
            )
            passes.append(round(decided / elapsed, 1))
        out[f"{mode}_pods_per_sec"] = sorted(passes)[len(passes) // 2]
        out[f"{mode}_passes"] = passes
        out[f"{mode}_bound"] = bound
        if pipelined:
            reg = sched.extender.registry
            out["speculation_kept"] = reg.get(
                "pipeline_speculation_total"
            ).value(outcome="kept")
            out["speculation_discarded"] = reg.get(
                "pipeline_speculation_total"
            ).value(outcome="discarded")
            out["prepare_stalls"] = reg.get(
                "pipeline_prepare_stalls_total"
            ).value()
    out["speedup"] = round(
        out["pipelined_pods_per_sec"] / max(out["serial_pods_per_sec"], 1e-9),
        3,
    )
    try:
        import jax

        tpu = [d for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        tpu = []
    if not tpu:
        out["measurement_note"] = (
            "CPU-only backend: the 'device' solve shares the host's "
            "cores with the prepare worker and the trailing commit, so "
            "the overlap's wall-clock effect is bounded by the host "
            "share and contends for the same silicon; the stage tables "
            "(additive vs overlapped) are the structural evidence"
        )
    if STAGE_REPORT or TRACE_PATH:
        # traced passes for BOTH modes: the serial table shows
        # prepare/commit additive with solve per cycle, the pipelined
        # one shows them overlapped (prepare on the worker, solve
        # pre-dispatched, `overlap` spanning dispatch→consume)
        for mode, pipelined in (("serial", False), ("pipelined", True)):
            sched, pods = build()
            sched.extender.monitor.stop_background()
            tracer = sched.extender.tracer
            tracer.enabled = True
            _drain_stream(
                sched, pods, pipelined=pipelined, max_batch=max_batch
            )
            stats = _stage_stats(tracer.records())
            suffix = "" if pipelined else "_serial"
            out[f"stage_breakdown{suffix}_ms"] = {
                k: v["total_ms"] for k, v in stats.items()
            }
            out[f"stage_p50_p99{suffix}_ms"] = {
                k: [v["p50_ms"], v["p99_ms"]] for k, v in stats.items()
            }
            if STAGE_REPORT:
                _print_stage_table(f"stream_pipelined[{mode}]", stats)
            if TRACE_PATH and pipelined:
                path = (
                    f"{TRACE_PATH.removesuffix('.json')}_stream_pipelined"
                    ".json"
                )
                with open(path, "w") as f:
                    json.dump(tracer.to_chrome_trace(), f)
                out["trace_file"] = path
    return out


def bench_recovery():
    """Cold-restart vs warm-standby takeover time (HA failover PR).

    One leader binds a cluster's worth of pods (journaled + published),
    then commits a tail of bindings that are journal-ACKNOWLEDGED but
    never published — the lost-ack window a takeover must replay. Two
    recovery paths are then timed end-to-end (statehub sync + journal
    replay + resident re-lower + bit-exactness verification):

    * **warm standby** — a second instance that has been informer-synced
      all along with its device-resident NodeState already lowered; its
      takeover pays only the journal-tail replay and a dirty-row scatter
      of the touched rows;
    * **cold restart** — a fresh instance re-wiring the statehub from
      nothing: full re-list (every node/metric/pod event), full replay,
      full-axis re-lower.

    The gap between the two is the number the HA design buys: recovery
    cost proportional to the takeover DELTA, not to cluster size."""
    import time as _t

    from koordinator_tpu.core.journal import (
        BindJournal,
        EpochFence,
        MemoryJournalStore,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.recovery import recover_scheduler
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )
    from koordinator_tpu.sim.cluster_gen import GenConfig, gen_nodes, gen_pods

    n_nodes, n_pods, tail = 2048, 4096, 256
    fence = EpochFence()
    store = MemoryJournalStore()

    def make_sched():
        s = BatchScheduler(
            ClusterSnapshot(),
            LoadAwareArgs(),
            batch_bucket=1024,
            max_rounds=8,
            journal=BindJournal(store),
            fence=fence,
        )
        s.extender.monitor.stop_background()
        return s

    hub = ClusterStateHub()
    leader = make_sched()
    standby = make_sched()
    hub.wire_scheduler(leader)
    hub.wire_scheduler(standby)
    hub.start()
    cfg = GenConfig(n_nodes=n_nodes, n_pods=n_pods + tail, seed=5)
    nodes, metrics = gen_nodes(cfg)
    for n in nodes:
        hub.publish(hub.nodes, n)
    for m in metrics:
        hub.publish(hub.node_metrics, m)
    assert hub.wait_synced()
    pods = gen_pods(cfg)
    leader.grant_leadership(fence.advance())
    out_bound = leader.schedule(pods[:n_pods])
    for pod, node in out_bound.bound:
        pod.spec.node_name = node
        hub.publish(hub.pods, pod)
    assert hub.wait_synced()
    # warm standby steady state: synced, resident tables lowered, and
    # the dirty-scatter jit specializations warmed across the bucket
    # sizes the takeover's replay can touch (a long-lived standby has
    # refreshed through delta streams before; first-call compiles must
    # not be billed to the takeover)
    standby.node_state()
    for warm_bucket in (8, 16, 32, 64, 128, 256, 512):
        standby.snapshot.touch_rows(range(warm_bucket))
        standby.node_state()
    # the lost-ack tail: journaled binds the takeover must replay
    out_tail = leader.schedule(pods[n_pods:])
    # quiesce the (shared, on CPU) device stream: the dead leader's
    # async solve tail must not be billed to the takeover timings
    import jax as _jax

    if leader._resident_nodes is not None:
        _jax.block_until_ready(leader._resident_nodes.requested)

    t0 = _t.perf_counter()
    rep_warm = recover_scheduler(
        standby,
        standby.bind_journal,
        hub=hub,
        epoch=fence.advance(),
        verify=True,
    )
    warm_ms = (_t.perf_counter() - t0) * 1e3

    hub.detach_consumers()
    cold = make_sched()
    hub.wire_scheduler(cold)
    hub.start()
    t0 = _t.perf_counter()
    rep_cold = recover_scheduler(
        cold, cold.bind_journal, hub=hub, epoch=fence.advance(), verify=True
    )
    cold_ms = (_t.perf_counter() - t0) * 1e3
    hub.stop()
    assert rep_warm.bitexact and rep_cold.bitexact

    # ---- journal-length sweep (state-integrity PR): cold vs
    # full-replay vs checkpoint+tail RTO as the journal grows. The
    # decision-bearing property: checkpoint+tail stays ROUGHLY FLAT
    # (recovery work = live set + tail; the bounded load never parses
    # the prefix) while full replay grows with history. File-backed
    # stores — the real durability path — with a churned live window
    # so the live set stays constant across lengths. ----
    import shutil
    import tempfile

    from koordinator_tpu.core.journal import FileJournalStore

    def _sweep_sched():
        snap = ClusterSnapshot()
        for i in range(512):
            snap.upsert_node(nodes[i])
        s = BatchScheduler(
            snap, LoadAwareArgs(), batch_bucket=512, max_rounds=8
        )
        s.extender.monitor.stop_background()
        return s

    def _recover_ms(sched, store_path, use_checkpoint=True):
        jnl = BindJournal(FileJournalStore(store_path))
        t0 = _t.perf_counter()
        r = recover_scheduler(sched, jnl, hub=None, verify=True)
        ms = (_t.perf_counter() - t0) * 1e3
        assert r.used_checkpoint == use_checkpoint
        return ms, r

    sweep = []
    sweep_dir = tempfile.mkdtemp(prefix="bench_recovery_sweep_")
    try:
        live_window, tail_len = 256, 32
        for n_records in (256, 4096, 32768):
            base = f"{sweep_dir}/j{n_records}.jsonl"
            jnl = BindJournal(FileJournalStore(base))
            entry = {
                "node": "node-00000",
                "req": [1000.0, 2048.0, 0.0, 0.0],
                "est": [1000.0, 2048.0, 0.0, 0.0],
                "prod": False,
                "nom": 0.0,
                "conf": True,
                "quota": None,
            }
            seq = 0
            while True:
                jnl.append_bind(
                    1, seq, [dict(entry, uid=f"s{seq:06d}",
                                  node=f"node-{seq % 512:05d}")]
                )
                seq += 1
                if seq > live_window:
                    jnl.append_forget(
                        1, seq, [f"s{seq - live_window - 1:06d}"]
                    )
                if 2 * seq - live_window >= n_records:
                    break
            jnl.store.close()
            full = base + ".full"
            shutil.copy(base, full)
            jnl = BindJournal(FileJournalStore(base))
            jnl.append_checkpoint(epoch=1)
            jf = BindJournal(FileJournalStore(full))
            for t in range(tail_len):
                for j2 in (jnl, jf):
                    j2.append_bind(
                        1, seq + t,
                        [dict(entry, uid=f"tail{t:03d}")],
                    )
            jnl.store.close()
            jf.store.close()
            # replay-only walls (the pure journal cost, 3-pass min)
            def _replay_ms(path, **kw):
                j3 = BindJournal(FileJournalStore(path))
                best, rep3 = None, None
                for _ in range(3):
                    t0 = _t.perf_counter()
                    rep3 = j3.replay(**kw)
                    ms = (_t.perf_counter() - t0) * 1e3
                    best = ms if best is None else min(best, ms)
                j3.store.close()
                return best, rep3

            full_ms, rep_full = _replay_ms(full, use_checkpoint=False)
            ck_ms, rep_ck = _replay_ms(base)
            assert rep_ck.used_checkpoint
            assert set(rep_ck.live) == set(rep_full.live)
            # end-to-end RTO: cold scheduler + full replay, vs cold
            # scheduler + checkpoint+tail (the resync/re-lower legs are
            # identical, so the delta IS the replay discipline)
            cold_full_ms, _ = _recover_ms(
                _sweep_sched(), full, use_checkpoint=False
            )
            cold_ck_ms, _ = _recover_ms(_sweep_sched(), base)
            sweep.append({
                "records": n_records,
                "live": len(rep_full.live),
                "replay_full_ms": round(full_ms, 2),
                "replay_ckpt_tail_ms": round(ck_ms, 2),
                "applied_full": rep_full.applied,
                "applied_ckpt_tail": rep_ck.applied,
                "recover_full_ms": round(cold_full_ms, 1),
                "recover_ckpt_tail_ms": round(cold_ck_ms, 1),
            })
    finally:
        shutil.rmtree(sweep_dir, ignore_errors=True)

    return {
        "scenario": "recovery",
        "nodes": n_nodes,
        "bound_published": len(out_bound.bound),
        "journal_tail": len(out_tail.bound),
        "warm_takeover_ms": round(warm_ms, 1),
        "cold_restart_ms": round(cold_ms, 1),
        "warm_replayed": rep_warm.replayed,
        "warm_reconfirmed": rep_warm.reconfirmed,
        "cold_replayed": rep_cold.replayed,
        "cold_reconfirmed": rep_cold.reconfirmed,
        "warm_relower_ms": round(rep_warm.warm_lower_s * 1e3, 2),
        "cold_relower_ms": round(rep_cold.warm_lower_s * 1e3, 2),
        "takeover_speedup": round(cold_ms / max(warm_ms, 1e-9), 2),
        "journal_sweep": sweep,
    }


def _build_sharded_streams(n_shards, n_pods, max_batch):
    """Partition the 10k-node loadaware cluster into S shard-scoped
    schedulers (PR 6): each shard owns a disjoint node subset, runs its
    own fenced BatchScheduler + write-ahead journal, and streams its
    routed share of the arrival process."""
    from koordinator_tpu.core.journal import (
        BindJournal,
        EpochFence,
        MemoryJournalStore,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.shards import ShardMap
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )
    from koordinator_tpu.sim.cluster_gen import GenConfig, gen_nodes, gen_pods

    cfg = GenConfig(n_nodes=10_000, n_pods=n_pods, seed=7)
    nodes, metrics = gen_nodes(cfg)
    pods = gen_pods(cfg)
    smap = ShardMap(n_shards)
    metric_of = {m.meta.name: m for m in metrics}
    scheds, fences = [], []
    for s in range(n_shards):
        snap = ClusterSnapshot()
        for n in nodes:
            if smap.shard_of_node(n.meta.name) != s:
                continue
            snap.upsert_node(n)
            m = metric_of.get(n.meta.name)
            if m is not None:
                snap.set_node_metric(
                    m, now=m.update_time + 1 if m.update_time else 1.0
                )
        fence = EpochFence()
        sched = BatchScheduler(
            snap,
            LoadAwareArgs(),
            batch_bucket=max_batch,
            max_rounds=8,
            percentage_of_nodes_to_score=0,
            journal=BindJournal(MemoryJournalStore(), shard=s),
            fence=fence,
        )
        sched.extender.monitor.stop_background()
        fence.adopt(1)
        sched.grant_leadership(1)
        scheds.append(sched)
        fences.append(fence)
    return smap, scheds, fences, pods


def _sharded_stream_run(
    backend_device,
    n_shards,
    rate,
    n_target=6000,
    max_batch=256,
    churn_at=None,
    churn_pause_s=0.15,
    isolated=False,
    stage_stats_out=None,
):
    """One sharded latency run: ONE Poisson arrival process at the
    aggregate ``rate``, routed to shards by uid hash, each shard pumping
    its own StreamScheduler (the N-concurrent-leaders operating point).

    ``isolated=False`` pumps every shard on its own THREAD inside this
    one container — an honest floor, not the deployment shape: the
    Python host path (lower/commit) serializes on the GIL and the XLA
    CPU executions contend for the same cores, so added shards mostly
    measure contention. ``isolated=True`` times each shard's pump ALONE
    (sequentially, its own clock, its own arrival share at rate/S) and
    reports wall = max(per-shard wall): the process-per-shard deployment
    projection, where each scheduler is its own process exactly as the
    partitioned control plane deploys.

    ``churn_at`` (0..1 fraction of the pod budget) deposes shard 0's
    leader mid-run — its epoch advances, in-flight commits are fenced
    (STALE_LEADER_EPOCH), pods requeue — and re-grants after
    ``churn_pause_s``, measuring the p99/backlog cost of leader churn.

    ``stage_stats_out`` (a dict) turns each shard's tracer ON and fills
    ``{shard: _stage_stats(...)}`` after the run — the per-shard stage
    table pass (distributed-observability PR satellite). Only use on a
    dedicated pass AFTER the measured ones: tracing overhead lands in
    the pump. With ``TRACE_PATH`` set it also dumps ONE merged Chrome
    trace, a process lane per shard (``obs.fleet.merge_chrome_traces``).
    Returns (latencies_ms, end_backlog_total, bound, wall_s)."""
    import threading

    import jax

    from koordinator_tpu.scheduler.stream import StreamScheduler

    with jax.default_device(backend_device):
        smap, scheds, fences, pods = _build_sharded_streams(
            n_shards, n_target + 2_048, max_batch
        )
        # warm every shard's jit specializations (bucket + partials)
        for sched in scheds:
            sched.schedule(pods[:max_batch])
            sched.schedule(pods[max_batch : max_batch + 30])
        if stage_stats_out is not None:
            for sched in scheds:
                sched.extender.tracer.enabled = True
        streams = [
            StreamScheduler(s, max_batch=max_batch, max_retries=200)
            for s in scheds
        ]
        offset = max_batch + 30
        rng = np.random.default_rng(3)
        route = [[] for _ in range(n_shards)]
        if isolated:
            # each shard's own Poisson process at its arrival share
            for pod in pods[offset : offset + n_target]:
                route[smap.shard_of_key(pod.meta.uid)].append(pod)
            route = [
                [
                    (p, t)
                    for p, t in zip(
                        mine,
                        np.cumsum(
                            rng.exponential(
                                n_shards / rate, size=len(mine)
                            )
                        ),
                    )
                ]
                for mine in route
            ]
        else:
            next_arr = 0.0
            for pod in pods[offset : offset + n_target]:
                route[smap.shard_of_key(pod.meta.uid)].append(
                    (pod, next_arr)
                )
                next_arr += rng.exponential(1.0 / rate)
        lat_lock = threading.Lock()
        lat: list = []
        churn_stamp = (
            route[0][int(len(route[0]) * churn_at)][1]
            if churn_at is not None and route[0]
            else None
        )

        def pump_shard(si, t0):
            stream = streams[si]
            mine = route[si]
            i = 0
            out: list = []
            empty_streak = 0
            while i < len(mine) or stream.backlog():
                now = time.perf_counter() - t0
                while i < len(mine) and mine[i][1] <= now:
                    stream.submit(mine[i][0], now=t0 + mine[i][1])
                    i += 1
                res = stream.pump()
                for _pod, node, l in res:
                    if node is not None:
                        out.append(l * 1e3)
                if not res and i < len(mine):
                    time.sleep(0.0005)
                if not res and i >= len(mine) and stream.backlog():
                    # no decisions while draining: either the fenced
                    # churn window (pods re-queue charge-free and the
                    # re-grant catches up) or genuine capacity
                    # exhaustion — tolerate a generous streak before
                    # stopping with the backlog reported
                    empty_streak += 1
                    if empty_streak > 200:
                        break
                else:
                    empty_streak = 0
            with lat_lock:
                lat.extend(out)

        def churn_shard0():
            # depose shard 0's leader mid-run; re-grant under the
            # next epoch after the pause — the backlog catches up
            time.sleep(max(churn_stamp, 0.001))
            new_epoch = fences[0].advance()
            time.sleep(churn_pause_s)
            scheds[0].grant_leadership(new_epoch)

        if isolated:
            walls = []
            for si in range(n_shards):
                t0 = time.perf_counter()
                cth = None
                if churn_stamp is not None and si == 0:
                    # churn is timed against shard 0's own clock — the
                    # other shards' solo runs are unaffected, exactly as
                    # a real per-shard leader flap would be
                    cth = threading.Thread(target=churn_shard0)
                    cth.start()
                pump_shard(si, t0)
                if cth is not None:
                    cth.join()
                walls.append(time.perf_counter() - t0)
            wall = max(walls)
        else:
            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=pump_shard, args=(si, t0))
                for si in range(n_shards)
            ]
            if churn_stamp is not None:
                threads.append(threading.Thread(target=churn_shard0))
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
        backlog = sum(st.backlog() for st in streams)
        if stage_stats_out is not None:
            for si, sched in enumerate(scheds):
                stage_stats_out[si] = _stage_stats(
                    sched.extender.tracer.records()
                )
            if TRACE_PATH:
                from koordinator_tpu.obs.fleet import merge_chrome_traces

                path = (
                    f"{TRACE_PATH.removesuffix('.json')}"
                    f"_latency_stream_sharded.json"
                )
                with open(path, "w") as f:
                    json.dump(
                        merge_chrome_traces(
                            {
                                si: s.extender.tracer
                                for si, s in enumerate(scheds)
                            }
                        ),
                        f,
                    )
    return lat, backlog, len(lat), wall


def bench_latency_stream_sharded():
    """PR 6 acceptance scenario: aggregate pods/s scaling with shard
    count at ≥10x the single-leader arrival rate (latency_stream drives
    3k pods/s into ONE leader; this drives 30k/s across shards), with
    p99 and backlog reported under leader churn vs steady state. Every
    shard runs the full HA configuration — per-shard fence + write-ahead
    journal on the commit path."""
    import jax

    cpu_dev = jax.devices("cpu")[0]
    out = {"scenario": "latency_stream_sharded"}
    runs = []
    AGG_RATE = 30_000.0  # 10x latency_stream_10k's 3k pods/s
    for n_shards in (1, 2, 4):
        # warmup pass on a throwaway budget: the adaptive-batch pump
        # hits partial-chunk jit specializations the static warmup can't
        # enumerate — standard warmup-pass discipline (see _measure), so
        # compile time never lands in the measured wall/p99
        _sharded_stream_run(
            cpu_dev, n_shards, rate=AGG_RATE, n_target=1200, isolated=True
        )
        lat, backlog, bound, wall = _sharded_stream_run(
            cpu_dev, n_shards, rate=AGG_RATE, n_target=6000, isolated=True
        )
        p50, p99 = _percentiles([l / 1e3 for l in lat])
        runs.append(
            {
                "backend": "cpu_colocated_proxy",
                "shards": n_shards,
                "aggregate_rate_pods_per_sec": AGG_RATE,
                "bound": bound,
                "pods_per_sec": round(bound / wall, 1),
                "pod_p50_ms": round(p50, 2),
                "pod_p99_ms": round(p99, 2),
                "end_backlog": backlog,
                "mode": "steady",
            }
        )
    # churn arm: same 4-shard process-per-shard config, shard 0's
    # leader deposed mid-run (epoch advance → fenced commits → re-grant
    # + catch-up); the aggregate and p99 show the churn cost vs steady
    lat, backlog, bound, wall = _sharded_stream_run(
        cpu_dev, 4, rate=AGG_RATE, n_target=6000, churn_at=0.4,
        isolated=True,
    )
    p50, p99 = _percentiles([l / 1e3 for l in lat])
    runs.append(
        {
            "backend": "cpu_colocated_proxy",
            "shards": 4,
            "aggregate_rate_pods_per_sec": AGG_RATE,
            "bound": bound,
            "pods_per_sec": round(bound / wall, 1),
            "pod_p50_ms": round(p50, 2),
            "pod_p99_ms": round(p99, 2),
            "end_backlog": backlog,
            "mode": "churn_1_of_4_shards",
        }
    )
    if STAGE_REPORT or TRACE_PATH:
        # dedicated traced pass AFTER the measured arms (same
        # stage-table discipline as _stage_report_pass): per-SHARD
        # stage breakdowns land in the BENCH entry so the sharded
        # scenario cites stage structure like the single-leader ones,
        # and --trace dumps one merged Chrome doc (a process lane per
        # shard, obs.fleet)
        per_shard: dict = {}
        _sharded_stream_run(
            cpu_dev, 4, rate=AGG_RATE, n_target=2000, isolated=True,
            stage_stats_out=per_shard,
        )
        out["stage_breakdown_ms_per_shard"] = {
            str(si): {k: v["total_ms"] for k, v in st.items()}
            for si, st in sorted(per_shard.items())
        }
        out["stage_p50_p99_ms_per_shard"] = {
            str(si): {
                k: [v["p50_ms"], v["p99_ms"]] for k, v in st.items()
            }
            for si, st in sorted(per_shard.items())
        }
        if STAGE_REPORT:
            for si, st in sorted(per_shard.items()):
                _print_stage_table(
                    f"latency_stream_sharded shard-{si}", st
                )
    out["runs"] = runs
    by_shards = {
        r["shards"]: r for r in runs if r["mode"] == "steady"
    }
    out["scaling_note"] = (
        "aggregate throughput at 10x the single-leader arrival rate, "
        "process-per-shard projection (wall = slowest shard): "
        + ", ".join(
            f"S={s}: {by_shards[s]['pods_per_sec']} pods/s "
            f"(p99 {by_shards[s]['pod_p99_ms']}ms)"
            for s in sorted(by_shards)
        )
    )
    out["measurement_note"] = (
        "process-per-shard timing: each shard's pump is measured ALONE "
        "(its own arrival share at rate/S, wall = max shard wall) — "
        "the deployment shape of the partitioned control plane. One "
        "CPU container cannot host N schedulers concurrently without "
        "measuring its own contention instead (GIL-serialized host "
        "path + shared XLA cores), the same single-container caveat "
        "PR 4's pipelining numbers carry"
    )
    return out


#: the sim-domain fields of a ``_fleet_day_run`` record — everything a
#: same-seed pair must agree on bit-exactly regardless of whether the
#: decision ledger is recording (wall_s / pods_per_sec are the only
#: legitimately ledger-sensitive fields)
_SIM_DOMAIN_KEYS = (
    "shards_start", "shards_final", "incarnations", "day_cycles",
    "arrived", "bound", "pod_p50_cycles", "pod_p99_cycles",
    "handoffs", "quota_updates", "nodes_added", "nodes_removed",
    "burst_cycles", "slo", "bands", "shed", "deferred_total",
    "brownout", "topology", "generation_final",
)


def _ledger_ab(on: dict, off: dict) -> dict:
    """Decision-ledger same-seed A/B entry (decision-observatory PR).

    ``on`` ran with the per-shard DecisionLedgers recording every
    controller decision (the default); ``off`` ran the SAME seed with
    ``decisions=False``. Recording is observation, never actuation, so
    every sim-domain outcome must be bit-identical — asserted here, the
    bench-side twin of the soak-side shadow-non-perturbation checks.
    What remains is the wall-clock cost of recording, the number the
    r11 artifact gates through ``tools/bench_regress.py``.
    """
    drift = [k for k in _SIM_DOMAIN_KEYS if on.get(k) != off.get(k)]
    assert not drift, (
        "decision ledger perturbed sim-domain outcomes (recording must "
        f"be pure observation); drifted keys: "
        f"{ {k: (on.get(k), off.get(k)) for k in drift} }"
    )
    overhead = (1.0 - on["pods_per_sec"] / off["pods_per_sec"]) * 100.0
    return {
        "ledger_on_pods_per_sec": on["pods_per_sec"],
        "ledger_off_pods_per_sec": off["pods_per_sec"],
        "overhead_pct": round(overhead, 2),
        "identical_sim_outcomes": True,
        "note": (
            "same-seed pair, ledger on vs off: all sim-domain outcomes "
            "(placement counts, p50/p99 cycles, SLO burn rows, band "
            "stats, shed/deferred, brownout transitions) bit-identical "
            "— the ledger observes, never acts. overhead_pct is a "
            "SINGLE-PAIR wall-clock delta and carries the full "
            "single-container host noise (BENCH history: ±30-50% on "
            "contended windows); the BENCH_DECISIONS artifact's "
            "bench_regress rows pool multi-pass noise bands for the "
            "gated comparison"
        ),
    }


def _fleet_day_run(
    n_shards,
    n_incs,
    day_cycles,
    seed=0,
    base_rate_per_shard=3.0,
    elastic=False,
    drain_limit=60,
    qos_mix=False,
    storm=None,
    overload=False,
    decisions=True,
):
    """Drive one compressed production 'day' through an in-process
    sharded fleet: diurnal sinusoid arrivals, two burst storms, tenant
    quota churn, node churn — the traffic SHAPE the per-scenario drains
    never exercise (Tesserae's argument, arxiv 2508.04953). Returns the
    measured run record; hard invariants (zero-dup, all placed,
    gap-free timelines, cell-correct binds) are asserted inside.

    Overload-control PR arms: ``qos_mix`` spreads arrivals across all
    four priority bands (3 PROD / 2 MID / 3 BATCH / 2 FREE per 10);
    ``storm=(lo_frac, hi_frac, mult)`` replaces the two 5x bursts with
    ONE ``mult``× storm window; ``overload=True`` wires the QoS-aware
    AdmissionController + BrownoutController into every incarnation —
    shed pods then count as terminal (placed + shed == arrived, shed
    only ever BATCH/FREE, timelines ending at ``shed``), which is the
    brownout-on arm of the storm A/B.

    Decision-observatory PR arm: ``decisions=False`` disables the
    per-shard decision ledgers entirely (every controller site back to
    one attribute-is-None check) — the OFF leg of the ledger-overhead
    same-seed A/B. Recording is observation, never actuation, so the
    sim-cycle outcomes of a same-seed on/off pair must be
    bit-identical; only wall-clock may differ."""
    import math
    import random as _random
    import time as _time

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        ElasticQuota,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.obs.lifecycle import PodLifecycle, validate_timeline
    from koordinator_tpu.obs.slo import SloTarget, SloTracker
    from koordinator_tpu.runtime.elastic import TopologyController
    from koordinator_tpu.runtime.shards import (
        ShardedScheduler,
        ShardFabric,
        ShardRouter,
    )
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        GroupQuotaManager,
    )

    ALLOC_CPU, ALLOC_MEM = 32_000.0, 128 * 1024.0
    POD_CPU, POD_MEM = 2_000.0, 4_096.0
    LIFETIME = 8
    MAX_BATCH = 32
    rng = _random.Random(seed)
    sim = [0.0]

    fabric = ShardFabric(
        n_shards, clock=lambda: sim[0], membership_ttl_s=2.5
    )
    lifecycle = PodLifecycle(clock=lambda: sim[0])
    # SLO targets in SIM-CYCLE units (the tracker rides the sim clock):
    # a pod should place within ~6 cycles of arrival even through the
    # bursts; queue age past 3 cycles is backlog pressure — exactly the
    # signal the elastic arm's controller scales on. The overload arm
    # adds burn time-horizons + evidence floors so the ladder can
    # OBSERVE recovery once the storm passes (the non-overload arms
    # keep the historical pure count-window targets bit-identical).
    if overload:
        slo_targets = (
            SloTarget(
                "p99_latency", threshold_s=12.0, budget=0.1, window=64,
                max_age_s=16.0, min_samples=4,
            ),
            SloTarget(
                "queue_age", threshold_s=3.0, budget=0.05, window=64,
                max_age_s=16.0, min_samples=4,
            ),
            SloTarget("recovery", threshold_s=6.0, budget=0.5, window=16),
        )
    else:
        slo_targets = (
            SloTarget("p99_latency", threshold_s=12.0, budget=0.1, window=64),
            SloTarget("queue_age", threshold_s=3.0, budget=0.05, window=64),
            SloTarget("recovery", threshold_s=6.0, budget=0.5, window=16),
        )
    slo = SloTracker(clock=lambda: sim[0], targets=slo_targets)
    hub = ClusterStateHub()
    node_names = [f"n{i:03d}" for i in range(6 * n_shards)]

    def _publish_node(name):
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: ALLOC_CPU,
                        ext.RES_MEMORY: ALLOC_MEM,
                    }
                ),
            ),
        )

    for name in node_names:
        _publish_node(name)
    tenants = ("tenant-a", "tenant-b")
    # tenant caps scale with the fleet (arrivals do too): headroom of
    # ~2x the tenant's steady arrival share so the day is drainable,
    # with churn halving it — bursts still pile a real quota backlog
    cap_hi = 6 * n_shards
    quota_caps = {t: cap_hi for t in tenants}

    def _publish_quota(tenant):
        cap = quota_caps[tenant]
        hub.publish(
            hub.quotas,
            ElasticQuota(
                meta=ObjectMeta(name=tenant),
                min={ext.RES_CPU: 2 * POD_CPU, ext.RES_MEMORY: 2 * POD_MEM},
                max={
                    ext.RES_CPU: cap * POD_CPU,
                    ext.RES_MEMORY: cap * POD_MEM,
                },
            ),
        )

    for t in tenants:
        _publish_quota(t)

    def make_scheduler(shard, snapshot, fence, journal):
        gqm = GroupQuotaManager(snapshot.config, enable_preemption=False)
        s = BatchScheduler(
            snapshot,
            LoadAwareArgs(usage_thresholds={}),
            quotas=gqm,
            batch_bucket=MAX_BATCH,
            journal=journal,
            fence=fence,
        )
        s.extender.monitor.stop_background()
        return s

    incs = []
    admission = brownout = None
    if overload:
        from koordinator_tpu.api.extension import PriorityClass
        from koordinator_tpu.runtime.overload import (
            AdmissionController,
            BrownoutController,
            OverloadConfig,
        )

        brownout = BrownoutController(
            slo=slo,
            shards=lambda: fabric.shard_map.active_shards(),
            thresholds=(1.0, 2.0, 4.0, 8.0),
            sustain=2,
            cooldown=4,
            clock=lambda: sim[0],
        )
        admission = AdmissionController(
            OverloadConfig(
                band_budget={
                    PriorityClass.BATCH: 2 * MAX_BATCH,
                    PriorityClass.FREE: MAX_BATCH // 2,
                },
                band_age_limit_s={
                    PriorityClass.BATCH: 12.0,
                    PriorityClass.FREE: 5.0,
                },
            ),
            brownout=brownout,
            lifecycle=lifecycle,
            clock=lambda: sim[0],
        )

    def _spawn():
        inc = ShardedScheduler(
            f"fd-inc{len(incs)}",
            hub,
            fabric,
            make_scheduler,
            pipelined=False,
            max_batch=MAX_BATCH,
            max_retries=8,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
            lifecycle=lifecycle,
            slo=slo,
            overload=admission,
            decisions=decisions,
        )
        fabric.membership.heartbeat(inc.name)
        incs.append(inc)
        return inc

    for _ in range(n_incs):
        _spawn()
    # plain route() — the fleet_day driver never consults backlogs for
    # fan-out (spill/hysteresis has its own regression test; wiring it
    # here would claim coverage the scenario doesn't actually exercise)
    router = ShardRouter(fabric.shard_map, lifecycle=lifecycle)
    ctrl = None
    if elastic:
        ctrl = TopologyController(
            fabric,
            slo=slo,
            incarnations=lambda: [i for i in incs if not i.dead],
            node_names=lambda: list(node_names),
            split_burn=1.0,
            merge_burn=0.02,
            sustain=2,
            cooldown=10,
            max_shards=4 * n_shards,
            lifecycle=lifecycle,
            spawn=_spawn,
        )

    def _owner_of(shard):
        for inc in incs:
            if not inc.dead and inc.owns(shard):
                return inc
        return None

    placed = {}
    live = []
    pending = []
    pending_handoff = []
    stats = {
        "arrived": 0,
        "placed": 0,
        "completed": 0,
        "handoffs": 0,
        "nodes_added": 0,
        "nodes_removed": 0,
        "quota_updates": 0,
        "burst_cycles": 0,
    }
    pod_seq = 0
    node_seq = 0
    churn_nodes = []
    shed: dict = {}      # uid -> ShedTicket, terminal (overload arm)
    prio_of: dict = {}   # uid -> priority (per-band latency split)
    burst_mult = 5.0
    burst_windows = (
        (int(0.35 * day_cycles), int(0.40 * day_cycles)),
        (int(0.70 * day_cycles), int(0.74 * day_cycles)),
    )
    if storm is not None:
        lo_f, hi_f, mult = storm
        burst_windows = (
            (int(lo_f * day_cycles), int(hi_f * day_cycles)),
        )
        burst_mult = float(mult)
    #: deterministic QoS mix: 3 PROD / 2 MID / 3 BATCH / 2 FREE per 10
    QOS_PRIO = (9000, 9000, 9000, 7500, 7500, 5500, 5500, 5500, 3500, 3500)

    def _absorb_handoffs(handoffs):
        for shard, hand in sorted(handoffs.items()):
            stats["handoffs"] += 1
            for pod, node, _lat in hand.decided:
                if node is not None:
                    _place(pod, node, shard)
                else:
                    pending.append(pod)
            for pod, arr, tries in hand.queued:
                pending_handoff.append((shard, pod, arr, tries))

    def _place(pod, node, shard):
        assert pod.meta.uid not in placed, (
            f"{pod.meta.name} placed twice"
        )
        assert fabric.shard_map.cell_covers(shard, node)
        placed[pod.meta.uid] = node
        pod.spec.node_name = node
        hub.publish(hub.pods, pod)
        live.append((pod, node, sim[0] + LIFETIME))
        stats["placed"] += 1

    wall0 = _time.perf_counter()
    for cycle in range(day_cycles + drain_limit):
        sim[0] = float(cycle)
        arriving = []
        if cycle < day_cycles:
            # diurnal arrival curve + burst storms
            rate = base_rate_per_shard * n_shards * (
                1.0 + 0.8 * math.sin(2.0 * math.pi * cycle / day_cycles)
            )
            if any(lo <= cycle < hi for lo, hi in burst_windows):
                rate *= burst_mult
                stats["burst_cycles"] += 1
            for _ in range(max(1, int(rate))):
                pod_seq += 1
                labels = {}
                # the QoS-mixed storm arms keep quota labels OUT: a 10x
                # storm saturates any realistic tenant cap, and that
                # quota backlog is orthogonal to what the admission A/B
                # measures (band-differentiated queueing)
                if pod_seq % 4 == 0 and not qos_mix:
                    labels[ext.LABEL_QUOTA_NAME] = tenants[
                        (pod_seq // 4) % len(tenants)
                    ]
                prio = (
                    QOS_PRIO[pod_seq % len(QOS_PRIO)]
                    if qos_mix
                    else (9000 if pod_seq % 3 else 5500)
                )
                pod = Pod(
                    meta=ObjectMeta(
                        name=f"day-{pod_seq:05d}", labels=labels
                    ),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: POD_CPU,
                            ext.RES_MEMORY: POD_MEM,
                        },
                        priority=prio,
                    ),
                )
                prio_of[pod.meta.uid] = prio
                arriving.append(pod)
            # tenant quota churn: caps breathe every 8 cycles
            if cycle % 8 == 4:
                t = tenants[(cycle // 8) % len(tenants)]
                quota_caps[t] = (
                    cap_hi // 2 if quota_caps[t] == cap_hi else cap_hi
                )
                _publish_quota(t)
                stats["quota_updates"] += 1
            # node churn: a node joins every 12 cycles; a previously
            # added node with no live pods leaves
            if cycle % 12 == 6:
                node_seq += 1
                fresh = f"churn{node_seq:03d}"
                _publish_node(fresh)
                node_names.append(fresh)
                churn_nodes.append(fresh)
                stats["nodes_added"] += 1
                busy = {n for _p, n, _d in live}
                for cand in list(churn_nodes):
                    # an EARLIER churn node with no live pods leaves —
                    # never the one that just joined (that would make
                    # the churn a same-cycle publish+delete no-op)
                    if cand != fresh and cand not in busy:
                        hub.delete(
                            hub.nodes, Node(meta=ObjectMeta(name=cand))
                        )
                        churn_nodes.remove(cand)
                        node_names.remove(cand)
                        stats["nodes_removed"] += 1
                        break
        stats["arrived"] += len(arriving)
        pending.extend(arriving)

        if ctrl is not None and cycle < day_cycles:
            ctrl.tick(cycle)
        for inc in incs:
            if not inc.dead:
                _absorb_handoffs(inc.tick())
        still = []
        for shard, pod, arr, tries in pending_handoff:
            if not fabric.shard_map.is_active(shard):
                shard = router.route(pod)
            owner = _owner_of(shard)
            if owner is not None and owner.resubmit(shard, pod, arr, tries):
                pass
            else:
                still.append((shard, pod, arr, tries))
        pending_handoff = still
        still = []
        for pod in pending:
            shard = router.route(pod)
            owner = _owner_of(shard)
            if not (
                owner is not None
                and owner.submit(shard, pod, now=float(cycle))
            ):
                still.append(pod)
        pending = still
        for inc in incs:
            if inc.dead:
                continue
            for s, pod, node, _lat in inc.pump():
                if node is not None:
                    _place(pod, node, s)
                else:
                    pending.append(pod)
        stillliving = []
        for pod, node, done in live:
            if done <= cycle:
                hub.delete(hub.pods, pod)
                fabric.claims.release(pod.meta.uid)
                stats["completed"] += 1
            else:
                stillliving.append((pod, node, done))
        live = stillliving
        assert hub.wait_synced()
        if brownout is not None:
            brownout.tick(cycle)
        if admission is not None:
            # the bench's drivers redeem nothing: every shed is
            # terminal (the A/B's point is what the storm COSTS each
            # band, not how drivers retry)
            for t in admission.take_tickets():
                shed[t.pod.meta.uid] = t
        if (
            cycle >= day_cycles
            and not pending
            and not pending_handoff
            and stats["placed"] + len(shed) == stats["arrived"]
        ):
            break
    for inc in incs:
        if inc.dead:
            continue
        for s, pod, node, _lat in inc.flush():
            if node is not None:
                _place(pod, node, s)
            else:
                pending.append(pod)
    if admission is not None:
        for t in admission.take_tickets():
            shed[t.pod.meta.uid] = t
    wall = _time.perf_counter() - wall0

    assert not pending and not pending_handoff, (
        f"{len(pending)}/{len(pending_handoff)} pods never placed; "
        f"pending labels: "
        f"{[p.meta.labels for p in pending[:5]]}; backlogs: "
        f"{ {s: _owner_of(s).backlog(s) for s in fabric.shard_map.active_shards() if _owner_of(s)} }"
    )
    assert stats["placed"] == len(placed)
    assert stats["placed"] + len(shed) == stats["arrived"], (
        f"arrived {stats['arrived']} != placed {stats['placed']} + "
        f"shed {len(shed)}"
    )
    if admission is None:
        assert not shed
    else:
        # the QoS contract: only BATCH/FREE ever pay for the storm
        from koordinator_tpu.api.extension import PriorityClass as _PC

        assert set(admission.shed_counts) <= {
            int(_PC.BATCH), int(_PC.FREE)
        }, admission.shed_counts
    # gap-free lifecycle timelines END TO END — through bursts, churn
    # and (elastic arm) live topology transitions; a shed pod's ends
    # TERMINALLY at shed (the brownout-on arm's sacrifice is traced,
    # never silent)
    latencies = []
    lat_by_uid = {}
    bad = 0
    for uid in placed:
        evs = lifecycle.timeline(uid)
        if validate_timeline(evs):
            bad += 1
        t0 = next(e.t for e in evs if e.stage == "submit")
        t_ack = next(e.t for e in reversed(evs) if e.stage == "ack")
        latencies.append(t_ack - t0)
        lat_by_uid[uid] = t_ack - t0
    for uid in shed:
        evs = lifecycle.timeline(uid)
        if validate_timeline(evs) or evs[-1].stage != "shed":
            bad += 1
    assert bad == 0, f"{bad} gap-ful timelines"
    # latencies are SIM-CYCLE counts, not seconds — no ms conversion
    p50 = float(np.percentile(np.asarray(latencies), 50))
    p99 = float(np.percentile(np.asarray(latencies), 99))
    slo_eval = slo.evaluate()
    out = {
        "shards_start": n_shards,
        "shards_final": len(fabric.shard_map.active_shards()),
        "incarnations": len([i for i in incs if not i.dead]),
        "day_cycles": day_cycles,
        "arrived": stats["arrived"],
        "bound": stats["placed"],
        "wall_s": round(wall, 3),
        "pods_per_sec": round(stats["placed"] / wall, 1),
        "pod_p50_cycles": round(p50, 2),
        "pod_p99_cycles": round(p99, 2),
        "handoffs": stats["handoffs"],
        "quota_updates": stats["quota_updates"],
        "nodes_added": stats["nodes_added"],
        "nodes_removed": stats["nodes_removed"],
        "burst_cycles": stats["burst_cycles"],
        "slo": {
            shard: {
                k: {
                    "burn_rate": row["burn_rate"],
                    "window_p99_s": row["window_p99_s"],
                }
                for k, row in rows.items()
            }
            for shard, rows in slo_eval.items()
        },
    }
    if ctrl is not None:
        out["topology"] = dict(ctrl.stats)
        out["generation_final"] = fabric.topology.generation
    if qos_mix:
        from koordinator_tpu.api.extension import PriorityClass as _PC

        per_band: dict = {}
        for uid, lat in lat_by_uid.items():
            band = _PC.from_priority(prio_of[uid]).name
            per_band.setdefault(band, []).append(lat)
        shed_bands: dict = {}
        for t in shed.values():
            shed_bands[t.band.name] = shed_bands.get(t.band.name, 0) + 1
        out["bands"] = {
            band: {
                "placed": len(lats),
                "shed": shed_bands.get(band, 0),
                "p50_cycles": round(
                    float(np.percentile(np.asarray(lats), 50)), 2
                ),
                "p99_cycles": round(
                    float(np.percentile(np.asarray(lats), 99)), 2
                ),
            }
            for band, lats in sorted(per_band.items())
        }
        out["shed"] = len(shed)
    if brownout is not None:
        out["brownout"] = {
            "peak": max(
                [t["to"] for t in brownout.transitions()] or [0]
            ),
            "final": brownout.level,
            "transitions": len(brownout.transitions()),
            "stats": dict(brownout.stats),
        }
        out["deferred_total"] = admission.deferred_total
    for inc in incs:
        if not inc.dead:
            inc.close()
    hub.stop()
    return out


def bench_fleet_day():
    """Elastic-topology PR acceptance scenario: one compressed
    production day (diurnal arrivals, burst storms, tenant quota churn,
    node churn) streamed through the sharded control plane — the
    traffic shape the per-scenario drains never exercise — with p99
    placement SLOs and gap-free lifecycle timelines asserted END TO
    END, a throughput-vs-S curve past S=8, and an ELASTIC arm where the
    SLO-burn topology controller splits shards under the burst storm.

    Backend note: in-process fleet on whatever backend is attached —
    all S points share the container, so the curve is a same-backend
    A/B (the decision-bearing comparison on CPU per the bench-backend
    standing rule); absolute pods/s carries the usual single-container
    contention caveat (GIL-serialized host path, shared XLA cores)."""
    out = {"scenario": "fleet_day"}
    runs = []
    DAY = 48
    for n_shards in (2, 4, 8, 12):
        n_incs = max(2, n_shards // 2)
        # warmup fleet on a throwaway budget: the adaptive pumps hit
        # partial-chunk jit specializations a static warmup can't
        # enumerate (same discipline as every stream scenario)
        _fleet_day_run(n_shards, n_incs, day_cycles=8, seed=1)
        rec = _fleet_day_run(n_shards, n_incs, day_cycles=DAY, seed=0)
        rec["mode"] = "static"
        runs.append(rec)
    # the SLO contract the day must hold at every S (sim-cycle units):
    # steady-state placement is ONE pump (p50 within a cycle), and the
    # burst storms' backlog clears inside ~1.5 days' worth of cycles at
    # p99 — the tail IS burst-recovery time, which is the point of the
    # scenario (a per-scenario drain never shows it)
    for rec in runs:
        assert rec["pod_p50_cycles"] <= 1.0, (
            f"S={rec['shards_start']}: p50 {rec['pod_p50_cycles']} cycles"
        )
        assert rec["pod_p99_cycles"] <= 1.5 * DAY, (
            f"S={rec['shards_start']}: p99 {rec['pod_p99_cycles']} cycles"
        )
    # DECISION-LEDGER A/B (decision-observatory PR): rerun the S=4 day
    # from the same seed with the per-shard decision ledgers disabled
    # entirely. Sim-domain outcomes must be bit-identical (the ledger
    # observes, never acts); the wall-clock delta is the recording
    # overhead the BENCH_DECISIONS artifact gates via bench_regress.
    ab_on = next(
        r for r in runs if r["mode"] == "static" and r["shards_start"] == 4
    )
    ab_off = _fleet_day_run(4, 2, day_cycles=DAY, seed=0, decisions=False)
    ab_off["mode"] = "ledger_off"
    out["decisions_ab"] = _ledger_ab(ab_on, ab_off)
    runs.append(ab_off)
    # ELASTIC arm: base S=4, the burn-driven controller splits under
    # the burst storm and spawns incarnations to match
    elastic = _fleet_day_run(
        4, 2, day_cycles=DAY, seed=0, base_rate_per_shard=4.0,
        elastic=True,
    )
    elastic["mode"] = "elastic"
    assert elastic["topology"]["splits"] >= 1, (
        "the burst storm must burn the SLO budget hard enough to split"
    )
    assert elastic["shards_final"] > elastic["shards_start"]
    assert elastic["pod_p50_cycles"] <= 1.0
    runs.append(elastic)
    out["runs"] = runs
    by_s = {r["shards_start"]: r for r in runs if r["mode"] == "static"}
    out["pods_per_sec"] = by_s[12]["pods_per_sec"]  # headline: past S=8
    out["passes"] = [r["pods_per_sec"] for r in runs if r["mode"] == "static"]
    out["throughput_vs_shards"] = {
        str(s): by_s[s]["pods_per_sec"] for s in sorted(by_s)
    }
    out["scaling_note"] = (
        "fleet-day aggregate throughput vs shard count (same backend, "
        "one container): "
        + ", ".join(
            f"S={s}: {by_s[s]['pods_per_sec']} pods/s "
            f"(p99 {by_s[s]['pod_p99_cycles']} cycles)"
            for s in sorted(by_s)
        )
        + f"; elastic arm: {elastic['shards_start']}->"
        f"{elastic['shards_final']} shards, "
        f"{elastic.get('topology', {}).get('splits', 0)} split(s)"
    )
    out["measurement_note"] = (
        "in-process fleet: every shard's pump shares one container "
        "(GIL-serialized host path + shared XLA cores), so the S curve "
        "measures scheduling-work partitioning, not added hardware — "
        "accelerator rounds with process-per-shard placement are where "
        "absolute scaling lands. p50/p99 are SIM-CYCLE placement "
        "latencies (arrival->ack on the sim clock); invariants "
        "(zero-dup, 100% placement, gap-free timelines, cell-correct "
        "binds) are asserted inside the run."
    )
    return out


def bench_overload_storm():
    """Overload-control PR acceptance A/B: ONE 10x arrival storm over a
    QoS-mixed fleet day, run twice from the same seed — brownout OFF
    (uniform FIFO queueing: every band, PROD included, waits behind the
    flood) vs brownout ON (QoS-aware bounded admission + the brownout
    ladder: BATCH/FREE are deferred then shed, PROD/MID sail through).
    The decision-bearing number is PROD p99 placement latency through
    the burst — it must be STRICTLY better with brownout on, bought
    only with BATCH/FREE degradation (shed counts are in the entry,
    each shed traced to a terminal ``shed`` timeline).

    Backend note: in-process CPU fleet, same-backend A/B (the bench-
    backend standing rule); latencies are SIM-CYCLE counts."""
    out = {"scenario": "overload_storm"}
    DAY = 48
    kw = dict(
        n_shards=4,
        n_incs=2,
        day_cycles=DAY,
        seed=0,
        base_rate_per_shard=3.0,
        qos_mix=True,
        storm=(0.35, 0.50, 10),
    )
    # warmup fleet on a throwaway budget (adaptive-pump jit shapes)
    _fleet_day_run(4, 2, day_cycles=8, seed=1, qos_mix=True)
    base = _fleet_day_run(overload=False, **kw)
    base["mode"] = "brownout_off"
    prot = _fleet_day_run(overload=True, **kw)
    prot["mode"] = "brownout_on"
    # DECISION-LEDGER A/B (decision-observatory PR): the brownout-on
    # storm is the decision-densest leg in the suite (ladder churn,
    # per-cycle admission verdicts, breaker probes) — rerun it from the
    # same seed with the ledgers disabled. Bit-identical sim outcomes
    # asserted; the wall-clock delta is the recording overhead.
    noledger = _fleet_day_run(overload=True, decisions=False, **kw)
    noledger["mode"] = "brownout_on_ledger_off"
    out["decisions_ab"] = _ledger_ab(prot, noledger)
    out["runs"] = [base, prot, noledger]
    prod_off = base["bands"]["PROD"]["p99_cycles"]
    prod_on = prot["bands"]["PROD"]["p99_cycles"]
    # the acceptance bar: PROD's storm tail is strictly protected, paid
    # for ONLY by the sheddable bands
    assert prod_on < prod_off, (
        f"brownout failed to protect PROD p99: on {prod_on} vs "
        f"off {prod_off} cycles"
    )
    assert base.get("shed", 0) == 0
    assert prot["bands"]["PROD"]["shed"] == 0
    assert prot["bands"].get("MID", {}).get("shed", 0) == 0
    out["pods_per_sec"] = prot["pods_per_sec"]
    out["passes"] = [prot["pods_per_sec"]]
    out["prod_p99_cycles"] = {
        "brownout_off": prod_off, "brownout_on": prod_on
    }
    out["mid_p99_cycles"] = {
        "brownout_off": base["bands"]["MID"]["p99_cycles"],
        "brownout_on": prot["bands"]["MID"]["p99_cycles"],
    }
    out["ab_note"] = (
        f"same-seed 10x storm A/B: PROD p99 {prod_off} -> {prod_on} "
        f"sim-cycles with brownout on "
        f"({prot['brownout']['peak']} peak ladder level, "
        f"{prot['shed']} BATCH/FREE pods shed with terminal traced "
        "timelines, 0 PROD/MID shed); brownout-off rides the storm "
        "uniformly — every band pays the queueing tail"
    )
    out["measurement_note"] = (
        "in-process CPU fleet (one container, GIL-shared): the "
        "decision-bearing comparison is the same-backend same-seed "
        "A/B between the two runs; latencies are SIM-CYCLE placement "
        "counts (arrival->ack), throughput is wall-clock and carries "
        "the usual single-container contention caveat"
    )
    return out


def _fleet_constrained_fixture(n_nodes, seed=0):
    """Columnar constrained state at fleet scale: the PR 17 fleet
    generator's NodeState plus NUMA zone / GPU slot tables derived from
    the same columns — no per-node Python objects anywhere."""
    import jax.numpy as jnp

    from koordinator_tpu.sim.cluster_gen import FleetConfig, fleet_node_state

    cfg = FleetConfig(n_nodes=n_nodes, seed=seed)
    nodes = fleet_node_state(cfg)
    return cfg, nodes, jnp


def _solver_ab(drain, n_pods, k, passes=3):
    """Same-backend shortlist A/B over one solver-level drain callable:
    ``drain(shortlist_k) -> (placed, fallbacks)``. Warms both arms (two
    static specializations), measures each, and pins decision identity
    between the arms — the A/B is only meaningful if the pruned solve
    made the SAME decisions."""
    placed_sl, fb = drain(k)        # warmup + placement (shortlist arm)
    placed_full, _ = drain(None)    # warmup (full-axis arm)
    assert placed_sl == placed_full, (placed_sl, placed_full)
    sl_pps, full_pps = [], []
    for _ in range(passes):
        t0 = time.perf_counter()
        drain(k)
        sl_pps.append(round(n_pods / (time.perf_counter() - t0), 1))
    for _ in range(passes):
        t0 = time.perf_counter()
        drain(None)
        full_pps.append(round(n_pods / (time.perf_counter() - t0), 1))
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    return {
        "pods_per_sec": med(sl_pps),
        "passes": sl_pps,
        "placed": placed_sl,
        "shortlist_k": k,
        "shortlist_ab": {
            "full_axis_pods_per_sec": med(full_pps),
            "full_axis_passes": full_pps,
            "speedup": round(med(sl_pps) / med(full_pps), 2),
            "fallbacks": [int(v) for v in fb],
            "identical_placements": True,
        },
    }


def bench_numa_20k():
    """Fleet-scale NUMA bin-pack: 20k heterogeneous columnar nodes with
    2-zone tables split from the fleet allocatable columns, LSR
    whole-core + SingleNUMANode-required pods, drained through
    ``solve_stream_full``. The embedded A/B is the node-axis pruning
    tentpole's headline: at 20k nodes the full-axis round body pays
    [P, 20k] feasibility/cost every round where the shortlisted body
    pays [P, 64]."""
    import jax

    from koordinator_tpu.ops.numa import NumaState
    from koordinator_tpu.ops.solver import (
        PodBatch,
        SolverParams,
        solve_stream_full,
    )
    from koordinator_tpu.sim.cluster_gen import gen_fleet_pod_arrays

    n_nodes, n_pods, chunk = 20_000, 4096, 512
    cfg, nodes, jnp = _fleet_constrained_fixture(n_nodes)
    alloc = np.asarray(nodes.allocatable)
    est = np.asarray(nodes.estimated_used)
    zone_cap = np.repeat((alloc / 2.0)[:, None, :], 2, axis=1).astype(
        np.float32
    )
    zone_free = np.clip(
        zone_cap - (est / 2.0)[:, None, :], 0.0, None
    ).astype(np.float32)
    numa = NumaState(
        zone_free=jnp.asarray(zone_free),
        zone_cap=jnp.asarray(zone_cap),
        policy=jnp.asarray(np.full(n_nodes, 3, np.int8)),  # SINGLE_NUMA
    )
    fix = gen_fleet_pod_arrays(cfg, n_pods)
    rng = np.random.default_rng(7)
    # whole-core pods carry LSR QoS (the cpuset-bind predicate), half the
    # batch requires SingleNUMANode outright — both alignment triggers
    qos = np.where(fix["requests"][:, 0] % 1000.0 == 0, 3, 0).astype(np.int8)
    pods = PodBatch.create(
        requests=fix["requests"],
        estimate=fix["estimate"],
        priority=fix["priority"],
        is_prod=fix["is_prod"],
        qos=qos,
        numa_required=rng.random(n_pods) < 0.5,
    )
    b = n_pods // chunk
    stacked = jax.tree.map(
        lambda a: a.reshape((b, chunk) + a.shape[1:]), pods
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray((65.0, 95.0), jnp.float32),
        prod_thresholds=jnp.zeros(2, jnp.float32),
        score_weights=jnp.ones(2, jnp.float32),
    )

    def drain(k):
        a, _z, _r, fb = solve_stream_full(
            stacked, nodes, params, numa=numa, max_rounds=12,
            shortlist_k=k,
        )
        return int(np.sum(np.asarray(a) >= 0)), np.asarray(fb).sum(0)

    result = {"scenario": "numa_binpack_20k"}
    result.update(_solver_ab(drain, n_pods, k=64))
    result.update(
        {
            "total": n_pods,
            "n_nodes": n_nodes,
            "measurement_note": (
                "solver-level drain over the columnar fleet generator "
                "(no host snapshot at this node count); both arms are "
                "the same jit program family on the same backend, so "
                "the A/B isolates the node-axis pruning"
            ),
        }
    )
    return result


def bench_device_gang_20k():
    """Fleet-scale device gangs: 20k columnar nodes with 8 free GPU
    slots each, 2048 two-member gangs (mixed 1/2/4-GPU sizes — a
    uniform all-4-GPU batch never converges early and every chunk burns
    the whole round budget in BOTH arms, drowning the A/B in the
    non-prunable commit machinery) drained chunk-by-chunk through
    ``assign`` + ``enforce_gangs`` with the device slot table chained
    between chunks — the per-chunk dispatch path the scheduler runs, at
    a node count where the round body's [P, N] work dominates."""
    import jax

    from koordinator_tpu.ops.device import DeviceState
    from koordinator_tpu.ops.solver import (
        PodBatch,
        SolverParams,
        assign,
        enforce_gangs,
    )

    n_nodes, n_gangs, chunk = 20_000, 2048, 512
    _cfg, nodes, jnp = _fleet_constrained_fixture(n_nodes)
    devices = DeviceState(
        slot_free=jnp.asarray(np.full((n_nodes, 8), 100.0, np.float32)),
        cap_total=jnp.asarray(np.full(n_nodes, 800.0, np.float32)),
    )
    p = n_gangs * 2
    rng = np.random.default_rng(3)
    gpu = np.repeat(
        rng.choice([1, 2, 4], n_gangs), 2
    ).astype(np.int32)  # both members of a gang share a size
    cpu = gpu.astype(np.float32) * 2000.0 + 2000.0
    req = np.stack([cpu, cpu * 4.0], 1).astype(np.float32)
    pods = PodBatch.create(
        requests=req,
        priority=np.full(p, 9000, np.int32),
        gang_id=np.repeat(np.arange(n_gangs, dtype=np.int32), 2),
        gang_min=np.full(p, 2, np.int32),
        gpu_whole=gpu,
    )
    b = p // chunk  # gang pairs are contiguous, chunk is even
    stacked = jax.tree.map(
        lambda a: a.reshape((b, chunk) + a.shape[1:]), pods
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray((65.0, 95.0), jnp.float32),
        prod_thresholds=jnp.zeros(2, jnp.float32),
        score_weights=jnp.ones(2, jnp.float32),
    )

    def drain(k):
        cur, dev_carry = nodes, None
        placed, fb = 0, np.zeros(2, np.int64)
        for c in range(b):
            pb = jax.tree.map(lambda a: a[c], stacked)
            res = assign(
                pb, cur, params, devices=devices, dev_carry=dev_carry,
                max_rounds=12, shortlist_k=k,
            )
            res = enforce_gangs(res, pb)
            cur = cur.replace(
                requested=res.node_requested,
                estimated_used=res.node_estimated_used,
                prod_used=res.node_prod_used,
            )
            dev_carry = (
                res.node_dev_slots, res.node_rdma_free, res.node_fpga_free
            )
            placed += int(np.sum(np.asarray(res.assignment) >= 0))
            if res.shortlist_fallbacks is not None:
                fb += np.asarray(res.shortlist_fallbacks)
        return placed, fb

    result = {"scenario": "device_gang_20k"}
    result.update(_solver_ab(drain, p, k=64))
    result.update(
        {
            "total": p,
            "n_nodes": n_nodes,
            "n_gangs": n_gangs,
            "measurement_note": (
                "per-chunk assign + enforce_gangs with chained device "
                "slot tables over the columnar fleet generator; both "
                "arms share the dispatch path so the A/B isolates the "
                "node-axis pruning"
            ),
        }
    )
    return result


SCENARIOS = {
    "loadaware": bench_loadaware,
    "loadaware_100k": bench_loadaware_100k,
    "loadaware_multichip": bench_loadaware_multichip,
    "fleet_day": bench_fleet_day,
    "overload_storm": bench_overload_storm,
    "numa": bench_numa,
    "numa_20k": bench_numa_20k,
    "device_gang": bench_device_gang,
    "device_gang_20k": bench_device_gang_20k,
    "quota_tree": bench_quota_tree,
    "reservation_fastpath": bench_reservation_fastpath,
    "preempt_priority": bench_preempt_priority,
    "latency_stream": bench_latency_stream,
    "latency_stream_sharded": bench_latency_stream_sharded,
    "stream_pipelined": bench_stream_pipelined,
    "recovery": bench_recovery,
}


def run_scenarios(
    wanted=None,
    stage_report: bool = False,
    trace=None,
    stream_note=None,
    prune: bool = False,
) -> None:
    """Run scenarios and merge results into BENCH_SUITE.json (also the
    entry point for ``bench.py --scenario``). ``stage_report`` adds the
    traced per-stage breakdown pass to each _measure scenario; ``trace``
    is a Chrome-trace path prefix for those passes."""
    global STAGE_REPORT, TRACE_PATH
    STAGE_REPORT = stage_report
    TRACE_PATH = trace
    wanted = list(wanted) if wanted else list(SCENARIOS)
    unknown = [n for n in wanted if n not in SCENARIOS]
    if unknown:
        sys.exit(
            f"unknown scenario(s) {unknown}; valid: {', '.join(SCENARIOS)}"
        )
    # merge into the existing artifact: a partial or interrupted run must
    # never discard other scenarios' numbers (BASELINE.md cites this file
    # as the source of record for every scenario)
    try:
        with open("BENCH_SUITE.json") as f:
            existing = {r["scenario"]: r for r in json.load(f)}
    except (OSError, ValueError, KeyError, TypeError):
        existing = {}
    ran = set()
    for name in wanted:
        res = SCENARIOS[name]()
        if stream_note and res["scenario"] == "latency_stream_10k":
            res["measurement_note"] = stream_note
        existing[res["scenario"]] = res
        ran.add(res["scenario"])
        print(json.dumps(res))
        with open("BENCH_SUITE.json", "w") as f:
            json.dump(list(existing.values()), f, indent=1)
    if prune:
        # a COMPLETED full run prunes stale entries (renamed/removed
        # scenarios); interruption keeps whatever was known
        with open("BENCH_SUITE.json", "w") as f:
            json.dump([existing[s] for s in existing if s in ran], f, indent=1)


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "scenarios",
        nargs="*",
        help=f"scenarios to run (default: all; valid: {', '.join(SCENARIOS)})",
    )
    ap.add_argument(
        "--stream-note",
        default=None,
        metavar="TEXT",
        help="attach a measurement_note to the latency_stream entry (used "
        "when the pure-host streams are re-measured standalone in a quiet "
        "window and the artifact must say so — BASELINE.md relies on the "
        "note surviving regeneration)",
    )
    ap.add_argument(
        "--stage-report",
        action="store_true",
        help="print per-stage total/p50/p99 tables and embed "
        "stage_breakdown_ms into the per-scenario BENCH_SUITE.json entries",
    )
    ap.add_argument(
        "--trace",
        nargs="?",
        const="bench_suite_trace.json",
        default=None,
        metavar="PATH",
        help="write a Chrome trace of each scenario's traced pass to "
        "PATH_<scenario>.json",
    )
    args = ap.parse_args()
    run_scenarios(
        args.scenarios or None,
        stage_report=args.stage_report,
        trace=args.trace,
        stream_note=args.stream_note,
        prune=not args.scenarios,
    )


if __name__ == "__main__":
    main()
