#!/usr/bin/env bash
# The single local CI gate: static analysis, generated-doc freshness,
# and the tier-1 fast test suite as ONE fail-fast command. Mirrors what
# the driver enforces; run it before pushing.
#
#   bash tools/ci_check.sh
#
# JAX_PLATFORMS defaults to cpu (the tier-1 environment); export it
# first to gate on another backend.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== koordlint (all passes) =="
python -m tools.koordlint

echo "== chaos-point catalog freshness =="
python -m tools.gen_chaos_catalog --check

echo "== shortlist equivalence subset (decision-identity pins) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/test_shortlist.py \
  -q -p no:cacheprovider

echo "== shortlist CPU bench artifact gate (committed vs itself: shape + scenarios present) =="
python tools/bench_regress.py \
  --baseline BENCH_SHORTLIST_r12_cpu.json \
  --current BENCH_SHORTLIST_r12_cpu.json \
  --scenario numa_binpack_2socket --scenario device_gang_8gpu \
  --scenario quota_tree_3level \
  --scenario numa_binpack_20k --scenario device_gang_20k

echo "== tier-1 fast tests (pytest -m 'not slow') =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider

echo "ci_check: ALL GREEN"
