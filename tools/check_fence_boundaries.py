#!/usr/bin/env python3
"""Thin shim: the fence-before-journal lint now lives in the koordlint
framework (``tools/koordlint/passes/fence_boundaries.py``, pass
``fence-boundaries``). This entry point keeps existing invocations and
imports working with bit-identical verdicts:

    python tools/check_fence_boundaries.py [paths...]
    python -m tools.koordlint --select fence-boundaries
"""

from __future__ import annotations

import sys
from pathlib import Path

if __package__ in (None, ""):  # script invocation
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.koordlint.passes.fence_boundaries import (  # noqa: E402,F401
    EXEMPT_FILES,
    FENCE_CHECK_HELPERS,
    GUARDED_APPENDS,
    check_file,
    check_paths,
    main,
)

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
