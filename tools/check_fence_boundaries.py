#!/usr/bin/env python3
"""Repo lint: every bind-journal write boundary flows through an epoch
check (PR 6 satellite).

The HA work (PRs 5–6) established the fencing discipline: a deposed
leader must be REFUSED at every boundary it could cross, and the
write-ahead journal append is the last one before a mutation becomes
durable. This lint makes the discipline mechanical: any function in
``koordinator_tpu/`` that appends an ``intent``/``bind``/``abort``
record (``append_intent``/``append_bind``/``append_abort``) must, in
the SAME function body, evaluate an epoch check — one of:

* a call to ``_fence_stale`` (the commit boundary's check helper);
* a ``.check(...)`` call on something named ``fence`` (the
  ``EpochFence.check`` form the fast path and channel client use).

``append_forget`` is deliberately OUT of scope: forgets mirror
apiserver-authoritative deletions, which standbys (and the sharded
soak's driver, on ownerless shards) journal fence-EXEMPT by design.
``core/journal.py`` itself is exempt — it IS the fencing authority (its
``_append`` refuses stale epochs at the storage boundary, the backstop
when every in-process check was bypassed), and :class:`ClaimTable`
fences claims the same way.

Usage:  python tools/check_fence_boundaries.py [paths...]
Enforced as a tier-1 test by ``tests/test_fence_boundaries_lint.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Iterable, List, Tuple

#: journal write ops that MUST be epoch-checked in the enclosing function
GUARDED_APPENDS = frozenset(
    {"append_intent", "append_bind", "append_abort"}
)

#: calls that count as an epoch check
FENCE_CHECK_HELPERS = frozenset({"_fence_stale"})

#: files exempt from the scan (relative to koordinator_tpu/)
EXEMPT_FILES = frozenset({"core/journal.py"})

Violation = Tuple[str, int, str]


def _call_attr(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _is_fence_check(call: ast.Call) -> bool:
    name = _call_attr(call)
    if name in FENCE_CHECK_HELPERS:
        return True
    if name != "check":
        return False
    # ``<something>.check(...)`` counts only when the receiver path
    # mentions a fence (``self.fence.check``, ``fence.check``,
    # ``fabric.fences[s].check``) — a stray ``x.check()`` does not.
    node = call.func.value if isinstance(call.func, ast.Attribute) else None
    while node is not None:
        if isinstance(node, ast.Attribute):
            if "fence" in node.attr.lower():
                return True
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            return "fence" in node.id.lower()
        else:
            return False
    return False


def _rel(path: Path, root: Path) -> str:
    try:
        return path.relative_to(root).as_posix()
    except ValueError:  # target outside the repo (ad-hoc invocation)
        return path.as_posix()


def check_file(path: Path, root: Path) -> List[Violation]:
    rel = _rel(path, root)
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except SyntaxError as exc:
        return [(rel, exc.lineno or 0, f"unparsable: {exc.msg}")]
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        appends: List[ast.Call] = []
        checked = False
        # scan this function's body EXCLUDING nested function defs —
        # a check inside a nested closure does not guard this frame's
        # appends (and vice versa); nested defs are walked on their own
        stack = list(node.body)
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.iter_child_nodes(stmt):
                stack.append(sub)
            if isinstance(stmt, ast.Call):
                if _call_attr(stmt) in GUARDED_APPENDS:
                    appends.append(stmt)
                elif _is_fence_check(stmt):
                    checked = True
        if appends and not checked:
            for call in appends:
                out.append(
                    (
                        rel,
                        call.lineno,
                        f"journal {_call_attr(call)} without an epoch "
                        "check in the enclosing function "
                        f"({node.name}) — fence before journal",
                    )
                )
    return out


def check_paths(paths: Iterable[Path], root: Path) -> List[Violation]:
    violations: List[Violation] = []
    for p in paths:
        for f in sorted(p.rglob("*.py")) if p.is_dir() else [p]:
            if _rel(f, root) in (
                f"koordinator_tpu/{e}" for e in EXEMPT_FILES
            ):
                continue
            violations.extend(check_file(f, root))
    return violations


def main(argv: List[str]) -> int:
    root = Path(__file__).resolve().parent.parent
    targets = (
        [Path(a).resolve() for a in argv]
        if argv
        else [root / "koordinator_tpu"]
    )
    violations = check_paths(targets, root)
    for rel, line, msg in violations:
        print(f"{rel}:{line}: {msg}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unfenced journal write boundar"
            f"{'y' if len(violations) == 1 else 'ies'}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
