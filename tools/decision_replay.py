#!/usr/bin/env python
"""Offline counterfactual replay of a recorded decision ledger.

Every controller records its decisions on the
:class:`koordinator_tpu.obs.decisions.DecisionLedger` as
``{controller, tick, inputs, action, state}`` where ``inputs`` is the
COMPLETE evidence it decided from and the decision itself is a PURE
function of that snapshot. That makes a recorded ledger a replayable
dataset:

* **Self-replay** (default): re-decide every record through the
  deterministic controllers' own ``decide()`` functions. Every
  recomputed action must match the recorded action bit-exactly — any
  drift is a determinism bug (a controller read evidence outside its
  snapshot), and the tool exits 1 with the first divergence's full
  context.
* **Candidate replay** (``--policy``): feed the SAME recorded inputs to
  an alternate policy and report counterfactual divergence — per-
  controller action agreement, the first divergence with its snapshot,
  and the reward inputs (per-tick ``outcome`` fields: placement p99,
  queue age, sheds, SLO violations — whatever the driver stamped)
  summed over the trace. This is the offline half of the
  :mod:`koordinator_tpu.obs.shadow` harness: the longrun sim + soaks
  produce ledgers, this tool evaluates policies against them without
  ever letting one act.

Accepted ledger shapes: a ``DecisionLedger.render()`` document
(``{"records": [...]}``), the fleet surface's ``/debug/decisions``
document (``{"shards": {...}}`` — flattened), or a bare JSON list of
records.

Usage::

    python tools/decision_replay.py --ledger /tmp/decisions.json
    python tools/decision_replay.py --ledger ... --policy pkg.mod:POLICY
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys
from typing import Callable, Dict, List, Optional

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # `python tools/decision_replay.py` from anywhere
    sys.path.insert(0, _REPO)


def deterministic_policies() -> Dict[str, Callable]:
    """controller name -> the acting controller's pure decide()."""
    from koordinator_tpu.runtime.elastic import TopologyController
    from koordinator_tpu.runtime.overload import (
        AdmissionController,
        BrownoutController,
        CircuitBreaker,
    )
    from koordinator_tpu.scheduler.pipeline import _DepthController

    return {
        "depth": _DepthController.decide,
        "brownout": BrownoutController.decide,
        "admission": AdmissionController.decide,
        "breaker": CircuitBreaker.decide,
        "topology": TopologyController.decide,
    }


def load_records(doc) -> List[dict]:
    """Normalize any accepted ledger shape to a flat record list."""
    if isinstance(doc, dict) and "records" in doc:
        return list(doc["records"])
    if isinstance(doc, dict) and "shards" in doc:
        out: List[dict] = []
        for _shard, sub in sorted(doc["shards"].items()):
            out.extend(load_records(sub))
        return out
    if isinstance(doc, list):
        return list(doc)
    raise ValueError(
        "unrecognized ledger shape (want a DecisionLedger.render() "
        "document, a /debug/decisions fleet document, or a record list)"
    )


def _proposed_action(policy, inputs: dict):
    """A policy entry may be a pure decide() returning (action, state)
    or a plain inputs -> action function (ShadowPolicy.propose shape)."""
    out = policy(inputs)
    if isinstance(out, tuple):
        return out[0]
    return out


def replay(
    records: List[dict],
    policies: Optional[Dict[str, Callable]] = None,
) -> dict:
    """Re-decide every record; per-controller agreement + reward sums."""
    if policies is None:
        policies = deterministic_policies()
    per: Dict[str, dict] = {}
    reward: Dict[str, float] = {}
    skipped = 0
    for rec in records:
        controller = str(rec.get("controller"))
        for key, val in (rec.get("outcome") or {}).items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                reward[key] = reward.get(key, 0.0) + float(val)
        policy = policies.get(controller)
        if policy is None:
            skipped += 1
            continue
        row = per.setdefault(
            controller,
            {"total": 0, "agreed": 0, "first_divergence": None},
        )
        row["total"] += 1
        proposed = _proposed_action(policy, rec["inputs"])
        if proposed == rec["action"]:
            row["agreed"] += 1
        elif row["first_divergence"] is None:
            row["first_divergence"] = {
                "seq": rec.get("seq"),
                "cseq": rec.get("cseq"),
                "tick": rec.get("tick"),
                "shard": rec.get("shard"),
                "recorded": rec["action"],
                "proposed": proposed,
                "inputs": rec["inputs"],
            }
    for row in per.values():
        row["agreement_pct"] = round(
            100.0 * row["agreed"] / row["total"], 2
        ) if row["total"] else 100.0
    return {
        "controllers": per,
        "records": len(records),
        "skipped": skipped,
        "diverged": sum(
            r["total"] - r["agreed"] for r in per.values()
        ),
        "reward": {k: round(v, 4) for k, v in sorted(reward.items())},
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument(
        "--ledger", required=True,
        help="recorded ledger JSON (DecisionLedger.render(), "
        "/debug/decisions, or a bare record list)",
    )
    ap.add_argument(
        "--policy", default="", metavar="MODULE:ATTR",
        help="candidate policy: a dict {controller: decide} (or "
        "inputs->action callables). Omitted = self-replay through the "
        "deterministic controllers (any drift exits 1)",
    )
    ap.add_argument(
        "--json", default="", metavar="PATH",
        help="also write the replay report as JSON ('-' = stdout only)",
    )
    args = ap.parse_args(argv)
    with open(args.ledger) as f:
        records = load_records(json.load(f))
    self_replay = not args.policy
    if self_replay:
        policies = deterministic_policies()
    else:
        mod_name, _, attr = args.policy.partition(":")
        if not attr:
            ap.error("--policy must be MODULE:ATTR")
        policies = dict(getattr(importlib.import_module(mod_name), attr))
    report = replay(records, policies)
    report["mode"] = "self" if self_replay else f"candidate:{args.policy}"
    doc = json.dumps(report, indent=1, sort_keys=True)
    if args.json and args.json != "-":
        with open(args.json, "w") as f:
            f.write(doc + "\n")
    print(doc)
    if self_replay and report["diverged"]:
        print(
            f"DETERMINISM DRIFT: {report['diverged']} recorded "
            "decision(s) did not reproduce from their own inputs",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
