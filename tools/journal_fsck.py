"""journal_fsck — verify/repair/report for checksummed journal files.

The offline arm of the state-integrity PR: the same codec + screening
the stores run at load time (:mod:`koordinator_tpu.core.integrity`),
usable against a journal file (or a directory of them) from the shell —
before adopting a recovered volume, after a corruption incident, or in
CI over soak artifacts.

Usage::

    python -m tools.journal_fsck [--repair] [--json [-|PATH]] PATH...

``PATH`` is a journal file or a directory (every regular file except
``*.tmp``/``*.quarantine`` sidecars is checked). Modes:

* **verify** (default) — screen every record; report corruption, write
  holes, duplicate seqs, torn tails and checkpoint-image digests. The
  file is not touched.
* **--repair** — additionally QUARANTINE corrupt lines into the
  ``<file>.quarantine`` sidecar, trim a torn tail, and atomically
  rewrite the file to the surviving records.

Exit codes: **0** clean (or every damaged record was repaired), **1**
corruption / quarantined records found (verify mode), **2** the store
could not be read at all (I/O error) or recovery semantics are damaged
beyond repair. The containment ledgers (poison-quarantine blame/redeem,
crash-loop boot/death) journal through the same codec — their op tallies
appear as ``containment_ops`` in each file's report.

Unrepairable means recovery semantics were damaged beyond what
quarantine restores: a checkpoint recovery image with a failed digest
and NO earlier history to fall back to — the live set cannot be
reconstructed from the remaining records.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # direct `python tools/journal_fsck.py` use
    sys.path.insert(0, _REPO_ROOT)

from koordinator_tpu.core import integrity  # noqa: E402
from koordinator_tpu.core.journal import BindJournal  # noqa: E402


def _journal_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                full = os.path.join(p, name)
                if not os.path.isfile(full):
                    continue
                if name.endswith(".tmp") or name.endswith(".quarantine"):
                    continue
                out.append(full)
        else:
            out.append(p)
    return out


def check_file(path: str, repair: bool = False) -> Dict[str, object]:
    """Screen one journal file; optionally repair in place. Returns the
    per-file report dict (shape shared by text and --json output)."""
    entries = []
    raw_lines: List[str] = []
    try:
        with open(path, "r", encoding="utf-8", newline="") as f:
            for line in f:
                stripped = line.strip()
                if not stripped:
                    continue
                raw_lines.append(stripped)
                try:
                    entries.append((json.loads(stripped), stripped))
                except json.JSONDecodeError:
                    entries.append((None, stripped))
    except OSError as exc:
        return {"path": path, "error": repr(exc), "ok": False}
    kept, quarantine, rep = integrity.screen_records(
        entries, store=os.path.basename(path)
    )
    # checkpoint recovery images: a bad digest is repairable only while
    # an older verified image (or raw pre-history) still covers it
    ckpt_total = ckpt_bad = 0
    unrepairable = False
    first_seq = min(
        (r.get("seq") for r in kept if isinstance(r.get("seq"), int)),
        default=None,
    )
    for i, rec in enumerate(kept):
        if rec.get("op") != "checkpoint":
            continue
        ckpt_total += 1
        if not BindJournal._checkpoint_image_ok(rec):
            ckpt_bad += 1
            if i == 0 and rec.get("seq") == first_seq:
                # the file STARTS at this image (compacted prefix):
                # nothing earlier can rebuild the live set it carried
                unrepairable = True
    # a QUARANTINED head-of-stream checkpoint is the same loss through
    # the other door: the line CRC failed, so the record never reached
    # the image check, and a compacted store has no history behind it
    for pos, raw in quarantine:
        if pos != 0 or raw is None:
            continue
        try:
            head = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if isinstance(head, dict) and head.get("op") == "checkpoint":
            unrepairable = True
    # gray-failure containment ledgers (quarantine + crash-loop) journal
    # their records through the same codec — tally their ops so a fsck
    # of a soak artifact shows the blame/boot history at a glance
    containment_ops: Dict[str, int] = {}
    for rec in kept:
        op = rec.get("op")
        if op in ("blame", "redeem", "boot", "death"):
            containment_ops[op] = containment_ops.get(op, 0) + 1
    report: Dict[str, object] = {
        "path": path,
        "records": rep.total,
        "kept": rep.kept,
        "legacy": rep.legacy,
        "corrupt": rep.corrupt,
        "dup_seq": rep.dup_seq,
        "seq_gaps": rep.seq_gaps,
        "torn_tail": rep.torn_tail,
        "checkpoints": ckpt_total,
        "checkpoint_digest_failures": ckpt_bad,
        "quarantined": list(rep.quarantined),
        "containment_ops": containment_ops,
        "unrepairable": unrepairable,
        "ok": rep.ok and ckpt_bad == 0,
        "repaired": False,
    }
    if repair and (not rep.ok or rep.torn_tail or rep.dup_seq):
        bad_raw = [raw for _pos, raw in quarantine if raw is not None]
        if bad_raw:
            with open(path + ".quarantine", "a", encoding="utf-8") as q:
                for raw in bad_raw:
                    q.write(raw + "\n")
        out_records = list(kept)
        # interior seqs now missing (quarantined records and write
        # holes) are EXPLAINED by the repair: a sealed seq_tombstone
        # record closes them, so the repaired file re-verifies clean
        # and the runtime's gap screening stays exact
        present = sorted(
            {
                r["seq"]
                for r in kept
                if isinstance(r.get("seq"), int)
            }
            | {
                s
                for r in kept
                if r.get("op") == "seq_tombstone"
                for s in r.get("seqs", ())
                if isinstance(s, int)
            }
        )
        holes = [
            s
            for a, b in zip(present, present[1:])
            for s in range(a + 1, b)
        ]
        if holes:
            out_records.append(
                {
                    "seq": present[-1] + 1,
                    "op": "seq_tombstone",
                    "seqs": holes,
                }
            )
        tmp = path + ".fsck.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in out_records:
                f.write(
                    json.dumps(
                        integrity.seal(rec), separators=(",", ":")
                    )
                    + "\n"
                )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        report["repaired"] = True
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="journal_fsck", description=__doc__.splitlines()[0]
    )
    ap.add_argument("paths", nargs="+", help="journal file(s) or dir(s)")
    ap.add_argument(
        "--repair",
        action="store_true",
        help="quarantine corrupt lines and rewrite the file clean",
    )
    ap.add_argument(
        "--json",
        nargs="?",
        const="-",
        default=None,
        metavar="PATH",
        help="emit the report as JSON to PATH (default stdout)",
    )
    args = ap.parse_args(argv)
    reports = [
        check_file(p, repair=args.repair)
        for p in _journal_files(args.paths)
    ]
    # exit contract (gray-failure containment PR split the old catch-all
    # 1 into two distinguishable failures):
    #   0 — clean, or repair restored everything repairable
    #   1 — corruption / quarantined records found (verify mode)
    #   2 — store unreadable (I/O error), or recovery semantics damaged
    #       beyond repair (a compacted head checkpoint is gone)
    unreadable = any(r.get("error") for r in reports)
    unrepairable = any(r.get("unrepairable") for r in reports)
    if args.repair:
        code = 2 if (unreadable or unrepairable) else 0
    elif unreadable:
        code = 2
    else:
        code = 0 if all(r.get("ok", False) for r in reports) else 1
    bad = code != 0
    doc = {"files": reports, "ok": not bad, "exit_code": code}
    if args.json is not None:
        text = json.dumps(doc, indent=1, sort_keys=True)
        if args.json == "-":
            print(text)
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(text)
    else:
        for r in reports:
            if r.get("error"):
                print(f"{r['path']}: ERROR {r['error']}")
                continue
            state = (
                "unrepairable"
                if r["unrepairable"]
                else (
                    "repaired"
                    if r["repaired"]
                    else ("ok" if r["ok"] else "corrupt")
                )
            )
            print(
                f"{r['path']}: {state} — records={r['records']} "
                f"kept={r['kept']} corrupt={r['corrupt']} "
                f"seq_gaps={r['seq_gaps']} dup_seq={r['dup_seq']} "
                f"torn_tail={r['torn_tail']} "
                f"ckpt_digest_failures={r['checkpoint_digest_failures']}"
            )
        print("OK" if not bad else "CORRUPTION FOUND")
    return code


if __name__ == "__main__":
    sys.exit(main())
