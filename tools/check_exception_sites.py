#!/usr/bin/env python3
"""Thin shim: the exception-accounting lint now lives in the koordlint
framework (``tools/koordlint/passes/exception_sites.py``, pass
``exception-sites``). This entry point keeps existing invocations and
imports working with bit-identical verdicts:

    python tools/check_exception_sites.py [paths...]
    python -m tools.koordlint --select exception-sites
"""

from __future__ import annotations

import sys
from pathlib import Path

if __package__ in (None, ""):  # script invocation
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.koordlint.passes.exception_sites import (  # noqa: E402,F401
    EXEMPT_FILES,
    REPORTING_HELPERS,
    check_file,
    check_paths,
    main,
)

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
