#!/usr/bin/env python3
"""Repo lint: every broad ``except Exception`` must be *accounted*.

The robustness PR established the invariant that no exception is
swallowed silently: every degrade-don't-crash ``except Exception`` site
routes through ``obs.errors.report_exception`` (directly or via a
reporting helper like ``_note_solver_failure``) or re-raises. Until now
that invariant was enforced only by review; this lint makes it a tier-1
test (``tests/test_exception_sites_lint.py``) and a standalone command:

    python tools/check_exception_sites.py [paths...]

A handler passes when its body (including nested statements) contains
at least one of:

* a call whose name is ``report_exception``;
* a call to a known reporting helper (``REPORTING_HELPERS``) that
  itself calls ``report_exception``;
* a ``raise`` statement (the exception is not swallowed).

Narrow handlers (``except ValueError``, ``except (OSError, KeyError)``)
are out of scope — the lint targets the catch-everything form that can
hide real failures.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Iterable, List, Tuple

#: helpers whose bodies call report_exception — a handler calling one of
#: these is accounted (keep in sync when adding new reporting funnels)
REPORTING_HELPERS = frozenset({"_note_solver_failure"})

#: the module that DEFINES the discipline (scanning it would be circular)
EXEMPT_FILES = frozenset({"obs/errors.py"})

Violation = Tuple[str, int, str]


def _names_in_type(node) -> Iterable[str]:
    """Exception-class names mentioned in an ``except`` clause type."""
    if node is None:
        # bare ``except:`` — broader than ``except Exception``
        yield "Exception"
        return
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr
        elif isinstance(n, ast.Tuple):
            stack.extend(n.elts)


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _handler_accounted(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name == "report_exception" or name in REPORTING_HELPERS:
                    return True
    return False


def check_file(path: Path, root: Path) -> List[Violation]:
    rel = path.relative_to(root).as_posix()
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except SyntaxError as exc:  # a broken file is its own violation
        return [(rel, exc.lineno or 0, f"unparsable: {exc.msg}")]
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if "Exception" not in set(_names_in_type(node.type)):
            continue
        if not _handler_accounted(node):
            out.append(
                (
                    rel,
                    node.lineno,
                    "broad `except Exception` neither calls "
                    "report_exception (or a reporting helper) nor "
                    "re-raises",
                )
            )
    return out


def check_paths(paths: Iterable[Path], root: Path) -> List[Violation]:
    violations: List[Violation] = []
    for p in paths:
        for f in sorted(p.rglob("*.py")) if p.is_dir() else [p]:
            if f.relative_to(root).as_posix() in (
                f"koordinator_tpu/{e}" for e in EXEMPT_FILES
            ):
                continue
            violations.extend(check_file(f, root))
    return violations


def main(argv: List[str]) -> int:
    root = Path(__file__).resolve().parent.parent
    targets = (
        [Path(a).resolve() for a in argv]
        if argv
        else [root / "koordinator_tpu"]
    )
    violations = check_paths(targets, root)
    for rel, line, msg in violations:
        print(f"{rel}:{line}: {msg}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unaccounted `except Exception` site(s)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
