#!/usr/bin/env python
"""Candidate-shortlist A/B driver: the ``BENCH_SHORTLIST_rNN_cpu.json``
artifact for the node-axis pruning PR.

Runs the constrained scenarios — the three existing scheduler-level ones
(``numa_binpack_2socket``, ``device_gang_8gpu``, ``quota_tree_3level``,
sized down for a CPU round) plus the two fleet-scale 20k-node solver
scenarios from ``bench_suite`` — each as a same-backend A/B between the
full-axis solve (``shortlist_k=0``) and the shortlisted solve (the
default ``shortlist_k=64``). Per the standing perf-claim rules every
scheduler scenario entry embeds:

- decision identity: the (pod, node) binding list of the two arms must
  match exactly (the A/B is meaningless otherwise),
- retrace evidence: a solver-observatory pass with the compile ledger
  marked steady after warmup — ``steady_retraces`` must be 0,
- the stage breakdown (``solve_breakdown_ms``) with the ``shortlist``
  stage visible (the ``shortlist_plan`` probe's watch window).

The artifact is a plain scenario list, so ``tools/bench_regress.py
--scenario NAME`` gates it directly; the headline ``pods_per_sec`` on
every entry is the SHORTLIST arm (the default config — a future round's
regression gate judges what users run). A trailing
``shortlist_ab_verdicts`` pseudo-entry carries the bench_regress verdict
table of shortlist-vs-full (full axis as baseline), and the driver exits
nonzero if any scenario's shortlist arm REGRESSES against its own
full-axis arm — "no slower at small N" is enforced, not eyeballed.

This is a CPU-round artifact: the committed accelerator
``BENCH_SUITE.json`` is never touched.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_shortlist.py \
        [--out BENCH_SHORTLIST_r12_cpu.json] [--passes 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

# runnable both as ``python tools/bench_shortlist.py`` and as
# ``python -m tools.bench_shortlist``: bench_suite lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_K = 64


def _drain(sched, pods):
    """One whole-backlog scheduling call; returns the binding list."""
    out = sched.schedule(list(pods))
    return [(p.meta.name, node) for p, node in out.bound]


def _measure_sched_arm(build, k, passes):
    """(median pods/s, passes, bindings) for one shortlist_k arm."""
    sched, pods = build(k)
    sched.extender.monitor.stop_background()
    bindings = _drain(sched, pods)  # warmup: compiles land here
    pps = []
    for _ in range(passes):
        sched, pods = build(k)
        sched.extender.monitor.stop_background()
        t0 = time.perf_counter()
        _drain(sched, pods)
        pps.append(round(len(pods) / (time.perf_counter() - t0), 1))
    return sorted(pps)[len(pps) // 2], pps, bindings


def _observatory_pass(build, k):
    """Instrumented extra pass (never the measured one): attach the
    solver observatory, drain once cold, mark the ledger steady, drain
    a fresh instance again — any trace after the mark is a retrace. The
    breakdown must show the ``shortlist`` stage (the plan probe)."""
    from koordinator_tpu.obs.devprof import DevProf

    dp = DevProf()
    try:
        for fresh in range(2):
            sched, pods = build(k)
            sched.extender.monitor.stop_background()
            sched.attach_devprof(dp)
            if fresh == 1:
                dp.capture(1 << 30)  # fence + record the steady drain
            _drain(sched, pods)
            if fresh == 0:
                dp.ledger.mark_steady()
        dp.capture(0)
        breakdown = dp.breakdown_ms()
        return {
            "steady_retraces": dp.ledger.steady_retraces(),
            "retrace_causes": dp.ledger.steady_causes(),
            "solve_breakdown_ms": breakdown,
            "shortlist_stage_visible": (
                "shortlist" in breakdown.get("stage_ms", {})
            ),
        }
    finally:
        dp.uninstall()


def _sched_scenario(name, make_build, passes):
    """Scheduler-level A/B: same builder, shortlist on (default K) vs
    off (shortlist_k=0), identical seeds → the binding lists must be
    identical."""
    print(f"--- {name}", file=sys.stderr)
    sl_pps, sl_passes, sl_bound = _measure_sched_arm(
        make_build, DEFAULT_K, passes
    )
    full_pps, full_passes, full_bound = _measure_sched_arm(
        make_build, 0, passes
    )
    if sl_bound != full_bound:
        raise SystemExit(
            f"{name}: shortlist arm diverged from full axis "
            f"({len(sl_bound)} vs {len(full_bound)} bindings)"
        )
    entry = {
        "scenario": name,
        "pods_per_sec": sl_pps,
        "passes": sl_passes,
        "placed": len(sl_bound),
        "shortlist_k": DEFAULT_K,
        "shortlist_ab": {
            "full_axis_pods_per_sec": full_pps,
            "full_axis_passes": full_passes,
            "speedup": round(sl_pps / full_pps, 2),
            "identical_placements": True,
        },
    }
    entry.update(_observatory_pass(make_build, DEFAULT_K))
    return entry


def _scenarios(passes):
    import bench_suite

    def numa(k):
        return bench_suite._build_numa(
            n_nodes=2000, n_pods=8192, batch_bucket=2048, shortlist_k=k
        )

    def gang(k):
        return bench_suite._build_device_gang(
            n_nodes=2000, n_gangs=2048, batch_bucket=1024, shortlist_k=k
        )

    def quota(k):
        return bench_suite._build_quota(
            n_nodes=2000, n_pods=8192, batch_bucket=2048, shortlist_k=k
        )

    entries = [
        _sched_scenario("numa_binpack_2socket", numa, passes),
        _sched_scenario("device_gang_8gpu", gang, passes),
        _sched_scenario("quota_tree_3level", quota, passes),
    ]
    # fleet-scale solver scenarios: their bench_suite entries already
    # embed the same-shape shortlist_ab (identical placements pinned by
    # _solver_ab itself)
    for fn in (bench_suite.bench_numa_20k, bench_suite.bench_device_gang_20k):
        print(f"--- {fn.__name__}", file=sys.stderr)
        entries.append(fn())
    return entries


def _verdicts(entries):
    """bench_regress verdict table, full-axis arm as the baseline."""
    from tools.bench_regress import compare

    baseline, current = {}, {}
    for e in entries:
        ab = e.get("shortlist_ab")
        if not ab:
            continue
        baseline[e["scenario"]] = {
            "scenario": e["scenario"],
            "pods_per_sec": ab["full_axis_pods_per_sec"],
            "passes": ab["full_axis_passes"],
        }
        current[e["scenario"]] = {
            "scenario": e["scenario"],
            "pods_per_sec": e["pods_per_sec"],
            "passes": e["passes"],
        }
    return compare(baseline, current)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--out", default="BENCH_SHORTLIST_r12_cpu.json")
    ap.add_argument("--passes", type=int, default=2)
    args = ap.parse_args(argv)

    entries = _scenarios(args.passes)
    rows = _verdicts(entries)
    entries.append(
        {
            "scenario": "shortlist_ab_verdicts",
            "note": (
                "shortlist arm judged against the SAME run's full-axis "
                "arm (baseline = full axis); REGRESSION here means the "
                "pruned solve was slower than not pruning"
            ),
            "rows": rows,
        }
    )
    with open(args.out, "w") as f:
        json.dump(entries, f, indent=1, sort_keys=True)
        f.write("\n")
    from tools.bench_regress import render_table

    print(render_table(rows))
    slower = [r for r in rows if r["verdict"] == "REGRESSION"]
    if slower:
        print(
            "shortlist arm slower than full axis on: "
            + ", ".join(r["scenario"] for r in slower),
            file=sys.stderr,
        )
        return 1
    print(f"wrote {args.out} ({len(entries)} entries)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
