#!/usr/bin/env python
"""Bench regression gate: compare two committed BENCH artifacts.

Five rounds of BENCH_SUITE.json show scenario numbers drifting between
PRs with nothing telling a regression from tunnel/host noise. This tool
compares a fresh artifact against the committed baseline PER SCENARIO
with noise-aware thresholds and emits a verdict table — the gate a perf
PR cites alongside its stage tables.

It compares **committed JSON only** — it never runs a bench itself, so
it is safe inside tier-1 (the self-test feeds it synthetic artifacts;
real invocations compare e.g. ``BENCH_SUITE.json`` against a fresh run's
output, or two historical rounds).

Noise model: every throughput scenario records its individual ``passes``.
The relative half-spread of a scenario's passes — ``(max-min)/(2·median)``
— is its measured noise band; the comparison band is
``max(--threshold, --noise-mult × pooled noise)`` pooled over both sides,
so a scenario whose own passes disagree by 20% cannot flag a 10% delta.

Verdicts: ``OK`` (inside the band), ``REGRESSION`` (below baseline by
more than the band; exit code 1), ``IMPROVED`` (above by more than the
band), ``NEW`` / ``MISSING`` (scenario present on one side only),
``NO_METRIC`` (entry carries no comparable number, e.g. the
latency_stream run tables).

Accepted artifact shapes: the BENCH_SUITE.json scenario list, bench.py's
single headline JSON line (``{"metric": ..., "value": ...}``), and the
driver's round files (``{"parsed": {...}}``).

Usage::

    python tools/bench_regress.py --baseline BENCH_SUITE.json \
        --current /tmp/bench_suite_fresh.json [--threshold 0.1] [--json out]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

#: metric extraction ladder per scenario entry: (value key, passes key,
#: higher-is-better). First hit wins.
_METRIC_LADDER: Tuple[Tuple[str, Optional[str], bool], ...] = (
    ("pods_per_sec", "passes", True),
    ("pipelined_pods_per_sec", "pipelined_passes", True),
    ("takeover_speedup", None, True),
    ("value", "passes", True),
)

#: default relative comparison band (10%): BENCH history shows same-PR
#: back-to-back CPU passes disagreeing by this much routinely (PR 2's
#: measurement notes record ±30-50% host noise on contended windows)
DEFAULT_THRESHOLD = 0.10

#: the verdict vocabulary — the --json artifact's contract with CI.
#: Every verdict the comparison emits MUST come from this set and every
#: member must be reachable (enforced by the koordlint ``bench-verdicts``
#: pass against this module's AST).
VERDICTS = (
    "OK", "REGRESSION", "IMPROVED", "NEW", "MISSING", "NO_METRIC",
)


def extract_metric(entry: dict) -> Optional[dict]:
    """Pull the comparable number out of one scenario entry, or None."""
    for key, passes_key, higher in _METRIC_LADDER:
        value = entry.get(key)
        if isinstance(value, (int, float)):
            passes = entry.get(passes_key) if passes_key else None
            if not (
                isinstance(passes, (list, tuple))
                and all(isinstance(p, (int, float)) for p in passes)
            ):
                passes = None
            return {
                "metric": key,
                "value": float(value),
                "passes": [float(p) for p in passes] if passes else None,
                "higher_better": higher,
            }
    return None


def _expand_curve(scenario: str, entry: dict, out: Dict[str, dict]) -> None:
    """Multichip artifact family: an entry carrying a ``curve`` list of
    per-device-count arms (``{"devices": S, "pods_per_sec": ...,
    "passes": [...]}``, the MULTICHIP_rNN.json shape) contributes one
    pseudo-scenario per arm — ``loadaware_multichip[S=8]`` — so each
    device count gets its OWN noise band and verdict row. The parent
    row stays (its metric is the widest arm's, the headline number)."""
    curve = entry.get("curve")
    if not isinstance(curve, list):
        return
    for arm in curve:
        if isinstance(arm, dict) and "devices" in arm:
            out[f"{scenario}[S={arm['devices']}]"] = dict(arm)


def load_artifact(doc) -> Dict[str, dict]:
    """Normalize any accepted artifact shape to scenario -> entry."""
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"]
    if isinstance(doc, dict) and "metric" in doc:
        return {str(doc["metric"]): dict(doc)}
    if isinstance(doc, dict) and "scenario" in doc:
        out = {str(doc["scenario"]): dict(doc)}
        _expand_curve(str(doc["scenario"]), doc, out)
        return out
    if isinstance(doc, list):
        out = {}
        for entry in doc:
            if isinstance(entry, dict) and "scenario" in entry:
                out[str(entry["scenario"])] = dict(entry)
                _expand_curve(str(entry["scenario"]), entry, out)
        return out
    raise ValueError(
        "unrecognized bench artifact shape (want a BENCH_SUITE scenario "
        "list, a bench.py headline object, or a driver round file)"
    )


def _rel_noise(passes: Optional[Sequence[float]]) -> float:
    """Relative half-spread of a scenario's passes (0 when unknown)."""
    if not passes or len(passes) < 2:
        return 0.0
    med = sorted(passes)[len(passes) // 2]
    if med <= 0:
        return 0.0
    return (max(passes) - min(passes)) / (2.0 * med)


def compare(
    baseline: Dict[str, dict],
    current: Dict[str, dict],
    threshold: float = DEFAULT_THRESHOLD,
    noise_mult: float = 1.0,
) -> List[dict]:
    """Per-scenario verdict rows, one per scenario on either side."""
    rows: List[dict] = []
    for scenario in sorted(set(baseline) | set(current)):
        b_entry = baseline.get(scenario)
        c_entry = current.get(scenario)
        if b_entry is None or c_entry is None:
            rows.append(
                {
                    "scenario": scenario,
                    "verdict": "NEW" if b_entry is None else "MISSING",
                    "baseline": None,
                    "current": None,
                    "delta_pct": None,
                    "band_pct": None,
                    "metric": None,
                }
            )
            continue
        b = extract_metric(b_entry)
        c = extract_metric(c_entry)
        if b is None or c is None or b["value"] <= 0:
            rows.append(
                {
                    "scenario": scenario,
                    "verdict": "NO_METRIC",
                    "baseline": b["value"] if b else None,
                    "current": c["value"] if c else None,
                    "delta_pct": None,
                    "band_pct": None,
                    "metric": (b or c or {}).get("metric"),
                }
            )
            continue
        noise = max(_rel_noise(b["passes"]), _rel_noise(c["passes"]))
        band = max(float(threshold), float(noise_mult) * noise)
        delta = c["value"] / b["value"] - 1.0
        if not b["higher_better"]:
            delta = -delta
        if delta < -band:
            verdict = "REGRESSION"
        elif delta > band:
            verdict = "IMPROVED"
        else:
            verdict = "OK"
        rows.append(
            {
                "scenario": scenario,
                "verdict": verdict,
                "baseline": b["value"],
                "current": c["value"],
                "delta_pct": round(delta * 100.0, 2),
                "band_pct": round(band * 100.0, 2),
                "metric": b["metric"],
                "noise_pct": round(noise * 100.0, 2),
            }
        )
    return rows


def render_table(rows: Sequence[dict]) -> str:
    head = (
        f"{'scenario':<28} {'metric':<22} {'baseline':>12} "
        f"{'current':>12} {'delta%':>8} {'band%':>7}  verdict"
    )
    lines = [head, "-" * len(head)]
    for r in rows:
        fmt = lambda v, w: (f"{v:>{w}.1f}" if isinstance(v, float) else f"{'-':>{w}}")  # noqa: E731
        lines.append(
            f"{r['scenario']:<28} {str(r['metric'] or '-'):<22} "
            f"{fmt(r['baseline'], 12)} {fmt(r['current'], 12)} "
            f"{fmt(r['delta_pct'], 8)} {fmt(r['band_pct'], 7)}  "
            f"{r['verdict']}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument(
        "--baseline", required=True,
        help="committed baseline artifact (e.g. BENCH_SUITE.json)",
    )
    ap.add_argument(
        "--current", required=True,
        help="fresh artifact to judge against the baseline",
    )
    ap.add_argument(
        "--threshold", type=float, default=DEFAULT_THRESHOLD,
        help="relative band floor (default 0.10 = 10%%)",
    )
    ap.add_argument(
        "--noise-mult", type=float, default=1.0,
        help="multiplier on the measured pass-spread noise band",
    )
    ap.add_argument(
        "--scenario", action="append", default=None, metavar="NAME",
        help="gate only the named scenario(s) (repeatable). Lets a "
        "scenario whose committed artifact lives in a separate file "
        "(e.g. the CPU-round fleet_day entry) be compared without "
        "dragging in cross-backend rows from the accelerator artifact",
    )
    ap.add_argument(
        "--json", default="", metavar="PATH",
        help="also emit the verdict table as one machine-readable "
        "artifact ('-' = stdout instead of the text table): rows + "
        "per-verdict counts + exit code, so CI and the human table "
        "consume the same comparison",
    )
    args = ap.parse_args(argv)
    with open(args.baseline) as f:
        baseline = load_artifact(json.load(f))
    with open(args.current) as f:
        current = load_artifact(json.load(f))
    if args.scenario:
        wanted = set(args.scenario)
        missing = wanted - (set(baseline) | set(current))
        if missing:
            ap.error(
                f"--scenario {sorted(missing)} not present in either "
                "artifact"
            )
        baseline = {k: v for k, v in baseline.items() if k in wanted}
        current = {k: v for k, v in current.items() if k in wanted}
    rows = compare(
        baseline, current,
        threshold=args.threshold, noise_mult=args.noise_mult,
    )
    regressions = [r for r in rows if r["verdict"] == "REGRESSION"]
    if args.json:
        counts = {v: 0 for v in VERDICTS}
        for r in rows:
            counts[r["verdict"]] += 1
        artifact = {
            "baseline": args.baseline,
            "current": args.current,
            "threshold": args.threshold,
            "noise_mult": args.noise_mult,
            "rows": rows,
            "counts": counts,
            "regressions": [r["scenario"] for r in regressions],
            "exit": 1 if regressions else 0,
        }
        doc = json.dumps(artifact, indent=1, sort_keys=True)
        if args.json == "-":
            print(doc)
        else:
            with open(args.json, "w") as f:
                f.write(doc + "\n")
    if args.json != "-":
        print(render_table(rows))
    if regressions:
        print(
            f"\n{len(regressions)} regression(s): "
            + ", ".join(r["scenario"] for r in regressions),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
