#!/usr/bin/env python
"""Multi-chip sharded-solve bench driver (first-class multichip PR).

Measures the PRODUCTION mesh path — a ``BatchScheduler`` whose resident
NodeState is tp-sharded over a ``(dp, tp)`` mesh, refreshed by the
sharded dirty-row scatter — at S ∈ {1, 2, 4, 8} virtual CPU devices and
writes a ``MULTICHIP_rNN.json`` artifact embedding the
pods/s-vs-device-count curve. The committed accelerator
``BENCH_SUITE.json`` is never touched; multichip numbers live in their
own artifact family, like the dryrun records ``MULTICHIP_r01..r05``.

Each device-count arm runs in its OWN subprocess: XLA parses
``--xla_force_host_platform_device_count`` once per process, so the
parent exports ``JAX_PLATFORMS=cpu`` + the flag and spawns
``python -m tools.bench_multichip --arm S``. The arm prints one JSON
line; the parent collects the curve.

Evidence discipline (PR 8 standing rule): every arm embeds

- ``steady_retraces`` from a ``CompileLedger`` marked steady after the
  warmup drain — the same ledger ``/debug/compiles`` serves, so a perf
  claim cites a retrace-free steady state, not just wall clock;
- ``donation_checks``/``donation_misses`` from the device-memory
  census' donation-effectiveness check over the sharded scatter — the
  donated resident buffer must die across the resharding boundary
  (a miss means the in-place update silently became a copy).

Measurement note: virtual CPU devices share one host's cores, so the
curve measures PARTITIONING overhead and scaling shape, not real
multi-chip speedup — on a single shared-memory host the S>1 arms pay
XLA's collective/all-gather costs without independent silicon to
amortize them. The artifact is the harness + evidence baseline that a
real TPU slice re-run replaces number-for-number.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

DEVICE_COUNTS = (1, 2, 4, 8)
N_NODES = 2048
N_PODS = 4096
BATCH_BUCKET = 512
PASSES = 3


def _pin_cpu_devices(n_devices: int) -> None:
    """Pin the virtual-CPU-device backend BEFORE any jnp array exists
    (mirrors ``__graft_entry__.dryrun_multichip`` / tests/conftest.py:
    the environment may pin a TPU platform at interpreter startup)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        # newer jax: raises the count even after XLA_FLAGS was parsed
        jax.config.update("jax_num_cpu_devices", n_devices)
    except (AttributeError, RuntimeError):
        pass  # rely on XLA_FLAGS (must pre-date any backend init)


def _build(mesh):
    """Production-path scheduler over the mesh: uniform 32-core nodes,
    bench.py's pod request mix, mesh-resident sharded NodeState."""
    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    snap = ClusterSnapshot()
    for i in range(N_NODES):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i:04d}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                ),
            )
        )
    rng = np.random.default_rng(0)
    cpus = rng.choice([500, 1000, 2000, 4000], N_PODS, p=[0.4, 0.3, 0.2, 0.1])
    pods = [
        Pod(
            meta=ObjectMeta(name=f"p{i:05d}", namespace="bench"),
            spec=PodSpec(
                requests={
                    ext.RES_CPU: int(cpus[i]),
                    ext.RES_MEMORY: int(cpus[i]) * 2,
                },
                priority=9000 - (i % 7),
            ),
        )
        for i in range(N_PODS)
    ]
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=BATCH_BUCKET, mesh=mesh
    )
    sched.extender.monitor.stop_background()
    return sched, pods


def _drain(sched, pods) -> int:
    bound = 0
    for start in range(0, len(pods), BATCH_BUCKET):
        out = sched.schedule(pods[start : start + BATCH_BUCKET])
        bound += len(out.bound)
    return bound


def run_arm(n_devices: int) -> dict:
    """One device-count arm, in-process (the caller owns the platform
    env). Warmup drain carries the solver observatory (cold compiles +
    donation census); measured passes run plain so their wall clock is
    comparable, with the compile ledger still recording retraces."""
    _pin_cpu_devices(n_devices)
    import jax

    from koordinator_tpu.obs.devprof import DevProf
    from koordinator_tpu.parallel.sharded import make_mesh

    assert len(jax.devices()) >= n_devices, (
        f"backend exposes {len(jax.devices())} devices, need {n_devices}"
    )
    mesh = make_mesh(n_devices)
    dp = DevProf()
    sched, pods = _build(mesh)
    sched.attach_devprof(dp)
    warm_bound = _drain(sched, pods)
    donation_checks = dp.census.donation_checks
    donation_misses = dp.census.donation_misses
    dp.ledger.mark_steady()

    pass_pps = []
    bound = 0
    for _ in range(PASSES):
        sched, pods = _build(mesh)
        t0 = time.perf_counter()
        bound = _drain(sched, pods)
        pass_pps.append(round(len(pods) / (time.perf_counter() - t0), 1))
    steady_retraces = dp.ledger.steady_retraces()
    dp.uninstall()
    return {
        "devices": n_devices,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "pods_per_sec": sorted(pass_pps)[len(pass_pps) // 2],
        "passes": pass_pps,
        "placed": bound,
        "warmup_placed": warm_bound,
        "total": N_PODS,
        "n_nodes": N_NODES,
        "batch_bucket": BATCH_BUCKET,
        "steady_retraces": steady_retraces,
        "donation_checks": donation_checks,
        "donation_misses": donation_misses,
        "fallback_level": sched._fallback_level,
    }


def _next_rev() -> str:
    import re

    best = 0
    for name in os.listdir("."):
        m = re.fullmatch(r"MULTICHIP_r(\d+)\.json", name)
        if m:
            best = max(best, int(m.group(1)))
    return f"MULTICHIP_r{best + 1:02d}.json"


def run_curve(device_counts=DEVICE_COUNTS, out_path: str | None = None) -> dict:
    """Spawn one subprocess per device count, collect the curve, write
    the artifact. Returns the artifact entry (bench_regress-comparable:
    top-level ``pods_per_sec``/``passes`` are the widest arm's, the
    per-S arms ride in ``curve`` for per-device-count noise bands)."""
    curve = []
    for s in device_counts:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={s}"
        ).strip()
        proc = subprocess.run(
            [sys.executable, "-m", "tools.bench_multichip", "--arm", str(s)],
            env=env,
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            sys.stderr.write(proc.stdout[-2000:] + proc.stderr[-2000:])
            raise RuntimeError(f"arm S={s} failed rc={proc.returncode}")
        line = proc.stdout.strip().splitlines()[-1]
        arm = json.loads(line)
        print(json.dumps(arm))
        curve.append(arm)
    widest = curve[-1]
    entry = {
        "scenario": "loadaware_multichip",
        "pods_per_sec": widest["pods_per_sec"],
        "passes": widest["passes"],
        "placed": widest["placed"],
        "total": widest["total"],
        "n_devices": widest["devices"],
        "curve": curve,
        "steady_retraces": max(a["steady_retraces"] for a in curve),
        "donation_misses": sum(a["donation_misses"] for a in curve),
        "measurement_note": (
            "virtual CPU devices on one shared-memory host: every arm "
            "contends for the same cores, so the curve bounds "
            "PARTITIONING overhead (S>1 pays XLA collectives with no "
            "independent silicon) rather than demonstrating speedup; "
            "steady_retraces==0 and donation_misses==0 are the "
            "hardware-independent claims, the harness re-runs unchanged "
            "on a real slice"
        ),
    }
    if out_path is None:
        out_path = _next_rev()
    with open(out_path, "w") as f:
        json.dump(entry, f, indent=1)
    print(f"wrote {out_path}")
    return entry


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--arm",
        type=int,
        default=None,
        metavar="S",
        help="run ONE device-count arm in-process and print its JSON "
        "line (internal: the driver sets the platform env and spawns "
        "this per S)",
    )
    ap.add_argument(
        "--devices",
        default=",".join(str(s) for s in DEVICE_COUNTS),
        help="comma-separated device counts for the curve",
    )
    ap.add_argument(
        "--out", default=None, help="artifact path (default: next MULTICHIP_rNN.json)"
    )
    args = ap.parse_args(argv)
    if args.arm is not None:
        print(json.dumps(run_arm(args.arm)))
        return 0
    counts = tuple(int(s) for s in args.devices.split(",") if s)
    run_curve(counts, out_path=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
