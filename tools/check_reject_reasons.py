#!/usr/bin/env python3
"""Repo lint: the rejection taxonomy stays fully attributed
(distributed-observability PR satellite).

``RejectReason`` is the vocabulary the whole attribution story hangs on:
``rejections_total{stage,plugin,reason}``, ``/debug/rejections``, the
flight recorder's per-cycle summaries and the SLO layer's outcome
accounting all assume every member is REACHABLE — some code path
actually attributes it. The host-side mask replay
(``BatchScheduler._classify_solver_reject``) is the default attributor:
it re-runs the solver's mask stages for a rejected pod and names the
first stage that zeroed its row. A member it does not cover must be
attributed at a DEDICATED site (fencing, journal, deadline, commit
revalidation, …) and carry an explicit exemption HERE, with the site —
so adding an enum member without wiring its attribution fails tier-1
instead of silently minting a reason no record can ever carry.

The lint enforces, mirroring ``check_exception_sites`` /
``check_fence_boundaries``:

* every ``RejectReason`` member is either referenced inside
  ``_classify_solver_reject`` or listed in :data:`EXEMPT` with its
  dedicated attribution site;
* no member is BOTH (an exemption for a covered member is stale);
* every exempt member really IS referenced somewhere in
  ``koordinator_tpu/`` outside the enum definition (the dedicated site
  exists), and every exemption names a member that still exists.

Usage:  python tools/check_reject_reasons.py
Enforced as a tier-1 test by ``tests/test_reject_reasons_lint.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

#: members attributed at a dedicated site instead of the solver-reject
#: mask replay — member name -> where (and why) it is attributed
EXEMPT: Dict[str, str] = {
    "POD_TRANSFORMER_DROPPED": (
        "gate stage: frameworkext pod-transformer drop, before any "
        "solve runs"
    ),
    "GANG_NOT_READY": (
        "gate stage: coscheduling holds the gang back pre-batch"
    ),
    "RESERVATION_UNAVAILABLE": (
        "reserve stage: reservation fast-path match refusal"
    ),
    "NODE_CAPACITY_REVALIDATION": (
        "commit stage: Reserve's host-side capacity recheck of a "
        "solver winner"
    ),
    "NUMA_ALLOCATION_FAILED": (
        "commit stage: NUMAManager zone allocation refusal"
    ),
    "DEVICE_ALLOCATION_FAILED": (
        "commit stage: DeviceManager slot allocation refusal"
    ),
    "NODE_VANISHED": (
        "commit stage: winner's node deleted between solve and Reserve"
    ),
    "NUMERIC_INVALID": (
        "pre-solve quarantine: non-finite req/est rows never reach the "
        "mask stages the replay re-runs"
    ),
    "SOLVE_RESULT_STALLED": (
        "solve stage: bounded result fetch timed out — a feeder stall, "
        "not a mask verdict"
    ),
    "CYCLE_DEADLINE_EXCEEDED": (
        "cycle deadline: deferred chunks were never solved, so there "
        "is no mask outcome to replay"
    ),
    "COMMIT_ROLLED_BACK": (
        "commit stage: mid-commit crash unwound the chunk's Reserve "
        "journal"
    ),
    "STALE_LEADER_EPOCH": (
        "fence boundary: a deposed leader's commit refused by epoch "
        "check, independent of solver feasibility"
    ),
    "JOURNAL_WRITE_FAILED": (
        "journal boundary: intent/bind append refused — "
        "journal-before-mutate rejects the chunk un-mutated"
    ),
}

#: where the enum and the classifier live
ENUM_FILE = "koordinator_tpu/obs/rejections.py"
CLASSIFIER_FILE = "koordinator_tpu/scheduler/batch_solver.py"
CLASSIFIER_FUNC = "_classify_solver_reject"

Violation = Tuple[str, int, str]


def enum_members(root: Path) -> Dict[str, int]:
    """``RejectReason`` member name -> definition line."""
    tree = ast.parse((root / ENUM_FILE).read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "RejectReason":
            out: Dict[str, int] = {}
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                ):
                    out[stmt.targets[0].id] = stmt.lineno
            return out
    raise AssertionError(f"RejectReason class not found in {ENUM_FILE}")


def _reason_refs(tree: ast.AST) -> Set[str]:
    """Every ``RejectReason.X`` attribute access under ``tree``."""
    return {
        n.attr
        for n in ast.walk(tree)
        if isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == "RejectReason"
    }


def classifier_coverage(root: Path) -> Set[str]:
    """Members referenced inside ``_classify_solver_reject``."""
    tree = ast.parse(
        (root / CLASSIFIER_FILE).read_text(encoding="utf-8")
    )
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == CLASSIFIER_FUNC
        ):
            return _reason_refs(node)
    raise AssertionError(
        f"{CLASSIFIER_FUNC} not found in {CLASSIFIER_FILE}"
    )


def repo_refs(root: Path) -> Set[str]:
    """Members referenced anywhere in koordinator_tpu/ OUTSIDE the enum
    definition file (attribution sites)."""
    refs: Set[str] = set()
    for f in sorted((root / "koordinator_tpu").rglob("*.py")):
        if f == root / ENUM_FILE:
            continue
        try:
            refs |= _reason_refs(
                ast.parse(f.read_text(encoding="utf-8"))
            )
        except SyntaxError:
            pass  # unparsable files are another lint's problem
    return refs


def check(
    root: Path, exempt_table: Optional[Dict[str, str]] = None
) -> List[Violation]:
    """``exempt_table`` overrides :data:`EXEMPT` (the lint's own tests
    scan synthetic repos whose enums the real table does not match)."""
    exemptions = EXEMPT if exempt_table is None else exempt_table
    members = enum_members(root)
    covered = classifier_coverage(root)
    referenced = repo_refs(root)
    out: List[Violation] = []
    for name, line in sorted(members.items()):
        in_classifier = name in covered
        exempt = name in exemptions
        if not in_classifier and not exempt:
            out.append(
                (
                    ENUM_FILE,
                    line,
                    f"RejectReason.{name} has no "
                    f"{CLASSIFIER_FUNC} arm and no exemption in "
                    "tools/check_reject_reasons.py — wire its "
                    "attribution or document its dedicated site",
                )
            )
        elif in_classifier and exempt:
            out.append(
                (
                    ENUM_FILE,
                    line,
                    f"RejectReason.{name} is covered by "
                    f"{CLASSIFIER_FUNC} but still exempted — remove "
                    "the stale exemption",
                )
            )
        elif exempt and name not in referenced:
            out.append(
                (
                    ENUM_FILE,
                    line,
                    f"RejectReason.{name} is exempted as attributed "
                    "at a dedicated site, but nothing in "
                    "koordinator_tpu/ references it — the site is "
                    "gone (or never existed)",
                )
            )
    for name in sorted(set(exemptions) - set(members)):
        out.append(
            (
                "tools/check_reject_reasons.py",
                0,
                f"exemption for unknown member RejectReason.{name}",
            )
        )
    return out


def main(argv: List[str]) -> int:
    root = (
        Path(argv[0]).resolve()
        if argv
        else Path(__file__).resolve().parent.parent
    )
    violations = check(root)
    for rel, line, msg in violations:
        print(f"{rel}:{line}: {msg}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unattributed / stale reject reason"
            f"{'' if len(violations) == 1 else 's'}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
