#!/usr/bin/env python3
"""Thin shim: the rejection-taxonomy lint now lives in the koordlint
framework (``tools/koordlint/passes/reject_reasons.py``, pass
``reject-reasons``). This entry point keeps existing invocations and
imports working with bit-identical verdicts:

    python tools/check_reject_reasons.py [root]
    python -m tools.koordlint --select reject-reasons
"""

from __future__ import annotations

import sys
from pathlib import Path

if __package__ in (None, ""):  # script invocation
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.koordlint.passes.reject_reasons import (  # noqa: E402,F401
    CLASSIFIER_FILE,
    CLASSIFIER_FUNC,
    ENUM_FILE,
    EXEMPT,
    check,
    classifier_coverage,
    enum_members,
    main,
    repo_refs,
)

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
