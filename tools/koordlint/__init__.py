"""koordlint: the repo's unified static-analysis framework.

One pass registry, one shared AST walk, one suppression syntax, one CLI —
replacing the three disconnected single-file lints (``check_exception_sites``,
``check_fence_boundaries``, ``check_reject_reasons``, kept as thin shims)
and adding the passes the standing rules demanded but review had to carry:

* ``retrace-hazard`` — jitted solver entry points must carry the
  ``_devprof.tracing`` trace-time hook, host dispatches must sit under a
  signature-carrying ``dp.watch(...)``, watch signatures must be bucketed,
  and jitted bodies must not branch/``int()``/``.item()``/iterate on
  traced parameters;
* ``donation-safety`` — a ``donate_argnums`` argument is DEAD after the
  call: never re-read in the caller, never a stored ``self.`` attribute;
* ``guarded-by`` — ``# guarded-by: self._lock`` annotations on shared
  mutable attributes; annotated writes outside a ``with`` on the named
  lock are flagged;
* ``chaos-coverage`` — every named chaos point has a soak fault-schedule
  arm (or a validated dedicated-test exemption), and vice versa;
* ``bench-verdicts`` — ``tools/bench_regress.py``'s emitted verdict
  strings stay inside its declared ``VERDICTS`` vocabulary.

Suppression syntax (trailing comment on the finding's line)::

    expr  # koordlint: disable=donation-safety        -- one line, one pass
    # koordlint: disable-file=retrace-hazard          -- whole file
    def f(self):  # koordlint: holds=self._lock       -- caller holds lock

Unused suppressions are themselves findings: a ``disable`` that stopped
matching anything is stale and must be deleted.

Usage::

    python -m tools.koordlint [--select p1,p2] [--ignore p1] [--json [-|PATH]]

Exit 0 iff the tree carries zero unsuppressed findings. Enforced tier-1
by ``tests/test_koordlint.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: python package every pass walks by default
PACKAGE = "koordinator_tpu"

#: comment grammar: disable / disable-file take comma-separated pass
#: names; holds takes a lock expression (guarded-by's caller-holds form)
_SUPPRESS_RE = re.compile(
    r"#\s*koordlint:\s*(disable-file|disable|holds)\s*=\s*([\w.,\-]+)"
)
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([\w.]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One verdict. ``code`` is the stable finding ID cited in commit
    messages and consumed by CI (e.g. ``RH003``)."""

    pass_name: str
    code: str
    file: str     # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.message} [{self.pass_name}]"

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


class SourceFile:
    """One parsed module: text, lines, AST (lazily), suppressions.

    ``suppression_scope`` is False for files loaded as DATA for a pass
    (tests/ for chaos-exemption validation): their comment lines are
    not koordlint suppressions and never count as unused/unknown."""

    def __init__(self, path: Path, rel: str, suppression_scope: bool = True):
        self.path = path
        self.rel = rel
        self.suppression_scope = suppression_scope
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._parse_error: Optional[SyntaxError] = None
        self._parsed = False
        # line -> set of pass names disabled on that line
        self.disabled_lines: Dict[int, Set[str]] = {}
        #: pass names disabled for the whole file
        self.disabled_file: Set[str] = set()
        #: line -> lock expr the enclosing def's caller already holds
        self.holds: Dict[int, str] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            kind, value = m.group(1), m.group(2)
            if kind == "holds":
                self.holds[i] = value
            else:
                names = {v.strip() for v in value.split(",") if v.strip()}
                if kind == "disable-file":
                    self.disabled_file |= names
                else:
                    self.disabled_lines.setdefault(i, set()).update(names)

    @property
    def tree(self) -> Optional[ast.AST]:
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as exc:
                self._parse_error = exc
        return self._tree

    @property
    def parse_error(self) -> Optional[SyntaxError]:
        self.tree  # noqa: B018 — force the parse
        return self._parse_error

    def guarded_by_on_line(self, line: int) -> Optional[str]:
        if 1 <= line <= len(self.lines):
            m = _GUARDED_BY_RE.search(self.lines[line - 1])
            if m:
                return m.group(1)
        return None


def want_file(path: Path) -> bool:
    """The shared walk filter: generated protobuf modules and bytecode
    caches are OUT of every lint's scope (a ``*_pb2.py`` tripping an AST
    lint was the failure mode this centralizes away)."""
    if path.suffix != ".py":
        return False
    if path.name.endswith("_pb2.py") or path.name.endswith("_pb2_grpc.py"):
        return False
    return "__pycache__" not in path.parts


class RepoIndex:
    """Shared, parse-once view of the repo every pass runs against."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self._cache: Dict[str, Optional[SourceFile]] = {}
        self._package: Optional[List[SourceFile]] = None
        self._tests: Optional[List[SourceFile]] = None

    def _load(
        self, path: Path, suppression_scope: bool = True
    ) -> Optional[SourceFile]:
        try:
            rel = path.relative_to(self.root).as_posix()
        except ValueError:
            rel = path.as_posix()
        if rel not in self._cache:
            self._cache[rel] = (
                SourceFile(path, rel, suppression_scope)
                if path.is_file()
                else None
            )
        return self._cache[rel]

    def file(self, rel: str) -> Optional[SourceFile]:
        """Load one repo-relative file (None when absent)."""
        return self._load(self.root / rel)

    def walk(
        self, rel_dir: str, suppression_scope: bool = True
    ) -> List[SourceFile]:
        base = self.root / rel_dir
        if not base.is_dir():
            return []
        out = []
        for p in sorted(base.rglob("*.py")):
            if want_file(p):
                sf = self._load(p, suppression_scope)
                if sf is not None:
                    out.append(sf)
        return out

    @property
    def package_files(self) -> List[SourceFile]:
        if self._package is None:
            self._package = self.walk(PACKAGE)
        return self._package

    @property
    def test_files(self) -> List[SourceFile]:
        if self._tests is None:
            # data for passes (chaos-exemption validation), not lint
            # subjects: their comments are not suppressions
            self._tests = self.walk("tests", suppression_scope=False)
        return self._tests

    def scanned_files(self) -> List[SourceFile]:
        """Every file any pass touched (suppression accounting)."""
        return [sf for sf in self._cache.values() if sf is not None]


class Pass:
    """Base class: subclasses set ``name``/``code``/``description`` and
    implement ``run``. ``code`` prefixes every finding ID the pass mints."""

    name: str = ""
    code: str = ""
    description: str = ""
    #: the standalone CLI this pass absorbed, if any (docs only)
    legacy_cli: Optional[str] = None

    def run(self, index: RepoIndex) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(
        self, n: int, file: str, line: int, message: str
    ) -> Finding:
        return Finding(
            pass_name=self.name,
            code=f"{self.code}{n:03d}",
            file=file,
            line=line,
            message=message,
        )


#: name -> Pass instance, in registration order
REGISTRY: Dict[str, Pass] = {}


def register(cls):
    """Class decorator: instantiate and register a pass."""
    inst = cls()
    if not inst.name or not inst.code:
        raise ValueError(f"pass {cls.__name__} must set name and code")
    if inst.name in REGISTRY:
        raise ValueError(f"duplicate pass name {inst.name!r}")
    REGISTRY[inst.name] = inst
    return cls


def all_passes() -> Dict[str, Pass]:
    from . import passes  # noqa: F401 — registration side effect

    return REGISTRY


@dataclasses.dataclass
class Report:
    """One framework run: kept + suppressed findings, per-pass counts."""

    findings: List[Finding]
    suppressed: List[Finding]
    passes_run: List[str]

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        by_pass: Dict[str, int] = {}
        for f in self.findings:
            by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
        summary = (
            f"{len(self.findings)} finding(s)"
            + (
                " (" + ", ".join(
                    f"{k}={v}" for k, v in sorted(by_pass.items())
                ) + ")"
                if by_pass
                else ""
            )
            + f", {len(self.suppressed)} suppressed, "
            + f"{len(self.passes_run)} passes"
        )
        return "\n".join(lines + [summary])

    def to_json(self) -> str:
        return json.dumps(
            {
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": [f.to_dict() for f in self.suppressed],
                "passes": self.passes_run,
                "exit": self.exit_code,
            },
            indent=1,
            sort_keys=True,
        )


def select_passes(
    select: Optional[Iterable[str]] = None,
    ignore: Optional[Iterable[str]] = None,
) -> List[Pass]:
    table = all_passes()
    names = list(table)
    if select:
        unknown = sorted(set(select) - set(names))
        if unknown:
            raise KeyError(f"unknown pass(es): {', '.join(unknown)}")
        names = [n for n in names if n in set(select)]
    if ignore:
        unknown = sorted(set(ignore) - set(table))
        if unknown:
            raise KeyError(f"unknown pass(es): {', '.join(unknown)}")
        names = [n for n in names if n not in set(ignore)]
    return [table[n] for n in names]


def run(
    root: Path,
    select: Optional[Iterable[str]] = None,
    ignore: Optional[Iterable[str]] = None,
    paths: Optional[Sequence[str]] = None,
) -> Report:
    """Run the selected passes over ``root``; apply suppressions; flag
    unused suppressions. ``paths`` (repo-relative prefixes) optionally
    restrict which files' findings are REPORTED — passes still see the
    whole tree (cross-file passes need it)."""
    index = RepoIndex(root)
    chosen = select_passes(select, ignore)
    raw: List[Finding] = []
    for p in chosen:
        raw.extend(p.run(index))

    kept: List[Finding] = []
    suppressed: List[Finding] = []
    used: Set[Tuple[str, int, str]] = set()       # (file, line, pass)
    used_file: Set[Tuple[str, str]] = set()       # (file, pass)
    for f in raw:
        sf = index.file(f.file)
        if sf is not None:
            if f.pass_name in sf.disabled_file:
                used_file.add((f.file, f.pass_name))
                suppressed.append(f)
                continue
            if f.pass_name in sf.disabled_lines.get(f.line, set()):
                used.add((f.file, f.line, f.pass_name))
                suppressed.append(f)
                continue
        kept.append(f)

    # unused / unknown suppressions are findings in their own right
    chosen_names = {p.name for p in chosen}
    known = set(all_passes())
    full_run = chosen_names == known
    for sf in index.scanned_files():
        if not sf.suppression_scope:
            continue
        for line, names in sorted(sf.disabled_lines.items()):
            for name in sorted(names):
                if name not in known:
                    kept.append(Finding(
                        "suppressions", "SUP002", sf.rel, line,
                        f"suppression names unknown pass {name!r}",
                    ))
                elif (
                    name in chosen_names
                    and (sf.rel, line, name) not in used
                ):
                    kept.append(Finding(
                        "suppressions", "SUP001", sf.rel, line,
                        f"unused suppression: pass {name!r} reports "
                        "nothing on this line — delete the stale disable",
                    ))
        for name in sorted(sf.disabled_file):
            if name not in known:
                kept.append(Finding(
                    "suppressions", "SUP002", sf.rel, 1,
                    f"suppression names unknown pass {name!r}",
                ))
            elif (
                full_run
                and name in chosen_names
                and (sf.rel, name) not in used_file
            ):
                kept.append(Finding(
                    "suppressions", "SUP003", sf.rel, 1,
                    f"unused file-wide suppression for pass {name!r}",
                ))

    if paths:
        prefixes = tuple(p.rstrip("/") for p in paths)

        def _in_scope(f: Finding) -> bool:
            return any(
                f.file == pre or f.file.startswith(pre + "/")
                for pre in prefixes
            )

        kept = [f for f in kept if _in_scope(f)]
        suppressed = [f for f in suppressed if _in_scope(f)]

    kept.sort(key=lambda f: (f.file, f.line, f.code))
    return Report(
        findings=kept,
        suppressed=suppressed,
        passes_run=[p.name for p in chosen],
    )


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent.parent


# ---------------------------------------------------------------------------
# shared AST helpers (used by several passes)
# ---------------------------------------------------------------------------


def call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def dotted_path(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for pure Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Iterable[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — defensive
        return ""
