"""Pass ``exception-sites`` (EX): every broad ``except Exception`` is
*accounted* — routes through ``report_exception`` (directly or via a
reporting helper) or re-raises. Absorbed from the standalone
``tools/check_exception_sites.py`` (PR 3 invariant) with bit-identical
verdicts; the legacy module remains as a delegating shim.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Iterable, List, Tuple

from .. import Finding, Pass, RepoIndex, register, want_file

#: helpers whose bodies call report_exception — a handler calling one of
#: these is accounted (keep in sync when adding new reporting funnels).
#: _contain_poison (gray-failure containment PR) reports the contained
#: ladder failure via report_exception, or re-raises it when bisection
#: cannot pin a poison pod.
REPORTING_HELPERS = frozenset({"_note_solver_failure", "_contain_poison"})

#: the module that DEFINES the discipline (scanning it would be circular)
EXEMPT_FILES = frozenset({"obs/errors.py"})

Violation = Tuple[str, int, str]


def _names_in_type(node) -> Iterable[str]:
    """Exception-class names mentioned in an ``except`` clause type."""
    if node is None:
        # bare ``except:`` — broader than ``except Exception``
        yield "Exception"
        return
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr
        elif isinstance(n, ast.Tuple):
            stack.extend(n.elts)


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _handler_accounted(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = _call_name(node)
                if name == "report_exception" or name in REPORTING_HELPERS:
                    return True
    return False


def check_tree(tree: ast.AST, rel: str) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if "Exception" not in set(_names_in_type(node.type)):
            continue
        if not _handler_accounted(node):
            out.append(
                (
                    rel,
                    node.lineno,
                    "broad `except Exception` neither calls "
                    "report_exception (or a reporting helper) nor "
                    "re-raises",
                )
            )
    return out


def check_file(path: Path, root: Path) -> List[Violation]:
    rel = path.relative_to(root).as_posix()
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except SyntaxError as exc:  # a broken file is its own violation
        return [(rel, exc.lineno or 0, f"unparsable: {exc.msg}")]
    return check_tree(tree, rel)


def check_paths(paths: Iterable[Path], root: Path) -> List[Violation]:
    violations: List[Violation] = []
    for p in paths:
        for f in sorted(p.rglob("*.py")) if p.is_dir() else [p]:
            if f.relative_to(root).as_posix() in (
                f"koordinator_tpu/{e}" for e in EXEMPT_FILES
            ):
                continue
            if p.is_dir() and not want_file(f):
                continue
            violations.extend(check_file(f, root))
    return violations


def main(argv: List[str]) -> int:
    from .. import repo_root

    root = repo_root()
    targets = (
        [Path(a).resolve() for a in argv]
        if argv
        else [root / "koordinator_tpu"]
    )
    violations = check_paths(targets, root)
    for rel, line, msg in violations:
        print(f"{rel}:{line}: {msg}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unaccounted `except Exception` site(s)",
            file=sys.stderr,
        )
        return 1
    return 0


@register
class ExceptionSitesPass(Pass):
    name = "exception-sites"
    code = "EX"
    description = (
        "broad `except Exception` must report_exception or re-raise"
    )
    legacy_cli = "tools/check_exception_sites.py"

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        exempt = {f"koordinator_tpu/{e}" for e in EXEMPT_FILES}
        for sf in index.package_files:
            if sf.rel in exempt:
                continue
            if sf.tree is None:
                exc = sf.parse_error
                out.append(self.finding(
                    0, sf.rel, exc.lineno or 0, f"unparsable: {exc.msg}"
                ))
                continue
            for rel, line, msg in check_tree(sf.tree, sf.rel):
                out.append(self.finding(1, rel, line, msg))
        return out
