"""Pass ``gate-coverage`` (GT): every named speculation gate has a
bit-exact equivalence arm — the open-the-gates PR's standing rule,
mirroring what ``chaos-coverage`` does for fault points.

The gate vocabulary is extracted from the code itself: the dict literal
``BatchScheduler.speculation_gate_report`` returns (batch_solver.py)
plus every ``gates["<name>"] = ...`` assignment in
``CyclePipeline._gates_ok`` (pipeline.py). The equivalence arms are
declared in ``tests/test_pipelined_stream.py`` as a module-level
``GATE_ARMS = {"<gate>": "test_fn" | ("test_fn", ...)}`` mapping; each
named test must actually exist in that file. Gates that stay CLOSED
(serial, decision-identical by construction) carry a written exemption
here instead.

* **GT001** — a named gate with neither a ``GATE_ARMS`` arm nor an
  exemption: the gate can change behavior with no bit-exactness test.
* **GT002** — a ``GATE_ARMS`` entry naming a test function that does not
  exist in ``tests/test_pipelined_stream.py``.
* **GT003** — a ``GATE_ARMS`` entry for a gate name the code no longer
  declares (stale arm).
* **GT004** — an exemption for a gate that ALSO has an arm: stale,
  delete one.
* **GT005** — an exemption naming a gate the code no longer declares.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import Finding, Pass, RepoIndex, register

REPORT_FILE = "koordinator_tpu/scheduler/batch_solver.py"
GATES_FILE = "koordinator_tpu/scheduler/pipeline.py"
ARMS_FILE = "tests/test_pipelined_stream.py"

#: gate -> why no speculative equivalence arm is required.
#: Open-the-last-gates PR: ``reservations`` and ``preemption`` left
#: this table — they now carry (validated fast-path prediction /
#: discard-on-eager-fire) and their bit-exactness arms live in
#: tests/test_pipelined_stream.py::GATE_ARMS like every opened gate.
#: First-class-multichip PR: ``mesh`` left too — the sharded dispatch
#: now threads ChainCarry and carries a GATE_ARMS arm of its own.
EXEMPT: Dict[str, str] = {
    "transformers": (
        "stays CLOSED: host batch/cost transformers rewrite solver "
        "inputs per cycle — a speculative lowering cannot reproduce a "
        "rewrite that has not happened yet"
    ),
    "sampling": (
        "stays CLOSED: the rotating sampled node window changes the "
        "solve's node axis per cycle — the chain carries the full axis "
        "only"
    ),
    "brownout": (
        "policy gate, not a carry gap: the brownout ladder (L2+) "
        "forces the serial path while the fleet sheds load — "
        "decision-identical by construction, and the ladder's own "
        "tests cover the gate flipping with the level"
    ),
}


def _report_gates(index: RepoIndex) -> Dict[str, int]:
    """Gate names declared by speculation_gate_report's dict literal."""
    out: Dict[str, int] = {}
    sf = index.file(REPORT_FILE)
    if sf is None or sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "speculation_gate_report"
        ):
            for ret in ast.walk(node):
                if isinstance(ret, ast.Return) and isinstance(
                    ret.value, ast.Dict
                ):
                    for key in ret.value.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            out.setdefault(key.value, key.lineno)
    return out


def _pipeline_gates(index: RepoIndex) -> Dict[str, int]:
    """Gate names assigned via ``gates["<name>"] = ...`` in _gates_ok."""
    out: Dict[str, int] = {}
    sf = index.file(GATES_FILE)
    if sf is None or sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_gates_ok":
            for assign in ast.walk(node):
                if not isinstance(assign, ast.Assign):
                    continue
                for tgt in assign.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "gates"
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)
                    ):
                        out.setdefault(tgt.slice.value, tgt.lineno)
    return out


def _arms(index: RepoIndex) -> Tuple[Dict[str, Tuple[tuple, int]], Set[str]]:
    """(GATE_ARMS mapping gate -> (test names, line), defined test fns)."""
    arms: Dict[str, Tuple[tuple, int]] = {}
    fns: Set[str] = set()
    sf = index.file(ARMS_FILE)
    if sf is None or sf.tree is None:
        return arms, fns
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            fns.add(node.name)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Name)
                    and tgt.id == "GATE_ARMS"
                    and isinstance(node.value, ast.Dict)
                ):
                    for key, val in zip(
                        node.value.keys, node.value.values
                    ):
                        if not (
                            isinstance(key, ast.Constant)
                            and isinstance(key.value, str)
                        ):
                            continue
                        names: List[str] = []
                        vals = (
                            val.elts
                            if isinstance(val, (ast.Tuple, ast.List))
                            else [val]
                        )
                        for v in vals:
                            if isinstance(v, ast.Constant) and isinstance(
                                v.value, str
                            ):
                                names.append(v.value)
                        arms[key.value] = (tuple(names), key.lineno)
    return arms, fns


@register
class GateCoveragePass(Pass):
    name = "gate-coverage"
    code = "GT"
    description = (
        "every named speculation gate has a bit-exact equivalence arm "
        "in tests/test_pipelined_stream.py (or a written exemption)"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        gates: Dict[str, Tuple[str, int]] = {}
        for name, line in _report_gates(index).items():
            gates.setdefault(name, (REPORT_FILE, line))
        for name, line in _pipeline_gates(index).items():
            gates.setdefault(name, (GATES_FILE, line))
        arms, fns = _arms(index)

        for gate, (rel, line) in sorted(gates.items()):
            armed = gate in arms
            exempt = gate in EXEMPT
            if not armed and not exempt:
                out.append(self.finding(
                    1, rel, line,
                    f"speculation gate {gate!r} has no equivalence arm "
                    f"in {ARMS_FILE} (GATE_ARMS) and no exemption — an "
                    "opened gate must land with its bit-exactness test "
                    "(open-the-gates standing rule)",
                ))
            elif armed and exempt:
                out.append(self.finding(
                    4, ARMS_FILE, arms[gate][1],
                    f"gate {gate!r} is exempted as serial-only but "
                    "GATE_ARMS also arms it — delete the stale "
                    "exemption (or the arm)",
                ))
            if armed:
                for fn in arms[gate][0]:
                    if fn not in fns:
                        out.append(self.finding(
                            2, ARMS_FILE, arms[gate][1],
                            f"GATE_ARMS[{gate!r}] names {fn!r}, which "
                            f"does not exist in {ARMS_FILE} — the "
                            "promised equivalence arm is gone",
                        ))

        for gate, (_names, line) in sorted(arms.items()):
            if gate not in gates:
                out.append(self.finding(
                    3, ARMS_FILE, line,
                    f"GATE_ARMS entry {gate!r} matches no gate declared "
                    "by speculation_gate_report / _gates_ok — the arm "
                    "is stale",
                ))
        for gate in sorted(set(EXEMPT) - set(gates)):
            out.append(self.finding(
                5, "tools/koordlint/passes/gate_coverage.py", 0,
                f"exemption names gate {gate!r}, which no code declares",
            ))
        return out
