"""Pass modules. Importing this package registers every pass."""

from . import (  # noqa: F401 — registration side effects
    bench_verdicts,
    chaos_coverage,
    decision_ledger,
    donation_safety,
    exception_sites,
    fence_boundaries,
    gate_coverage,
    guarded_by,
    reject_reasons,
    retrace_hazard,
    shed_paths,
    staleness_snapshot,
    store_integrity,
)
