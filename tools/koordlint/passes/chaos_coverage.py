"""Pass ``chaos-coverage`` (CC): every named chaos point is exercised —
the PR 3 standing rule ("new failure domains add a named chaos point …
and extend the soak's fault schedule"), until now enforced by review.

Fire sites are ``<injector>.fire("domain.point")`` calls in the package
(one positional string argument; f-string points become ``*`` patterns,
e.g. ``channel.{name}.drop`` ⇒ ``channel.*.drop``). The soak fault
schedule is the set of ``arm("...")`` calls in
``koordinator_tpu/sim/longrun.py``.

* **CC001** — a fired point that appears in no soak fault schedule and
  carries no exemption: the failure domain exists but the composition
  soak never exercises it.
* **CC002** — a scheduled point no fire site can ever evaluate: the
  schedule entry is stale (the point was renamed or removed).
* **CC003** — an exemption for a point the soak ALSO arms: stale, delete
  it.
* **CC004** — an exemption naming a point with no fire site.
* **CC005** — an exempt point whose promised dedicated test never arms
  it: the exemption's site is gone (or never existed).

Exemptions name points whose effects cannot ride the deterministic soak
(they fire on background threads, racing the same-seed fault-trace
order, or belong to components the soak does not run) and are covered by
a DEDICATED fault test instead — validated against ``arm(...)`` calls in
``tests/``.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Set, Tuple

from .. import Finding, Pass, RepoIndex, register

#: the soak whose fault schedules define coverage
SCHEDULE_FILE = "koordinator_tpu/sim/longrun.py"

#: point -> (dedicated site, why it cannot ride the soak schedule)
EXEMPT: Dict[str, Tuple[str, str]] = {
    "solver.fetch.stall": (
        "tests/test_chaos.py",
        "fires on the result-fetch worker thread — arming it in the "
        "soak would race the same-seed fault-trace order",
    ),
    "informer.watch_closed": (
        "tests/test_chaos.py",
        "fires on informer threads; the soak severs watches "
        "deterministically via hub.disconnect() instead",
    ),
    "informer.relist.delay": (
        "tests/test_chaos.py",
        "fires on informer threads (same thread-order rule as "
        "informer.watch_closed)",
    ),
    "koordlet.collect_tick": (
        "tests/test_koordlet.py",
        "the scheduler soak runs no koordlet daemon",
    ),
    "koordlet.qos_tick": (
        "tests/test_koordlet.py",
        "the scheduler soak runs no koordlet daemon",
    ),
    "journal.compact_crash": (
        "tests/test_journal.py",
        "compaction is driven by the scheduler run loop, which the "
        "cycle-stepped soak does not spin",
    ),
}


def _fire_points(index: RepoIndex) -> Dict[str, Tuple[str, int]]:
    """point (or ``*`` pattern) -> first (file, line) firing it."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in index.package_files:
        tree = sf.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "fire"
                and len(node.args) == 1
                and not node.keywords
            ):
                continue
            arg = node.args[0]
            point = None
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                point = arg.value
            elif isinstance(arg, ast.JoinedStr):
                parts = []
                for v in arg.values:
                    if isinstance(v, ast.Constant):
                        parts.append(str(v.value))
                    else:
                        parts.append("*")
                point = "".join(parts)
            if point and "." in point:
                out.setdefault(point, (sf.rel, node.lineno))
    return out


def _scheduled_points(index: RepoIndex) -> Dict[str, int]:
    """soak-armed point -> first arm line."""
    sf = index.file(SCHEDULE_FILE)
    out: Dict[str, int] = {}
    if sf is None or sf.tree is None:
        return out
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "arm"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.setdefault(node.args[0].value, node.lineno)
    return out


def _test_armed_points(index: RepoIndex) -> Dict[str, Set[str]]:
    """armed point -> test files arming it (the exemption's citation is
    load-bearing: the point must be armed in the NAMED file)."""
    out: Dict[str, Set[str]] = {}
    for sf in index.test_files:
        tree = sf.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "arm"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.setdefault(node.args[0].value, set()).add(sf.rel)
    return out


def _covered(point: str, scheduled: Dict[str, int]) -> bool:
    if point in scheduled:
        return True
    if "*" in point:
        return any(fnmatch.fnmatch(s, point) for s in scheduled)
    return False


@register
class ChaosCoveragePass(Pass):
    name = "chaos-coverage"
    code = "CC"
    description = (
        "every chaos point rides a soak fault schedule (or a validated "
        "dedicated-test exemption), and vice versa"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        fires = _fire_points(index)
        scheduled = _scheduled_points(index)
        test_armed = _test_armed_points(index)

        for point, (rel, line) in sorted(fires.items()):
            exempt = point in EXEMPT
            covered = _covered(point, scheduled)
            if not covered and not exempt:
                out.append(self.finding(
                    1, rel, line,
                    f"chaos point {point!r} appears in no soak fault "
                    f"schedule ({SCHEDULE_FILE}) and carries no "
                    "exemption — extend the soak's schedule or document "
                    "its dedicated fault test (PR 3 standing rule)",
                ))
            elif covered and exempt:
                out.append(self.finding(
                    3, rel, line,
                    f"chaos point {point!r} is exempted as "
                    "soak-unschedulable but the soak arms it — delete "
                    "the stale exemption",
                ))
            elif exempt:
                site = EXEMPT[point][0]
                armed_in = set()
                for t, files in test_armed.items():
                    if (
                        fnmatch.fnmatch(t, point)
                        if "*" in point
                        else t == point
                    ):
                        armed_in |= files
                if site not in armed_in:
                    out.append(self.finding(
                        5, rel, line,
                        f"chaos point {point!r} is exempted as covered "
                        f"by a dedicated test ({site}), but that file "
                        "does not arm it — the promised site is gone "
                        "(or never existed)",
                    ))

        sched_sf = index.file(SCHEDULE_FILE)
        sched_rel = sched_sf.rel if sched_sf else SCHEDULE_FILE
        for point, line in sorted(scheduled.items()):
            if not any(
                point == f or ("*" in f and fnmatch.fnmatch(point, f))
                for f in fires
            ):
                out.append(self.finding(
                    2, sched_rel, line,
                    f"soak schedule arms {point!r} but no fire site "
                    "evaluates it — the schedule entry is stale",
                ))

        for point in sorted(set(EXEMPT) - set(fires)):
            if any("*" in f and fnmatch.fnmatch(point, f) for f in fires):
                continue
            out.append(self.finding(
                4, "tools/koordlint/passes/chaos_coverage.py", 0,
                f"exemption names chaos point {point!r} but no fire "
                "site evaluates it",
            ))
        return out
