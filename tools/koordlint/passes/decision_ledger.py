"""Pass ``decision-ledger`` (DL): every controller tick()/decide entry
point that mutates control state records its decision — full input
snapshot, action, post-decision state — through the
``obs.decisions.DecisionLedger``, or carries a written exemption. The
decision-observatory PR's standing rule, mirroring what ``shed-paths``
does for queue drops.

The vocabulary is bidirectional:

* ``CONTROLLER_SITES`` declares every control-state decision entry
  point. Each body must record: read a ``.decisions`` ledger attribute
  (the one-attribute-check disabled contract) or delegate to a
  ``._record(...)`` helper that does.
* ``EXEMPT`` declares tick-shaped methods that deliberately do NOT
  record — each carries the written reason (e.g. a protocol pump that
  makes no policy decision).

* **DL001** — a declared controller site whose body neither reads a
  decision ledger nor delegates to a recording helper: an invisible
  control decision.
* **DL002** — an UNDECLARED package method named ``tick``/``choose``
  that mutates instance state without recording: a new controller must
  join ``CONTROLLER_SITES`` (or ``EXEMPT``, with its reason) so review
  sees it.
* **DL003** — a stale table entry: the named file/function is gone.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .. import Finding, Pass, RepoIndex, register

Site = Tuple[str, str]  # (repo-relative file, dotted qualname)

#: every control-state decision entry point → why it is one. New
#: controllers JOIN this table (DL002 forces it).
CONTROLLER_SITES: Dict[Site, str] = {
    (
        "koordinator_tpu/scheduler/pipeline.py",
        "_DepthController.choose",
    ): "adaptive pipeline-depth choice from the discard-rate window",
    (
        "koordinator_tpu/runtime/overload.py",
        "BrownoutController.tick",
    ): "brownout-ladder move from the fleet-worst SLO burn",
    (
        "koordinator_tpu/runtime/overload.py",
        "AdmissionController.admit",
    ): "submit-time admission verdict from band occupancy + ladder",
    (
        "koordinator_tpu/runtime/overload.py",
        "CircuitBreaker.allow",
    ): "breaker admit/probe decision (delegates to _record)",
    (
        "koordinator_tpu/runtime/overload.py",
        "CircuitBreaker.record_failure",
    ): "breaker trip decision from the consecutive-failure count",
    (
        "koordinator_tpu/runtime/overload.py",
        "CircuitBreaker.record_success",
    ): "breaker close decision",
    (
        "koordinator_tpu/runtime/elastic.py",
        "TopologyController.tick",
    ): "split/merge choice from per-shard burn streaks",
}

#: tick-shaped methods that deliberately do NOT record → written reason
EXEMPT: Dict[Site, str] = {
    (
        "koordinator_tpu/runtime/ha.py",
        "LeaderCoordinator.tick",
    ): (
        "election protocol step: acquire/renew is lease mechanics, not "
        "a control-state policy decision over SLO evidence"
    ),
    (
        "koordinator_tpu/runtime/shards.py",
        "ShardedScheduler.tick",
    ): (
        "ownership pump: drives per-shard election ticks and stream "
        "pumps; the policy decisions live in the controllers it hosts"
    ),
    (
        "koordinator_tpu/koordlet/pleg.py",
        "Pleg.tick",
    ): (
        "event scanner: diffs container state into PLEG events, "
        "decides nothing (InotifyPleg inherits this tick)"
    ),
}

#: entry-point names the DL002 sweep considers controller-shaped
_ENTRY_NAMES = frozenset({"tick", "choose"})

#: call-attribute names that count as delegating to a recording helper
_DELEGATE_ATTRS = frozenset({"_record"})


def _qualnames(tree: ast.AST) -> Dict[str, ast.AST]:
    """Dotted qualname -> function node, for every (possibly nested)
    function/method in the module."""
    out: Dict[str, ast.AST] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out[q] = child
                visit(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _records_decision(fn: ast.AST) -> bool:
    """A read of a ``.decisions`` ledger attribute (the record sites all
    spell it ``dl = self.decisions`` / ``if dl is not None``) or a
    delegation to a ``._record(...)`` helper."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "decisions":
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _DELEGATE_ATTRS
        ):
            return True
    return False


def _mutates_self(fn: ast.AST) -> bool:
    """Any assignment/augmented-assignment to a ``self.*`` attribute —
    the 'mutates control state' half of the DL002 heuristic."""
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                return True
    return False


def _is_method(qualname: str) -> bool:
    return "." in qualname


@register
class DecisionLedgerPass(Pass):
    name = "decision-ledger"
    code = "DL"
    description = (
        "every controller tick()/decide entry point that mutates "
        "control state records inputs -> action -> state through the "
        "decision ledger (or carries a written exemption)"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        declared = set(CONTROLLER_SITES) | set(EXEMPT)
        funcs: Dict[Site, ast.AST] = {}
        for sf in index.package_files:
            if sf.tree is None:
                continue
            for q, fn in _qualnames(sf.tree).items():
                funcs[(sf.rel, q)] = fn

        # DL001: declared controller sites must actually record
        for site, why in sorted(CONTROLLER_SITES.items()):
            fn = funcs.get(site)
            if fn is None:
                out.append(self.finding(
                    3, site[0], 0,
                    f"decision-ledger table names {site[1]!r} in "
                    f"{site[0]} but it does not exist — delete the "
                    "stale entry",
                ))
                continue
            if not _records_decision(fn):
                out.append(self.finding(
                    1, site[0], fn.lineno,
                    f"{site[1]} is a declared controller decision site "
                    "but neither reads a .decisions ledger nor "
                    "delegates to a recording helper — a control "
                    "decision made here is invisible to the decision "
                    "observatory (decision-observatory standing rule)",
                ))

        # DL003 over the exemptions
        for site, why in sorted(EXEMPT.items()):
            if funcs.get(site) is None:
                out.append(self.finding(
                    3, site[0], 0,
                    f"decision-ledger exemption names {site[1]!r} in "
                    f"{site[0]} but it does not exist — delete the "
                    "stale exemption",
                ))

        # DL002: undeclared controller-shaped methods anywhere in the
        # package that mutate instance state without recording
        for site, fn in sorted(funcs.items()):
            if site in declared:
                continue
            name = site[1].rsplit(".", 1)[-1]
            if name not in _ENTRY_NAMES or not _is_method(site[1]):
                continue
            if _mutates_self(fn) and not _records_decision(fn):
                out.append(self.finding(
                    2, site[0], fn.lineno,
                    f"{site[1]} looks like a controller decision entry "
                    "point (tick/choose mutating instance state) but "
                    "records nothing on the decision ledger — declare "
                    "it in CONTROLLER_SITES (or EXEMPT, with a written "
                    "reason) so review sees every control decision",
                ))
        return out
