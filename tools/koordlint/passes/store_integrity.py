"""Pass ``store-integrity`` (SI): every durable record stream goes
through the checksummed codec — the state-integrity PR's standing rule.

A *journal store* is any package class exposing the store protocol
(``append`` + ``load`` + ``rewrite`` methods): ``MemoryJournalStore``,
``FileJournalStore``, and whatever a future PR adds (a kv-backed store,
an object-store journal). The rule: the store itself seals on write and
screens on load, so EVERY ``store.append``/``store.rewrite`` call site —
BindJournal, ClaimTable, the flight recorder, future writers — rides the
codec without per-site discipline.

* **SI001** — a store class whose ``append`` or ``rewrite`` never calls
  ``integrity.seal``/``seal_records`` (records reach disk unchecksummed).
* **SI002** — a store class whose ``load`` never calls
  ``integrity.screen_records`` (corruption silently truncates again).
* **SI003** — an ``EXEMPT`` entry naming a class that no longer exists
  (stale exemption).

Exemptions name store-protocol classes that are NOT durable record
streams (with the written reason the standing rule demands).
"""

from __future__ import annotations

import ast
from typing import Dict, List

from .. import Finding, Pass, RepoIndex, register

#: class name -> written reason it may bypass the codec
EXEMPT: Dict[str, str] = {}

_STORE_METHODS = {"append", "load", "rewrite"}


def _calls_any(fn: ast.AST, names: set) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            attr = (
                f.attr
                if isinstance(f, ast.Attribute)
                else (f.id if isinstance(f, ast.Name) else "")
            )
            if attr in names:
                return True
    return False


@register
class StoreIntegrityPass(Pass):
    name = "store-integrity"
    code = "SI"
    description = (
        "journal-store classes seal every append/rewrite with the "
        "shared CRC codec and screen every load (state-integrity PR "
        "standing rule)"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        seen_classes: set = set()
        for sf in index.package_files:
            tree = sf.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                methods = {
                    n.name: n
                    for n in node.body
                    if isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                }
                if not _STORE_METHODS <= set(methods):
                    continue
                seen_classes.add(node.name)
                if node.name in EXEMPT:
                    continue
                for writer in ("append", "rewrite"):
                    if not _calls_any(
                        methods[writer], {"seal", "seal_records"}
                    ):
                        out.append(self.finding(
                            1, sf.rel, methods[writer].lineno,
                            f"store class {node.name}.{writer} does not "
                            "seal its records with the shared CRC codec "
                            "(core.integrity.seal/seal_records) — every "
                            "durable record stream must be checksummed, "
                            "or carry a written EXEMPT entry",
                        ))
                if not _calls_any(methods["load"], {"screen_records"}):
                    out.append(self.finding(
                        2, sf.rel, methods["load"].lineno,
                        f"store class {node.name}.load does not screen "
                        "records (core.integrity.screen_records) — "
                        "corruption would silently truncate the stream "
                        "again (the bug the state-integrity PR removed)",
                    ))
        for name in sorted(set(EXEMPT) - seen_classes):
            out.append(self.finding(
                3, "tools/koordlint/passes/store_integrity.py", 0,
                f"EXEMPT names store class {name!r} but no package "
                "class with the store protocol has that name — delete "
                "the stale exemption",
            ))
        return out
