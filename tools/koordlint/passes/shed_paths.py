"""Pass ``shed-paths`` (SP): every site that terminally drops a queued
pod emits the ``shed`` lifecycle event and counts a named metric — the
overload-control PR's standing rule, mirroring what ``chaos-coverage``
does for fault points and ``reject-reasons`` for the taxonomy.

The vocabulary is bidirectional:

* ``SHED_SITES`` declares every function that may drop a queued pod
  terminally. Each must either be a CANONICAL shed (its body both emits
  a ``"shed"`` lifecycle event and increments a metric — today
  ``AdmissionController.shed``) or DELEGATE to one (a ``.shed(...)``
  call in its body).
* ``EXEMPT`` declares queue-drop sites that deliberately do NOT shed —
  each carries the written reason (e.g. a claim loser is scheduled by
  the winning shard, so the drop is not terminal).

* **SP001** — a declared shed site whose body neither shed-emits
  (event + metric) nor delegates to a shed API: a silent pod drop.
* **SP002** — an UNDECLARED function that emits a ``"shed"`` event or
  calls a ``.shed(...)`` API: a new drop site must join ``SHED_SITES``
  (or ``EXEMPT``, with its reason) so review sees it.
* **SP003** — a stale table entry: the named file/function is gone.
* **SP004** — an ``EXEMPT`` site that actually sheds: move it to
  ``SHED_SITES`` and delete the stale exemption.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import Finding, Pass, RepoIndex, register

Site = Tuple[str, str]  # (repo-relative file, dotted qualname)

#: every function allowed to terminally drop a queued pod → why it is a
#: shed site. New shed paths JOIN this table (SP002 forces it).
SHED_SITES: Dict[Site, str] = {
    (
        "koordinator_tpu/runtime/overload.py",
        "AdmissionController.shed",
    ): (
        "the canonical shed: terminal lifecycle event + "
        "overload_shed_total{band} + the resubmit ticket"
    ),
    (
        "koordinator_tpu/scheduler/stream.py",
        "StreamScheduler.submit",
    ): (
        "submit-time shed (band over budget at L4 / brownout sheds the "
        "band) — delegates to AdmissionController.shed"
    ),
    (
        "koordinator_tpu/scheduler/stream.py",
        "StreamScheduler._overload_sweep",
    ): (
        "deferred-parking-lot sweep (aged-out past the band limit, or "
        "the ladder reached its shed level) — delegates to "
        "AdmissionController.shed"
    ),
    (
        "koordinator_tpu/scheduler/stream.py",
        "StreamScheduler._shed_quarantined",
    ): (
        "poison-quarantine exit (gray-failure containment PR): a pod "
        "the quarantine ledger blames sheds terminally with reason "
        "POISON_QUARANTINED instead of burning retries on a "
        "deterministic rejection — delegates to AdmissionController."
        "shed; the ticket stays redeemable (changed spec fingerprint "
        "re-admits)"
    ),
}

#: queue-drop sites that deliberately do NOT shed → the written reason
EXEMPT: Dict[Site, str] = {
    (
        "koordinator_tpu/scheduler/stream.py",
        "StreamScheduler._next_batch",
    ): (
        "claim loser: the WINNING shard schedules the pod — the drop "
        "is not terminal, and the claim gate already stamped "
        "claim_lost on the timeline"
    ),
}

#: call-attribute names that count as delegating to a shed API
_DELEGATE_ATTRS = frozenset({"shed"})


def _qualnames(tree: ast.AST) -> Dict[str, ast.AST]:
    """Dotted qualname -> function node, for every (possibly nested)
    function/method in the module."""
    out: Dict[str, ast.AST] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out[q] = child
                visit(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _emits_shed_event(fn: ast.AST) -> bool:
    """A ``*.event(..., "shed", ...)`` call (positional or keyword) or a
    stage-helper call carrying the literal ``"shed"``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "event"
        ):
            continue
        values = list(node.args) + [kw.value for kw in node.keywords]
        for v in values:
            if isinstance(v, ast.Constant) and v.value == "shed":
                return True
    return False


def _increments_metric(fn: ast.AST) -> bool:
    """Any ``.inc(...)`` call — the named-metric half of the rule."""
    return any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "inc"
        for node in ast.walk(fn)
    )


def _delegates_shed(fn: ast.AST) -> bool:
    """A ``<expr>.shed(...)`` call in the body — delegation to a shed
    API (the canonical site satisfies the stronger emit+metric test
    first, so a recursive-looking match here changes nothing)."""
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _DELEGATE_ATTRS
        ):
            return True
    return False


@register
class ShedPathsPass(Pass):
    name = "shed-paths"
    code = "SP"
    description = (
        "every terminal queued-pod drop emits the shed lifecycle event "
        "and counts a named metric (or carries a written exemption)"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        declared = set(SHED_SITES) | set(EXEMPT)
        #: (file, qualname) -> function node, package-wide
        funcs: Dict[Site, ast.AST] = {}
        files_seen: Set[str] = set()
        for sf in index.package_files:
            if sf.tree is None:
                continue
            files_seen.add(sf.rel)
            for q, fn in _qualnames(sf.tree).items():
                funcs[(sf.rel, q)] = fn

        # SP001: declared shed sites must actually shed (or delegate)
        for site, why in sorted(SHED_SITES.items()):
            fn = funcs.get(site)
            if fn is None:
                out.append(self.finding(
                    3, site[0], 0,
                    f"shed-paths table names {site[1]!r} in {site[0]} "
                    "but it does not exist — delete the stale entry",
                ))
                continue
            canonical = _emits_shed_event(fn) and _increments_metric(fn)
            if not canonical and not _delegates_shed(fn):
                out.append(self.finding(
                    1, site[0], fn.lineno,
                    f"{site[1]} is a declared shed site but neither "
                    "emits the terminal shed lifecycle event with a "
                    "counted metric nor delegates to a shed API — a "
                    "queued pod dropped here vanishes untraced "
                    "(overload-control standing rule)",
                ))

        # SP004 / SP003 over the exemptions
        for site, why in sorted(EXEMPT.items()):
            fn = funcs.get(site)
            if fn is None:
                out.append(self.finding(
                    3, site[0], 0,
                    f"shed-paths exemption names {site[1]!r} in "
                    f"{site[0]} but it does not exist — delete the "
                    "stale exemption",
                ))
                continue
            if _emits_shed_event(fn) or _delegates_shed(fn):
                out.append(self.finding(
                    4, site[0], fn.lineno,
                    f"{site[1]} is exempted as a non-shedding drop "
                    "site but its body sheds — move it to SHED_SITES "
                    "and delete the stale exemption",
                ))

        # SP002: undeclared shedding functions anywhere in the package
        for site, fn in sorted(funcs.items()):
            if site in declared:
                continue
            if _emits_shed_event(fn) or _delegates_shed(fn):
                out.append(self.finding(
                    2, site[0], fn.lineno,
                    f"{site[1]} sheds (emits the shed event or calls a "
                    ".shed(...) API) but is not declared in the "
                    "shed-paths SHED_SITES table — declare it (or "
                    "exempt it with a written reason) so review sees "
                    "every drop path",
                ))
        return out
