"""Pass ``staleness-snapshot`` (SS): controllers that consult informer
freshness must take the verdict FROM their decision snapshot, not as an
ad-hoc live read — the gray-failure containment PR's standing rule.

Why: the staleness watchdog's verdict gates evidence-hungry actions
(preemption, descheduler eviction, topology split). If a controller
reads it live mid-decision, a verdict flip between the snapshot and the
act produces a decision the recorded inputs cannot explain — replay
(`tools/decision_replay.py`) would disagree with what the acting
controller did. Folding the verdict into the snapshot (or capturing it
ONCE at cycle start) keeps decide() pure and the ledger replayable.

The vocabulary is bidirectional, like ``shed-paths``:

* ``SNAPSHOT_SITES`` — the functions ALLOWED to call the freshness
  callable live, because they ARE the snapshot/capture point.
* ``EXEMPT`` — live reads deliberately outside a snapshot, each with
  the written reason.

* **SS001** — an undeclared live ``.freshness()`` / ``.staleness()``
  call: fold it into the controller's snapshot (or capture-once site),
  or exempt it with a written reason.
* **SS002** — a declared capture site that no longer reads freshness:
  the fold moved — update the table.
* **SS003** — a stale table entry: the named file/function is gone.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .. import Finding, Pass, RepoIndex, register

Site = Tuple[str, str]  # (repo-relative file, dotted qualname)

#: attribute names whose CALL is a live freshness read. The wiring
#: convention passes the watchdog's bound ``stale`` method as a
#: ``freshness=`` / ``staleness=`` ctor argument; calling that
#: attribute is the read this pass polices.
_FRESHNESS_ATTRS = frozenset({"freshness", "staleness"})

#: the sanctioned capture points: each folds the verdict into a pure
#: snapshot (or captures it once per cycle) that decide()/the gates
#: read — the ONLY places a live read is the correct thing.
SNAPSHOT_SITES: Dict[Site, str] = {
    (
        "koordinator_tpu/runtime/elastic.py",
        "TopologyController.snapshot",
    ): (
        "folds the verdict into the topology decision snapshot as "
        "inputs['stale']; decide() refuses split/merge FROM the "
        "snapshot, so replay sees the same refusal"
    ),
    (
        "koordinator_tpu/scheduler/batch_solver.py",
        "BatchScheduler._schedule_locked",
    ): (
        "captures the verdict ONCE per cycle into _cycle_stale at "
        "cycle init; both preemption gates read the captured value, "
        "never the live callable"
    ),
}

#: live reads deliberately outside a snapshot → the written reason
EXEMPT: Dict[Site, str] = {
    (
        "koordinator_tpu/descheduler/migration.py",
        "MigrationController.reconcile",
    ): (
        "the descheduler records no decision snapshot: the read gates "
        "the WHOLE reconcile pass at its first statement, before any "
        "evidence is consulted — there is no later act the verdict "
        "could diverge from (refused passes count refused_stale + "
        "stale_evidence_refusals_total)"
    ),
}


def _qualnames(tree: ast.AST) -> Dict[str, ast.AST]:
    """Dotted qualname -> function node, for every (possibly nested)
    function/method in the module."""
    out: Dict[str, ast.AST] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out[q] = child
                visit(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _own_nodes(fn: ast.AST):
    """Walk a function's OWN body — nested function/class definitions
    belong to their own qualname and are skipped (each is checked under
    its own table entry, so a read is attributed exactly once)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _freshness_call(fn: ast.AST):
    """The first live ``<expr>.freshness()`` / ``<expr>.staleness()``
    call in the function's own body, or None."""
    for node in _own_nodes(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _FRESHNESS_ATTRS
        ):
            return node
    return None


@register
class StalenessSnapshotPass(Pass):
    name = "staleness-snapshot"
    code = "SS"
    description = (
        "informer-freshness verdicts are read from decision snapshots "
        "(or one capture per cycle), never ad-hoc mid-decision"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        declared = set(SNAPSHOT_SITES) | set(EXEMPT)
        funcs: Dict[Site, ast.AST] = {}
        for sf in index.package_files:
            if sf.tree is None:
                continue
            for q, fn in _qualnames(sf.tree).items():
                funcs[(sf.rel, q)] = fn

        # SS002 / SS003 over the declared capture sites
        for site, why in sorted(SNAPSHOT_SITES.items()):
            fn = funcs.get(site)
            if fn is None:
                out.append(self.finding(
                    3, site[0], 0,
                    f"staleness-snapshot table names {site[1]!r} in "
                    f"{site[0]} but it does not exist — delete the "
                    "stale entry",
                ))
                continue
            if _freshness_call(fn) is None:
                out.append(self.finding(
                    2, site[0], fn.lineno,
                    f"{site[1]} is a declared freshness capture site "
                    "but no longer reads the freshness callable — the "
                    "fold moved; update the staleness-snapshot table",
                ))

        # SS003 over the exemptions
        for site, why in sorted(EXEMPT.items()):
            if site not in funcs:
                out.append(self.finding(
                    3, site[0], 0,
                    f"staleness-snapshot exemption names {site[1]!r} "
                    f"in {site[0]} but it does not exist — delete the "
                    "stale exemption",
                ))

        # SS001: undeclared live reads anywhere in the package
        for site, fn in sorted(funcs.items()):
            if site in declared:
                continue
            call = _freshness_call(fn)
            if call is not None:
                out.append(self.finding(
                    1, site[0], call.lineno,
                    f"{site[1]} reads informer freshness live "
                    "(.freshness()/.staleness() call) outside a "
                    "declared capture site — fold the verdict into the "
                    "controller's decision snapshot (or its once-per-"
                    "cycle capture) so replay sees the same refusal, "
                    "or exempt the site with a written reason",
                ))
        return out
