"""Pass ``fence-boundaries`` (FB): every bind-journal write boundary
(``append_intent``/``append_bind``/``append_abort``) evaluates an epoch
check in the SAME function (``_fence_stale`` or a ``.check(...)`` on
something named ``fence``). ``append_forget`` stays out of scope (the
standby-forget rule journals apiserver-authoritative deletions
fence-exempt by design); ``core/journal.py`` is exempt — it IS the
fencing authority. Absorbed from ``tools/check_fence_boundaries.py``
(PR 6 satellite) with bit-identical verdicts.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Iterable, List, Tuple

from .. import Finding, Pass, RepoIndex, register, want_file

#: journal write ops that MUST be epoch-checked in the enclosing function
GUARDED_APPENDS = frozenset(
    {"append_intent", "append_bind", "append_abort"}
)

#: calls that count as an epoch check
FENCE_CHECK_HELPERS = frozenset({"_fence_stale"})

#: files exempt from the scan (relative to koordinator_tpu/)
EXEMPT_FILES = frozenset({"core/journal.py"})

Violation = Tuple[str, int, str]


def _call_attr(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _is_fence_check(call: ast.Call) -> bool:
    name = _call_attr(call)
    if name in FENCE_CHECK_HELPERS:
        return True
    if name != "check":
        return False
    # ``<something>.check(...)`` counts only when the receiver path
    # mentions a fence (``self.fence.check``, ``fence.check``,
    # ``fabric.fences[s].check``) — a stray ``x.check()`` does not.
    node = call.func.value if isinstance(call.func, ast.Attribute) else None
    while node is not None:
        if isinstance(node, ast.Attribute):
            if "fence" in node.attr.lower():
                return True
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Name):
            return "fence" in node.id.lower()
        else:
            return False
    return False


def check_tree(tree: ast.AST, rel: str) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        appends: List[ast.Call] = []
        checked = False
        # scan this function's body EXCLUDING nested function defs —
        # a check inside a nested closure does not guard this frame's
        # appends (and vice versa); nested defs are walked on their own
        stack = list(node.body)
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.iter_child_nodes(stmt):
                stack.append(sub)
            if isinstance(stmt, ast.Call):
                if _call_attr(stmt) in GUARDED_APPENDS:
                    appends.append(stmt)
                elif _is_fence_check(stmt):
                    checked = True
        if appends and not checked:
            for call in appends:
                out.append(
                    (
                        rel,
                        call.lineno,
                        f"journal {_call_attr(call)} without an epoch "
                        "check in the enclosing function "
                        f"({node.name}) — fence before journal",
                    )
                )
    return out


def _rel(path: Path, root: Path) -> str:
    try:
        return path.relative_to(root).as_posix()
    except ValueError:  # target outside the repo (ad-hoc invocation)
        return path.as_posix()


def check_file(path: Path, root: Path) -> List[Violation]:
    rel = _rel(path, root)
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except SyntaxError as exc:
        return [(rel, exc.lineno or 0, f"unparsable: {exc.msg}")]
    return check_tree(tree, rel)


def check_paths(paths: Iterable[Path], root: Path) -> List[Violation]:
    violations: List[Violation] = []
    for p in paths:
        for f in sorted(p.rglob("*.py")) if p.is_dir() else [p]:
            if _rel(f, root) in (
                f"koordinator_tpu/{e}" for e in EXEMPT_FILES
            ):
                continue
            if p.is_dir() and not want_file(f):
                continue
            violations.extend(check_file(f, root))
    return violations


def main(argv: List[str]) -> int:
    from .. import repo_root

    root = repo_root()
    targets = (
        [Path(a).resolve() for a in argv]
        if argv
        else [root / "koordinator_tpu"]
    )
    violations = check_paths(targets, root)
    for rel, line, msg in violations:
        print(f"{rel}:{line}: {msg}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unfenced journal write boundar"
            f"{'y' if len(violations) == 1 else 'ies'}",
            file=sys.stderr,
        )
        return 1
    return 0


@register
class FenceBoundariesPass(Pass):
    name = "fence-boundaries"
    code = "FB"
    description = "journal appends need an epoch check in-function"
    legacy_cli = "tools/check_fence_boundaries.py"

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        exempt = {f"koordinator_tpu/{e}" for e in EXEMPT_FILES}
        for sf in index.package_files:
            if sf.rel in exempt:
                continue
            if sf.tree is None:
                exc = sf.parse_error
                out.append(self.finding(
                    0, sf.rel, exc.lineno or 0, f"unparsable: {exc.msg}"
                ))
                continue
            for rel, line, msg in check_tree(sf.tree, sf.rel):
                out.append(self.finding(1, rel, line, msg))
        return out
