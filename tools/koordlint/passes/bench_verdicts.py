"""Pass ``bench-verdicts`` (BV): ``tools/bench_regress.py`` declares its
verdict vocabulary (``VERDICTS``) and the ``--json`` artifact is what CI
consumes — an emitted verdict string outside the declared enum (or a
declared member nothing emits) silently breaks every machine consumer.

* **BV001** — a verdict string the module emits (``"verdict": "X"`` in a
  dict literal, or ``verdict = "X"``) that is not in ``VERDICTS``;
* **BV002** — a ``VERDICTS`` member the module never emits;
* **BV003** — the ``VERDICTS`` declaration is missing entirely.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .. import Finding, Pass, RepoIndex, register

BENCH_FILE = "tools/bench_regress.py"


def _declared(tree: ast.AST) -> Dict[str, int]:
    """VERDICTS member -> declaration line ({} when absent)."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "VERDICTS"
        ):
            members: Dict[str, int] = {}
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]  # frozenset({...}) / tuple([...])
            if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                for e in value.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, str
                    ):
                        members[e.value] = node.lineno
            return members
    return {}


def _emitted(tree: ast.AST) -> Dict[str, int]:
    """Verdict strings the module produces -> first line."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "verdict":
                    # the value may be conditional ("NEW" if ... else
                    # "MISSING") — every string constant in it is an
                    # emitted verdict
                    for sub in ast.walk(v):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, str
                        ):
                            out.setdefault(sub.value, sub.lineno)
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "verdict"
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out.setdefault(node.value.value, node.lineno)
    return out


@register
class BenchVerdictsPass(Pass):
    name = "bench-verdicts"
    code = "BV"
    description = (
        "bench_regress emits only its declared VERDICTS vocabulary"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        sf = index.file(BENCH_FILE)
        if sf is None or sf.tree is None:
            return [self.finding(
                3, BENCH_FILE, 0,
                "tools/bench_regress.py is missing or unparsable",
            )]
        declared = _declared(sf.tree)
        emitted = _emitted(sf.tree)
        out: List[Finding] = []
        if not declared:
            return [self.finding(
                3, sf.rel, 0,
                "no VERDICTS vocabulary declared — machine consumers "
                "of the --json artifact have nothing to validate "
                "against",
            )]
        for v, line in sorted(emitted.items()):
            if v not in declared:
                out.append(self.finding(
                    1, sf.rel, line,
                    f"emitted verdict {v!r} is not in the declared "
                    "VERDICTS vocabulary",
                ))
        for v, line in sorted(declared.items()):
            if v not in emitted:
                out.append(self.finding(
                    2, sf.rel, line,
                    f"VERDICTS declares {v!r} but nothing emits it — "
                    "stale vocabulary entry",
                ))
        return out
