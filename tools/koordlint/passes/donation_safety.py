"""Pass ``donation-safety`` (DS): dataflow check at every
``donate_argnums`` call site — the PR 2/4 standing rule "never donate a
buffer the caller re-reads", previously guarded only by whichever tests
happened to exercise the path.

After a donating dispatch the donated buffer is DEAD: XLA may have
written the output into its memory. The pass verifies, in the calling
function:

* **DS001** — the donated binding (name or dotted path) is never READ
  again after the call without an intervening rebind of the binding (or
  of its root object);
* **DS002** — the donated argument is not a directly-stored ``self.``
  attribute: an object field outlives the call, so anything else holding
  the object can re-read the donated buffer (pass a local handle and
  re-store the result instead, the ``_scatter_refresh`` discipline).

Scope: host call sites only (a donation inside an enclosing jit is
inlined and its donate_argnums ignored), linear statement order within
the calling function. Reads the checker cannot see (cross-function
aliases) remain the donation-effectiveness census's job at runtime.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import (
    Finding,
    Pass,
    RepoIndex,
    ancestors,
    dotted_path,
    parent_map,
    register,
)
from ..jitindex import (
    collect_jitted,
    resolve_call,
    resolve_targets,
    traced_context_nodes,
)


def _enclosing_function(node, parents):
    for a in ancestors(node, parents):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def _store_paths(target: ast.AST) -> List[str]:
    """Dotted paths (re)bound by an assignment target."""
    out: List[str] = []
    for node in ast.walk(target):
        if isinstance(node, (ast.Name, ast.Attribute)):
            p = dotted_path(node)
            if p is not None and isinstance(
                getattr(node, "ctx", None), ast.Store
            ):
                out.append(p)
    return out


def _reads_after(
    fn: ast.AST,
    path: str,
    call: ast.Call,
) -> Optional[int]:
    """Line of the first Load of ``path`` after the donating call (its
    END line — a multi-line call's own arguments are not "after") with
    no intervening rebind of ``path``/its root/a prefix. None if clean."""
    call_start = call.lineno
    call_end = getattr(call, "end_lineno", call.lineno) or call.lineno
    root = path.split(".", 1)[0]
    rebinds: List[int] = []
    loads: List[int] = []
    for node in ast.walk(fn):
        line = getattr(node, "lineno", None)
        if line is None:
            continue
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # the call's own statement may rebind (x = f(x)): stores on
            # the call's start line count as killing the binding
            if line < call_start:
                continue
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                for p in _store_paths(t):
                    if p == path or p == root or path.startswith(p + "."):
                        rebinds.append(line)
        elif (
            isinstance(node, (ast.Name, ast.Attribute))
            and isinstance(getattr(node, "ctx", None), ast.Load)
            and line > call_end
        ):
            p = dotted_path(node)
            if p == path:
                loads.append(line)
    for ll in sorted(loads):
        if not any(call_start <= rl <= ll for rl in rebinds):
            return ll
    return None


@register
class DonationSafetyPass(Pass):
    name = "donation-safety"
    code = "DS"
    description = (
        "donate_argnums buffers are dead after the call: no re-read, "
        "no stored-attribute donation"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        jitted = [j for j in collect_jitted(index) if j.donated]
        if not jitted:
            return out
        targets = resolve_targets(index, jitted)
        donors = {
            rel: {n: j for n, j in local.items() if j.donated}
            for rel, local in targets.items()
        }
        all_jitted = collect_jitted(index)
        for sf in index.package_files:
            local = donors.get(sf.rel) or {}
            tree = sf.tree
            if tree is None:
                continue
            scoped = [
                j for j in all_jitted
                if j.file == sf.rel and j.scope is not None and j.donated
            ]
            if not local and not scoped:
                continue
            parents = parent_map(tree)
            traced_ctx = traced_context_nodes(
                tree, [j for j in all_jitted if j.file == sf.rel]
            )
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                ):
                    continue
                anc = list(ancestors(node, parents))
                j = resolve_call(node, local, scoped, anc)
                if j is None:
                    continue
                if any(a in traced_ctx for a in anc):
                    continue  # nested under jit: donation is inlined away
                fn = _enclosing_function(node, parents)
                for i in j.donated:
                    if i >= len(node.args):
                        continue
                    arg = node.args[i]
                    path = dotted_path(arg)
                    if path is None:
                        continue  # fresh temporary (e.g. jnp.asarray(x))
                    if path.startswith("self."):
                        out.append(self.finding(
                            2, sf.rel, node.lineno,
                            f"`{path}` donated to `{node.func.id}` is a "
                            "stored attribute — anything holding the "
                            "object can re-read the dead buffer; donate "
                            "a local handle and re-store the result",
                        ))
                        continue
                    if fn is None:
                        continue
                    bad = _reads_after(fn, path, node)
                    if bad is not None:
                        out.append(self.finding(
                            1, sf.rel, bad,
                            f"`{path}` is read after being donated to "
                            f"`{node.func.id}` on line {node.lineno} — "
                            "the buffer is dead there (never donate a "
                            "buffer the caller re-reads)",
                        ))
        return out
