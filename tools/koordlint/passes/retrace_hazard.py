"""Pass ``retrace-hazard`` (RH): static enforcement of the PR 8 solver
observatory standing rule, which review previously carried by hand.

* **RH001** — a HOST-DISPATCHED jit-wrapped function without the
  ``_devprof.tracing`` trace-time hook: its (re)compiles are invisible
  to the CompileLedger, so a steady-state retrace burns a bench round
  before anyone notices. Jitted functions whose every call site sits
  inside another jitted body are sub-jaxprs of that entry point — a
  hook there would double-bill the outer trace, so none is required.
* **RH002** — Python-level branching / ``int()`` / ``float()`` /
  ``bool()`` / ``.item()`` / ``.tolist()`` / iteration on a TRACED
  parameter inside a jitted body: a concretization error at best, a
  silent per-value retrace at worst. ``x is None`` structure tests and
  static argnames are exempt (None prunes at trace time).
* **RH003** — a host-side dispatch of a jitted function outside a
  signature-carrying ``dp.watch("<fn>", ...)`` context: a retrace fired
  there cannot be attributed to the shape/flag delta that caused it.
* **RH004** — a ``.watch(...)`` signature kwarg computed with a raw
  ``len(...)``: the host signature mirror must carry the PADDED bucket
  (``x.shape[0]`` of the lowered array, or the bucket variable), or
  every batch-size wiggle reads as a distinct signature and the retrace
  cause table turns to noise.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .. import (
    Finding,
    Pass,
    RepoIndex,
    ancestors,
    call_name,
    parent_map,
    register,
)
from ..jitindex import (
    JittedFn,
    collect_jitted,
    resolve_call,
    resolve_targets,
    traced_context_nodes,
    traced_params,
)

#: host-forcing builtins on a traced value
_FORCING_CALLS = frozenset({"int", "float", "bool"})
#: host-forcing methods on a traced value
_FORCING_METHODS = frozenset({"item", "tolist"})


def _is_structure_test(node: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` (any operand shape) — a pytree
    STRUCTURE test, resolved at trace time, not a traced-value branch."""
    return isinstance(node, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
    )


def _traced_names_in(expr: ast.AST, traced: Set[str]) -> List[ast.Name]:
    """Traced-parameter Name loads in ``expr``, skipping structure
    tests and ``.shape``/``.dtype``/``.ndim`` metadata reads (static
    under jit)."""
    hits: List[ast.Name] = []
    skip: Set[ast.AST] = set()
    for node in ast.walk(expr):
        if node in skip:
            continue
        if _is_structure_test(node):
            skip.update(ast.walk(node))
            continue
        if isinstance(node, ast.Attribute) and node.attr in (
            "shape", "dtype", "ndim", "size",
        ):
            skip.update(ast.walk(node.value))
            continue
        if isinstance(node, ast.Name) and node.id in traced:
            hits.append(node)
    return hits


def _hazards_in_body(p: Pass, fn: JittedFn) -> List[Finding]:
    traced = traced_params(fn)
    out: List[Finding] = []
    for node in ast.walk(fn.node):
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            for hit in _traced_names_in(node.test, traced):
                out.append(p.finding(
                    2, fn.file, node.lineno,
                    f"Python-level branch on traced parameter "
                    f"{hit.id!r} inside jitted `{fn.name}` — "
                    "concretization/retrace hazard (use jnp.where / "
                    "lax.cond, or make it a static argname)",
                ))
        elif isinstance(node, ast.For):
            it = node.iter
            root = it
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in traced:
                out.append(p.finding(
                    2, fn.file, node.lineno,
                    f"Python iteration over traced parameter "
                    f"{root.id!r} inside jitted `{fn.name}` — the loop "
                    "unrolls per element at trace time",
                ))
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in _FORCING_CALLS and any(
                isinstance(a, ast.Name) and a.id in traced
                for a in node.args
            ):
                out.append(p.finding(
                    2, fn.file, node.lineno,
                    f"host-forcing {name}() on a traced parameter "
                    f"inside jitted `{fn.name}`",
                ))
            elif (
                name in _FORCING_METHODS
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in traced
            ):
                out.append(p.finding(
                    2, fn.file, node.lineno,
                    f"host-forcing .{name}() on traced parameter "
                    f"{node.func.value.id!r} inside jitted "
                    f"`{fn.name}`",
                ))
    return out


def _watch_names_in_withitems(stmt: ast.With) -> Set[str]:
    """First-arg strings of every ``.watch("<fn>", ...)`` call reachable
    in the with-items (the ``dp.watch(...) if dp is not None else
    NULL_WATCH`` conditional form included)."""
    names: Set[str] = set()
    for item in stmt.items:
        for node in ast.walk(item.context_expr):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "watch"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                names.add(node.args[0].value)
    return names


@register
class RetraceHazardPass(Pass):
    name = "retrace-hazard"
    code = "RH"
    description = (
        "jitted entry points carry tracing hooks, watched bucketed "
        "dispatches, and no traced-parameter host branching"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        jitted = collect_jitted(index)
        by_file: Dict[str, List[JittedFn]] = {}
        for j in jitted:
            by_file.setdefault(j.file, []).append(j)

        # RH002: traced-parameter hazards, every jitted body
        seen_nodes: Set[int] = set()
        for j in jitted:
            if id(j.node) in seen_nodes:
                continue
            seen_nodes.add(id(j.node))
            out.extend(_hazards_in_body(self, j))

        # RH003: host dispatches outside a matching watch (and, as a
        # byproduct, WHICH jitted fns are host-dispatched at all — the
        # RH001 hook requirement applies to exactly those; a jit whose
        # every call site is inside another jitted body is a sub-jaxpr
        # of that entry point and must NOT carry its own hook)
        host_dispatched: Set[int] = set()
        targets = resolve_targets(index, jitted)
        for sf in index.package_files:
            tree = sf.tree
            if tree is None:
                continue
            local = targets.get(sf.rel, {})
            scoped = [
                j for j in by_file.get(sf.rel, []) if j.scope is not None
            ]
            if not local and not scoped and sf.rel not in by_file:
                continue
            parents = parent_map(tree)
            traced_ctx = traced_context_nodes(
                tree, by_file.get(sf.rel, [])
            )
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                ):
                    continue
                anc = list(ancestors(node, parents))
                j = resolve_call(node, local, scoped, anc)
                if j is None:
                    continue
                if any(a in traced_ctx for a in anc):
                    continue  # call happens at trace time, inlined
                host_dispatched.add(id(j.node))
                wanted = j.hook or j.name
                watched = any(
                    isinstance(a, ast.With)
                    and wanted in _watch_names_in_withitems(a)
                    for a in anc
                )
                if not watched:
                    out.append(self.finding(
                        3, sf.rel, node.lineno,
                        f"host dispatch of jitted `{node.func.id}` "
                        f"outside a dp.watch({wanted!r}, ...) window — "
                        "retraces here have no signature to be "
                        "attributed to (PR 8 standing rule)",
                    ))

        # RH001: host-dispatched jits must carry the trace-time hook
        seen_nodes.clear()
        for j in jitted:
            if id(j.node) in seen_nodes:
                continue
            seen_nodes.add(id(j.node))
            if j.hook is None and id(j.node) in host_dispatched:
                out.append(self.finding(
                    1, j.file, j.line,
                    f"jitted solver entry point `{j.name}` carries no "
                    "_devprof.tracing(...) trace-time hook — its "
                    "(re)compiles are invisible to the CompileLedger "
                    "(PR 8 standing rule)",
                ))

        # RH004: raw len() in watch signatures
        for sf in index.package_files:
            tree = sf.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "watch"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    continue
                for kw in node.keywords:
                    if kw.value is None:
                        continue
                    for sub in ast.walk(kw.value):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Name)
                            and sub.func.id == "len"
                        ):
                            out.append(self.finding(
                                4, sf.rel, node.lineno,
                                f"watch({node.args[0].value!r}) "
                                f"signature kwarg {kw.arg!r} carries a "
                                "raw len() — pass the padded bucket "
                                "(.shape[0] of the lowered array), or "
                                "every batch-size wiggle reads as a "
                                "retrace cause",
                            ))
                            break
        return out
