"""Pass ``reject-reasons`` (RR): the rejection taxonomy stays fully
attributed — every ``RejectReason`` member has a
``_classify_solver_reject`` arm or an explicit, still-true exemption
naming its dedicated attribution site. Absorbed from
``tools/check_reject_reasons.py`` (distributed-observability PR
satellite) with bit-identical verdicts.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .. import Finding, Pass, RepoIndex, register, want_file

#: members attributed at a dedicated site instead of the solver-reject
#: mask replay — member name -> where (and why) it is attributed
EXEMPT: Dict[str, str] = {
    "POD_TRANSFORMER_DROPPED": (
        "gate stage: frameworkext pod-transformer drop, before any "
        "solve runs"
    ),
    "GANG_NOT_READY": (
        "gate stage: coscheduling holds the gang back pre-batch"
    ),
    "RESERVATION_UNAVAILABLE": (
        "reserve stage: reservation fast-path match refusal"
    ),
    "NODE_CAPACITY_REVALIDATION": (
        "commit stage: Reserve's host-side capacity recheck of a "
        "solver winner"
    ),
    "NUMA_ALLOCATION_FAILED": (
        "commit stage: NUMAManager zone allocation refusal"
    ),
    "DEVICE_ALLOCATION_FAILED": (
        "commit stage: DeviceManager slot allocation refusal"
    ),
    "NODE_VANISHED": (
        "commit stage: winner's node deleted between solve and Reserve"
    ),
    "NUMERIC_INVALID": (
        "pre-solve quarantine: non-finite req/est rows never reach the "
        "mask stages the replay re-runs"
    ),
    "SOLVE_RESULT_STALLED": (
        "solve stage: bounded result fetch timed out — a feeder stall, "
        "not a mask verdict"
    ),
    "CYCLE_DEADLINE_EXCEEDED": (
        "cycle deadline: deferred chunks were never solved, so there "
        "is no mask outcome to replay"
    ),
    "COMMIT_ROLLED_BACK": (
        "commit stage: mid-commit crash unwound the chunk's Reserve "
        "journal"
    ),
    "STALE_LEADER_EPOCH": (
        "fence boundary: a deposed leader's commit refused by epoch "
        "check, independent of solver feasibility"
    ),
    "JOURNAL_WRITE_FAILED": (
        "journal boundary: intent/bind append refused — "
        "journal-before-mutate rejects the chunk un-mutated"
    ),
    "OVERLOAD_SHED": (
        "admission boundary: QoS-band shed at StreamScheduler submit/"
        "sweep — the pod never reaches a solve, so there is no mask "
        "outcome to replay; attributed via overload_shed_total{band} "
        "plus the terminal shed lifecycle event (koordlint shed-paths "
        "pass enforces both)"
    ),
    "POISON_QUARANTINED": (
        "cycle gate: the quarantine ledger blames the pod (its lowering "
        "deterministically crashed a dispatch and bisection isolated "
        "it) — rejected at the batch scheduler's gate and shed through "
        "StreamScheduler._shed_quarantined before any solve; "
        "redeemable, a changed spec fingerprint re-admits"
    ),
}

#: where the enum and the classifier live
ENUM_FILE = "koordinator_tpu/obs/rejections.py"
CLASSIFIER_FILE = "koordinator_tpu/scheduler/batch_solver.py"
CLASSIFIER_FUNC = "_classify_solver_reject"

#: the shim file exemptions point error messages at (kept stable so the
#: migrated verdicts stay bit-identical with the legacy CLI)
SELF_FILE = "tools/check_reject_reasons.py"

Violation = Tuple[str, int, str]


def _enum_members_tree(tree: ast.AST) -> Dict[str, int]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "RejectReason":
            out: Dict[str, int] = {}
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                ):
                    out[stmt.targets[0].id] = stmt.lineno
            return out
    raise AssertionError(f"RejectReason class not found in {ENUM_FILE}")


def enum_members(root: Path) -> Dict[str, int]:
    """``RejectReason`` member name -> definition line."""
    return _enum_members_tree(
        ast.parse((root / ENUM_FILE).read_text(encoding="utf-8"))
    )


def _reason_refs(tree: ast.AST) -> Set[str]:
    """Every ``RejectReason.X`` attribute access under ``tree``."""
    return {
        n.attr
        for n in ast.walk(tree)
        if isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == "RejectReason"
    }


def _classifier_coverage_tree(tree: ast.AST) -> Set[str]:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == CLASSIFIER_FUNC
        ):
            return _reason_refs(node)
    raise AssertionError(
        f"{CLASSIFIER_FUNC} not found in {CLASSIFIER_FILE}"
    )


def classifier_coverage(root: Path) -> Set[str]:
    """Members referenced inside ``_classify_solver_reject``."""
    return _classifier_coverage_tree(
        ast.parse((root / CLASSIFIER_FILE).read_text(encoding="utf-8"))
    )


def repo_refs(root: Path) -> Set[str]:
    """Members referenced anywhere in koordinator_tpu/ OUTSIDE the enum
    definition file (attribution sites)."""
    refs: Set[str] = set()
    for f in sorted((root / "koordinator_tpu").rglob("*.py")):
        if f == root / ENUM_FILE or not want_file(f):
            continue
        try:
            refs |= _reason_refs(
                ast.parse(f.read_text(encoding="utf-8"))
            )
        except SyntaxError:
            pass  # unparsable files are another lint's problem
    return refs


def check(
    root: Path,
    exempt_table: Optional[Dict[str, str]] = None,
    index: Optional[RepoIndex] = None,
) -> List[Violation]:
    """``exempt_table`` overrides :data:`EXEMPT` (the lint's own tests
    scan synthetic repos whose enums the real table does not match).
    ``index`` reuses a framework run's parse-once cache; without one
    (the legacy shim path) the files are read directly."""
    exemptions = EXEMPT if exempt_table is None else exempt_table
    if index is not None:
        enum_sf = index.file(ENUM_FILE)
        cls_sf = index.file(CLASSIFIER_FILE)
        if enum_sf is None or enum_sf.tree is None:
            raise AssertionError(f"{ENUM_FILE} missing or unparsable")
        if cls_sf is None or cls_sf.tree is None:
            raise AssertionError(
                f"{CLASSIFIER_FILE} missing or unparsable"
            )
        members = _enum_members_tree(enum_sf.tree)
        covered = _classifier_coverage_tree(cls_sf.tree)
        referenced = set()
        for sf in index.package_files:
            if sf.rel == ENUM_FILE or sf.tree is None:
                continue
            referenced |= _reason_refs(sf.tree)
    else:
        members = enum_members(root)
        covered = classifier_coverage(root)
        referenced = repo_refs(root)
    out: List[Violation] = []
    for name, line in sorted(members.items()):
        in_classifier = name in covered
        exempt = name in exemptions
        if not in_classifier and not exempt:
            out.append(
                (
                    ENUM_FILE,
                    line,
                    f"RejectReason.{name} has no "
                    f"{CLASSIFIER_FUNC} arm and no exemption in "
                    "tools/check_reject_reasons.py — wire its "
                    "attribution or document its dedicated site",
                )
            )
        elif in_classifier and exempt:
            out.append(
                (
                    ENUM_FILE,
                    line,
                    f"RejectReason.{name} is covered by "
                    f"{CLASSIFIER_FUNC} but still exempted — remove "
                    "the stale exemption",
                )
            )
        elif exempt and name not in referenced:
            out.append(
                (
                    ENUM_FILE,
                    line,
                    f"RejectReason.{name} is exempted as attributed "
                    "at a dedicated site, but nothing in "
                    "koordinator_tpu/ references it — the site is "
                    "gone (or never existed)",
                )
            )
    for name in sorted(set(exemptions) - set(members)):
        out.append(
            (
                SELF_FILE,
                0,
                f"exemption for unknown member RejectReason.{name}",
            )
        )
    return out


def main(argv: List[str]) -> int:
    from .. import repo_root

    root = Path(argv[0]).resolve() if argv else repo_root()
    violations = check(root)
    for rel, line, msg in violations:
        print(f"{rel}:{line}: {msg}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unattributed / stale reject reason"
            f"{'' if len(violations) == 1 else 's'}",
            file=sys.stderr,
        )
        return 1
    return 0


@register
class RejectReasonsPass(Pass):
    name = "reject-reasons"
    code = "RR"
    description = (
        "every RejectReason member has a classifier arm or a live "
        "dedicated-site exemption"
    )
    legacy_cli = "tools/check_reject_reasons.py"

    def run(self, index: RepoIndex) -> List[Finding]:
        try:
            violations = check(index.root, index=index)
        except (AssertionError, OSError) as exc:
            return [self.finding(0, ENUM_FILE, 0, str(exc))]
        return [
            self.finding(1, rel, line, msg)
            for rel, line, msg in violations
        ]
