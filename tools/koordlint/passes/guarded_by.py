"""Pass ``guarded-by`` (GB): lock-discipline annotations for the shared
mutable state the pump / prepare-worker / shard / informer / HTTP
threads all touch (CyclePipeline's worker, StreamScheduler's scheduler,
PodLifecycle's buffers, ShardFabric's handoff log, FlightRecorder's
ring, the obs trackers).

Annotate the attribute where it is initialized::

    self._ring: deque = deque(maxlen=cap)  # guarded-by: self._lock

The pass then flags every WRITE to the annotated attribute (assignment,
aug-assign, ``del``, or a mutating method call — append/pop/update/...)
that is not lexically inside a ``with`` on the named lock:

* **GB001** — write via ``self.<attr>`` inside the declaring class;
* **GB002** — write via another object (``fabric.handoff_log.append``):
  the lock is rebased onto the same owner path (annotation
  ``self.handoff_lock`` ⇒ required ``with fabric.handoff_lock``).

Exempt: ``__init__`` (construction happens-before publication), methods
whose name ends in ``_locked`` (the repo's caller-holds convention), and
defs annotated ``# koordlint: holds=self._lock`` on their ``def`` line.
Reads are out of scope — lock-free snapshot reads of GIL-atomic
structures are an intentional idiom here; it is the WRITES that must
serialize.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Set, Tuple

from .. import (
    Finding,
    Pass,
    RepoIndex,
    SourceFile,
    ancestors,
    dotted_path,
    parent_map,
    register,
)

#: method names that mutate their receiver
MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "update", "setdefault",
    "add", "discard", "rotate", "sort", "reverse",
})


@dataclasses.dataclass(frozen=True)
class Annotation:
    file: str
    cls: str
    attr: str
    lock: str      # e.g. "self._lock" (annotation form)
    line: int

    @property
    def lock_attr(self) -> str:
        return self.lock.split(".", 1)[1] if "." in self.lock else self.lock


def collect_annotations(
    index: RepoIndex,
) -> Tuple[List[Annotation], Set[str]]:
    """(annotations, ambiguous attr names). An attr name also declared
    by a class that does NOT annotate it is AMBIGUOUS for the
    cross-object rule — without types, ``other._series`` cannot be told
    apart from the annotated class's ``_series``; only ``self.`` writes
    in the annotated class stay enforced for those."""
    out: List[Annotation] = []
    declared_elsewhere: Set[str] = set()
    annotated_cls: Set[Tuple[str, str]] = set()
    for sf in index.package_files:
        tree = sf.tree
        if tree is None:
            continue
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    path = dotted_path(t)
                    if path is None or not path.startswith("self."):
                        continue
                    attr = path[len("self."):]
                    if "." in attr:
                        continue
                    lock = sf.guarded_by_on_line(node.lineno)
                    if lock is not None:
                        out.append(Annotation(
                            file=sf.rel, cls=cls.name, attr=attr,
                            lock=lock, line=node.lineno,
                        ))
                        annotated_cls.add((cls.name, attr))
                    else:
                        declared_elsewhere.add((cls.name, attr))
    ambiguous = {
        attr
        for cls, attr in declared_elsewhere
        if any(a.attr == attr for a in out)
        and (cls, attr) not in annotated_cls
    }
    return out, ambiguous


def _write_paths(node: ast.AST) -> List[Tuple[str, int]]:
    """(dotted path written, line) pairs this statement/expr mutates."""
    out: List[Tuple[str, int]] = []
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        # tuple/list/starred unpacking targets write each element
        flat: List[ast.AST] = []
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)
            else:
                flat.append(t)
        for t in flat:
            base = t
            # self.x[k] = v / fabric.log[k] = v — the CONTAINER mutates
            while isinstance(base, ast.Subscript):
                base = base.value
            p = dotted_path(base)
            if p is not None:
                out.append((p, node.lineno))
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            p = dotted_path(base)
            if p is not None:
                out.append((p, node.lineno))
    elif (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in MUTATORS
    ):
        p = dotted_path(node.func.value)
        if p is not None:
            out.append((p, node.lineno))
    return out


def _with_lock_exprs(stmt: ast.With) -> Set[str]:
    out: Set[str] = set()
    for item in stmt.items:
        p = dotted_path(item.context_expr)
        if p is not None:
            out.add(p)
    return out


def _exempt_def(
    fn: ast.AST, sf: SourceFile, required_lock: str
) -> bool:
    name = getattr(fn, "name", "")
    if name == "__init__" or name.endswith("_locked"):
        return True
    held = sf.holds.get(getattr(fn, "lineno", -1))
    return held is not None and held == required_lock


def _locked(anc: List[ast.AST], required: str) -> bool:
    return any(
        isinstance(a, ast.With) and required in _with_lock_exprs(a)
        for a in anc
    )


@register
class GuardedByPass(Pass):
    name = "guarded-by"
    code = "GB"
    description = (
        "# guarded-by: annotated attributes are only written under "
        "their named lock"
    )

    def run(self, index: RepoIndex) -> List[Finding]:
        out: List[Finding] = []
        annotations, ambiguous = collect_annotations(index)
        if not annotations:
            return out
        # attr name -> annotations carrying it (cross-object rule keys
        # on the terminal attribute name; collisions are resolved by
        # requiring the rebased lock on the same owner path)
        by_attr: Dict[str, List[Annotation]] = {}
        for a in annotations:
            by_attr.setdefault(a.attr, []).append(a)

        for sf in index.package_files:
            tree = sf.tree
            if tree is None:
                continue
            parents = parent_map(tree)
            # class name active at each node (None at module level)
            for node in ast.walk(tree):
                for path, line in _write_paths(node):
                    parts = path.split(".")
                    if len(parts) < 2:
                        continue
                    attr = parts[-1]
                    base = ".".join(parts[:-1])
                    hits = by_attr.get(attr)
                    if not hits:
                        continue
                    anc = list(ancestors(node, parents))
                    fn = next(
                        (
                            a for a in anc
                            if isinstance(
                                a,
                                (ast.FunctionDef, ast.AsyncFunctionDef),
                            )
                        ),
                        None,
                    )
                    if base == "self":
                        cls = next(
                            (
                                a.name for a in anc
                                if isinstance(a, ast.ClassDef)
                            ),
                            None,
                        )
                        ann = next(
                            (
                                a for a in hits
                                if a.file == sf.rel and a.cls == cls
                            ),
                            None,
                        )
                        if ann is None:
                            continue  # same attr name, another class
                        required = ann.lock
                        if fn is not None and _exempt_def(
                            fn, sf, required
                        ):
                            continue
                        if not _locked(anc, required):
                            out.append(self.finding(
                                1, sf.rel, line,
                                f"write to {ann.cls}.{attr} "
                                f"(# guarded-by: {ann.lock}) outside "
                                f"`with {required}`",
                            ))
                    else:
                        # cross-object write: rebase the lock onto the
                        # same owner path (self.handoff_lock ->
                        # <base>.handoff_lock). Several annotated
                        # classes may share the attr name with
                        # DIFFERENT locks — without types the owner is
                        # unknowable, so holding ANY candidate's
                        # rebased lock satisfies the check (the
                        # same-class GB001 rule stays exact).
                        if attr in ambiguous:
                            continue
                        required_any = sorted({
                            f"{base}.{a.lock_attr}" for a in hits
                        })
                        if fn is not None and any(
                            _exempt_def(fn, sf, req)
                            for req in required_any
                        ):
                            continue
                        if not any(
                            _locked(anc, req) for req in required_any
                        ):
                            ann = hits[0]
                            out.append(self.finding(
                                2, sf.rel, line,
                                f"write to `{path}` "
                                f"({ann.cls}.{attr} is # guarded-by: "
                                f"{ann.lock}) outside "
                                "`with "
                                + (" | ".join(required_any))
                                + "`",
                            ))
        return out
