"""Shared jit-wrapping discovery for the retrace-hazard and
donation-safety passes: which functions are jitted (decorator form,
``jax.jit(fn, ...)`` wrapper form, ``shard_map`` form), their static
argnames, donated positions, trace-time hook string, and how call sites
resolve to them (module-level imports + in-function jitted bindings).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from . import RepoIndex, call_name, parent_map


@dataclasses.dataclass
class JittedFn:
    file: str                 # repo-relative file of the def
    name: str                 # the DISPATCH name (binding or def name)
    node: ast.AST             # the FunctionDef whose body is traced
    line: int
    statics: Set[str]
    donated: Tuple[int, ...]  # donated positional indices
    hook: Optional[str]       # _devprof.tracing("<fn>") string, if any
    kind: str                 # "decorator" | "wrapper" | "shard_map"
    #: for wrapper-form bindings: the def (or None = module) the binding
    #: lives in — the name only resolves for calls inside that scope
    scope: Optional[ast.AST] = None


def _is_jax_jit(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    ) or (isinstance(node, ast.Name) and node.id == "jit")


def _is_shard_map(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "shard_map"
    if isinstance(node, ast.Attribute):
        return node.attr == "shard_map"
    return False


def _const_names(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if node is None:
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    return out


def _const_ints(node: Optional[ast.AST]) -> Tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return ()


def _jit_call_opts(call: ast.Call) -> Tuple[Set[str], Tuple[int, ...]]:
    statics: Set[str] = set()
    donated: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            statics = _const_names(kw.value)
        elif kw.arg == "donate_argnums":
            donated = _const_ints(kw.value)
    return statics, donated


def find_hook(fn: ast.AST) -> Optional[str]:
    """The ``tracing("<name>")`` string inside a (to-be-)jitted body."""
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and call_name(node) == "tracing"
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return node.args[0].value
    return None


def _decorated_jit(fn) -> Optional[Tuple[Set[str], Tuple[int, ...], str]]:
    """(statics, donated, kind) when ``fn`` is jitted by decorator."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return set(), (), "decorator"
        if isinstance(dec, ast.Call):
            fname = call_name(dec)
            if fname == "partial" and dec.args:
                if _is_jax_jit(dec.args[0]):
                    statics, donated = _jit_call_opts(dec)
                    return statics, donated, "decorator"
                if _is_shard_map(dec.args[0]):
                    return set(), (), "shard_map"
            if _is_jax_jit(dec.func):
                statics, donated = _jit_call_opts(dec)
                return statics, donated, "decorator"
            if _is_shard_map(dec.func):
                return set(), (), "shard_map"
    return None


def collect_jitted(index: RepoIndex) -> List[JittedFn]:
    """Every jit-wrapped function in the package. Memoized on the index
    (retrace-hazard and donation-safety share one walk per run)."""
    cached = getattr(index, "_jitindex_cache", None)
    if cached is not None:
        return cached
    out: List[JittedFn] = []
    for sf in index.package_files:
        tree = sf.tree
        if tree is None:
            continue
        # decorator + shard_map forms
        for node in ast.walk(tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            hit = _decorated_jit(node)
            if hit is not None:
                statics, donated, kind = hit
                out.append(JittedFn(
                    file=sf.rel, name=node.name, node=node,
                    line=node.lineno, statics=statics, donated=donated,
                    hook=find_hook(node), kind=kind,
                ))
        # wrapper form: ``X = jax.jit(local_def, ...)`` — the binding X
        # is the dispatch name; the wrapped local def's body is traced
        defs_by_scope: Dict[ast.AST, Dict[str, ast.AST]] = {}
        parents = parent_map(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = _enclosing_scope(node, parents)
                defs_by_scope.setdefault(scope, {})[node.name] = node
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_jax_jit(node.value.func)
                and node.value.args
                and isinstance(node.value.args[0], ast.Name)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            scope = _enclosing_scope(node, parents)
            wrapped = defs_by_scope.get(scope, {}).get(
                node.value.args[0].id
            )
            if wrapped is None:
                continue
            statics, donated = _jit_call_opts(node.value)
            out.append(JittedFn(
                file=sf.rel, name=node.targets[0].id, node=wrapped,
                line=node.lineno, statics=statics, donated=donated,
                hook=find_hook(wrapped), kind="wrapper",
                scope=scope if not isinstance(scope, ast.Module) else None,
            ))
    index._jitindex_cache = out
    return out


def _enclosing_scope(node: ast.AST, parents) -> ast.AST:
    cur = parents.get(node)
    while cur is not None and not isinstance(
        cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
    ):
        cur = parents.get(cur)
    return cur


def traced_params(fn: JittedFn) -> Set[str]:
    """Parameter names whose values are TRACED (non-static) at trace
    time. ``self``-style params never appear on jitted fns here."""
    a = fn.node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    return set(names) - fn.statics


def module_of(rel: str) -> str:
    """``koordinator_tpu/ops/solver.py`` -> ``koordinator_tpu.ops.solver``."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def import_map(sf) -> Dict[str, Tuple[str, str]]:
    """local name -> (module, original name) for ``from X import a as b``
    (absolute or relative, resolved against the file's package path)."""
    tree = sf.tree
    out: Dict[str, Tuple[str, str]] = {}
    if tree is None:
        return out
    pkg_parts = module_of(sf.rel).split(".")[:-1]
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or node.module is None:
            continue
        if node.level:
            base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            mod = ".".join(base + node.module.split("."))
        else:
            mod = node.module
        for alias in node.names:
            out[alias.asname or alias.name] = (mod, alias.name)
    return out


def resolve_targets(
    index: RepoIndex, jitted: List[JittedFn]
) -> Dict[str, Dict[str, JittedFn]]:
    """Per-file map: local callable name -> JittedFn it dispatches.

    Covers (a) defs/wrappers in the same file, (b) ``from mod import
    name`` of a jitted def in another module. Call sites the map cannot
    resolve are simply out of scope."""
    by_module: Dict[Tuple[str, str], JittedFn] = {
        (module_of(j.file), j.name): j for j in jitted
    }
    out: Dict[str, Dict[str, JittedFn]] = {}
    for sf in index.package_files:
        local: Dict[str, JittedFn] = {}
        for j in jitted:
            if j.file == sf.rel and j.scope is None:
                local[j.name] = j
        for name, (mod, orig) in import_map(sf).items():
            j = by_module.get((mod, orig))
            if j is not None:
                local[name] = j
        out[sf.rel] = local
    return out


def resolve_call(
    call: ast.Call,
    local: Dict[str, JittedFn],
    scoped: List[JittedFn],
    anc: List[ast.AST],
) -> Optional[JittedFn]:
    """Resolve a ``Name(...)`` call against function-scoped jitted
    bindings first (``fn = jax.jit(...)`` inside the enclosing def),
    then the file/module-level map."""
    if not isinstance(call.func, ast.Name):
        return None
    name = call.func.id
    for j in scoped:
        if j.name == name and j.scope is not None and j.scope in anc:
            return j
    return local.get(name)


def traced_context_nodes(tree: ast.AST, jitted_in_file) -> Set[ast.AST]:
    """Every def node lexically inside (or being) a jitted body — calls
    from there run at TRACE time, not as host dispatches."""
    out: Set[ast.AST] = set()
    for j in jitted_in_file:
        for node in ast.walk(j.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.add(node)
    return out
