"""CLI: ``python -m tools.koordlint [paths...] [--select ...] [--json]``.

Exit 0 iff zero unsuppressed findings. ``--json -`` prints the
machine-readable report to stdout; ``--json PATH`` writes it beside the
human table. ``paths`` are repo-relative prefixes that restrict which
files' findings are reported (passes still analyze the whole tree)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

if __package__ in (None, ""):  # `python tools/koordlint/__main__.py`
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
    from tools.koordlint import all_passes, repo_root, run
else:
    from . import all_passes, repo_root, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.koordlint",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument(
        "paths", nargs="*",
        help="repo-relative path prefixes to report on (default: all)",
    )
    ap.add_argument(
        "--select", default="",
        help="comma-separated pass names to run (default: all)",
    )
    ap.add_argument(
        "--ignore", default="",
        help="comma-separated pass names to skip",
    )
    ap.add_argument(
        "--json", default="", metavar="PATH",
        help="write the machine-readable report ('-' = stdout)",
    )
    ap.add_argument(
        "--root", default="", metavar="DIR",
        help="repo root to scan (default: this checkout)",
    )
    ap.add_argument(
        "--list-passes", action="store_true",
        help="list registered passes and exit",
    )
    args = ap.parse_args(argv)

    if args.list_passes:
        for name, p in all_passes().items():
            legacy = f" (absorbs {p.legacy_cli})" if p.legacy_cli else ""
            print(f"{name:<18} {p.code:<4} {p.description}{legacy}")
        return 0

    select = [s for s in args.select.split(",") if s.strip()] or None
    ignore = [s for s in args.ignore.split(",") if s.strip()] or None
    root = Path(args.root).resolve() if args.root else repo_root()
    try:
        report = run(
            root, select=select, ignore=ignore, paths=args.paths or None
        )
    except KeyError as exc:
        print(exc.args[0], file=sys.stderr)
        return 2
    if args.json == "-":
        print(report.to_json())
    else:
        if args.json:
            Path(args.json).write_text(report.to_json() + "\n")
        print(report.render())
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
