"""NodeNUMAResource tests: cpu accumulator, zone masks, hint merge, e2e
(reference ``pkg/scheduler/plugins/nodenumaresource`` +
``frameworkext/topologymanager``)."""

import json

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import QoSClass
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import (
    CPUAccumulator,
    CPUBindPolicy,
    CPUTopology,
    NUMAPolicy,
    format_cpuset,
    parse_cpuset,
)
from koordinator_tpu.ops.numa import (
    NumaState,
    merge_hints,
    numa_alignment_cost,
    numa_fit_mask,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.nodenumaresource import NUMAManager


# ---- cpuset formatting ----


def test_cpuset_roundtrip():
    assert format_cpuset([0, 1, 2, 3, 8, 10, 11]) == "0-3,8,10-11"
    assert parse_cpuset("0-3,8,10-11") == {0, 1, 2, 3, 8, 10, 11}
    assert format_cpuset([]) == ""
    assert parse_cpuset("") == set()


# ---- accumulator (reference cpu_accumulator.go takeCPUs) ----


def topo():
    # 2 sockets x 1 numa x 4 cores x 2 threads = 16 cpus
    return CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=4, threads_per_core=2
    )


def test_take_full_socket_first():
    acc = CPUAccumulator(topo())
    got = acc.take("p1", 8)
    # one whole socket (cpus 0-7)
    assert got == set(range(8))


def test_take_full_cores_when_less_than_socket():
    acc = CPUAccumulator(topo())
    got = acc.take("p1", 4)
    # two whole physical cores
    cores = {c // 2 for c in got}
    assert len(got) == 4 and len(cores) == 2


def test_full_pcpus_policy_rejects_odd():
    acc = CPUAccumulator(topo())
    assert acc.take("p1", 3, policy=CPUBindPolicy.FULL_PCPUS) is None
    got = acc.take("p1", 4, policy=CPUBindPolicy.FULL_PCPUS)
    assert len(got) == 4 and len({c // 2 for c in got}) == 2


def test_spread_by_pcpus_one_thread_per_core():
    acc = CPUAccumulator(topo())
    got = acc.take("p1", 4, policy=CPUBindPolicy.SPREAD_BY_PCPUS)
    # 4 cpus over 4 distinct cores
    assert len({c // 2 for c in got}) == 4


def test_numa_pinning_and_exhaustion():
    acc = CPUAccumulator(topo())
    got = acc.take("p1", 8, numa=0)
    assert {c for c in got} == set(range(8))
    assert acc.take("p2", 1, numa=0) is None
    assert acc.take("p2", 8, numa=1) == set(range(8, 16))


def test_release_returns_capacity():
    acc = CPUAccumulator(topo())
    acc.take("p1", 16)
    assert acc.take("p2", 1) is None
    acc.release("p1")
    assert len(acc.take("p2", 16)) == 16


# ---- zone masks ----


def numa_state(policy):
    # 2 nodes x 2 zones; node 0 zones: 4000/2000 cpu free
    zone_free = np.array(
        [
            [[4000.0, 8192.0], [2000.0, 8192.0]],
            [[8000.0, 8192.0], [8000.0, 8192.0]],
        ],
        np.float32,
    )
    return NumaState(
        zone_free=jnp.asarray(zone_free),
        zone_cap=jnp.asarray(zone_free),  # fresh zones: cap == free
        policy=jnp.asarray(np.array([policy, policy], np.int8)),
    )


def test_single_numa_mask():
    ns = numa_state(3)  # SINGLE_NUMA_NODE
    req = np.zeros((2, 4), np.float32)
    req[0, :2] = [3000.0, 1024.0]   # fits zone 0 of node 0, any of node 1
    req[1, :2] = [6000.0, 1024.0]   # no single zone on node 0; node 1 ok
    wants = np.array([True, True])
    mask = np.asarray(numa_fit_mask(jnp.asarray(req), jnp.asarray(wants), ns))
    assert mask[0].tolist() == [True, True]
    assert mask[1].tolist() == [False, True]


def test_best_effort_mask_allows_spanning():
    ns = numa_state(1)  # BEST_EFFORT
    req = np.zeros((1, 4), np.float32)
    req[0, :2] = [6000.0, 1024.0]   # spans node 0's zones (4000+2000)
    mask = np.asarray(
        numa_fit_mask(jnp.asarray(req), jnp.asarray(np.array([True])), ns)
    )
    assert mask[0].tolist() == [True, True]


def test_alignment_cost_prefers_headroom():
    ns = numa_state(3)
    req = np.zeros((1, 4), np.float32)
    req[0, :2] = [1000.0, 512.0]
    cost = np.asarray(numa_alignment_cost(jnp.asarray(req), ns))
    assert cost[0, 1] < cost[0, 0]  # node 1 zones have more headroom


# ---- hint merge ----


def test_merge_hints_narrowest_wins():
    # 2 zones -> candidates {01, 10, 11}; provider A allows zone0 or both,
    # provider B allows anything containing zone0
    m = 4
    a = np.zeros(m, bool); a[[1, 3]] = True          # {z0}, {z0,z1}
    b = np.zeros(m, bool); b[[1, 3]] = True
    best = int(merge_hints(jnp.asarray(np.stack([a, b])), 2))
    assert best == 1  # single zone 0 preferred over both
    # no overlap -> -1
    c = np.zeros(m, bool); c[2] = True               # {z1} only
    best = int(merge_hints(jnp.asarray(np.stack([a, c])), 2))
    assert best == -1


# ---- end to end ----


def lsr_pod(name, cpu_milli, bind=None):
    labels = {ext.LABEL_POD_QOS: "LSR"}
    annotations = {}
    if bind:
        annotations[ext.ANNOTATION_RESOURCE_SPEC] = json.dumps(
            {"preferredCPUBindPolicy": bind}
        )
    return Pod(
        meta=ObjectMeta(name=name, labels=labels, annotations=annotations),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu_milli, ext.RES_MEMORY: 1024},
            priority=9500,
        ),
    )


def test_end_to_end_lsr_cpuset():
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 32768}
            ),
        )
    )
    numa = NUMAManager(snap)
    numa.register_node(
        "n0",
        CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=4),
        policy=NUMAPolicy.SINGLE_NUMA_NODE,
        memory_per_zone_mib=16384,
    )
    sched = BatchScheduler(snap, numa=numa)
    pod = lsr_pod("lsr-1", 4000, bind="FullPCPUs")
    out = sched.schedule([pod])
    assert len(out.bound) == 1
    status = json.loads(
        out.bound[0][0].meta.annotations[ext.ANNOTATION_RESOURCE_STATUS]
    )
    cpus = parse_cpuset(status["cpuset"])
    assert len(cpus) == 4
    assert len({c // 2 for c in cpus}) == 2  # whole physical cores
    assert status["numaNodeResources"] == [{"node": 0}]

    # second LSR pod of 6 cpus: zone 0 has 4 left -> goes to zone 1
    pod2 = lsr_pod("lsr-2", 6000)
    out2 = sched.schedule([pod2])
    assert len(out2.bound) == 1
    status2 = json.loads(
        out2.bound[0][0].meta.annotations[ext.ANNOTATION_RESOURCE_STATUS]
    )
    assert status2["numaNodeResources"] == [{"node": 1}]
    # and its cpuset is disjoint from pod 1's
    assert not (parse_cpuset(status2["cpuset"]) & cpus)


def test_end_to_end_single_numa_infeasible():
    """A pod too big for any single zone on a strict node is unschedulable."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 32768}
            ),
        )
    )
    numa = NUMAManager(snap)
    numa.register_node(
        "n0",
        CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=4),
        policy=NUMAPolicy.SINGLE_NUMA_NODE,
        memory_per_zone_mib=16384,
    )
    sched = BatchScheduler(snap, numa=numa)
    out = sched.schedule([lsr_pod("big", 12000)])  # > 8 cpus per zone
    assert out.bound == []
    assert len(out.unschedulable) == 1


def test_exhausted_zones_stay_infeasible():
    """A node whose zones are fully allocated must not become feasible via
    the 'no topology' fallback (capacity, not free, drives has_zones)."""
    zone_free = np.zeros((1, 2, 2), np.float32)
    zone_cap = np.full((1, 2, 2), 100.0, np.float32)
    ns = NumaState(
        zone_free=jnp.asarray(zone_free),
        zone_cap=jnp.asarray(zone_cap),
        policy=jnp.asarray(np.array([3], np.int8)),
    )
    req = np.zeros((1, 4), np.float32)
    req[0, :2] = [10.0, 10.0]
    mask = np.asarray(
        numa_fit_mask(jnp.asarray(req), jnp.asarray(np.array([True])), ns)
    )
    assert mask[0, 0] == False  # noqa: E712


def test_unreported_memory_dim_ignored():
    """Zones registered with zero memory capacity skip the memory check
    (like a disabled threshold) instead of rejecting every pod."""
    zone_free = np.zeros((1, 2, 2), np.float32)
    zone_free[0, :, 0] = 8000.0  # cpu only; memory unreported
    ns = NumaState(
        zone_free=jnp.asarray(zone_free),
        zone_cap=jnp.asarray(zone_free),
        policy=jnp.asarray(np.array([3], np.int8)),
    )
    req = np.zeros((1, 4), np.float32)
    req[0, :2] = [4000.0, 2048.0]
    mask = np.asarray(
        numa_fit_mask(jnp.asarray(req), jnp.asarray(np.array([True])), ns)
    )
    assert mask[0, 0] == True  # noqa: E712


def test_accumulator_adversarial_take_release_invariants():
    """Randomized take/release churn: ownership stays disjoint and equal to
    the allocated set, FullPCPUs results stay core-aligned, numa pins hold.
    (Guards the heap fast path against stale-cache bugs — an ABA length
    match once left a freed core in the heap.)"""
    import random

    from koordinator_tpu.core.topology import CPUAccumulator, CPUBindPolicy

    for seed in range(2):
        rng = random.Random(seed)
        t = CPUTopology.uniform(
            sockets=2, numa_per_socket=2, cores_per_numa=4, threads_per_core=2
        )
        core_of = {c.cpu_id: c.core_id for c in t.cpus}
        numa_of = {c.cpu_id: c.numa_node for c in t.cpus}
        acc = CPUAccumulator(t)
        owners = {}
        for step in range(1500):
            if owners and rng.random() < 0.45:
                o = rng.choice(list(owners))
                acc.release(o)
                del owners[o]
            else:
                o = f"o{step}"
                n = rng.choice([1, 2, 4, 6, 8])
                pol = rng.choice(
                    [
                        CPUBindPolicy.DEFAULT,
                        CPUBindPolicy.FULL_PCPUS,
                        CPUBindPolicy.SPREAD_BY_PCPUS,
                    ]
                )
                numa = rng.choice([None, 0, 1, 2, 3])
                got = acc.take(o, n, policy=pol, numa=numa)
                if got is not None:
                    owners[o] = got
                    assert len(got) == n
                    if numa is not None:
                        assert {numa_of[c] for c in got} == {numa}
                    if pol == CPUBindPolicy.FULL_PCPUS:
                        from collections import Counter

                        cores = Counter(core_of[c] for c in got)
                        assert all(v == 2 for v in cores.values())
            all_owned = set()
            for o, cpus in owners.items():
                assert not (all_owned & cpus), "double allocation"
                all_owned |= cpus
            assert all_owned == acc._allocated


# ---- NUMA-aligned Least/MostAllocated scoring (scoring.go:66-120) ----


def test_numa_aligned_cost_reference_values():
    """leastRequestedScore / mostRequestedScore integer semantics over the
    zone the host allocator would pick."""
    import jax.numpy as jnp
    import numpy as np

    from koordinator_tpu.ops.costs import numa_aligned_cost

    zone_cap = np.zeros((1, 2, 2), np.float32)
    zone_cap[0, :, 0] = 16000.0
    zone_cap[0, :, 1] = 1000.0
    zone_free = zone_cap.copy()
    zone_free[0, 0, 0] = 8000.0          # zone0 cpu half used
    req = np.asarray([[4000.0, 0.0]], np.float32)
    wants = np.asarray([True])
    w = np.asarray([1.0, 0.0], np.float32)

    def score(zfree, most):
        c = numa_aligned_cost(
            jnp.asarray(req), jnp.asarray(wants), jnp.asarray(zfree),
            jnp.asarray(zone_cap), jnp.asarray(w), most_allocated=most,
        )
        return float(-np.asarray(c)[0, 0])

    # empty zone1 is least utilized -> picked: least (16000-4000)*100/16000=75
    assert score(zone_free, most=False) == 75.0
    assert score(zone_free, most=True) == 25.0
    # make zone1 unfit -> forced onto half-used zone0: (16000-12000)*100/16000
    zf2 = zone_free.copy()
    zf2[0, 1, 0] = 2000.0
    assert score(zf2, most=False) == 25.0
    assert score(zf2, most=True) == 75.0
    # a pod without NUMA interest contributes zero
    c = numa_aligned_cost(
        jnp.asarray(req), jnp.asarray([False]), jnp.asarray(zone_free),
        jnp.asarray(zone_cap), jnp.asarray(w),
    )
    assert float(np.asarray(c)[0, 0]) == 0.0


def _scoring_cluster(strategy):
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.nodenumaresource import NUMAManager

    snap = ClusterSnapshot()
    numa = NUMAManager(snap, scoring_strategy=strategy)
    topo = CPUTopology.uniform(sockets=1, numa_per_socket=1, cores_per_numa=16)
    for name in ("n0", "n1"):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 262144}
                ),
            )
        )
        numa.register_node(name, topo, memory_per_zone_mib=131072.0)
    sched = BatchScheduler(snap, numa=numa, batch_bucket=64)
    sched.extender.monitor.stop_background()

    def lsr(name, cpu, node=None):
        return Pod(
            meta=ObjectMeta(name=name, labels={ext.LABEL_POD_QOS: "LSR"}),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 8192},
                priority=9500,
                node_name=node,
            ),
        )

    # pre-fill n0's single zone half-way
    out = sched.schedule([lsr("filler", 16000, node="n0")])
    assert [(p.meta.name, n) for p, n in out.bound] == [("filler", "n0")]
    out2 = sched.schedule([lsr("probe", 4000)])
    assert len(out2.bound) == 1
    return out2.bound[0][1]


def test_most_allocated_scoring_packs_fuller_zone():
    assert _scoring_cluster("MostAllocated") == "n0"


def test_least_allocated_scoring_spreads():
    assert _scoring_cluster("LeastAllocated") == "n1"


def test_topology_report_flows_to_scheduler_numa_manager():
    """The koordlet's NodeResourceTopology report reaches the scheduler's
    NUMAManager through the informer hub (the reference NodeNUMAResource
    plugin consumes the CRD the same way): policy, zones, and
    kubelet-reserved CPUs all take effect."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
        NUMAPolicy,
    )

    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    sched = BatchScheduler(snap, batch_bucket=64, numa=numa)
    sched.extender.monitor.stop_background()
    hub = ClusterStateHub()
    hub.wire_scheduler(sched)
    hub.start()
    try:
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name="n0"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 65536}
                ),
            ),
        )
        # the koordlet builds the report; the hub carries it over
        si = StatesInformer(node_name="n0")
        topo = CPUTopology.uniform(
            sockets=2, numa_per_socket=1, cores_per_numa=4
        )
        report = si.report_topology(
            topo,
            kubelet_reserved=[0, 1],
            policy="SingleNUMANode",
            mem_per_numa_bytes=32768,
        )
        hub.publish(hub.topologies, report)
        assert hub.wait_synced()
        st = numa.node("n0")
        assert st is not None
        assert st.policy == NUMAPolicy.SINGLE_NUMA_NODE
        # kubelet-reserved CPUs are pre-taken and zone-charged
        assert st.accumulator.cpuset_of("kubelet-reserved") == {0, 1}
        assert st.zone_used[0][0] == 2000.0
        # an LSR pod scheduled through the hub-wired manager never gets
        # the reserved CPUs in its exclusive cpuset
        pod = Pod(
            meta=ObjectMeta(
                name="lsr", labels={ext.LABEL_POD_QOS: "LSR"}
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096},
                priority=9500,
            ),
        )
        out = sched.schedule([pod])
        assert len(out.bound) == 1
        from koordinator_tpu.core.topology import parse_cpuset
        import json as _json

        status = _json.loads(
            out.bound[0][0].meta.annotations[ext.ANNOTATION_RESOURCE_STATUS]
        )
        cpus = parse_cpuset(status["cpuset"])
        assert cpus.isdisjoint({0, 1})
        # topology delete unregisters the node
        hub.delete(hub.topologies, report)
        assert hub.wait_synced()
        assert numa.node("n0") is None
    finally:
        hub.stop()
