"""Tracing disabled-mode overhead guard + bench --trace round trip
(ISSUE 1 CI satellite).

The contract: with sampling off, ``tracer.span()`` returns a shared
no-op singleton — no Span allocation, no ring write, no lock — so the
permanent instrumentation of the hot scheduling path is free when nobody
is looking. ``bench.py --trace`` must emit a Chrome trace_event JSON
that chrome://tracing / Perfetto can load.
"""

import json
import time

from koordinator_tpu.obs import NULL_TRACER, Tracer


class TestDisabledModeOverhead:
    def test_disabled_span_is_shared_singleton(self):
        tr = Tracer(enabled=False)
        s1 = tr.span("a")
        s2 = tr.span("b", cat="x")
        assert s1 is s2, "disabled span() must not allocate per call"
        with s1:
            pass
        s1.set(k=1)  # arg sink is a no-op
        assert tr.records() == []
        assert NULL_TRACER.span("c") is s1

    def test_reenable_starts_recording_again(self):
        tr = Tracer(enabled=False)
        with tr.span("invisible"):
            pass
        tr.enabled = True
        with tr.span("visible"):
            pass
        assert [r.name for r in tr.records()] == ["visible"]

    def test_disabled_overhead_is_negligible(self):
        # Generous absolute bound: 100k disabled span() calls in well
        # under a second (one attribute read + singleton return each).
        # Catches accidental allocation/locking on the disabled path
        # without being flaky on slow CI hosts.
        tr = Tracer(enabled=False)
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("hot"):
                pass
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"{n} disabled spans took {elapsed:.2f}s"
        assert tr.records() == []

    def test_scheduler_emits_nothing_when_disabled(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.api.types import (
            Node,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from koordinator_tpu.scheduler.batch_solver import BatchScheduler

        s = BatchScheduler()
        s.extender.monitor.stop_background()
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name="n0"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000.0, ext.RES_MEMORY: 1e9}
                ),
            )
        )
        pod = Pod(
            meta=ObjectMeta(name="p", uid="p"),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000.0, ext.RES_MEMORY: 1e6},
                priority=9500,
            ),
        )
        out = s.schedule([pod])
        assert len(out.bound) == 1
        assert s.extender.tracer.records() == []
        # metrics keep flowing regardless of tracing state
        text = s.extender.services.dispatch("GET", "/metrics")[1]
        assert "koord_scheduler_cycle_latency_seconds_count 1" in text


class TestBenchTraceRoundTrip:
    def test_bench_trace_emits_valid_chrome_trace(
        self, tmp_path, monkeypatch, capsys
    ):
        import bench

        # shrink the fixture so the round trip runs in seconds on CPU
        monkeypatch.setattr(bench, "N_NODES", 64)
        monkeypatch.setattr(bench, "N_PODS", 256)
        monkeypatch.setattr(bench, "BATCH", 128)
        monkeypatch.setattr(bench, "MAX_ROUNDS", 4)
        monkeypatch.setattr(bench, "PASSES", 1)
        monkeypatch.setattr(bench, "BASELINE_PODS", 16)
        trace_path = tmp_path / "bench_trace.json"
        bench.main(["--trace", str(trace_path)])

        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["trace_file"] == str(trace_path)
        assert "stage_breakdown_ms" in out
        assert {"fixture", "baseline", "compile_warmup", "solve_pass"} <= set(
            out["stage_breakdown_ms"]
        )

        doc = json.loads(trace_path.read_text())
        events = doc["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in xs} >= {
            "fixture",
            "baseline",
            "compile_warmup",
            "solve_pass",
        }
        for e in xs:
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)

    def test_bench_without_trace_flag_emits_no_file(
        self, tmp_path, monkeypatch, capsys
    ):
        import bench

        monkeypatch.setattr(bench, "N_NODES", 64)
        monkeypatch.setattr(bench, "N_PODS", 256)
        monkeypatch.setattr(bench, "BATCH", 128)
        monkeypatch.setattr(bench, "MAX_ROUNDS", 4)
        monkeypatch.setattr(bench, "PASSES", 1)
        monkeypatch.setattr(bench, "BASELINE_PODS", 16)
        monkeypatch.chdir(tmp_path)
        bench.main([])
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "trace_file" not in out
        assert not (tmp_path / "bench_trace.json").exists()


class TestDevprofDisabledMode:
    """Devprof PR (PR 7 standing rule): disabled-mode solver-observatory
    instrumentation is ONE attribute-is-None check per hot-path site,
    and the trace-time hooks cost nothing once compiled."""

    def test_tracing_hook_is_free_without_a_ledger(self):
        from koordinator_tpu.obs import devprof

        assert not devprof._LEDGERS  # no test leaked an install
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            devprof.tracing("hot")
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"{n} uninstalled hooks took {elapsed:.2f}s"

    def test_null_watch_is_shared_singleton(self):
        from koordinator_tpu.obs.devprof import NULL_WATCH, _NullWatch

        assert isinstance(NULL_WATCH, _NullWatch)
        with NULL_WATCH as w:
            w.result(None)  # arg sink is a no-op

    def test_hot_path_sites_guard_on_attribute_is_none(self):
        """Every batch-solver hot-path site reads ``self.devprof`` into
        a local and branches on ``is not None`` — the same one-check
        discipline the tracer/lifecycle sites follow. No other hot-path
        spelling is allowed to creep in."""
        import inspect

        from koordinator_tpu.scheduler import batch_solver

        src = inspect.getsource(batch_solver)
        reads = src.count("dp = self.devprof")
        guards = src.count("if dp is not None")
        # every read is paired with at least one is-None guard; the
        # cycle shell guards twice (begin + end) on one read
        assert reads >= 6
        assert guards >= reads

    def test_scheduler_without_observatory_emits_nothing(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.api.types import (
            Node,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from koordinator_tpu.obs import devprof
        from koordinator_tpu.scheduler.batch_solver import BatchScheduler

        s = BatchScheduler()
        s.extender.monitor.stop_background()
        assert s.devprof is None
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name="n0"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000.0, ext.RES_MEMORY: 1e9}
                ),
            )
        )
        pod = Pod(
            meta=ObjectMeta(name="p", uid="p"),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000.0, ext.RES_MEMORY: 1e6},
                priority=9500,
            ),
        )
        out = s.schedule([pod])
        assert len(out.bound) == 1
        assert not devprof._LEDGERS
        text = s.extender.services.dispatch("GET", "/metrics")[1]
        assert "solver_compiles_total" not in text
        assert "solver_device_bytes" not in text


class TestOverloadDisabledMode:
    """Overload-control PR (PR 7 standing-rule discipline): with no
    AdmissionController/BrownoutController wired, every hot-path site
    is ONE attribute-is-None check — no band accounting, no sweep, no
    deferred queue, no ladder reads."""

    def test_hot_path_sites_guard_on_attribute_is_none(self):
        import inspect

        from koordinator_tpu.scheduler import (
            batch_solver,
            pipeline,
            stream,
        )

        src = inspect.getsource(stream)
        # the band accounting helper and the sweep both bail on the one
        # attribute check; submit reads it into a local once
        assert src.count("if self.overload is None") >= 1
        assert src.count("ov = self.overload") >= 2
        assert "if ov is None or not self._deferred" in src
        # the scheduler's bucket degrade and the pipeline's depth cap /
        # serial gate read `brownout` into a local and branch on is-None
        bs = inspect.getsource(batch_solver)
        assert "bo = self.brownout" in bs
        pl = inspect.getsource(pipeline)
        assert pl.count("bo = sched.brownout") >= 2
        assert pl.count("if bo is not None") >= 1

    def test_stream_without_overload_does_no_band_accounting(self):
        from koordinator_tpu.api import extension as ext
        from koordinator_tpu.api.types import (
            Node,
            NodeStatus,
            ObjectMeta,
            Pod,
            PodSpec,
        )
        from koordinator_tpu.scheduler.batch_solver import (
            BatchScheduler,
            LoadAwareArgs,
        )
        from koordinator_tpu.scheduler.stream import StreamScheduler

        s = BatchScheduler(args=LoadAwareArgs(usage_thresholds={}))
        s.extender.monitor.stop_background()
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name="n0"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000.0, ext.RES_MEMORY: 1e9}
                ),
            )
        )
        st = StreamScheduler(s)
        assert st.overload is None and s.brownout is None
        verdict = st.submit(
            Pod(
                meta=ObjectMeta(name="p", uid="p"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 1000.0, ext.RES_MEMORY: 1e6},
                    priority=3500,  # FREE — still always admitted
                ),
            )
        )
        assert verdict == "admit"
        assert st._band_live == {} and st.deferred_backlog() == 0
        out = st.pump()
        assert len(out) == 1 and out[0][1] is not None
        reg = s.extender.registry
        assert reg.get("overload_shed_total").value(band="FREE") == 0.0
        assert reg.get("brownout_level").value() == 0.0


class TestDecisionLedgerDisabledMode:
    """Decision-observatory PR: with no DecisionLedger wired, every
    controller record site is ONE attribute-is-None check — no snapshot
    copies for shadows, no store writes, no metric labels. With one
    wired, memory is bounded: the ring holds ``capacity`` records and
    store compaction keeps the journal under the 2x-capacity rewrite
    bound even through a storm-shaped burst."""

    def test_record_sites_guard_on_attribute_is_none(self):
        """Every controller record site reads ``self.decisions`` into a
        local ``dl`` and branches on ``is not None`` — the same
        one-check discipline as the devprof/overload sites."""
        import inspect

        from koordinator_tpu.runtime import elastic, overload
        from koordinator_tpu.scheduler import pipeline

        for mod, min_sites in ((pipeline, 1), (overload, 3), (elastic, 1)):
            src = inspect.getsource(mod)
            reads = src.count("dl = self.decisions")
            # attach_flight's wiring path branches on the opposite
            # polarity (creates the default ledger); every read still
            # pairs with exactly one is-None branch
            guards = src.count("if dl is not None") + src.count(
                "if dl is None"
            )
            assert reads >= min_sites, mod.__name__
            assert guards >= reads, mod.__name__

    def test_controllers_without_ledger_record_nothing(self):
        from koordinator_tpu.runtime.overload import (
            BrownoutController,
            CircuitBreaker,
        )
        from koordinator_tpu.scheduler.pipeline import _DepthController

        dc = _DepthController(max_depth=4)
        bo = BrownoutController(clock=lambda: 0.0)
        cb = CircuitBreaker(clock=lambda: 0.0)
        assert dc.decisions is None
        assert bo.decisions is None and cb.decisions is None
        for _ in range(5):
            dc.choose()
            bo.tick()
            cb.allow()
        assert dc.decisions is None  # nothing lazily created
        assert bo.decisions is None and cb.decisions is None

    def test_disabled_overhead_is_negligible(self):
        from koordinator_tpu.scheduler.pipeline import _DepthController

        dc = _DepthController(max_depth=4)
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            dc.choose()
        elapsed = time.perf_counter() - t0
        assert elapsed < 2.0, f"{n} unledgered chooses took {elapsed:.2f}s"

    def test_storm_burst_memory_is_bounded(self):
        from koordinator_tpu.core.journal import MemoryJournalStore
        from koordinator_tpu.obs.decisions import DecisionLedger

        store = MemoryJournalStore()
        cap = 32
        dl = DecisionLedger(store, capacity=cap)
        # a storm-shaped burst: ~100x capacity decisions in a tight loop
        for i in range(100 * cap):
            dl.record(
                "admission", i + 1,
                {"band": "FREE", "band_depth": i % 7},
                {"verdict": "shed"}, {},
            )
        assert len(dl.last()) == cap            # ring: exactly capacity
        assert len(store.load()) <= 2 * cap     # store: rewrite bound
        # the retained tail is the newest, gap-free
        from koordinator_tpu.obs.decisions import controller_gaps

        assert controller_gaps(dl.last()) == {}
        assert dl.last(1)[0]["cseq"] == 100 * cap
