"""Horizontally partitioned control plane tests (PR 6 tentpole).

Covers: stable shard partitioning; multi-standby election (3+ candidates
racing a lapsed shard lease admit exactly one, per-shard epochs stay
monotonic, a deposed owner's queued commit is fenced with
STALE_LEADER_EPOCH); rendezvous rebalancing + shard handoff with queue
continuity across owners; cross-shard single-winner claims under
fan-out; per-shard channel fencing; and the exact NUMA-zone / GPU-slot
hold journal coverage with bit-exact recovery (kill mid-commit with
device holds outstanding).
"""

import json

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Device,
    DeviceInfo,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.journal import (
    BindJournal,
    MemoryJournalStore,
    StaleEpochError,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.obs.rejections import RejectReason
from koordinator_tpu.runtime.recovery import recover_scheduler
from koordinator_tpu.runtime.shards import (
    Membership,
    ShardFabric,
    ShardRouter,
    ShardedScheduler,
    ShardMap,
)
from koordinator_tpu.runtime.statehub import ClusterStateHub
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.utils.leaderelection import (
    InMemoryLeaseLock,
    LeaderElector,
    preferred_candidate,
)

N_NODES = 12
N_SHARDS = 4


def _node(name, cpu=32_000.0, mem=128 * 1024.0):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _pod(name, cpu=2000.0, mem=4096.0):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}, priority=9000
        ),
    )


def _make_scheduler(shard, snapshot, fence, journal):
    s = BatchScheduler(
        snapshot,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=16,
        journal=journal,
        fence=fence,
    )
    s.extender.monitor.stop_background()
    return s


class _World:
    """Shared fabric + hub + a simulated cycle clock."""

    def __init__(self, n_shards=N_SHARDS, n_nodes=N_NODES):
        self.t = [0.0]
        self.fabric = ShardFabric(
            n_shards, clock=lambda: self.t[0], membership_ttl_s=2.5
        )
        self.hub = ClusterStateHub()
        self.node_names = [f"n{i:03d}" for i in range(n_nodes)]
        for name in self.node_names:
            self.hub.publish(self.hub.nodes, _node(name))

    def incarnation(self, name, pipelined=False):
        return ShardedScheduler(
            name,
            self.hub,
            self.fabric,
            _make_scheduler,
            pipelined=pipelined,
            max_batch=32,
            lease_duration=3.0,
            renew_deadline=2.0,
            retry_period=0.5,
        )

    def advance(self, dt=1.0):
        self.t[0] += dt


# ---------------------------------------------------------------------------
# ShardMap / router
# ---------------------------------------------------------------------------


def test_shard_map_partition_covers_and_is_stable():
    m = ShardMap(N_SHARDS)
    names = [f"n{i:03d}" for i in range(64)]
    part = m.partition(names)
    assert sorted(sum(part.values(), [])) == sorted(names)
    # stable across instances (no process-seeded hashing)
    m2 = ShardMap(N_SHARDS)
    assert all(
        m.shard_of_node(n) == m2.shard_of_node(n) for n in names
    )
    flt = m.node_filter(1)
    assert all(flt(n) == (m.shard_of_node(n) == 1) for n in names)


def test_router_quota_home_and_spill_targets():
    m = ShardMap(N_SHARDS)
    router = ShardRouter(m, spill_backlog=4)
    q_pod = Pod(
        meta=ObjectMeta(
            name="q1", labels={ext.LABEL_QUOTA_NAME: "team-a"}
        ),
        spec=PodSpec(requests={ext.RES_CPU: 1000.0}),
    )
    home = m.shard_of_key("quota:team-a")
    assert router.route(q_pod) == home
    # quota-homed pods never spill — one ledger owns the charge
    assert router.targets(q_pod, backlog_of=lambda s: 100) == [home]
    free = _pod("free-1")
    primary = router.route(free)
    assert router.targets(free, backlog_of=lambda s: 0) == [primary]
    spilled = router.targets(free, backlog_of=lambda s: 10)
    assert spilled[0] == primary and len(spilled) == 2
    assert spilled[1] != primary
    # a node-pinned pod routes to its node's shard, never spills
    pinned = _pod("pin-1")
    pinned.spec.node_name = "n003"
    assert router.targets(pinned, backlog_of=lambda s: 100) == [
        m.shard_of_node("n003")
    ]


# ---------------------------------------------------------------------------
# Multi-standby election (satellite)
# ---------------------------------------------------------------------------


def test_three_candidates_racing_lapsed_lease_admit_exactly_one():
    """3+ candidates racing a lapsed shard lease: exactly one wins the
    CAS, and the winner's epoch is the dead owner's + 1 (per-shard
    monotonic)."""
    t = [0.0]
    lock = InMemoryLeaseLock()

    def elector(ident):
        return LeaderElector(
            lock,
            ident,
            lease_duration=3.0,
            renew_deadline=2.0,
            now_fn=lambda: t[0],
            sleep_fn=lambda _dt: None,
        )

    old = elector("old-owner")
    assert old.try_acquire_or_renew() and old.current_epoch() == 1
    t[0] = 10.0  # the owner died; its lease lapsed
    racers = [elector(f"standby-{i}") for i in range(3)]
    results = [e.try_acquire_or_renew() for e in racers]
    assert sum(results) == 1, "exactly one racer may win the CAS"
    winner = racers[results.index(True)]
    assert winner.current_epoch() == 2
    # the losers observe the new grant; none of them holds an epoch
    assert all(
        e.current_epoch() is None for e in racers if e is not winner
    )
    # a second race while the fresh lease is live admits nobody
    assert not any(
        e.try_acquire_or_renew() for e in racers if e is not winner
    )


def test_rendezvous_election_spreads_dead_members_shards():
    """The rendezvous ranking is deterministic, total, and re-points to
    survivors when a member dies — no coordination round needed."""
    members = ["inc-a", "inc-b", "inc-c"]
    assign = {
        s: preferred_candidate(members, f"shard-{s}") for s in range(6)
    }
    assert set(assign.values()) == set(members)  # everyone got shards
    survivors = ["inc-a", "inc-c"]
    reassign = {
        s: preferred_candidate(survivors, f"shard-{s}") for s in range(6)
    }
    for s in range(6):
        if assign[s] in survivors:
            assert reassign[s] == assign[s]  # stable for survivors
        else:
            assert reassign[s] in survivors  # dead member's spread
    # the dead member's shards do not all dogpile one survivor
    took = [s for s in range(6) if assign[s] == "inc-b"]
    assert len({reassign[s] for s in took}) > 1 or len(took) <= 1


def test_membership_ttl_expires_silent_members():
    t = [0.0]
    m = Membership(2.5, clock=lambda: t[0])
    m.heartbeat("a")
    m.heartbeat("b")
    assert m.alive() == ["a", "b"]
    t[0] = 2.0
    m.heartbeat("b")
    t[0] = 4.0
    assert m.alive() == ["b"]


# ---------------------------------------------------------------------------
# Sharded control plane end-to-end
# ---------------------------------------------------------------------------


def _settle(world, incs, ticks=3):
    for _ in range(ticks):
        world.advance(1.0)
        for inc in incs:
            inc.tick()


def test_concurrent_owners_schedule_disjoint_shards():
    world = _World()
    a = world.incarnation("inc-a")
    b = world.incarnation("inc-b")
    world.fabric.membership.heartbeat("inc-a")
    world.fabric.membership.heartbeat("inc-b")
    try:
        _settle(world, [a, b])
        owned_a, owned_b = set(a.owned()), set(b.owned())
        assert owned_a and owned_b, "both incarnations must own shards"
        assert not (owned_a & owned_b), "shard ownership must be disjoint"
        assert owned_a | owned_b == set(range(N_SHARDS))
        router = ShardRouter(world.fabric.shard_map)
        placed = {}
        pods = [_pod(f"p{i:03d}") for i in range(24)]
        for pod in pods:
            s = router.route(pod)
            owner = a if a.owns(s) else b
            assert owner.submit(s, pod)
        for inc in (a, b):
            for s, pod, node, _lat in inc.pump() + inc.flush():
                assert node is not None
                assert pod.meta.uid not in placed
                placed[pod.meta.uid] = node
                # shard-correct: bound on a node the serving shard owns
                assert world.fabric.shard_map.shard_of_node(node) == s
        assert len(placed) == len(pods)
    finally:
        a.close()
        b.close()
        world.hub.stop()


def test_deposed_owner_queued_commit_fenced_stale_epoch():
    """A deposed shard owner that missed its own deposition (partition:
    it never saw the new grant) has its queued commit REJECTED at the
    commit boundary with the named STALE_LEADER_EPOCH reason and the
    leader_fenced_commits_total metric — never double-placed — while the
    new owner schedules the same shard under the new epoch."""
    world = _World()
    a = world.incarnation("inc-a")
    world.fabric.membership.heartbeat("inc-a")
    try:
        _settle(world, [a])
        assert set(a.owned()) == set(range(N_SHARDS))
        # b joins; a is partitioned (stops ticking/renewing/heartbeating)
        b = world.incarnation("inc-b")
        world.advance(4.0)  # a's leases lapse, its membership expires
        for _ in range(3):
            world.advance(1.0)
            b.tick()
        taken = set(b.owned())
        assert taken, "the survivor must have taken over lapsed shards"
        s = sorted(taken)[0]
        assert world.fabric.fences[s].current() == 2
        # the partitioned owner still BELIEVES it owns s…
        assert a.owns(s)
        pod = _pod("fenced-pod")
        assert a.submit(s, pod)
        decided = a.pump()
        fenced = [
            (sh, p, n) for sh, p, n, _l in decided if p.meta.uid == pod.meta.uid
        ]
        # …but its commit is fenced: the pod comes back undecided (it
        # retries) or terminally unschedulable — NEVER bound
        assert all(n is None for _sh, _p, n in fenced)
        rt = a.runtime(s)
        reg = rt.sched.extender.registry
        assert reg.get("leader_fenced_commits_total").value() >= 1.0
        reasons = {
            r.reason for r in rt.sched.extender.rejections.records()
        }
        assert RejectReason.STALE_LEADER_EPOCH in reasons
        # per-shard epochs stayed monotonic; untouched shards unaffected
        assert world.fabric.fences[s].current() == 2
        for other in range(N_SHARDS):
            if other not in taken:
                assert world.fabric.fences[other].current() == 1
        b.close()
    finally:
        a.close()
        world.hub.stop()


def test_pump_skips_cycle_when_gate_drops_whole_batch():
    """A queue whose every pod lost its claim to another shard must not
    cost a scheduler cycle: pump() returns no decisions AND the cycle id
    does not advance (no snapshot lock, no tracer span, no begin_cycle)
    when the feed gate empties the batch."""
    from koordinator_tpu.scheduler.stream import StreamScheduler

    snap = ClusterSnapshot()
    snap.upsert_node(_node("n000"))
    sched = BatchScheduler(
        snap, LoadAwareArgs(usage_thresholds={}), batch_bucket=16
    )
    sched.extender.monitor.stop_background()
    stream = StreamScheduler(sched, max_batch=8, feed_gate=lambda pod: False)
    for i in range(4):
        stream.submit(_pod(f"lost{i}"))
    before = sched.extender.current_cycle_id
    assert stream.pump() == []
    assert sched.extender.current_cycle_id == before
    assert stream.backlog() == 0  # the claim-lost pods were dropped


def test_handoff_log_is_bounded():
    # the fabric outlives every incarnation; its seam log must not —
    # unlike the shared stores — grow for the fabric's whole lifetime
    from koordinator_tpu.runtime.shards import ShardFabric

    fabric = ShardFabric(2, handoff_log_cap=4)
    for i in range(10):
        fabric.handoff_log.append(
            {"shard": 0, "t_out": float(i), "t_in": float(i),
             "from": "a", "to": "b"}
        )
    assert len(fabric.handoff_log) == 4
    assert fabric.handoff_log[0]["t_out"] == 6.0  # oldest seams evicted


def test_graceful_close_releases_leases_and_membership():
    """Graceful ``close()`` must never behave worse than a crash: every
    owned shard's lease is RELEASED (a successor acquires immediately
    instead of waiting out the TTL) and the incarnation leaves the
    membership table, so a driver's ``_owner_of`` stops routing pods at
    the closed process."""
    world = _World()
    a = world.incarnation("inc-a")
    world.fabric.membership.heartbeat("inc-a")
    try:
        _settle(world, [a])
        assert set(a.owned()) == set(range(N_SHARDS))
        handoffs = a.close()
        assert set(handoffs) == set(range(N_SHARDS))
        assert not any(a.owns(s) for s in range(N_SHARDS))
        assert "inc-a" not in world.fabric.membership.alive()
        # a successor takes every shard over while well inside the lease
        # duration (3.0s): total elapsed below stays at 1.5s, so this
        # only works because close() surrendered the leases
        b = world.incarnation("inc-b")
        world.fabric.membership.heartbeat("inc-b")
        for _ in range(3):
            world.advance(0.5)
            b.tick()
        assert set(b.owned()) == set(range(N_SHARDS))
        for s in range(N_SHARDS):
            assert world.fabric.fences[s].current() == 2
        b.close()
    finally:
        world.hub.stop()


def test_shard_handoff_queue_continuity_and_journal_across_owners():
    """Voluntary handoff (rendezvous rebalance): the donor's queued pods
    move to the new owner with arrival stamps intact, binds from BOTH
    owners coexist in the shard journal under their respective epochs,
    and nothing is placed twice. The donor's other shards keep serving."""
    world = _World()
    a = world.incarnation("inc-a")
    world.fabric.membership.heartbeat("inc-a")
    try:
        _settle(world, [a])  # a owns everything (sole member)
        # bind one pod per shard under epoch 1
        router = ShardRouter(world.fabric.shard_map)
        placed = {}
        first = [_pod(f"early-{i:02d}") for i in range(8)]
        for pod in first:
            assert a.submit(router.route(pod), pod)
        for s, pod, node, _l in a.pump() + a.flush():
            assert node is not None
            placed[pod.meta.uid] = node
        # queue MORE pods, then b joins → rendezvous reassigns some
        # shards → a voluntarily hands them off with queues intact
        second = [_pod(f"late-{i:02d}") for i in range(12)]
        for pod in second:
            assert a.submit(router.route(pod), pod)
        b = world.incarnation("inc-b")
        world.fabric.membership.heartbeat("inc-b")
        handed = {}
        for _ in range(6):
            world.advance(1.0)
            for s, hand in a.tick().items():
                for pod, arr, tries in hand.queued:
                    handed[pod.meta.uid] = (s, pod, arr, tries)
                for pod, node, _l in hand.decided:
                    if node is not None:
                        assert pod.meta.uid not in placed
                        placed[pod.meta.uid] = node
            b.tick()
            # the new owner takes the queue over, stamps intact
            for uid, (s, pod, arr, tries) in list(handed.items()):
                if b.resubmit(s, pod, arr, tries):
                    handed.pop(uid)
        assert b.owned(), "the joiner must have taken over shards"
        assert a.owned(), "the donor's other shards keep serving"
        assert not handed, "every handed-off pod must re-enqueue"
        for inc in (a, b):
            for s, pod, node, _l in inc.pump() + inc.flush():
                if node is not None:
                    assert pod.meta.uid not in placed, "double placement"
                    placed[pod.meta.uid] = node
        assert len(placed) == len(first) + len(second)
        # journal continuity per shard: replay live == placed-on-shard,
        # with records under BOTH epochs where ownership moved
        for s in b.owned():
            rep = BindJournal(world.fabric.journal_stores[s]).replay()
            for uid, entry in rep.live.items():
                assert placed[uid] == entry["node"]
            epochs = {
                r["epoch"]
                for r in world.fabric.journal_stores[s].load()
                if r["op"] == "bind"
            }
            if any(
                world.fabric.shard_map.shard_of_node(placed[p.meta.uid]) == s
                for p in first
                if p.meta.uid in placed
            ):
                assert 1 in epochs, "donor-era binds survive in the log"
        b.close()
    finally:
        a.close()
        world.hub.stop()


def test_deposed_owner_queued_pods_survive_to_handoff():
    """A deposed owner whose claim authority is gone (the new owner has
    claimed under the next epoch) must KEEP its queued pods for the
    handoff — dropping them like claim-losers would lose pods nobody
    else holds."""
    world = _World()
    a = world.incarnation("inc-a")
    world.fabric.membership.heartbeat("inc-a")
    try:
        _settle(world, [a])
        b = world.incarnation("inc-b")
        world.advance(4.0)  # a partitioned: leases lapse, membership out
        for _ in range(3):
            world.advance(1.0)
            b.tick()
        s = sorted(b.owned())[0]
        # the new owner claims a pod on s → claim epoch high becomes 2
        probe = _pod("b-probe")
        assert b.submit(s, probe)
        assert any(
            n is not None for _s, p, n, _l in b.pump()
            if p.meta.uid == probe.meta.uid
        )
        # the partitioned donor still queues pods for s…
        stale_pods = [_pod(f"stale-{i}") for i in range(5)]
        for pod in stale_pods:
            assert a.submit(s, pod)
        # …its pump must neither bind, drop, nor decide them
        decided = {p.meta.uid for _s, p, _n, _l in a.pump()}
        assert not ({p.meta.uid for p in stale_pods} & decided)
        assert a.backlog(s) == len(stale_pods)
        # the handoff surfaces every one of them for the new owner
        world.advance(1.0)
        handoffs = a.tick()
        assert s in handoffs
        handed = {p.meta.uid for p, _arr, _t in handoffs[s].queued}
        assert handed == {p.meta.uid for p in stale_pods}
        b.close()
    finally:
        a.close()
        world.hub.stop()


def test_fanout_claim_single_winner_binds_once():
    """A pod fanned out to TWO shards' queues is bound exactly once: the
    first pump wins the claim, the other shard's pump drops its copy."""
    world = _World()
    a = world.incarnation("inc-a")
    b = world.incarnation("inc-b")
    world.fabric.membership.heartbeat("inc-a")
    world.fabric.membership.heartbeat("inc-b")
    try:
        _settle(world, [a, b])
        sa, sb = sorted(a.owned())[0], sorted(b.owned())[0]
        pod = _pod("fanout-1")
        assert a.submit(sa, pod)
        assert b.submit(sb, pod)  # fan-out: both queues hold it
        decided_a = a.pump()
        decided_b = b.pump()
        bound = [
            (s, n)
            for s, p, n, _l in decided_a + decided_b
            if p.meta.uid == pod.meta.uid and n is not None
        ]
        assert len(bound) == 1, f"bound {len(bound)} times: {bound}"
        winner_shard = world.fabric.claims.winner(pod.meta.uid)
        assert winner_shard == bound[0][0] == sa  # a pumped first
        assert b.stats["claims_lost"] >= 1
    finally:
        a.close()
        b.close()
        world.hub.stop()


# ---------------------------------------------------------------------------
# Per-shard channel fencing
# ---------------------------------------------------------------------------


def test_snapshot_channel_per_shard_epoch_fencing():
    """x-shard-id scopes the channel fence: shard 0's takeover (epoch 2)
    must refuse shard 0's deposed owner but NOT shard 1's still-live
    epoch-1 owner."""
    from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
    from koordinator_tpu.runtime.snapshot_channel import (
        ChannelFenced,
        SolverClient,
        SolverService,
        serve,
    )

    service = SolverService()
    service.scheduler.extender.monitor.stop_background()
    server, port = serve(service)
    s0_new = SolverClient(f"127.0.0.1:{port}")
    s0_old = SolverClient(f"127.0.0.1:{port}")
    s1 = SolverClient(f"127.0.0.1:{port}")
    try:
        s0_new.set_epoch(2, shard=0)
        s0_old.set_epoch(1, shard=0)
        s1.set_epoch(1, shard=1)
        delta = pb.SnapshotDelta(revision=1)
        delta.node_upserts.add(
            name="n0", allocatable=pb.ResourceVector(values=[32000.0])
        )
        assert s0_new.sync(delta).applied_revision == 1
        assert service.shard_epochs == {0: 2}
        with pytest.raises(ChannelFenced):
            s0_old.sync(pb.SnapshotDelta(revision=2))
        # shard 1's epoch-1 owner is NOT fenced by shard 0's epoch 2
        ack = s1.sync(pb.SnapshotDelta(revision=2))
        assert ack.applied_revision == 2
        assert service.shard_epochs == {0: 2, 1: 1}
    finally:
        s0_new.close()
        s0_old.close()
        s1.close()
        server.stop(grace=None)


# ---------------------------------------------------------------------------
# Exact NUMA-zone / GPU-slot hold journal coverage (satellite)
# ---------------------------------------------------------------------------


def _gpu_world(store):
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager

    snap = ClusterSnapshot()
    snap.upsert_node(_node("g0", cpu=64000.0, mem=262144.0))
    dm = DeviceManager(snap)
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="g0"),
            devices=[DeviceInfo(dev_type="gpu", minor=g) for g in range(4)],
        )
    )
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=8,
        devices=dm,
        journal=BindJournal(store),
    )
    sched.extender.monitor.stop_background()
    return snap, dm, sched


def _gpu_pod(name, whole):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(
            requests={
                ext.RES_CPU: 1000.0,
                ext.RES_MEMORY: 1024.0,
                ext.RES_GPU: whole,
            },
            priority=9000,
        ),
    )


def test_bind_journal_carries_exact_gpu_slots_and_recovery_restores():
    """Kill with device holds outstanding: the bind records carry the
    EXACT minors, and a fresh instance's recovery restores them — a new
    allocation cannot steal the dead leader's slots."""
    store = MemoryJournalStore()
    _snap, dm, sched = _gpu_world(store)
    out = sched.schedule([_gpu_pod("gp-1", 2), _gpu_pod("gp-2", 1)])
    assert len(out.bound) == 2
    held = {
        p.meta.uid: sorted(
            m for m, _pct, _c in dm.node("g0").owners[p.meta.uid]
        )
        for p, _n in out.bound
    }
    # journal carries the exact slot indices
    journaled = {}
    for rec in store.load():
        if rec["op"] == "bind":
            for e in rec["binds"]:
                journaled[e["uid"]] = sorted(
                    m for m, _p, _c in e["dev"]["gpu"]
                )
    assert journaled == held
    # process death: fresh snapshot/manager/scheduler, same store
    snap2, dm2, sched2 = _gpu_world(store)
    rep = recover_scheduler(sched2, BindJournal(store), hub=None)
    assert rep.replayed == 2
    st = dm2.node("g0")
    for uid, minors in held.items():
        assert sorted(m for m, _p, _c in st.owners[uid]) == minors
    # 3 of 4 gpus held → a 2-gpu pod must NOT fit on the free remainder
    assert dm2.allocate(_gpu_pod("thief", 2), "g0") is None
    assert dm2.allocate(_gpu_pod("fits", 1), "g0") is not None


def test_crash_mid_commit_device_holds_not_resurrected():
    """commit.crash after Reserve: the chunk rolls back (abort record),
    so recovery must restore NOTHING for it — the rolled-back pod's
    minors stay free on the recovered instance."""
    store = MemoryJournalStore()
    chaos = FaultInjector(seed=0)
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager

    snap = ClusterSnapshot()
    snap.upsert_node(_node("g0", cpu=64000.0, mem=262144.0))
    dm = DeviceManager(snap)
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name="g0"),
            devices=[DeviceInfo(dev_type="gpu", minor=g) for g in range(4)],
        )
    )
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=8,
        devices=dm,
        chaos=chaos,
        journal=BindJournal(store),
    )
    sched.extender.monitor.stop_background()
    chaos.arm("commit.crash", error=RuntimeError, times=1)
    out = sched.schedule([_gpu_pod("doomed", 2)])
    assert out.bound == []  # rolled back
    assert "doomed" not in "".join(
        e["uid"] for r in store.load() if r["op"] == "bind"
        for e in r["binds"]
    )
    snap2, dm2, sched2 = _gpu_world(store)
    rep = recover_scheduler(sched2, BindJournal(store), hub=None)
    assert rep.replayed == 0
    assert dm2.node("g0").gpu_free == [100.0] * 4
    assert rep.open_intents == 0  # the abort record closed the intent


def test_bind_journal_carries_numa_zone_and_cpuset_and_restores():
    """LSR pod with an exclusive cpuset: the bind record carries the
    chosen zone + cpu ids, and recovery re-installs the zone charge and
    the cpuset reservation bit-exactly."""
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
    )

    def build(store):
        snap = ClusterSnapshot()
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name="m0"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: 16000.0,
                        ext.RES_MEMORY: 32768.0,
                    }
                ),
            )
        )
        numa = NUMAManager(snap)
        numa.register_node(
            "m0",
            CPUTopology.uniform(
                sockets=2, numa_per_socket=1, cores_per_numa=4
            ),
            memory_per_zone_mib=16384.0,
        )
        sched = BatchScheduler(
            snap,
            LoadAwareArgs(usage_thresholds={}),
            batch_bucket=8,
            numa=numa,
            journal=BindJournal(store),
        )
        sched.extender.monitor.stop_background()
        return snap, numa, sched

    store = MemoryJournalStore()
    _snap, numa, sched = build(store)
    pod = Pod(
        meta=ObjectMeta(
            name="lsr-1", labels={ext.LABEL_POD_QOS: "LSR"}
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 4000.0, ext.RES_MEMORY: 2048.0},
            priority=9500,
        ),
    )
    out = sched.schedule([pod])
    assert len(out.bound) == 1
    hold = numa.hold_of(pod.meta.uid, "m0")
    assert hold is not None and len(hold["cpus"]) == 4
    entry = next(
        e
        for r in store.load()
        if r["op"] == "bind"
        for e in r["binds"]
        if e["uid"] == pod.meta.uid
    )
    assert entry["numa"]["cpus"] == hold["cpus"]
    assert entry["numa"]["zone"] == hold["zone"]
    # fresh instance recovers the exact zone + cpuset
    _snap2, numa2, sched2 = build(store)
    recover_scheduler(sched2, BindJournal(store), hub=None)
    hold2 = numa2.hold_of(pod.meta.uid, "m0")
    assert hold2 == hold
    st = numa2.node("m0")
    assert st.zone_used[hold["zone"]][0] == pytest.approx(
        hold["zreq"][0]
    )
    # the recovered cpuset is reserved: a full-node LSR pod that would
    # need those cpus cannot take them
    taken = set(hold["cpus"])
    assert not (
        set(
            st.accumulator.take("probe", 8, policy=None)
            or ()
        )
        & taken
    )


# ---------------------------------------------------------------------------
# ClaimTable tombstone GC (open-the-gates PR satellite; PR 6 follow-on)
# ---------------------------------------------------------------------------


def test_claim_tombstone_gc_retention_and_reload():
    """Tombstone GC: settled uids OLDER than the retention window are
    compacted away; INSIDE the window a post-GC claim on a settled uid
    still LOSES (a backlogged queue copy must never re-schedule a dead
    pod), and a reload from the compacted store preserves both the
    retained tombstones and every shard's claim-epoch high (fencing must
    not weaken across GC + restart)."""
    from koordinator_tpu.core.journal import ClaimTable

    now = [1000.0]
    store = MemoryJournalStore()
    table = ClaimTable(store, clock=lambda: now[0])
    assert table.claim("old-uid", 0, epoch=5)
    assert table.claim("young-uid", 1, epoch=7)
    assert table.claim("live-uid", 0, epoch=5)
    table.release("old-uid")          # settled at t=1000
    now[0] = 1900.0
    table.release("young-uid")        # settled at t=1900
    assert table.tombstones_live() == 2
    now[0] = 2000.0
    live = table.gc_tombstones(retention_s=500.0)  # cutoff t=1500
    assert live == 1
    # inside the window: the young tombstone still loses a claim
    assert table.claim("young-uid", 2, epoch=1) is False
    # outside the window: the uid is genuinely forgotten (fresh claims
    # may win — the retention contract is the queue-lifetime bound)
    assert table.claim("old-uid", 2, epoch=1) is True
    # reload from the compacted store: tombstone + winners + epoch highs
    reloaded = ClaimTable(store, clock=lambda: now[0])
    assert reloaded.claim("young-uid", 2, epoch=1) is False
    assert reloaded.winner("live-uid") == 0
    with pytest.raises(StaleEpochError):
        # shard 1's epoch high (7) survived even though its only claim
        # record was for a tombstoned uid
        reloaded.claim("new-uid", 1, epoch=6)


def test_claim_tombstone_gc_rides_journal_compaction():
    """Wiring: a shard's run-loop journal compaction fires the fabric's
    claim tombstone GC and publishes claim_tombstones_live."""
    world = _World()
    a = world.incarnation("inc-a")
    world.fabric.membership.heartbeat("inc-a")
    try:
        _settle(world, [a])
        shard = sorted(a.owned())[0]
        rt = a.runtime(shard)
        sched = rt.sched
        # aggressive threshold so one cycle's records trip compaction
        sched.journal_compact_records = 1
        claims = world.fabric.claims
        now = world.fabric.clock()
        assert claims.claim("dead-pod", shard, sched._fence_epoch)
        claims.release("dead-pod")
        assert claims.tombstones_live() == 1
        # retention 0 with a clock far in the future: the tombstone is
        # GC-eligible the moment compaction fires
        a.claim_tombstone_retention_s = -1.0
        pod = _pod("compact-driver")
        assert a.submit(shard, pod)
        a.pump()
        a.flush()
        assert claims.tombstones_live() == 0
        gauge = sched.extender.registry.get("claim_tombstones_live")
        assert gauge.value() == 0.0
    finally:
        a.close()
        world.hub.stop()
