"""Systematic concurrency harness across the daemon loops.

The reference runs `go test -race` over lock-based components (SURVEY §5);
CPython has no TSan, so this harness drives every concurrently-touched
structure from racing threads and asserts post-conditions — torn
iteration, dict-mutation-during-iteration, and lost-update bugs all
surface as exceptions or violated invariants under this load.

Covered surfaces: MetricCache (append/aggregate/gc/checkpoint),
StatesInformer (setters vs readers vs callback registration), the
ResourceExecutor's serialized audited writes, the koordlet daemon's
collect/qos/report ticks racing pod updates, and the gRPC snapshot
channel under concurrent Sync + Nominate (complementing
test_snapshot_channel's consistency test).
"""

import threading
import time

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec


def run_racers(fns, duration_s=1.0, threads_per_fn=2):
    """Run each fn in a loop from several threads; re-raise any error."""
    stop = threading.Event()
    errors = []

    def runner(fn):
        try:
            while not stop.is_set():
                fn()
        except Exception as e:  # noqa: BLE001 — the harness reports all
            errors.append(e)
            stop.set()

    ts = [
        threading.Thread(target=runner, args=(fn,))
        for fn in fns
        for _ in range(threads_per_fn)
    ]
    for t in ts:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in ts:
        t.join(timeout=10)
    # a deadlocked component must FAIL the harness, not time out silently
    assert not any(t.is_alive() for t in ts), "racer thread deadlocked"
    if errors:
        raise errors[0]


def test_metriccache_races(tmp_path):
    from koordinator_tpu.koordlet.metriccache import MetricCache

    mc = MetricCache(capacity_per_series=256)
    clock = {"t": 0.0}
    lock = threading.Lock()

    def writer():
        with lock:
            clock["t"] += 1.0
            t = clock["t"]
        mc.append("cpu", "node", t, t * 2.0)
        mc.append_many([("mem", "node", t, t * 3.0)])

    def aggregator():
        agg = mc.aggregate("cpu", "node", 0, 1e12)
        if agg is not None:
            assert agg.count > 0

    def checkpointer():
        mc.checkpoint(str(tmp_path / "ck.npz"))

    def collector():
        mc.gc(before=clock["t"] - 10_000)

    run_racers([writer, aggregator, checkpointer, collector], duration_s=1.0)
    # post-condition: the surviving series is internally consistent
    back = MetricCache.restore(str(tmp_path / "ck.npz"))
    ring = back._series.get(("cpu", "node"))
    if ring is not None and ring.count:
        idx = np.arange(ring.head - ring.count, ring.head) % ring.ts.shape[0]
        np.testing.assert_allclose(ring.values[idx], ring.ts[idx] * 2.0)


def test_statesinformer_races():
    from koordinator_tpu.koordlet.statesinformer import StatesInformer, StateType

    si = StatesInformer(node_name="me")
    seen = []
    i = {"n": 0}

    def setter():
        i["n"] += 1
        pods = [
            Pod(meta=ObjectMeta(name=f"p{k}", namespace=f"ns{i['n'] % 3}"))
            for k in range(5)
        ]
        si.set_pods(pods)
        si.set_node(Node(meta=ObjectMeta(name="me")))

    def reader():
        pods = si.pods()
        # torn list would duplicate/drop: each view is exactly one batch
        assert len({p.meta.uid for p in pods}) == len(pods)
        si.node()

    def registrar():
        si.callbacks.register(StateType.ALL_PODS, "r", lambda v: seen.append(1))

    run_racers([setter, reader, registrar], duration_s=0.7)
    assert si.pods()


def test_resourceexecutor_serialized_writes(tmp_path):
    from koordinator_tpu.koordlet import resourceexecutor as rex

    executor = rex.ResourceExecutor(str(tmp_path))
    k = {"n": 0}

    def applier():
        k["n"] += 1
        executor.apply(
            [("kubepods/pod-x", "cpu.shares", str(1024 + k["n"] % 7))],
            reason="race",
        )

    def auditor():
        events = executor.auditor.query(since=0.0)
        for e in events:
            assert e.file

    run_racers([applier, auditor], duration_s=0.7)
    # final file content is one of the written values, not interleaved junk
    val = executor.read("kubepods/pod-x", "cpu.shares")
    assert val is not None and 1024 <= int(val) <= 1031


def test_koordlet_ticks_race_pod_updates(tmp_path):
    from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig

    agent = Koordlet(
        KoordletConfig(
            node_name="race-node",
            cgroup_root=str(tmp_path),
            n_cpus=8,
            node_allocatable_milli=8000,
            node_memory_capacity_mib=16384,
            checkpoint_dir=str(tmp_path / "ck"),
            report_interval_s=0.0,
        )
    )
    clock = {"t": 1000.0}

    def ticker():
        clock["t"] += 1.0
        now = clock["t"]
        agent.collect_tick(now)
        agent.qos_tick(now)
        agent.report_tick(now)

    def churner():
        n = int(clock["t"]) % 4
        agent.update_pods(
            [
                Pod(
                    meta=ObjectMeta(
                        name=f"be{k}", labels={ext.LABEL_POD_QOS: "BE"}
                    ),
                    spec=PodSpec(
                        requests={ext.RES_BATCH_CPU: 1000}, priority=5500
                    ),
                )
                for k in range(n)
            ]
        )

    run_racers([ticker, churner], duration_s=1.5)
    # daemon still functional after the storm: one more clean tick cycle
    agent.collect_tick(clock["t"] + 1)
    report = agent.report_tick(clock["t"] + 2)
    assert report is not None


def test_snapshot_channel_sync_nominate_races():
    from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
    from koordinator_tpu.runtime.snapshot_channel import (
        SolverClient,
        SolverService,
        serve,
    )

    service = SolverService(batch_bucket=64)
    service.scheduler.extender.monitor.stop_background()
    server, port = serve(service, max_workers=8)
    client = SolverClient(f"127.0.0.1:{port}")
    cfg = service.snapshot.config

    def vec(cpu, mem):
        return pb.ResourceVector(
            values=[
                float(
                    cpu
                    if r == ext.RES_CPU
                    else mem if r == ext.RES_MEMORY else 0
                )
                for r in cfg.resources
            ]
        )

    try:
        i = {"n": 0}

        def syncer():
            i["n"] += 1
            d = pb.SnapshotDelta(now=1000.0 + i["n"])
            d.node_upserts.add(
                name=f"n{i['n'] % 8}", allocatable=vec(32000, 131072)
            )
            if i["n"] % 5 == 0:
                d.node_removes.append(f"n{(i['n'] + 3) % 8}")
            client.sync(d)

        def nominator():
            req = pb.NominateRequest()
            req.pods.add(
                uid=f"p{i['n']}", requests=vec(1000, 1024), priority=9000
            )
            resp = client.nominate(req)
            assert len(resp.nominations) == 1

        run_racers([syncer, nominator], duration_s=1.5)
        # accounting survives: requested matches the assumed set exactly
        snap = service.snapshot
        want = np.zeros_like(snap.nodes.requested)
        for ap in snap._assumed.values():
            want[ap.node_idx] += ap.request
        np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)
    finally:
        client.close()
        server.stop(grace=None)


def test_statehub_informers_race_scheduling_cycles():
    """Informer handler threads (node churn, metric updates, binds,
    deletes) race live schedule() calls; the snapshot's coarse lock
    serializes them like the reference cache lock. At quiesce the
    accounting invariant must hold exactly: requested == Σ live assumes."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    snap = ClusterSnapshot()
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    hub = ClusterStateHub()
    hub.wire_scheduler(sched)
    hub.start()

    def node(i, cpu=64000):
        return Node(
            meta=ObjectMeta(name=f"n{i}"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: 262144}
            ),
        )

    try:
        for i in range(8):
            hub.publish(hub.nodes, node(i))
        assert hub.wait_synced()
        # warm the jit cache before the race (compile stalls would
        # serialize everything and hide interleavings)
        sched.schedule(
            [
                Pod(
                    meta=ObjectMeta(name="warm"),
                    spec=PodSpec(
                        requests={ext.RES_CPU: 100, ext.RES_MEMORY: 128},
                        priority=9000,
                    ),
                )
            ]
        )

        stop = threading.Event()
        errors: list = []
        seq = {"n": 0}

        def churner():
            k = 0
            while not stop.is_set():
                k += 1
                # re-upsert nodes (same capacity) and bounce one node
                hub.publish(hub.nodes, node(k % 8))
                if k % 7 == 0:
                    hub.delete(hub.nodes, node((k + 3) % 8))
                    hub.publish(hub.nodes, node((k + 3) % 8))
                time.sleep(0.001)

        def external_binder():
            k = 0
            while not stop.is_set():
                k += 1
                p = Pod(
                    meta=ObjectMeta(name=f"ext-{k}"),
                    spec=PodSpec(
                        requests={ext.RES_CPU: 500, ext.RES_MEMORY: 512},
                        priority=9000,
                        node_name=f"n{k % 8}",
                    ),
                )
                hub.publish(hub.pods, p)
                time.sleep(0.002)
                if k % 2 == 0:
                    hub.delete(hub.pods, p)
                time.sleep(0.001)

        threads = [
            threading.Thread(target=churner, daemon=True),
            threading.Thread(target=external_binder, daemon=True),
        ]
        for t in threads:
            t.start()
        try:
            for _ in range(15):
                seq["n"] += 1
                pods = [
                    Pod(
                        meta=ObjectMeta(name=f"s{seq['n']}-{j}"),
                        spec=PodSpec(
                            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024},
                            priority=9000,
                        ),
                    )
                    for j in range(8)
                ]
                out = sched.schedule(pods)
                assert len(out.bound) + len(out.unschedulable) == 8
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors, errors
        assert hub.wait_synced()
        # quiesce: the invariant must hold exactly under the lock
        with snap.lock:
            want = np.zeros_like(snap.nodes.requested)
            for _uid, ap in snap._assumed.items():
                want[ap.node_idx] += ap.request
            np.testing.assert_allclose(
                snap.nodes.requested, want, atol=1e-3
            )
    finally:
        hub.stop()
