"""Node resource amplification parity (reference
``apis/extension/node_resource_amplification.go`` +
``pkg/scheduler/plugins/nodenumaresource/plugin.go:408-443`` filterAmplifiedCPUs
and ``plugin.go:630-645`` amplifyNUMANodeResources/getResourceOptions).

Semantics under test: a node whose allocatable was amplified (ratio > 1)
stretches *shared* CPU capacity, but cpuset-bound pods (LSR/LSE whole-core)
consume physical cores — their requests count ×ratio against the amplified
allocatable, and already-held exclusive CPUs surcharge node requested by
(ratio−1)×held.
"""

import json

import numpy as np
import jax.numpy as jnp

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import CPUTopology, NUMAPolicy
from koordinator_tpu.ops.solver import (
    NodeState,
    PodBatch,
    SolverParams,
    assign,
    assign_sequential,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.nodenumaresource import NUMAManager


def params(d=2):
    return SolverParams(
        usage_thresholds=jnp.zeros(d, jnp.float32),
        prod_thresholds=jnp.zeros(d, jnp.float32),
        score_weights=jnp.ones(d, jnp.float32),
    )


def qos_pod_batch(cpu_milli, qos_values, d=2):
    p = len(qos_values)
    req = np.zeros((p, d), np.float32)
    req[:, 0] = cpu_milli
    req[:, 1] = 1024.0
    return PodBatch.create(
        requests=req,
        estimate=req,
        priority=np.full(p, 9500, np.int32),
        qos=np.asarray(qos_values, np.int8),
    )


def test_parse_node_amplification():
    ann = {ext.ANNOTATION_NODE_AMPLIFICATION: "cpu=1.5,memory=1.2"}
    got = ext.parse_node_amplification(ann)
    assert got == {"cpu": 1.5, "memory": 1.2}
    assert ext.parse_node_amplification({}) == {}
    bad = {ext.ANNOTATION_NODE_AMPLIFICATION: "cpu=abc,=2,junk"}
    assert ext.parse_node_amplification(bad) == {}


def test_bind_pod_request_amplified_in_filter():
    """plugin.go:421-423: requestCPUBind ⇒ podRequest ×ratio; an 8-core
    LSR pod needs 16000 amplified milli on a ratio-2 node — free 14000
    rejects it while a shared LS pod of the same size passes."""
    # amplified allocatable 64000, requested 50000 -> free 14000
    nodes = NodeState.create(
        allocatable=np.array([[64000.0, 1 << 20]], np.float32),
        requested=np.array([[50000.0, 0.0]], np.float32),
        cpu_amp=np.array([2.0], np.float32),
    )
    QOS_LS, QOS_LSR = 2, 3
    pods = qos_pod_batch(8000.0, [QOS_LSR, QOS_LS])
    res = assign(pods, nodes, params())
    a = np.asarray(res.assignment)
    assert a[0] == -1  # bound pod: 16000 > 14000
    assert a[1] == 0   # shared pod: 8000 <= 14000


def test_commit_charges_amplified_cpu():
    """Within a batch, a committed bound pod consumes ×ratio so the next
    bound pod sees true remaining capacity (the reference reaches this
    state pod-at-a-time via Reserve → cpuset allocate)."""
    QOS_LSR = 3
    nodes = NodeState.create(
        allocatable=np.array([[24000.0, 1 << 20]], np.float32),
        cpu_amp=np.array([2.0], np.float32),
    )
    pods = qos_pod_batch(8000.0, [QOS_LSR, QOS_LSR])
    res = assign(pods, nodes, params())
    a = np.asarray(res.assignment)
    # each charges 16000 against 24000: only one fits
    assert sorted(a.tolist()) == [-1, 0]
    req_f = np.asarray(res.node_requested)
    assert req_f[0, 0] == 16000.0
    # sequential golden agrees
    res_seq = assign_sequential(pods, nodes, params())
    a_seq = np.asarray(res_seq.assignment)
    assert sorted(a_seq.tolist()) == [-1, 0]
    assert np.asarray(res_seq.node_requested)[0, 0] == 16000.0


def test_unamplified_node_unchanged():
    QOS_LSR = 3
    nodes = NodeState.create(
        allocatable=np.array([[24000.0, 1 << 20]], np.float32),
    )
    pods = qos_pod_batch(8000.0, [QOS_LSR, QOS_LSR])
    a = np.asarray(assign(pods, nodes, params()).assignment)
    assert sorted(a.tolist()) == [0, 0]


def amplified_node(name="n0", physical_cpus=16, ratio=1.5):
    return Node(
        meta=ObjectMeta(
            name=name,
            annotations={ext.ANNOTATION_NODE_AMPLIFICATION: f"cpu={ratio}"},
        ),
        status=NodeStatus(
            allocatable={
                ext.RES_CPU: physical_cpus * 1000 * ratio,
                ext.RES_MEMORY: 32768,
            }
        ),
    )


def lsr_pod(name, cpu_milli):
    return Pod(
        meta=ObjectMeta(name=name, labels={ext.LABEL_POD_QOS: "LSR"}),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu_milli, ext.RES_MEMORY: 1024},
            priority=9500,
        ),
    )


def ls_pod(name, cpu_milli):
    return Pod(
        meta=ObjectMeta(name=name, labels={ext.LABEL_POD_QOS: "LS"}),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu_milli, ext.RES_MEMORY: 1024},
            priority=9500,
        ),
    )


def test_snapshot_parses_ratio_and_surcharge_fold():
    """upsert_node reads the annotation; after an exclusive allocation the
    BatchScheduler folds (ratio−1)×held into node requested
    (plugin.go:430-438 requested − allocated + amplify(allocated))."""
    snap = ClusterSnapshot()
    snap.upsert_node(amplified_node(ratio=1.5))
    idx = snap.node_id("n0")
    assert snap.nodes.cpu_amp[idx] == 1.5

    numa = NUMAManager(snap)
    numa.register_node(
        "n0",
        CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=4),
        memory_per_zone_mib=16384,
    )
    # zone CPU capacity registered in amplified space: 8 cpus × 1.5
    st = numa.node("n0")
    assert st.zone_alloc[0][0] == 12000.0

    sched = BatchScheduler(snap, numa=numa)
    out = sched.schedule([lsr_pod("p1", 8000)])
    assert len(out.bound) == 1
    ns = sched.node_state()
    # nominal assume 8000 + surcharge (1.5−1)×8000 = 12000
    assert float(np.asarray(ns.requested)[idx, 0]) == 12000.0


def test_e2e_amplified_packing():
    """16 physical cores at ratio 2 (amplified 32000): two 8-core LSR pods
    fill the node (each charges 16000); a third LSR and a shared LS pod
    both reject. On an unamplified node of the same amplified size, four
    LSR pods would fit."""
    snap = ClusterSnapshot()
    snap.upsert_node(amplified_node(physical_cpus=16, ratio=2.0))
    numa = NUMAManager(snap)
    numa.register_node(
        "n0",
        CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=8),
        memory_per_zone_mib=16384,
    )
    sched = BatchScheduler(snap, numa=numa)
    out = sched.schedule([lsr_pod(f"p{i}", 8000) for i in range(3)])
    assert len(out.bound) == 2
    assert len(out.unschedulable) == 1
    # exclusive holds: 16 physical cpus taken
    assert numa.node("n0").accumulator.allocated_count() == 16
    out2 = sched.schedule([ls_pod("shared", 4000)])
    assert out2.bound == []  # amplified free is 0


def test_shared_pods_ride_amplified_capacity():
    """The point of amplification: shared (LS) pods overcommit CPU. 16
    physical cores at ratio 2 accept 60000 milli of LS requests (< 32000
    would be the physical cap)."""
    snap = ClusterSnapshot()
    snap.upsert_node(amplified_node(physical_cpus=16, ratio=2.0))
    sched = BatchScheduler(snap)
    pods = [ls_pod(f"s{i}", 7500) for i in range(4)]  # 30000 > physical 16000
    out = sched.schedule(pods)
    assert len(out.bound) == 4


def test_cross_cycle_surcharge_without_numa_manager():
    """Code-review regression: the ×ratio charge must survive across
    scheduling cycles even with no registered NUMA topology — assume_pod
    itself charges amplified, so cycle 2 sees the true remaining
    capacity (12 physical cores can't hold two 8-core LSR pods)."""
    snap = ClusterSnapshot()
    snap.upsert_node(amplified_node(physical_cpus=12, ratio=2.0))  # 24000
    sched = BatchScheduler(snap)
    out1 = sched.schedule([lsr_pod("a", 8000)])
    assert len(out1.bound) == 1
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 16000.0
    out2 = sched.schedule([lsr_pod("b", 8000)])
    assert out2.bound == []
    # forget releases the amplified charge symmetrically
    snap.forget_pod(out1.bound[0][0].meta.uid)
    assert snap.nodes.requested[idx, 0] == 0.0


def test_register_before_upsert_syncs_live_ratio():
    """Code-review regression: register_node before the Node upsert froze
    cpu_amp=1.0; the manager must re-base onto the live snapshot ratio so
    an LSR pod amplified by the solver still fits its (amplified) zone."""
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    numa.register_node(
        "n0",
        CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=4),
        policy=NUMAPolicy.SINGLE_NUMA_NODE,
        memory_per_zone_mib=16384,
    )
    snap.upsert_node(amplified_node(physical_cpus=16, ratio=2.0))
    sched = BatchScheduler(snap, numa=numa)
    # 8-core LSR: amplified request 16000 == amplified zone capacity 16000
    out = sched.schedule([lsr_pod("p1", 8000)])
    assert len(out.bound) == 1
    st = numa.node("n0")
    assert st.cpu_amp == 2.0
    assert st.zone_alloc[0][0] == 16000.0
    # the bound charge lives in amplified space too
    zone = st.owners[out.bound[0][0].meta.uid][0]
    assert st.zone_used[zone][0] == 16000.0


def test_strict_zone_stretches_for_shared_pods():
    """amplifyNUMANodeResources: on a single-numa-node ratio-1.5 node a
    shared pod larger than one physical zone (8000) but under the
    amplified zone (12000) is feasible; a bound pod of the same size is
    checked physically (amplified request vs amplified zone) and must
    still fit real cores."""
    snap = ClusterSnapshot()
    snap.upsert_node(amplified_node(physical_cpus=16, ratio=1.5))
    numa = NUMAManager(snap)
    numa.register_node(
        "n0",
        CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=4),
        policy=NUMAPolicy.SINGLE_NUMA_NODE,
        memory_per_zone_mib=16384,
    )
    sched = BatchScheduler(snap, numa=numa)
    out = sched.schedule([ls_pod("big-shared", 10000)])
    assert len(out.bound) == 1
    # 10-core bound pod: amplified request 15000 > amplified zone 12000
    out2 = sched.schedule([lsr_pod("big-bound", 10000)])
    assert out2.bound == []


def test_ratio_change_rebases_live_charges():
    """Code-review regression: raising/lowering the amplification
    annotation re-bases already-assumed bound pods' charges in node
    requested, keeping node accounting and zone accounting in one space."""
    snap = ClusterSnapshot()
    snap.upsert_node(amplified_node(physical_cpus=12, ratio=2.0))  # 24000
    sched = BatchScheduler(snap)
    out = sched.schedule([lsr_pod("a", 8000)])
    assert len(out.bound) == 1
    idx = snap.node_id("n0")
    assert snap.nodes.requested[idx, 0] == 16000.0
    # ratio 2.0 -> 3.0: the live bound charge re-bases to 24000
    snap.upsert_node(amplified_node(physical_cpus=12, ratio=3.0))
    assert snap.nodes.requested[idx, 0] == 24000.0
    # back down to 1.0: nominal charge
    snap.upsert_node(amplified_node(physical_cpus=12, ratio=1.0))
    assert snap.nodes.requested[idx, 0] == 8000.0
    # forget stays symmetric after the re-base
    snap.forget_pod(out.bound[0][0].meta.uid)
    assert snap.nodes.requested[idx, 0] == 0.0
