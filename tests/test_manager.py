"""Manager layer tests: batch/mid resource calc, colocation profile
mutation, pod validation, NodeSLO rendering, and the full colocation
feedback loop (SURVEY §3.3)."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import PriorityClass, QoSClass
from koordinator_tpu.api.types import (
    ClusterColocationProfile,
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
    ResourceThresholdStrategy,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.manager.noderesource import (
    ColocationStrategy,
    NodeResourceController,
)
from koordinator_tpu.manager.nodeslo import NodeSLOController, SLOControllerConfig
from koordinator_tpu.manager.profile import ProfileMutator
from koordinator_tpu.manager.validating import validate_pod
from koordinator_tpu.scheduler.batch_solver import BatchScheduler


def make_node(snap, name, cpu=100_000, mem=100_000, prod_cpu=30_000):
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name=name),
            status=NodeStatus(allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
        )
    )
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name=name),
            node_usage=ResourceMetric(
                usage={ext.RES_CPU: prod_cpu + 5000, ext.RES_MEMORY: prod_cpu}
            ),
            prod_usage=ResourceMetric(
                usage={ext.RES_CPU: prod_cpu, ext.RES_MEMORY: prod_cpu}
            ),
            update_time=1000.0,
        ),
        now=1010.0,
    )


def test_batch_resource_formula():
    snap = ClusterSnapshot()
    make_node(snap, "n0", cpu=100_000, prod_cpu=30_000)
    # prod pods requested 50k but peak at 30k -> 20k reclaimable
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 100_000, ext.RES_MEMORY: 100_000}
            ),
        )
    )
    prod = Pod(
        meta=ObjectMeta(name="prod-1"),
        spec=PodSpec(
            requests={ext.RES_CPU: 50_000, ext.RES_MEMORY: 50_000}, priority=9500
        ),
    )
    snap.assume_pod(prod, "n0", now=900.0)
    # re-ingest the metric so the assumed-pending estimate is absorbed
    make_node(snap, "n0", cpu=100_000, prod_cpu=30_000)
    ctrl = NodeResourceController(
        snap, ColocationStrategy(reserve_ratio=0.1, mid_reclaim_ratio=0.5)
    )
    batch, mid = ctrl.calculate()
    idx = snap.node_id("n0")
    # batch = 100k * 0.9 - 30k = 60k
    assert abs(batch[idx][0] - 60_000) < 1e-2
    # mid = reclaimable prod = (50k requested - 30k peak) * 0.5 = 10k
    assert abs(mid[idx][0] - 10_000) < 1e-2


def test_batch_degrades_on_stale_metric():
    snap = ClusterSnapshot()
    make_node(snap, "n0")
    snap.nodes.metric_fresh[snap.node_id("n0")] = False
    batch, mid = NodeResourceController(snap).calculate()
    assert batch[snap.node_id("n0")][0] == 0.0


def test_reconcile_updates_allocatable_tensor():
    snap = ClusterSnapshot()
    make_node(snap, "n0")
    ctrl = NodeResourceController(snap)
    updates = ctrl.reconcile()
    assert ext.RES_BATCH_CPU in updates["n0"]
    col = snap.config.resources.index(ext.RES_BATCH_CPU)
    assert snap.nodes.allocatable[snap.node_id("n0"), col] == updates["n0"][
        ext.RES_BATCH_CPU
    ]


def test_profile_mutation_spark_to_be():
    """The reference's flagship example: Spark pods become BE/batch."""
    profile = ClusterColocationProfile(
        meta=ObjectMeta(name="spark"),
        selector={"spark-role": "executor"},
        qos_class=QoSClass.BE,
        priority=5500,
        scheduler_name="koord-scheduler",
        resource_translation={
            ext.RES_CPU: ext.RES_BATCH_CPU,
            ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
        },
        labels={"mutated": "yes"},
    )
    mutator = ProfileMutator([profile])
    pod = Pod(
        meta=ObjectMeta(name="exec-1", labels={"spark-role": "executor"}),
        spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192}),
    )
    mutator.mutate(pod)
    assert pod.qos is QoSClass.BE
    assert pod.priority_class is PriorityClass.BATCH
    assert pod.spec.scheduler_name == "koord-scheduler"
    assert pod.spec.requests == {
        ext.RES_BATCH_CPU: 4000,
        ext.RES_BATCH_MEMORY: 8192,
    }
    assert pod.meta.labels["mutated"] == "yes"
    # non-matching pod untouched
    other = Pod(meta=ObjectMeta(name="web"), spec=PodSpec(requests={ext.RES_CPU: 1}))
    mutator.mutate(other)
    assert other.spec.requests == {ext.RES_CPU: 1}


def test_validation_rules():
    ok = Pod(
        meta=ObjectMeta(name="p", labels={ext.LABEL_POD_QOS: "LSR"}),
        spec=PodSpec(requests={ext.RES_CPU: 2000}, priority=9500),
    )
    assert validate_pod(ok) == []
    bad_lsr = Pod(
        meta=ObjectMeta(name="p", labels={ext.LABEL_POD_QOS: "LSR"}),
        spec=PodSpec(requests={ext.RES_CPU: 2000}, priority=5000),
    )
    assert any("prod priority" in e for e in validate_pod(bad_lsr))
    bad_be = Pod(
        meta=ObjectMeta(name="p", labels={ext.LABEL_POD_QOS: "BE"}),
        spec=PodSpec(priority=9500),
    )
    assert any("batch/free" in e for e in validate_pod(bad_be))


def test_nodeslo_override():
    cfg = SLOControllerConfig(
        threshold=ResourceThresholdStrategy(
            enable=True, cpu_suppress_threshold_percent=65
        ),
        node_overrides={
            "pool=sensitive": ResourceThresholdStrategy(
                enable=True, cpu_suppress_threshold_percent=45
            )
        },
    )
    ctrl = NodeSLOController(cfg)
    default = ctrl.render("n0", {})
    assert default.threshold.cpu_suppress_threshold_percent == 65
    override = ctrl.render("n1", {"pool": "sensitive"})
    assert override.threshold.cpu_suppress_threshold_percent == 45


def test_colocation_feedback_loop_e2e():
    """koordlet metrics -> batch resource -> BE pod schedules on batch tier
    (the cross-process loop of SURVEY §3.3, in-process here)."""
    snap = ClusterSnapshot()
    make_node(snap, "n0", cpu=100_000, mem=100_000, prod_cpu=30_000)
    NodeResourceController(snap).reconcile()

    profile = ClusterColocationProfile(
        meta=ObjectMeta(name="spark"),
        selector={"spark-role": "executor"},
        qos_class=QoSClass.BE,
        priority=5500,
        resource_translation={
            ext.RES_CPU: ext.RES_BATCH_CPU,
            ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
        },
    )
    mutator = ProfileMutator([profile])
    sched = BatchScheduler(snap)

    pod = Pod(
        meta=ObjectMeta(name="exec-1", labels={"spark-role": "executor"}),
        spec=PodSpec(requests={ext.RES_CPU: 20_000, ext.RES_MEMORY: 20_000}),
    )
    assert mutator.admit(pod) == []
    out = sched.schedule([pod])
    assert [(p.meta.name, n) for p, n in out.bound] == [("exec-1", "n0")]
    # batch tier consumed, prod cpu untouched
    idx = snap.node_id("n0")
    bcol = snap.config.resources.index(ext.RES_BATCH_CPU)
    ccol = snap.config.resources.index(ext.RES_CPU)
    assert snap.nodes.requested[idx, bcol] == 20_000
    assert snap.nodes.requested[idx, ccol] == 0

    # an oversized BE pod is rejected by the batch tier, even though raw
    # cpu would have fit
    big = Pod(
        meta=ObjectMeta(name="exec-2", labels={"spark-role": "executor"}),
        spec=PodSpec(requests={ext.RES_CPU: 50_000, ext.RES_MEMORY: 50_000}),
    )
    mutator.admit(big)
    out2 = sched.schedule([big])
    assert out2.bound == []


# ---- Recommendation controller (analysis.koordinator.sh) ----


def test_recommendation_tracks_p95_peak():
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.manager.recommendation import RecommendationController

    ctl = RecommendationController(safety_margin=1.0)
    # 100 samples ramping 100..1090 milli-cpu: p95 ~ near the top
    for i in range(100):
        ctl.observe("web", {ext.RES_CPU: 100.0 + 10.0 * i}, ts=1000.0 + i)
    recs = ctl.reconcile()
    assert "web" in recs
    cpu = recs["web"].recommended[ext.RES_CPU]
    # p95 of the ramp is ~1040; exponential buckets round up one step
    assert 900.0 <= cpu <= 1250.0, cpu


def test_recommendation_margin_and_gc():
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.manager.recommendation import RecommendationController

    ctl = RecommendationController(safety_margin=1.5)
    for i in range(50):
        ctl.observe("a", {ext.RES_CPU: 1000.0}, ts=1000.0 + i)
        ctl.observe("b", {ext.RES_MEMORY: 2048.0}, ts=1000.0 + i)
    recs = ctl.reconcile()
    assert recs["a"].recommended[ext.RES_CPU] >= 1400.0
    assert ext.RES_MEMORY in recs["b"].recommended
    # workload b disappears -> its recommendation is dropped
    recs2 = ctl.reconcile(workloads=["a"])
    assert "b" not in recs2 and "a" in recs2


def test_recommendation_gc_forgets_samples():
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.manager.recommendation import RecommendationController

    ctl = RecommendationController()
    for i in range(20):
        ctl.observe("gone", {ext.RES_CPU: 500.0}, ts=1000.0 + i)
    assert "gone" in ctl.reconcile()
    ctl.reconcile(workloads=[])
    # an argument-less reconcile must NOT resurrect the dropped workload
    assert ctl.reconcile() == {}
    # and the predictor slot was recycled
    assert ctl.predictor.peak("gone#" + ext.RES_CPU) is None
