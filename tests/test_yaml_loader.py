"""YAML front door (VERDICT r3 #5): Koordinator-format manifests load
into api.types and drive the SAME placements as the Python-literal path;
the reference's own example manifests parse when present."""

import os

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    ClusterColocationProfile,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.api.yaml_loader import (
    NamespaceInfo,
    convert_resource_list,
    load_file,
    load_objects,
    parse_quantity,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "examples", "colocation-demo.yaml")
REFERENCE_PROFILE = "/root/reference/examples/spark-jobs/cluster-colocation-profile.yaml"


def test_quantity_parsing():
    assert parse_quantity("500m") == 0.5
    assert parse_quantity("1") == 1.0
    assert parse_quantity("2Gi") == 2 << 30
    assert parse_quantity("128Mi") == 128 << 20
    assert parse_quantity(3) == 3.0
    with pytest.raises(ValueError):
        parse_quantity("abc")


def test_resource_list_units():
    rl = convert_resource_list(
        {
            ext.RES_CPU: "1500m",
            ext.RES_MEMORY: "2Gi",
            ext.RES_BATCH_CPU: "4000",
            ext.RES_BATCH_MEMORY: "1Gi",
            ext.RES_GPU: 2,
        }
    )
    assert rl[ext.RES_CPU] == 1500.0       # milli
    assert rl[ext.RES_MEMORY] == 2048.0    # MiB
    assert rl[ext.RES_BATCH_CPU] == 4000.0
    assert rl[ext.RES_BATCH_MEMORY] == 1024.0
    assert rl[ext.RES_GPU] == 2.0


def test_demo_manifest_loads_typed_objects():
    objs = load_file(DEMO)
    kinds = [type(o).__name__ for o in objs]
    assert kinds.count("Node") == 2
    assert kinds.count("Pod") == 3
    assert kinds.count("ClusterColocationProfile") == 1
    assert kinds.count("NamespaceInfo") == 1
    pod = next(
        o for o in objs if isinstance(o, Pod) and o.meta.name == "analytics-exec-0"
    )
    assert pod.spec.requests[ext.RES_CPU] == 2000.0
    assert pod.spec.requests[ext.RES_MEMORY] == 1024.0
    prod = next(
        o for o in objs if isinstance(o, Pod) and o.meta.name == "online-api"
    )
    assert prod.spec.priority == 9000  # koord-prod class value


def _schedule(objs):
    """Admission (profile mutation) + scheduling for a loaded object set;
    returns {pod name: (node, qos, priority, request keys)}."""
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.manager.profile import ProfileMutator
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

    nodes = [o for o in objs if isinstance(o, Node)]
    pods = [o for o in objs if isinstance(o, Pod)]
    profiles = [o for o in objs if isinstance(o, ClusterColocationProfile)]
    namespaces = [o for o in objs if isinstance(o, NamespaceInfo)]
    mutator = ProfileMutator(
        profiles, namespace_labels={n.name: n.labels for n in namespaces}
    )
    snap = ClusterSnapshot()
    for n in nodes:
        snap.upsert_node(n)
    sched = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=64)
    sched.extender.monitor.stop_background()
    for p in pods:
        mutator.mutate(p)
    out = sched.schedule(pods)
    return {
        p.meta.name: (
            node,
            p.qos.name,
            p.spec.priority,
            tuple(sorted(p.spec.requests)),
        )
        for p, node in out.bound
    }


def test_yaml_path_places_like_python_literal_path():
    """Golden equivalence: the YAML-loaded world and a hand-built
    Python-literal world produce identical admission rewrites and
    placements."""
    yaml_placements = _schedule(load_file(DEMO))

    # the same world, straight from Python literals
    def node(name):
        return Node(
            meta=ObjectMeta(name=name),
            status=NodeStatus(
                allocatable={
                    ext.RES_CPU: 32000.0,
                    ext.RES_MEMORY: 128 * 1024.0,
                    ext.RES_BATCH_CPU: 20000.0,
                    ext.RES_BATCH_MEMORY: 65536.0,
                }
            ),
        )

    profile = ClusterColocationProfile(
        meta=ObjectMeta(name="analytics-batch"),
        selector={"workload-kind": "batch-analytics"},
        namespace_selector={"koordinator.sh/enable-colocation": "true"},
        labels={
            ext.LABEL_POD_PRIORITY_CLASS: "koord-batch",
            ext.LABEL_POD_PRIORITY: "1000",
        },
        qos_class=ext.QoSClass.BE,
        priority=5000,
        scheduler_name="koord-scheduler",
        resource_translation={
            ext.RES_CPU: ext.RES_BATCH_CPU,
            ext.RES_MEMORY: ext.RES_BATCH_MEMORY,
        },
    )

    def batch_pod(name, cpu, mem):
        return Pod(
            meta=ObjectMeta(
                name=name,
                namespace="analytics",
                labels={"workload-kind": "batch-analytics"},
            ),
            spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
        )

    literal = [
        NamespaceInfo(
            name="analytics",
            labels={"koordinator.sh/enable-colocation": "true"},
        ),
        profile,
        node("demo-node-0"),
        node("demo-node-1"),
        batch_pod("analytics-driver", 1000.0, 512.0),
        batch_pod("analytics-exec-0", 2000.0, 1024.0),
        Pod(
            meta=ObjectMeta(name="online-api", namespace="analytics"),
            spec=PodSpec(
                requests={ext.RES_CPU: 500.0, ext.RES_MEMORY: 256.0},
                priority=9000,
            ),
        ),
    ]
    literal_placements = _schedule(literal)
    assert yaml_placements == literal_placements
    # the profile actually rewired the batch pods: BE QoS + batch-tier
    # requests, while the prod pod kept plain cpu/memory
    node_, qos, prio, reqs = yaml_placements["analytics-exec-0"]
    assert qos == "BE"
    assert prio == 5000
    assert ext.RES_BATCH_CPU in reqs and ext.RES_CPU not in reqs
    _, qos_p, prio_p, reqs_p = yaml_placements["online-api"]
    assert qos_p != "BE" and prio_p == 9000 and ext.RES_CPU in reqs_p


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_PROFILE),
    reason="reference manifests not present",
)
def test_reference_spark_profile_parses():
    """The reference's own spark-jobs colocation profile loads into the
    typed profile with BE/batch semantics intact."""
    objs = load_file(REFERENCE_PROFILE)
    ns = next(o for o in objs if isinstance(o, NamespaceInfo))
    assert ns.name == "spark-demo"
    assert ns.labels["koordinator.sh/enable-colocation"] == "true"
    prof = next(
        o for o in objs if isinstance(o, ClusterColocationProfile)
    )
    assert prof.qos_class == ext.QoSClass.BE
    assert prof.priority == 5000                      # koord-batch base
    assert prof.scheduler_name == "koord-scheduler"
    assert prof.namespace_selector == {
        "koordinator.sh/enable-colocation": "true"
    }
    assert prof.selector == {
        "sparkoperator.k8s.io/launched-by-spark-operator": "true"
    }
    assert prof.resource_translation[ext.RES_CPU] == ext.RES_BATCH_CPU
    # a spark-operator-launched pod admitted through it becomes a
    # batch-tier BE pod — the demo's whole point
    from koordinator_tpu.manager.profile import ProfileMutator

    mutator = ProfileMutator(
        [prof], namespace_labels={ns.name: ns.labels}
    )
    pod = Pod(
        meta=ObjectMeta(
            name="spark-pi-exec-1",
            namespace="spark-demo",
            labels={
                "sparkoperator.k8s.io/launched-by-spark-operator": "true"
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000.0, ext.RES_MEMORY: 512.0}
        ),
    )
    mutator.mutate(pod)
    assert pod.qos == ext.QoSClass.BE
    assert ext.RES_BATCH_CPU in pod.spec.requests
