"""Candidate-shortlist solve: decision-identity pins.

The shortlisted round solver (``assign(..., shortlist_k=K)``) prunes the
per-pod node axis to each pod's top-K build-time candidates. The exactness
bound (feasibility is monotone non-increasing and masked cost monotone
non-decreasing as capacity commits, so the (K+1)-th best build cost
lower-bounds every excluded node forever) plus the full-axis re-nomination
escape hatch make the pruned solve DECISION-IDENTICAL, not approximately
equal — these tests pin bit-exactness of the assignment, the per-pod zone
pick and every post-commit capacity table against the full-axis solver
across the constrained feature matrix, including runs where the fallback
fires.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from koordinator_tpu.ops.device import DeviceState
from koordinator_tpu.ops.numa import NumaState
from koordinator_tpu.ops.solver import (
    NodeState,
    PodBatch,
    QuotaState,
    SolverParams,
    _jitter_hash,
    assign,
    assign_sequential,
    enforce_gangs,
    solve_stream_full,
)
from koordinator_tpu.sim import golden

D = 2

# Every decision-bearing SolveResult field: the assignment itself, the
# on-device zone pick, and the post-commit capacity tables that chain
# into the next chunk/cycle (ISSUE: "quota/slot/zone end-state tables
# bit-exact").
DECISION_FIELDS = (
    "assignment",
    "pod_zone",
    "pod_zone_charge",
    "node_requested",
    "node_estimated_used",
    "node_prod_used",
    "quota_used",
    "node_dev_slots",
    "node_rdma_free",
    "node_fpga_free",
    "node_zone_free",
    "rounds_used",
)


def assert_same_decisions(full, pruned):
    for f in DECISION_FIELDS:
        a, b = getattr(full, f), getattr(pruned, f)
        if a is None or b is None:
            assert a is None and b is None, f
            continue
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"SolveResult.{f} diverged"
        )


def rich_fixture(
    p=96,
    n=24,
    seed=0,
    pod_scale=1.0,
    base_util=0.25,
    thresholds=(70.0, 90.0),
    quota=False,
    numa=False,
    devices=False,
    mask=False,
    gang=False,
):
    """Randomized constrained fixture over the full feature matrix."""
    rng = np.random.default_rng(seed)
    alloc = (
        rng.choice([32.0, 64.0, 96.0], (n, 1)) * np.ones((1, D))
    ).astype(np.float32)
    requested = (alloc * rng.uniform(0.0, 0.2, (n, D))).astype(np.float32)
    est_used = (alloc * base_util * rng.uniform(0.5, 1.5, (n, D))).astype(
        np.float32
    )
    prod_used = (est_used * 0.6).astype(np.float32)
    sched = np.ones(n, bool)
    sched[rng.integers(0, n)] = False
    fresh = np.ones(n, bool)
    fresh[rng.integers(0, n)] = False

    req = (rng.choice([1.0, 2.0, 4.0, 8.0], (p, D)) * pod_scale).astype(
        np.float32
    )
    est = (req * 0.85).astype(np.float32)
    prio = rng.integers(5000, 9999, p).astype(np.int32)

    kw = {}
    quotas = None
    if quota:
        # 3-quota tree: leaves 1..2 under root 0; leaf 1 deliberately
        # tight so quota admission actually rejects pods mid-solve
        chain = np.full((p, 4), -1, np.int32)
        chain[:, 0] = rng.integers(1, 3, p)
        chain[:, 1] = 0
        kw["quota_chain"] = chain
        total = req.sum(0)
        runtime = np.full((3, D), np.inf, np.float32)
        runtime[1] = total * 0.25
        runtime[2] = total * 0.5
        quotas = QuotaState(
            runtime=jnp.asarray(runtime),
            used=jnp.zeros((3, D), jnp.float32),
        )
    numa_state = None
    if numa:
        z = 2
        zone_cap = np.repeat((alloc / z)[:, None, :], z, axis=1).astype(
            np.float32
        )
        zone_used = (
            zone_cap * rng.uniform(0.0, 0.4, zone_cap.shape)
        ).astype(np.float32)
        numa_state = NumaState(
            zone_free=jnp.asarray(zone_cap - zone_used),
            zone_cap=jnp.asarray(zone_cap),
            policy=jnp.asarray(rng.choice([0, 3], n).astype(np.int8)),
        )
        kw["numa_required"] = rng.random(p) < 0.3
    device_state = None
    if devices:
        g = 4
        slot = rng.choice(
            [0.0, 45.0, 100.0], (n, g), p=[0.2, 0.2, 0.6]
        ).astype(np.float32)
        device_state = DeviceState(
            slot_free=jnp.asarray(slot),
            rdma_free=jnp.asarray(rng.integers(0, 3, n).astype(np.float32)),
            cap_total=jnp.asarray(np.full(n, g * 100.0, np.float32)),
        )
        gpu_whole = rng.choice([0, 0, 1, 2], p).astype(np.int32)
        gpu_share = np.where(
            (gpu_whole == 0) & (rng.random(p) < 0.4),
            rng.choice([30.0, 55.0], p),
            0.0,
        ).astype(np.float32)
        kw["gpu_whole"] = gpu_whole
        kw["gpu_share"] = gpu_share
        kw["rdma"] = (rng.random(p) < 0.2).astype(np.int32)
    node_mask = None
    if mask:
        m = rng.random((p, n)) < 0.6
        m[:, 1] = True  # keep every pod at least one allowed node
        node_mask = jnp.asarray(m)
    if gang:
        gid = np.full(p, -1, np.int32)
        gid[:12] = np.repeat(np.arange(3, dtype=np.int32), 4)
        gmin = np.zeros(p, np.int32)
        gmin[:12] = 3
        kw["gang_id"] = gid
        kw["gang_min"] = gmin

    pods = PodBatch.create(requests=req, priority=prio, estimate=est, **kw)
    nodes = NodeState.create(
        allocatable=alloc,
        requested=requested,
        estimated_used=est_used,
        prod_used=prod_used,
        metric_fresh=fresh,
        schedulable=sched,
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray(thresholds, jnp.float32),
        prod_thresholds=jnp.asarray((50.0, 95.0), jnp.float32),
        score_weights=jnp.ones(D, jnp.float32),
    )
    return pods, nodes, params, quotas, numa_state, device_state, node_mask


def run_pair(fix, k, **akw):
    pods, nodes, params, quotas, numa_state, device_state, node_mask = fix
    common = dict(
        quotas=quotas,
        numa=numa_state,
        devices=device_state,
        node_mask=node_mask,
        **akw,
    )
    full = assign(pods, nodes, params, shortlist_k=None, **common)
    pruned = assign(pods, nodes, params, shortlist_k=k, **common)
    return full, pruned


COMBOS = {
    "plain": {},
    "quota": {"quota": True},
    "numa": {"numa": True},
    "devices": {"devices": True},
    "node_mask": {"mask": True},
    "kitchen_sink": {
        "quota": True,
        "numa": True,
        "devices": True,
        "mask": True,
    },
}


@pytest.mark.parametrize("combo", sorted(COMBOS))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_decision_identity(combo, seed):
    """ISSUE acceptance: the shortlisted solve is decision-identical to
    the full-axis solver across quota+NUMA+device+node_mask combos."""
    fix = rich_fixture(seed=seed, **COMBOS[combo])
    akw = {}
    if COMBOS[combo].get("numa"):
        akw["numa_scoring"] = "LeastAllocated"
    if COMBOS[combo].get("devices"):
        akw["device_scoring"] = "LeastAllocated"
    full, pruned = run_pair(fix, 8, **akw)
    assert int(np.sum(np.asarray(full.assignment) >= 0)) > 0  # non-vacuous
    assert_same_decisions(full, pruned)


@pytest.mark.parametrize("seed", [3, 4])
def test_gang_rollback_identity(seed):
    """Gang enforcement consumes the solve verbatim: identical solves →
    identical all-or-nothing rollbacks, including the device-slot refunds."""
    fix = rich_fixture(
        seed=seed, devices=True, gang=True, pod_scale=3.0, base_util=0.4
    )
    pods = fix[0]
    full, pruned = run_pair(fix, 8)
    assert_same_decisions(enforce_gangs(full, pods), enforce_gangs(pruned, pods))


def test_high_contention_forces_fallback_still_exact():
    """Adversarial batch: near-identical pods hammering the same few cheap
    nodes with a tiny K. The exactness bound must actually fire (the
    shortlist alone cannot prove the decisions safe) and the full-axis
    re-nomination escape hatch must keep the decisions bit-exact — the
    fallback is a perf event, never a behavior change."""
    rng = np.random.default_rng(7)
    p, n = 384, 32
    alloc = np.full((n, D), 64.0, np.float32)
    est_used = (alloc * 0.2 * rng.uniform(0.9, 1.1, (n, D))).astype(np.float32)
    req = np.full((p, D), 4.0, np.float32)
    pods = PodBatch.create(
        requests=req,
        priority=rng.integers(5000, 9999, p).astype(np.int32),
        estimate=req * 0.85,
    )
    nodes = NodeState.create(
        allocatable=alloc,
        estimated_used=est_used,
        prod_used=est_used * 0.5,
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray((60.0, 60.0), jnp.float32),
        prod_thresholds=jnp.zeros(D, jnp.float32),
        score_weights=jnp.ones(D, jnp.float32),
    )
    full = assign(pods, nodes, params, shortlist_k=None)
    pruned = assign(pods, nodes, params, shortlist_k=4)
    fb = np.asarray(pruned.shortlist_fallbacks)
    assert fb.shape == (2,) and fb.sum() > 0, fb
    assert_same_decisions(full, pruned)


def test_shortlist_k_ge_n_degenerate():
    """K >= N covers the whole axis: shortlisting is statically off, the
    result is the plain full-axis solve and the fallback counter is the
    all-zero sentinel (never None — stream outputs stay shape-stable)."""
    fix = rich_fixture(seed=5, n=16)
    full, pruned = run_pair(fix, 64)
    assert_same_decisions(full, pruned)
    np.testing.assert_array_equal(
        np.asarray(pruned.shortlist_fallbacks), np.zeros(2, np.int32)
    )


def test_jitter_hash_gather_invariant():
    """ISSUE satellite: add_jitter determinism under candidate gather.

    The nomination tie-break band hashes ORIGINAL node ids, so a (pod,
    node) pair perturbs identically whether the cost row is full-axis
    [P, N] or a gathered [P, K] candidate sub-tensor — gathering then
    hashing equals hashing then gathering."""
    rng = np.random.default_rng(11)
    p, n, k = 64, 128, 16
    pi = jnp.arange(p, dtype=jnp.uint32)
    ni = jnp.arange(n, dtype=jnp.uint32)
    h_full = np.asarray(_jitter_hash(pi[:, None], ni[None, :]))
    cand = np.stack(
        [rng.choice(n, size=k, replace=False) for _ in range(p)]
    ).astype(np.int32)
    cand.sort(axis=1)  # build emits candidates ascending by node id
    h_cols = np.asarray(
        _jitter_hash(pi[:, None], jnp.asarray(cand).astype(jnp.uint32))
    )
    np.testing.assert_array_equal(
        h_cols, np.take_along_axis(h_full, cand, axis=1)
    )
    # and the band is genuinely per-pair (not constant along either axis)
    assert len(np.unique(h_full[0])) > 1 and len(np.unique(h_full[:, 0])) > 1


# ---- sequential (golden-comparable) solver ----


def seq_fixture(p=48, n=24, seed=0, pod_scale=1.0, base_util=0.3):
    rng = np.random.default_rng(seed)
    alloc = (
        rng.choice([32.0, 64.0, 96.0], (n, 1)) * np.ones((1, D))
    ).astype(np.float32)
    requested = np.zeros((n, D), np.float32)
    est_used = (alloc * base_util * rng.uniform(0.5, 1.5, (n, D))).astype(
        np.float32
    )
    prod_used = (est_used * 0.6).astype(np.float32)
    fresh = np.ones(n, bool)
    sched = np.ones(n, bool)
    req = (rng.choice([1.0, 2.0, 4.0, 8.0], (p, D)) * pod_scale).astype(
        np.float32
    )
    est = (req * 0.85).astype(np.float32)
    prio = rng.integers(5000, 9999, p).astype(np.int32)
    is_prod = prio >= 9000
    thresholds = (65.0, 95.0)
    prod_thresholds = (50.0, 95.0)
    pods = PodBatch.create(
        requests=req, estimate=est, priority=prio, is_prod=is_prod
    )
    nodes = NodeState.create(
        allocatable=alloc,
        requested=requested,
        estimated_used=est_used,
        prod_used=prod_used,
        metric_fresh=fresh,
        schedulable=sched,
    )
    params = SolverParams(
        usage_thresholds=jnp.asarray(thresholds, jnp.float32),
        prod_thresholds=jnp.asarray(prod_thresholds, jnp.float32),
        score_weights=jnp.ones(D, jnp.float32),
    )
    np_fix = dict(
        pod_req=req,
        pod_estimate=est,
        pod_priority=prio,
        pod_is_prod=is_prod,
        allocatable=alloc,
        requested0=requested,
        estimated_used0=est_used,
        prod_used0=prod_used,
        metric_fresh=fresh,
        schedulable=sched,
        usage_thresholds=np.asarray(thresholds, np.float32),
        prod_thresholds=np.asarray(prod_thresholds, np.float32),
        score_weights=np.ones(D, np.float32),
    )
    return pods, nodes, params, np_fix


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("k", [4, 16])
def test_sequential_shortlist_matches_full_and_host(seed, k):
    """ISSUE acceptance: decision-identical to the full-axis solver AND
    the host reference (``sim.golden.sequential_assign``)."""
    pods, nodes, params, np_fix = seq_fixture(seed=seed)
    full = assign_sequential(pods, nodes, params)
    pruned = assign_sequential(pods, nodes, params, shortlist_k=k)
    assert_same_decisions(full, pruned)
    want = golden.sequential_assign(**np_fix)
    np.testing.assert_array_equal(np.asarray(pruned.assignment), want)


def test_sequential_fallback_fires_still_exact():
    """Contended sequential solve with K=2: later pods' shortlists go
    stale as earlier pods commit, the score-side bound cannot prove the
    pick safe, and the per-step full-axis cond re-nominates — decisions
    (and the golden host reference) stay bit-exact."""
    pods, nodes, params, np_fix = seq_fixture(
        seed=9, p=96, n=16, pod_scale=4.0, base_util=0.45
    )
    full = assign_sequential(pods, nodes, params)
    pruned = assign_sequential(pods, nodes, params, shortlist_k=2)
    fb = np.asarray(pruned.shortlist_fallbacks)
    assert fb.shape == (2,) and fb.sum() > 0, fb
    assert_same_decisions(full, pruned)
    np.testing.assert_array_equal(
        np.asarray(pruned.assignment), golden.sequential_assign(**np_fix)
    )


def test_sequential_shortlist_k_ge_n_degenerate():
    pods, nodes, params, _ = seq_fixture(seed=6, n=12)
    full = assign_sequential(pods, nodes, params)
    pruned = assign_sequential(pods, nodes, params, shortlist_k=128)
    assert_same_decisions(full, pruned)


# ---- stream plumbing ----


def test_solve_stream_full_carries_fallback_counts():
    """The scanned stream returns a 4th output: per-chunk [C, 2] fallback
    counts (all-zero sentinel when shortlisting is off) so the dispatcher
    fetches them packed with rounds in the same transfer."""
    fix = rich_fixture(seed=8, p=64, quota=True)
    pods, nodes, params, quotas, _numa, _dev, _mask = fix
    stacked = jax.tree.map(
        lambda a: a.reshape((2, 32) + a.shape[1:]), pods
    )
    a_full, z_full, r_full, fb_full = solve_stream_full(
        stacked, nodes, params, quotas=quotas, shortlist_k=None
    )
    a_sl, z_sl, r_sl, fb_sl = solve_stream_full(
        stacked, nodes, params, quotas=quotas, shortlist_k=8
    )
    np.testing.assert_array_equal(np.asarray(a_full), np.asarray(a_sl))
    np.testing.assert_array_equal(np.asarray(z_full), np.asarray(z_sl))
    np.testing.assert_array_equal(np.asarray(r_full), np.asarray(r_sl))
    assert np.asarray(fb_sl).shape == (2, 2)
    np.testing.assert_array_equal(
        np.asarray(fb_full), np.zeros((2, 2), np.int32)
    )
