"""Tier-1 enforcement of the attributed-rejection-taxonomy discipline
(distributed-observability PR satellite): every ``RejectReason`` member
has a ``_classify_solver_reject`` arm or an explicit, still-true
exemption naming its dedicated attribution site. See
``tools/check_reject_reasons.py``."""

import sys
import textwrap
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

import check_reject_reasons as lint  # noqa: E402


def test_repo_taxonomy_is_fully_attributed():
    violations = lint.check(ROOT)
    assert not violations, "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in violations
    )


def _fake_repo(tmp_path, members, classifier_body, extra=""):
    """Minimal tree the lint scans: the enum file, the classifier file,
    and optionally another module carrying dedicated-site references."""
    enum_f = tmp_path / "koordinator_tpu" / "obs" / "rejections.py"
    enum_f.parent.mkdir(parents=True)
    enum_f.write_text(
        "import enum\n\nclass RejectReason(str, enum.Enum):\n"
        + "".join(f'    {m} = "{m.lower()}"\n' for m in members)
    )
    cls_f = tmp_path / "koordinator_tpu" / "scheduler" / "batch_solver.py"
    cls_f.parent.mkdir(parents=True)
    cls_f.write_text(
        "from ..obs.rejections import RejectReason\n\n"
        "class BatchScheduler:\n"
        "    def _classify_solver_reject(self, pod, req, est):\n"
        + textwrap.indent(textwrap.dedent(classifier_body), " " * 8)
    )
    if extra:
        site = tmp_path / "koordinator_tpu" / "other.py"
        site.write_text(
            "from .obs.rejections import RejectReason\n" + extra
        )
    return tmp_path


def test_lint_flags_member_without_arm_or_exemption(tmp_path):
    root = _fake_repo(
        tmp_path,
        ["INSUFFICIENT_RESOURCES", "BRAND_NEW_REASON"],
        "return RejectReason.INSUFFICIENT_RESOURCES\n",
    )
    out = lint.check(root, exempt_table={})
    assert len(out) == 1 and "BRAND_NEW_REASON" in out[0][2]
    assert "no _classify_solver_reject arm" in out[0][2]


def test_lint_accepts_classifier_arm(tmp_path):
    root = _fake_repo(
        tmp_path,
        ["INSUFFICIENT_RESOURCES"],
        "return RejectReason.INSUFFICIENT_RESOURCES\n",
    )
    assert lint.check(root, exempt_table={}) == []


def test_lint_accepts_exempt_member_with_live_site(tmp_path):
    root = _fake_repo(
        tmp_path,
        ["INSUFFICIENT_RESOURCES", "STALE_LEADER_EPOCH"],
        "return RejectReason.INSUFFICIENT_RESOURCES\n",
        extra="REASON = RejectReason.STALE_LEADER_EPOCH\n",
    )
    assert lint.check(
        root, exempt_table={"STALE_LEADER_EPOCH": "fence boundary"}
    ) == []


def test_lint_flags_exempt_member_with_no_site(tmp_path):
    # exempted, but nothing outside the enum file references it: the
    # dedicated attribution site the exemption promises does not exist
    root = _fake_repo(
        tmp_path,
        ["INSUFFICIENT_RESOURCES", "STALE_LEADER_EPOCH"],
        "return RejectReason.INSUFFICIENT_RESOURCES\n",
    )
    out = lint.check(
        root, exempt_table={"STALE_LEADER_EPOCH": "fence boundary"}
    )
    assert len(out) == 1 and "STALE_LEADER_EPOCH" in out[0][2]
    assert "the site is gone" in out[0][2]


def test_lint_flags_stale_exemption_for_covered_member(tmp_path):
    # the classifier grew an arm for an exempted member: the exemption
    # must be deleted, not silently shadow the arm
    root = _fake_repo(
        tmp_path,
        ["STALE_LEADER_EPOCH"],
        "return RejectReason.STALE_LEADER_EPOCH\n",
        extra="REASON = RejectReason.STALE_LEADER_EPOCH\n",
    )
    out = lint.check(
        root, exempt_table={"STALE_LEADER_EPOCH": "fence boundary"}
    )
    assert len(out) == 1 and "stale exemption" in out[0][2]


def test_every_current_exemption_names_a_real_member():
    members = set(lint.enum_members(ROOT))
    assert set(lint.EXEMPT) <= members
    # and the split is genuine: the classifier covers SOMETHING, and the
    # exemptions cover everything else, disjointly
    covered = lint.classifier_coverage(ROOT)
    assert covered and covered.isdisjoint(lint.EXEMPT)
    assert covered | set(lint.EXEMPT) == members
