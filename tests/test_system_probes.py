"""Kernel feature probe layer (util/system rebuild): probes against a fake
filesystem gate runtimehook plans (reference IsCoreSchedSupported,
core_sched.go:275-294; VERDICT r1 missing item 8)."""

import os

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
from koordinator_tpu.koordlet import resourceexecutor as rex
from koordinator_tpu.koordlet import runtimehooks as hooks
from koordinator_tpu.koordlet.system import KernelProbes, SystemConfig


def fake_fs(tmp_path, core_sched_sysctl=False, sched_features=None,
            bvt=False, resctrl=False, psi=False):
    proc = tmp_path / "proc"
    sys_ = tmp_path / "sys"
    cg = tmp_path / "cgroup"
    for d in (proc, sys_, cg):
        d.mkdir(parents=True, exist_ok=True)
    if core_sched_sysctl:
        (proc / "sys" / "kernel").mkdir(parents=True)
        (proc / "sys" / "kernel" / "sched_core").write_text("1\n")
    if sched_features is not None:
        (sys_ / "kernel" / "debug").mkdir(parents=True)
        (sys_ / "kernel" / "debug" / "sched_features").write_text(sched_features)
    if bvt:
        (cg / "cpu.bvt_warp_ns").write_text("0\n")
    if resctrl:
        (sys_ / "fs" / "resctrl").mkdir(parents=True)
        (sys_ / "fs" / "resctrl" / "schemata").write_text("L3:0=fffff\n")
    if psi:
        (proc / "pressure").mkdir(parents=True, exist_ok=True)
        (proc / "pressure" / "cpu").write_text("some avg10=0.00\n")
    return KernelProbes(
        SystemConfig(proc_root=str(proc), sys_root=str(sys_), cgroup_root=str(cg))
    )


def test_core_sched_probe_paths(tmp_path):
    assert fake_fs(tmp_path / "a", core_sched_sysctl=True).core_sched_supported() == (
        True, "sysctl supported")
    assert fake_fs(tmp_path / "b", sched_features="PLACE_LAG NO_CORE_SCHED"
                   ).core_sched_supported()[0] is True
    assert fake_fs(tmp_path / "c", sched_features="PLACE_LAG"
                   ).core_sched_supported()[0] is False
    assert fake_fs(tmp_path / "d").core_sched_supported()[0] is False


def test_other_probes(tmp_path):
    p = fake_fs(tmp_path, bvt=True, resctrl=True, psi=True)
    assert p.bvt_supported() and p.resctrl_supported() and p.psi_supported()
    q = fake_fs(tmp_path / "none")
    assert not (q.bvt_supported() or q.resctrl_supported() or q.psi_supported())


def test_reconciler_gates_unsupported_plans(tmp_path):
    """A kernel without core-sched/bvt/resctrl support must not receive
    those writes; a fully-featured kernel gets the whole plan."""
    pod = Pod(
        meta=ObjectMeta(name="p", labels={ext.LABEL_POD_QOS: "BE"}),
        spec=PodSpec(
            requests={ext.RES_BATCH_CPU: 4000, ext.RES_BATCH_MEMORY: 4096},
            priority=5500,
        ),
    )
    executor = rex.ResourceExecutor(str(tmp_path / "cgfs"))

    bare = hooks.Reconciler(executor, probes=fake_fs(tmp_path / "bare"))
    files_bare = {f for _g, f, _v in bare.render(pod)}
    assert rex.CORE_SCHED_COOKIE not in files_bare
    assert rex.CPU_BVT not in files_bare
    assert "resctrl.group" not in files_bare
    assert files_bare  # batch shares etc. still planned

    rich = hooks.Reconciler(
        executor,
        probes=fake_fs(
            tmp_path / "rich", core_sched_sysctl=True, bvt=True, resctrl=True
        ),
    )
    files_rich = {f for _g, f, _v in rich.render(pod)}
    assert rex.CORE_SCHED_COOKIE in files_rich
    assert rex.CPU_BVT in files_rich
    assert "resctrl.group" in files_rich
