"""Multi-chip sharding tests on the 8-device virtual CPU mesh."""

import numpy as np

import jax

from koordinator_tpu.parallel.sharded import make_mesh, sharded_assign
from koordinator_tpu.ops.solver import assign

from test_solver import make_fixture


def test_mesh_shape():
    mesh = make_mesh(8)
    assert mesh.shape["dp"] * mesh.shape["tp"] == 8
    assert mesh.shape["tp"] >= mesh.shape["dp"]


def test_sharded_matches_single_device():
    mesh = make_mesh(8)
    p = 32 * mesh.shape["dp"]
    n = 16 * mesh.shape["tp"]
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=21, base_util=0.2)
    want = np.asarray(assign(pods, nodes, params).assignment)
    got = np.asarray(sharded_assign(mesh, pods, nodes, params).assignment)
    np.testing.assert_array_equal(got, want)


def test_dryrun_multichip_entry():
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        pathlib.Path(__file__).resolve().parent.parent / "__graft_entry__.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256,)
