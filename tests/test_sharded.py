"""Multi-chip sharding tests on the 8-device virtual CPU mesh."""

import functools

import numpy as np

import jax
import pytest

from koordinator_tpu.parallel.sharded import make_mesh, sharded_assign
from koordinator_tpu.ops.solver import assign

from test_solver import make_fixture


@functools.lru_cache(maxsize=None)
def _gspmd_compiles(p: int, n: int, max_rounds: int = 1) -> bool:
    """PER-SHAPE availability probe (first-class multichip PR), replacing
    the old blanket once-per-run probe. The historical toolchain defect —
    the SPMD partitioner mis-sizing the all-gather/slice pair that 1-D
    permutation scatter lowers to on dp-sharded operands — is fixed at
    the ROOT in ops.solver.assign (the final un-sort is now the
    inverse-permutation gather, bit-identical and partition-friendly),
    so every shape compiles and the sharded==single equality suite runs
    in tier-1. The probe stays, per (p, n, max_rounds): a partitioner
    regression on one program must skip exactly the shapes it breaks
    with a loud reason, never blanket-skip the suite. A successful probe
    seeds the jit cache, so the test paying for it re-uses the compile."""
    mesh = make_mesh(8)
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=3)
    try:
        sharded_assign(mesh, pods, nodes, params, max_rounds=max_rounds)
        return True
    except Exception:  # noqa: BLE001 — any compile/partition failure
        return False


def _require_gspmd(p: int, n: int, max_rounds: int = 1) -> None:
    """Skip the calling test iff THIS shape's GSPMD program cannot
    compile on the current jaxlib (see :func:`_gspmd_compiles`)."""
    if len(jax.devices()) < 8:
        pytest.skip(
            "needs the 8-device virtual CPU mesh (tests/conftest.py "
            "forces xla_force_host_platform_device_count=8)"
        )
    if not _gspmd_compiles(p, n, max_rounds):
        pytest.skip(
            f"XLA SPMD partitioner cannot compile the sharded solver at "
            f"p={p} n={n} on this jaxlib; other shapes still run"
        )


def test_gspmd_partitioner_fixed_on_virtual_mesh():
    """Multi-device CPU arm: tier-1 must RUN the sharded==single suite,
    not silently skip it. The conftest's virtual mesh must expose 8 real
    devices, and the canonical solver shapes must compile under GSPMD —
    if the partitioner (or the solver's un-sort lowering) regresses to
    the old all-gather/slice mis-sizing, this FAILS loudly instead of
    the equality tests quietly skipping."""
    assert len(jax.devices()) >= 8, "virtual CPU mesh missing"
    mesh = make_mesh(8)
    assert _gspmd_compiles(4 * mesh.shape["dp"], 4 * mesh.shape["tp"], 1)
    assert _gspmd_compiles(32 * mesh.shape["dp"], 16 * mesh.shape["tp"], 1)


def test_mesh_shape():
    mesh = make_mesh(8)
    assert mesh.shape["dp"] * mesh.shape["tp"] == 8
    assert mesh.shape["tp"] >= mesh.shape["dp"]


def test_sharded_matches_single_device():
    mesh = make_mesh(8)
    p = 32 * mesh.shape["dp"]
    n = 16 * mesh.shape["tp"]
    _require_gspmd(p, n, 24)
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=21, base_util=0.2)
    want = np.asarray(assign(pods, nodes, params).assignment)
    got = np.asarray(sharded_assign(mesh, pods, nodes, params).assignment)
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(
    not hasattr(jax.config, "jax_num_cpu_devices"),
    reason="this jax version has no jax_num_cpu_devices config option "
    "(added after 0.4.x); the dryrun entry point requires it",
)
def test_dryrun_multichip_entry():
    import importlib.util, pathlib

    _require_gspmd(2048, 8192, 8)  # the dryrun's own at-scale shapes

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        pathlib.Path(__file__).resolve().parent.parent / "__graft_entry__.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256,)


def test_sharded_solve_stream_matches_single_device():
    from koordinator_tpu.parallel.sharded import sharded_solve_stream
    from koordinator_tpu.ops.solver import solve_stream

    mesh = make_mesh(8)
    b, pp = 2, 16 * mesh.shape["dp"]
    n = 16 * mesh.shape["tp"]
    pods, nodes, params, _ = make_fixture(p=b * pp, n=n, seed=31, base_util=0.2)
    stacked = jax.tree.map(lambda a: a.reshape((b, pp) + a.shape[1:]), pods)
    want, want_nodes, want_placed, _ = solve_stream(stacked, nodes, params)
    got, got_nodes, got_placed, _ = sharded_solve_stream(
        mesh, stacked, nodes, params
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(
        np.asarray(got_placed), np.asarray(want_placed)
    )
    np.testing.assert_allclose(
        np.asarray(got_nodes.requested), np.asarray(want_nodes.requested),
        rtol=1e-6,
    )


def test_shard_map_nominate_matches_replicated_topk():
    """The hand-scheduled node-sharded nomination (local top-k +
    all-gather combine) must produce exactly the candidates the
    replicated cost+topk produces — including the jitter hash, which is
    defined on global node indices and therefore shard-invariant."""
    import jax.numpy as jnp

    from koordinator_tpu.ops import costs as cost_ops, masks as mask_ops
    from koordinator_tpu.parallel.sharded import shard_map_nominate

    mesh = make_mesh(8)
    tp = mesh.shape["tp"]
    p, n = 24, 16 * tp
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=41, base_util=0.3)

    neg, idx = shard_map_nominate(mesh, pods, nodes, params, topk=4)
    neg, idx = np.asarray(neg), np.asarray(idx)

    # replicated reference
    free = nodes.allocatable - nodes.requested
    feas = mask_ops.fit_mask(pods.requests, free)
    feas &= mask_ops.usage_threshold_mask(
        pods.estimate, nodes.estimated_used, nodes.allocatable,
        params.usage_thresholds, nodes.metric_fresh,
    )
    feas &= nodes.schedulable[None, :]
    cost = cost_ops.load_aware_cost(
        pods.estimate, nodes.estimated_used, nodes.allocatable,
        params.score_weights, metric_fresh=nodes.metric_fresh,
    )
    pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
    ni = jnp.arange(n, dtype=jnp.uint32)[None, :]
    h = (pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)) & jnp.uint32(0xFFFF)
    cost = cost + h.astype(jnp.float32) * (4.0 / 65536.0)
    cost = jnp.where(feas, cost, jnp.inf)
    wneg, widx = jax.lax.top_k(-cost, 4)
    np.testing.assert_allclose(neg, np.asarray(wneg), rtol=1e-6)
    np.testing.assert_array_equal(idx, np.asarray(widx))


def test_sharded_matches_single_device_at_scale():
    """VERDICT r2 weak #4: correctness at the shapes where sharding
    matters — 2048 pods x 8192 nodes on the 8-device mesh, each tp shard
    holding 2048 node rows. Exact assignment equality with the
    single-device solver."""
    mesh = make_mesh(8)
    p, n = 2048, 8192
    _require_gspmd(p, n, 8)
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=77, base_util=0.2)
    want = np.asarray(assign(pods, nodes, params, max_rounds=8).assignment)
    got = np.asarray(
        sharded_assign(mesh, pods, nodes, params, max_rounds=8).assignment
    )
    np.testing.assert_array_equal(got, want)
    assert int((want >= 0).sum()) > p // 2  # the scale run actually places


def test_shard_map_nominate_pads_ragged_node_table():
    """n % tp != 0 no longer raises: the node table is padded with
    infeasible rows and the candidate sets still match the replicated
    reference over the REAL nodes."""
    import jax.numpy as jnp

    from koordinator_tpu.ops import costs as cost_ops, masks as mask_ops
    from koordinator_tpu.parallel.sharded import shard_map_nominate

    mesh = make_mesh(8)
    tp = mesh.shape["tp"]
    p, n = 16, 16 * tp + 3          # ragged: 3 rows past a shard boundary
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=51, base_util=0.3)

    neg, idx = shard_map_nominate(mesh, pods, nodes, params, topk=4)
    neg, idx = np.asarray(neg), np.asarray(idx)

    free = nodes.allocatable - nodes.requested
    feas = mask_ops.fit_mask(pods.requests, free)
    feas &= mask_ops.usage_threshold_mask(
        pods.estimate, nodes.estimated_used, nodes.allocatable,
        params.usage_thresholds, nodes.metric_fresh,
    )
    feas &= nodes.schedulable[None, :]
    cost = cost_ops.load_aware_cost(
        pods.estimate, nodes.estimated_used, nodes.allocatable,
        params.score_weights, metric_fresh=nodes.metric_fresh,
    )
    pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
    ni = jnp.arange(n, dtype=jnp.uint32)[None, :]
    h = (pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)) & jnp.uint32(0xFFFF)
    cost = cost + h.astype(jnp.float32) * (4.0 / 65536.0)
    cost = jnp.where(feas, cost, jnp.inf)
    wneg, widx = jax.lax.top_k(-cost, 4)
    wneg, widx = np.asarray(wneg), np.asarray(widx)
    # wherever the reference candidate is real (finite), the sharded one
    # must agree exactly; -inf slots (pod fits nowhere) are don't-cares
    finite = np.isfinite(wneg)
    np.testing.assert_allclose(neg[finite], wneg[finite], rtol=1e-6)
    np.testing.assert_array_equal(idx[finite], widx[finite])
    # no REAL finite candidate may ever point at a padded row
    assert (idx[np.isfinite(neg)] < n).all()


def test_mesh_mode_production_scheduler_equality():
    """VERDICT r3 #3 / r4 #3: multi-chip as a production mode. The SAME
    BatchScheduler pipeline (NUMA manager + DeviceManager + quota tree +
    an Available reservation) run with mesh=(dp,tp) must place exactly
    like the single-device path — including the per-winner cpusets,
    device minors and reservation consumption. Multiple solver chunks,
    so the on-device zone/slot/capacity chaining crosses shard
    boundaries (the driver dryrun runs the same check at 2048 pods ×
    4096 nodes)."""
    import __graft_entry__ as graft
    from koordinator_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(8)
    placed = graft._dryrun_production_scheduler(
        mesh, n_nodes=1024, n_pods=512, batch_bucket=256
    )
    assert placed == 512


def test_mesh_mode_pipelined_multichunk():
    """Mesh mode through the multi-chunk pipelined dispatch (chained
    capacity on device): placements equal the single-device run."""
    import copy

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

    mesh_probe = make_mesh(8)
    _require_gspmd(
        32 * mesh_probe.shape["dp"], 16 * mesh_probe.shape["tp"], 24
    )

    def build(mesh):
        snap = ClusterSnapshot()
        for i in range(200):
            snap.upsert_node(
                Node(
                    meta=ObjectMeta(name=f"n{i:03d}"),
                    status=NodeStatus(
                        allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                    ),
                )
            )
        sched = BatchScheduler(
            snap, LoadAwareArgs(), batch_bucket=128, mesh=mesh
        )
        sched.extender.monitor.stop_background()
        return sched

    pods = [
        Pod(
            meta=ObjectMeta(name=f"p{i:04d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048},
                priority=9000,
            ),
        )
        for i in range(400)  # 4 chunks of 128 → pipelined dispatch
    ]
    single = build(None).schedule(copy.deepcopy(pods))
    meshed = build(make_mesh(8)).schedule(copy.deepcopy(pods))
    a = {p.meta.uid: n for p, n in single.bound}
    b = {p.meta.uid: n for p, n in meshed.bound}
    assert len(a) == len(pods)
    assert a == b


def test_sharded_dispatch_watch_windows_feed_the_ledger():
    """The devprof watch plumbing on the sharded dispatches (koordlint
    retrace-hazard RH003 fix): every mesh-path dispatch lands in the
    CompileLedger as a watched, signature-carrying call, and the watched
    path's outputs are identical to the unwatched path's. shard_map
    partitions on every toolchain; the GSPMD entry points get the same
    assertion when this jaxlib's partitioner can compile them."""
    from koordinator_tpu.obs.devprof import DevProf
    from koordinator_tpu.parallel.sharded import shard_map_nominate

    mesh = make_mesh(8)
    p, n = 16, 16 * mesh.shape["tp"]
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=51, base_util=0.2)

    dp = DevProf().install()
    try:
        neg, idx = shard_map_nominate(
            mesh, pods, nodes, params, topk=4, devprof=dp
        )
        neg2, idx2 = shard_map_nominate(mesh, pods, nodes, params, topk=4)
        np.testing.assert_array_equal(np.asarray(idx), np.asarray(idx2))
        np.testing.assert_array_equal(np.asarray(neg), np.asarray(neg2))
        row = dp.ledger.report()["functions"]["shard_map_nominate"]
        assert row["calls"] == 1 and row["traces"] >= 1
        assert row["signatures"] == 1 and row["compile_seconds"] > 0
        cause = next(
            c
            for c in dp.ledger.report()["recent_causes"]
            if c.get("watched_fn") == "shard_map_nominate"
        )
        assert cause["delta"] == {"first_call": True}

        if _gspmd_compiles(p, n, 24):
            out = sharded_assign(mesh, pods, nodes, params, devprof=dp)
            want = sharded_assign(mesh, pods, nodes, params)
            np.testing.assert_array_equal(
                np.asarray(out.assignment), np.asarray(want.assignment)
            )
            row = dp.ledger.report()["functions"]["sharded_assign"]
            assert row["calls"] == 1 and row["traces"] >= 1
    finally:
        dp.uninstall()


def _mesh_sched(n_nodes=64, batch_bucket=64, **kw):
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i:03d}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                ),
            )
        )
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=batch_bucket,
        mesh=make_mesh(8), **kw
    )
    sched.extender.monitor.stop_background()
    return sched


def test_mesh_resident_scatter_matches_full_relower_after_churn():
    """Tentpole discipline: the tp-SHARDED resident NodeState is
    refreshed across cycles by the sharded dirty-row scatter (touch_rows
    — a handful of padded rows, never a full node-axis re-lower), the
    scatter's output keeps the NamedSharding (out_shardings pinned equal
    for the donated operand), and after node churn the shards are
    BIT-EXACTLY what a from-scratch lowering of the host snapshot
    produces."""
    from jax.sharding import PartitionSpec as P

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec

    sched = _mesh_sched()
    snap = sched.snapshot
    reg = sched.extender.registry

    def assert_resident_equals_host():
        ns = sched.node_state()
        na = snap.nodes
        est = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
        np.testing.assert_array_equal(np.asarray(ns.allocatable), na.allocatable)
        np.testing.assert_array_equal(np.asarray(ns.requested), na.requested)
        np.testing.assert_array_equal(np.asarray(ns.estimated_used), est)
        np.testing.assert_array_equal(np.asarray(ns.schedulable), na.schedulable)
        return ns

    ns0 = assert_resident_equals_host()          # initial full lower
    assert ns0.allocatable.sharding.spec == P("tp"), "not mesh-resident"

    # small mutation -> sharded dirty-row scatter, not a re-lower
    h2d0 = reg.get("solver_h2d_rows_total").value()
    pod = Pod(
        meta=ObjectMeta(name="s0", uid="s0"),
        spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 512}),
    )
    assert snap.assume_pod(pod, snap.node_name(7))
    ns1 = assert_resident_equals_host()
    uploaded = reg.get("solver_h2d_rows_total").value() - h2d0
    n_bucket = snap.nodes.allocatable.shape[0]
    assert 0 < uploaded < n_bucket, uploaded
    assert ns1.allocatable.sharding.spec == P("tp"), (
        "scatter_rows_sharded dropped the resident sharding"
    )

    # node churn -> full re-lower of the (new) axis, still bit-exact and
    # still sharded; the NEXT small mutation scatters again
    snap.remove_node(snap.node_name(3))
    from koordinator_tpu.api.types import Node, NodeStatus

    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="late-node"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
            ),
        )
    )
    ns2 = assert_resident_equals_host()
    assert ns2.allocatable.sharding.spec == P("tp")
    h2d1 = reg.get("solver_h2d_rows_total").value()
    pod2 = Pod(
        meta=ObjectMeta(name="s1", uid="s1"),
        spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 512}),
    )
    assert snap.assume_pod(pod2, "late-node")
    assert_resident_equals_host()
    uploaded2 = reg.get("solver_h2d_rows_total").value() - h2d1
    assert 0 < uploaded2 < n_bucket, uploaded2


def test_mesh_dispatch_fault_degrades_down_ladder_not_crash():
    """Chaos arm (first-class multichip): mesh mode rides the SAME
    fallback ladder as single-device instead of bypassing it. A
    solver.dispatch fault on the mesh path degrades to the per-chunk
    sharded level and still places; both device levels failing degrades
    to the host reference — never a crash, never a wedge."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.chaos import FaultInjector

    def pods(n, prefix="p"):
        return [
            Pod(
                meta=ObjectMeta(name=f"{prefix}{i}", uid=f"{prefix}{i}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048},
                    priority=9000,
                ),
            )
            for i in range(n)
        ]

    chaos = FaultInjector(seed=7)
    s = _mesh_sched(n_nodes=16, batch_bucket=8, chaos=chaos)
    chaos.arm("solver.dispatch", error=RuntimeError, times=1)
    out = s.schedule(pods(6))
    assert len(out.bound) == 6, "ladder must still place under the fault"
    assert s._fallback_level >= 1
    reg = s.extender.registry
    assert reg.get("solver_fallback_total").value(level="1") >= 1.0

    chaos2 = FaultInjector(seed=7)
    s2 = _mesh_sched(n_nodes=16, batch_bucket=8, chaos=chaos2)
    chaos2.arm("solver.dispatch", error=RuntimeError, times=1)
    chaos2.arm("solver.dispatch_chunk", error=RuntimeError, times=1)
    out2 = s2.schedule(pods(5, prefix="q"))
    assert len(out2.bound) == 5
    assert s2._fallback_level == 2, "host reference is the floor"
