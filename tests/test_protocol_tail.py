"""Round-4 annotation-protocol tail (VERDICT r3 #4): behaviors, not just
keys — LS/BE CPU shared pools end-to-end, quota non-preemptible
min-bounded admission, numa-topology-spec, node-level
cpu-bind-policy/numa-allocate-strategy labels, kubelet cpu-manager state
consumption, extended-resource-spec."""

import json

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    ElasticQuota,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import CPUTopology, parse_cpuset
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager
from koordinator_tpu.scheduler.plugins.nodenumaresource import (
    NUMAManager,
    NUMAPolicy,
)


# ---- LS/BE CPU shared pools: koordlet computes → annotation → cpuset hook ----


def _informer_with_pods(pods):
    inf = StatesInformer("n0")
    inf.set_pods(pods)
    return inf


def _bound_lsr(name, cpuset, qos="LSR"):
    return Pod(
        meta=ObjectMeta(
            name=name,
            labels={ext.LABEL_POD_QOS: qos},
            annotations={
                ext.ANNOTATION_RESOURCE_STATUS: json.dumps({"cpuset": cpuset})
            },
        ),
        spec=PodSpec(requests={ext.RES_CPU: 4000}, node_name="n0"),
    )


def test_shared_pools_computed_and_stamped():
    """calCPUSharePools semantics: LS pools exclude EVERY cpuset-bound
    pod's CPUs; BE pools exclude only LSE pods' CPUs (BE may ride LSR
    cores, never LSE); pools group per (socket, numa)."""
    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=4, threads_per_core=1
    )
    inf = _informer_with_pods(
        [
            _bound_lsr("lsr", "0-1", qos="LSR"),    # numa 0
            _bound_lsr("lse", "4-5", qos="LSE"),    # numa 1
        ]
    )
    report = inf.report_topology(topo, policy="SingleNUMANode")
    ann = report.meta.annotations
    ls = ext.parse_cpu_shared_pools(ann)
    be = ext.parse_cpu_shared_pools(ann, be=True)
    ls_by_node = {p["node"]: p["cpuset"] for p in ls}
    be_by_node = {p["node"]: p["cpuset"] for p in be}
    # LS: both LSR and LSE cpus carved out
    assert parse_cpuset(ls_by_node[0]) == {2, 3}
    assert parse_cpuset(ls_by_node[1]) == {6, 7}
    # BE: only the LSE cpus carved out — BE may ride the LSR cores
    assert parse_cpuset(be_by_node[0]) == {0, 1, 2, 3}
    assert parse_cpuset(be_by_node[1]) == {6, 7}
    # kubelet policy annotation stamped
    kubelet = ext.parse_kubelet_cpu_manager_policy(ann)
    assert kubelet["policy"] == "none"


def test_cpuset_rule_places_ls_and_be_pods():
    """rule.go getContainerCPUSet: LS → all LS pools; BE → cleared;
    SYSTEM → the system carve-out; numa-aware alloc → that zone's pool;
    unlabeled under kubelet static → hands off."""
    from koordinator_tpu.koordlet.runtimehooks import CpusetRule, cpuset_plan

    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=4, threads_per_core=1
    )
    inf = _informer_with_pods([_bound_lsr("lsr", "0-1")])
    report = inf.report_topology(topo, system_qos_cpuset="7")
    rule = CpusetRule.from_topology(report)

    def qos_pod(qos, ann=None):
        return Pod(
            meta=ObjectMeta(
                name=f"p-{qos}",
                labels={ext.LABEL_POD_QOS: qos},
                annotations=ann or {},
            ),
            spec=PodSpec(requests={ext.RES_CPU: 1000}),
        )

    # LS pod: every LS pool (exclusive LSR cpus + system carve-out gone)
    ls_plan = cpuset_plan(qos_pod("LS"), rule)
    assert len(ls_plan) == 1
    got = set()
    for part in ls_plan[0][2].split(","):
        got |= parse_cpuset(part)
    assert got == {2, 3, 4, 5, 6}
    # BE pod: cleared (cpu-suppress owns the group)
    be_plan = cpuset_plan(qos_pod("BE"), rule)
    assert be_plan[0][2] == ""
    # SYSTEM pod: the carve-out
    sys_plan = cpuset_plan(qos_pod("SYSTEM"), rule)
    assert sys_plan[0][2] == "7"
    # numa-aware LS pod: zone-1 pool only
    numa_pod = qos_pod(
        "LS",
        ann={
            ext.ANNOTATION_RESOURCE_STATUS: json.dumps(
                {"numaNodeResources": [{"node": 1}]}
            )
        },
    )
    numa_plan = cpuset_plan(numa_pod, rule)
    assert parse_cpuset(numa_plan[0][2]) == {4, 5, 6}
    # exclusive cpuset still wins outright
    excl_plan = cpuset_plan(_bound_lsr("x", "0-1"), rule)
    assert excl_plan[0][2] == "0-1"
    # kubelet static + unlabeled pod: hands off
    rule_static = CpusetRule.from_topology(report)
    rule_static.kubelet_policy = "static"
    none_pod = Pod(meta=ObjectMeta(name="plain"), spec=PodSpec())
    assert cpuset_plan(none_pod, rule_static) == []


# ---- quota non-preemptible min-bounded admission ----


def _quota_cluster(min_cpu=8.0, max_cpu=100.0):
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 400.0, ext.RES_MEMORY: 400.0}
            ),
        )
    )
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400}
    )
    mgr.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="team"),
            min={ext.RES_CPU: min_cpu, ext.RES_MEMORY: min_cpu},
            max={ext.RES_CPU: max_cpu, ext.RES_MEMORY: max_cpu},
        )
    )
    sched = BatchScheduler(snap, quotas=mgr, batch_bucket=64)
    sched.extender.monitor.stop_background()
    return snap, mgr, sched


def _npod(name, cpu, nonpre=False):
    labels = {ext.LABEL_QUOTA_NAME: "team"}
    if nonpre:
        labels[ext.LABEL_PREEMPTIBLE] = "false"
    return Pod(
        meta=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}, priority=9000
        ),
    )


def test_non_preemptible_bounded_by_min_not_runtime():
    """plugin.go:252-262: a non-preemptible pod must fit
    nonPreemptibleUsed + request ≤ quota MIN even when runtime has room;
    preemptible pods still use the full runtime."""
    snap, mgr, sched = _quota_cluster(min_cpu=8.0, max_cpu=100.0)
    # two non-preemptible 6-cpu pods: first fits min (6 ≤ 8), second
    # (12 > 8) rejected despite abundant runtime
    out = sched.schedule([_npod("a", 6.0, nonpre=True), _npod("b", 6.0, nonpre=True)])
    assert len(out.bound) == 1
    assert len(out.unschedulable) == 1
    # a preemptible pod of the same size sails through on runtime
    out2 = sched.schedule([_npod("c", 6.0)])
    assert len(out2.bound) == 1
    # ledger: nonpre_used == 6 at the leaf
    idx = mgr.index_of("team")
    assert mgr.nonpre_used[idx][0] == 6.0
    # status sync stamps the non-preemptible annotations
    report = mgr.sync_status()
    assert report["team"]["nonPreemptibleUsed"][ext.RES_CPU] == 6.0
    eq_ann = mgr._nodes["team"].quota.meta.annotations
    assert ext.ANNOTATION_QUOTA_NON_PREEMPTIBLE_USED in eq_ann


def test_non_preemptible_in_batch_sequencing():
    """The shadow-level enforcement is cumulative WITHIN one batch: three
    4-cpu non-preemptible pods against min=8 admit exactly two."""
    snap, mgr, sched = _quota_cluster(min_cpu=8.0, max_cpu=100.0)
    pods = [_npod(f"p{i}", 4.0, nonpre=True) for i in range(3)]
    out = sched.schedule(pods)
    assert len(out.bound) == 2
    assert len(out.unschedulable) == 1


def test_non_preemptible_refund_on_unassign():
    snap, mgr, sched = _quota_cluster(min_cpu=8.0)
    pod = _npod("a", 6.0, nonpre=True)
    out = sched.schedule([pod])
    assert len(out.bound) == 1
    idx = mgr.index_of("team")
    assert mgr.nonpre_used[idx][0] == 6.0
    mgr.unassign_pod("team", pod)
    assert mgr.nonpre_used[idx][0] == 0.0


def test_non_preemptible_enforced_on_full_depth_chain():
    """A quota at the maximum lowered tree depth still gets its shadow
    slot (chains carry one spare column), so the MIN bound holds even
    for the deepest leaves."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 400.0, ext.RES_MEMORY: 400.0}
            ),
        )
    )
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400}
    )
    # 4-level tree: root -> org -> team -> squad (leaf at MAX_LEVELS)
    parent = ""
    for name in ("root-q", "org-q", "team-q", "squad-q"):
        mgr.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name=name),
                min={ext.RES_CPU: 8, ext.RES_MEMORY: 8},
                max={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
                parent=parent,
                is_parent=name != "squad-q",
            )
        )
        parent = name
    sched = BatchScheduler(snap, quotas=mgr, batch_bucket=64)
    sched.extender.monitor.stop_background()

    def npod(name, cpu):
        return Pod(
            meta=ObjectMeta(
                name=name,
                labels={
                    ext.LABEL_QUOTA_NAME: "squad-q",
                    ext.LABEL_PREEMPTIBLE: "false",
                },
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu},
                priority=9000,
            ),
        )

    out = sched.schedule([npod("a", 6.0), npod("b", 6.0)])
    # min=8 at the leaf: only one 6-cpu non-preemptible pod fits
    assert len(out.bound) == 1
    assert len(out.unschedulable) == 1


# ---- numa-topology-spec ----


def test_numa_topology_spec_requires_single_zone():
    """AnnotationNUMATopologySpec SingleNUMANode: the pod needs a
    one-zone fit on ANY node (even policy=None nodes); a pod too big for
    one zone is unschedulable while a plain pod of the same size lands."""
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=8, threads_per_core=1
    )
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    numa.register_node(
        "n0", topo, NUMAPolicy.NONE, memory_per_zone_mib=32768
    )
    sched = BatchScheduler(snap, LoadAwareArgs(), numa=numa, batch_bucket=64)
    sched.extender.monitor.stop_background()

    def spec_pod(name, cpu):
        return Pod(
            meta=ObjectMeta(
                name=name,
                annotations={
                    ext.ANNOTATION_NUMA_TOPOLOGY_SPEC: json.dumps(
                        {"numaTopologyPolicy": "SingleNUMANode"}
                    )
                },
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 1024},
                priority=9000,
            ),
        )

    # 12 cores > one 8-core zone: plain pod fits the node total, the
    # single-numa-required pod does not
    plain = Pod(
        meta=ObjectMeta(name="plain"),
        spec=PodSpec(
            requests={ext.RES_CPU: 12000, ext.RES_MEMORY: 1024},
            priority=9000,
        ),
    )
    out = sched.schedule([spec_pod("req", 12000)])
    assert out.bound == []
    out2 = sched.schedule([plain])
    assert len(out2.bound) == 1
    # a zone-sized required pod lands and records its zone
    snap2 = ClusterSnapshot()
    numa2 = NUMAManager(snap2)
    snap2.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    numa2.register_node("n0", topo, NUMAPolicy.NONE, memory_per_zone_mib=32768)
    sched2 = BatchScheduler(snap2, LoadAwareArgs(), numa=numa2, batch_bucket=64)
    sched2.extender.monitor.stop_background()
    out3 = sched2.schedule([spec_pod("ok", 6000)])
    assert len(out3.bound) == 1
    pod = out3.bound[0][0]
    status = json.loads(pod.meta.annotations[ext.ANNOTATION_RESOURCE_STATUS])
    assert status["numaNodeResources"][0]["node"] in (0, 1)


# ---- node-level labels + kubelet allocs through the topology report ----


def test_node_cpu_allocs_and_system_qos_reserved_in_scheduler():
    """pod-cpu-allocs + kubelet reservedCPUs + exclusive system-qos CPUs
    are pre-taken: a cpuset-bound pod can never receive them."""
    from koordinator_tpu.api.types import NodeResourceTopology

    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    topo = CPUTopology.uniform(
        sockets=1, numa_per_socket=1, cores_per_numa=8, threads_per_core=1
    )
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 8000, ext.RES_MEMORY: 32768}
            ),
        )
    )
    report = NodeResourceTopology(
        meta=ObjectMeta(
            name="n0",
            annotations={
                ext.ANNOTATION_NODE_CPU_ALLOCS: json.dumps(
                    [{"namespace": "kube-system", "name": "g", "cpuset": "0-1"}]
                ),
                ext.ANNOTATION_NODE_SYSTEM_QOS_RESOURCE: json.dumps(
                    {"cpuset": "2", "cpusetExclusive": True}
                ),
            },
        ),
        cpu_topology={
            c.cpu_id: (c.core_id, c.numa_node, c.socket) for c in topo.cpus
        },
        topology_policy="SingleNUMANode",
    )
    numa.register_from_topology(report)
    st = numa._nodes["n0"]
    # 0,1 (kubelet alloc) + 2 (system qos) are gone
    taken = st.accumulator._allocated
    assert {0, 1, 2} <= taken
    cpuset = st.accumulator.take("pod", 4)
    assert cpuset is not None and not (cpuset & {0, 1, 2})


def test_node_numa_allocate_strategy_least_allocated():
    """LabelNodeNUMAAllocateStrategy=LeastAllocated spreads winners
    across zones instead of bin-packing one zone first."""
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=8, threads_per_core=1
    )
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 16000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    numa.register_node(
        "n0", topo, NUMAPolicy.SINGLE_NUMA_NODE, memory_per_zone_mib=32768
    )
    numa._nodes["n0"].numa_allocate_strategy = (
        ext.NODE_NUMA_STRATEGY_MOST_ALLOCATED
    )
    # MostAllocated: both pods pack into one (tighter) zone sequence:
    # first pod zone 0, second pod joins zone 0 (more utilized)
    res = numa.allocate_batch(
        uids=["a", "b"],
        annotations=[{}, {}],
        node_names=["n0", "n0"],
        cpu_milli=[2000.0, 2000.0],
        mem_mib=[1024.0, 1024.0],
        bind=[False, False],
    )
    assert all(r is not None for r in res)
    zones = [numa._nodes["n0"].owners[u][0] for u in ("a", "b")]
    assert zones[0] == zones[1]


# ---- extended-resource-spec ----


def test_extended_resource_spec_round_trip():
    containers = {
        "main": {
            "requests": {ext.RES_BATCH_CPU: 2000, ext.RES_BATCH_MEMORY: 4096}
        }
    }
    ann = {
        ext.ANNOTATION_EXTENDED_RESOURCE_SPEC: ext.format_extended_resource_spec(
            containers
        )
    }
    parsed = ext.parse_extended_resource_spec(ann)
    assert parsed["main"]["requests"][ext.RES_BATCH_CPU] == 2000
    assert ext.parse_extended_resource_spec({}) == {}


# ---- controller-managed / skip-update-resources (r5, the last two keys:
# apis/extension/cluster_colocation_profile.go:24-41) ----


def _profile(name, labels=None, annotations=None, translate=True):
    from koordinator_tpu.api.types import ClusterColocationProfile, ObjectMeta

    return ClusterColocationProfile(
        meta=ObjectMeta(
            name=name,
            labels=dict(labels or {}),
            annotations=dict(annotations or {}),
        ),
        selector={"app": "colo"},
        labels={f"from-{name}": "yes"},
        resource_translation=(
            {ext.RES_CPU: ext.RES_BATCH_CPU} if translate else {}
        ),
    )


def _colo_pod(name="c1", phase=None, node=None):
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodPhase, PodSpec

    return Pod(
        meta=ObjectMeta(name=name, labels={"app": "colo"}),
        spec=PodSpec(requests={ext.RES_CPU: 1000}, node_name=node),
        phase=phase or PodPhase.PENDING,
    )


def test_skip_update_resources_suppresses_resource_mutation():
    """A matched profile carrying the skip annotation keeps labels/QoS
    mutations but suppresses the resource rewrite for the WHOLE pod —
    even when another matched profile has no such annotation
    (cluster_colocation_profile.go:94-115)."""
    from koordinator_tpu.manager.profile import ProfileMutator

    mut = ProfileMutator(
        [
            _profile("a"),
            _profile(
                "b",
                annotations={ext.ANNOTATION_SKIP_UPDATE_RESOURCES: ""},
                translate=False,
            ),
        ]
    )
    pod = _colo_pod()
    mut.mutate(pod)
    assert pod.meta.labels["from-a"] == "yes"
    assert pod.meta.labels["from-b"] == "yes"
    # resource translation suppressed by b's annotation
    assert ext.RES_CPU in pod.spec.requests
    assert ext.RES_BATCH_CPU not in pod.spec.requests
    # without the skip profile the translation applies
    pod2 = _colo_pod("c2")
    ProfileMutator([_profile("a")]).mutate(pod2)
    assert ext.RES_BATCH_CPU in pod2.spec.requests


def test_controller_managed_gates_reconcile():
    """With ReconcileByDefault off, the controller reconciles only
    profiles labeled controller-managed="true"
    (colocationprofile_controller.go:86-91)."""
    from koordinator_tpu.manager.colocation_controller import (
        ColocationProfileController,
    )
    from koordinator_tpu.manager.profile import ProfileMutator

    unmanaged = _profile("un")
    managed = _profile(
        "mg", labels={ext.LABEL_CONTROLLER_MANAGED: "true"}
    )
    mut = ProfileMutator([unmanaged, managed])
    ctrl = ColocationProfileController(mut, reconcile_by_default=False)
    pod = _colo_pod()
    changed = ctrl.reconcile([pod])
    assert changed == [pod]
    assert pod.meta.labels.get("from-mg") == "yes"
    assert "from-un" not in pod.meta.labels
    # default-on reconciles both (the reference's ReconcileByDefault)
    pod2 = _colo_pod("c3")
    ColocationProfileController(mut).reconcile([pod2])
    assert pod2.meta.labels.get("from-un") == "yes"


def test_device_plugin_adapter_annotations():
    """Device winners carry the vendor device-plugin protocol
    (device_plugin_adapter.go): bind-timestamp always, gpu-minors for
    GPU allocations, and the Huawei NPU pair on huawei-vendor nodes."""
    from koordinator_tpu.api.types import (
        Device,
        DeviceInfo,
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager

    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    for name, labels in (("gen", {}), ("hw", {ext.LABEL_GPU_VENDOR: "huawei"})):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
                ),
            )
        )
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name, labels=labels),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g) for g in range(4)
                ],
            )
        )
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()

    def place(name, node):
        pod = Pod(
            meta=ObjectMeta(name=name),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_GPU: 2},
                node_name=node,
                priority=9000,
            ),
        )
        out = sched.schedule([pod])
        assert len(out.bound) == 1, out.unschedulable
        return out.bound[0][0]

    gen = place("p-gen", "gen")
    assert ext.ANNOTATION_BIND_TIMESTAMP in gen.meta.annotations
    assert gen.meta.annotations[ext.ANNOTATION_GPU_MINORS] == "0,1"
    assert ext.ANNOTATION_HUAWEI_NPU_CORE not in gen.meta.annotations
    hw = place("p-hw", "hw")
    assert hw.meta.annotations[ext.ANNOTATION_HUAWEI_NPU_CORE] == "0,1"
    assert ext.ANNOTATION_PREDICATE_TIME in hw.meta.annotations
