"""QoS-differentiated overload control (brownout PR).

Covers the three tentpole mechanisms and their wiring:

* bounded, QoS-aware admission at ``StreamScheduler.submit`` — PROD/MID
  always admitted, BATCH/FREE deferred past their band budget and SHED
  (terminal lifecycle event + metric + resubmit ticket) once the age
  limit passes too; deferred pods promote when pressure clears, ride
  handoffs, and are promoted unconditionally by a terminal flush;
* the ``BrownoutController`` ladder — monotonic ±1 transitions under
  sustain/cooldown hysteresis, per-level policy (pipeline depth cap,
  serial gate, bucket degrade, defers/sheds), topology yield, flight-
  recorder journaling, ``/healthz`` row and ``/debug/brownout``;
* the ``CircuitBreaker`` on ``SolverClient`` — K consecutive failures
  open it, calls fail FAST (``ChannelBreakerOpen``), the half-open
  probe recloses, the ``channel.breaker_storm`` chaos point trips it
  deterministically;

plus the satellites: burn/brownout-aware router spill, the
``shed``-terminal ``validate_timeline`` arm, the storm-shaped lifecycle
eviction regression, ``ClaimTable.void_claims``, and the SLO burn
time-horizon/evidence-floor semantics the ladder leans on.
"""

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import PriorityClass
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.obs.lifecycle import (
    LifecycleEvent,
    PodLifecycle,
    validate_timeline,
)
from koordinator_tpu.obs.slo import SloTarget, SloTracker
from koordinator_tpu.runtime.overload import (
    AdmissionController,
    BrownoutController,
    CircuitBreaker,
    OverloadConfig,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.stream import StreamScheduler

ALLOC = {ext.RES_CPU: 32_000.0, ext.RES_MEMORY: 128 * 1024.0}
REQ = {ext.RES_CPU: 1_000.0, ext.RES_MEMORY: 2_048.0}

PRIO = {
    PriorityClass.PROD: 9000,
    PriorityClass.MID: 7500,
    PriorityClass.BATCH: 5500,
    PriorityClass.FREE: 3500,
}


def _pod(name: str, band: PriorityClass = PriorityClass.PROD) -> Pod:
    return Pod(
        meta=ObjectMeta(name=name, uid=name),
        spec=PodSpec(requests=dict(REQ), priority=PRIO[band]),
    )


def _sched(n_nodes: int = 4) -> BatchScheduler:
    s = BatchScheduler(
        args=LoadAwareArgs(usage_thresholds={}), batch_bucket=16
    )
    s.extender.monitor.stop_background()
    for i in range(n_nodes):
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(allocatable=dict(ALLOC)),
            )
        )
    return s


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _admission(
    clock,
    budget_batch=2,
    budget_free=1,
    age_batch=5.0,
    age_free=2.0,
    brownout=None,
    lifecycle=None,
    registry=None,
):
    return AdmissionController(
        OverloadConfig(
            band_budget={
                PriorityClass.BATCH: budget_batch,
                PriorityClass.FREE: budget_free,
            },
            band_age_limit_s={
                PriorityClass.BATCH: age_batch,
                PriorityClass.FREE: age_free,
            },
        ),
        brownout=brownout,
        lifecycle=lifecycle,
        clock=clock,
    )


# ---------------------------------------------------------------------------
# bounded, QoS-aware admission
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_prod_and_mid_always_admitted(self):
        clock = _Clock()
        ov = _admission(clock, budget_batch=0, budget_free=0)
        for band in (PriorityClass.PROD, PriorityClass.MID):
            assert ov.admit(_pod("p", band), band_depth=10**6) == ov.ADMIT

    def test_batch_defers_past_budget_then_age_sheds(self):
        clock = _Clock()
        lc = PodLifecycle(clock=clock)
        ov = _admission(clock, lifecycle=lc)
        sched = _sched()
        st = StreamScheduler(
            sched, max_batch=16, overload=ov, lifecycle=lc
        )
        # budget 2: two BATCH pods admit, the third defers
        assert st.submit(_pod("b0", PriorityClass.BATCH), now=0.0) == "admit"
        assert st.submit(_pod("b1", PriorityClass.BATCH), now=0.0) == "admit"
        assert st.submit(_pod("b2", PriorityClass.BATCH), now=0.0) == "defer"
        assert st.backlog() == 2 and st.deferred_backlog() == 1
        # pumping drains the live queue; the deferred pod PROMOTES once
        # its band is back under budget — original stamp intact
        out = st.pump()
        assert {p.meta.uid for p, n, _l in out if n} == {"b0", "b1"}
        out = st.pump()
        assert [p.meta.uid for p, n, _l in out if n] == ["b2"]
        assert st.deferred_backlog() == 0
        evs = [e.stage for e in lc.timeline("b2")]
        assert evs[-1] == "ack"
        # deferral + promotion both recorded as enqueue events
        assert evs.count("enqueue") == 2

    def test_deferred_pod_ages_out_to_terminal_shed_with_ticket(self):
        clock = _Clock()
        lc = PodLifecycle(clock=clock)
        ov = _admission(clock, budget_batch=1, age_batch=3.0, lifecycle=lc)
        sched = _sched(n_nodes=1)
        st = StreamScheduler(sched, max_batch=1, overload=ov)
        # b0 occupies the band budget FOREVER (max_batch=1 and a PROD
        # stream ahead of it keeps the band full by re-submitting)
        assert st.submit(_pod("b0", PriorityClass.BATCH), now=0.0) == "admit"
        assert st.submit(_pod("b1", PriorityClass.BATCH), now=0.0) == "defer"
        # keep the band AT budget by refilling as pumps drain it; b1's
        # age crosses the limit while still unpromotable
        for i in range(6):
            clock.t = float(i)
            st.pump()
            if st._band_live.get(int(PriorityClass.BATCH), 0) == 0:
                st.submit(
                    _pod(f"fill{i}", PriorityClass.BATCH), now=clock.t
                )
        clock.t = 10.0
        st.pump()
        tickets = ov.take_tickets()
        assert [t.pod.meta.uid for t in tickets] == ["b1"]
        t = tickets[0]
        assert t.band == PriorityClass.BATCH and t.arrival == 0.0
        assert t.reason == "overload_shed"
        evs = lc.timeline("b1")
        assert evs[-1].stage == "shed"
        assert validate_timeline(evs) == []
        assert ov.shed_counts == {int(PriorityClass.BATCH): 1}

    def test_shed_metric_counts_per_band(self):
        clock = _Clock()
        sched = _sched()
        ov = _admission(clock, registry=None)
        st = StreamScheduler(sched, overload=ov)
        reg = sched.extender.registry
        # L4 brownout sheds FREE at submit
        bo = BrownoutController(clock=clock)
        bo.level = BrownoutController.L4
        ov.brownout = bo
        assert st.submit(_pod("f0", PriorityClass.FREE), now=0.0) == "shed"
        assert (
            reg.get("overload_shed_total").value(band="FREE") == 1.0
        )

    def test_extract_queued_includes_deferred_and_resets_bands(self):
        clock = _Clock()
        ov = _admission(clock, budget_batch=1)
        st = StreamScheduler(_sched(), overload=ov)
        st.submit(_pod("b0", PriorityClass.BATCH), now=0.0)
        st.submit(_pod("b1", PriorityClass.BATCH), now=1.0)
        st.submit(_pod("p0", PriorityClass.PROD), now=2.0)
        out = st.extract_queued()
        assert {p.meta.uid for p, _a, _t in out} == {"b0", "b1", "p0"}
        # stamps ride along; band accounting reset for the next owner
        assert {a for _p, a, _t in out} == {0.0, 1.0, 2.0}
        assert st.backlog() == 0 and st.deferred_backlog() == 0
        assert st._band_live == {}

    def test_flush_promotes_deferred_unconditionally(self):
        clock = _Clock()
        ov = _admission(clock, budget_batch=1)
        st = StreamScheduler(_sched(), overload=ov)
        st.submit(_pod("b0", PriorityClass.BATCH), now=0.0)
        assert st.submit(_pod("b1", PriorityClass.BATCH), now=0.0) == "defer"
        out = st.flush()
        assert {p.meta.uid for p, n, _l in out if n} == {"b0", "b1"}

    def test_band_accounting_matches_queue_contents(self):
        clock = _Clock()
        ov = _admission(clock, budget_batch=3, budget_free=2)
        st = StreamScheduler(_sched(), max_batch=4, overload=ov)
        for i in range(3):
            st.submit(_pod(f"b{i}", PriorityClass.BATCH), now=0.0)
        for i in range(2):
            st.submit(_pod(f"f{i}", PriorityClass.FREE), now=0.0)
        st.submit(_pod("p0", PriorityClass.PROD), now=0.0)
        st.pump()
        st.flush()

        def _recount():
            counts = {}
            for p, _a, _t in st._queue:
                b = int(p.priority_class)
                counts[b] = counts.get(b, 0) + 1
            return counts

        live = {b: n for b, n in st._band_live.items() if n}
        assert live == _recount()


# ---------------------------------------------------------------------------
# the brownout ladder
# ---------------------------------------------------------------------------


class _BurnStub:
    """SloTracker stand-in: a settable per-call burn."""

    def __init__(self):
        self.burn = 0.0

    def burn_rate(self, shard, slo):
        return self.burn


class _TopoStub:
    def __init__(self, can=True, cooling=False):
        self.can = can
        self.cooling = cooling

    @property
    def in_cooldown(self):
        return self.cooling

    def can_scale_out(self):
        return self.can


def _ladder(burn, sustain=2, cooldown=2, topology=None, clock=None):
    return BrownoutController(
        slo=burn,
        shards=lambda: [0],
        sustain=sustain,
        cooldown=cooldown,
        clock=clock or _Clock(),
        topology=topology,
    )


class TestBrownoutLadder:
    def test_escalates_one_step_per_sustain_and_deescalates_on_cooldown(self):
        burn = _BurnStub()
        bo = _ladder(burn)
        burn.burn = 100.0  # target L4 immediately
        levels = []
        for _ in range(10):
            bo.tick()
            levels.append(bo.level)
        # one step per `sustain` ticks — NEVER a jump
        assert levels == [0, 1, 1, 2, 2, 3, 3, 4, 4, 4]
        burn.burn = 0.0
        down = []
        for _ in range(10):
            bo.tick()
            down.append(bo.level)
        assert down == [4, 3, 3, 2, 2, 1, 1, 0, 0, 0]
        assert all(
            abs(t["to"] - t["from"]) == 1 for t in bo.transitions()
        )

    def test_hysteresis_no_flap_on_oscillating_burn(self):
        burn = _BurnStub()
        bo = _ladder(burn, sustain=3, cooldown=3)
        # burn oscillates across the L1 threshold every tick: neither
        # streak ever reaches sustain/cooldown — zero transitions
        for i in range(20):
            burn.burn = 1.5 if i % 2 else 0.0
            bo.tick()
        assert bo.level == 0 and bo.transitions() == []

    def test_yields_to_topology_split_boundedly(self):
        burn = _BurnStub()
        topo = _TopoStub(can=True, cooling=False)
        bo = _ladder(burn, sustain=2, topology=topo)
        burn.burn = 100.0
        bo.tick()
        bo.tick()  # sustain met — but the topology can still split
        assert bo.level == 0 and bo.stats["yielded_to_split"] == 1
        bo.tick()  # yield budget (max_yield = sustain = 2) not yet spent
        assert bo.level == 0 and bo.stats["yielded_to_split"] == 2
        bo.tick()  # budget exhausted: brown out anyway
        assert bo.level == 1
        # during a transition cooldown there is NO yield
        topo.cooling = True
        bo2 = _ladder(burn, sustain=1, topology=topo)
        bo2.tick()
        assert bo2.level == 1 and bo2.stats["yielded_to_split"] == 0

    def test_policy_accessors_per_level(self):
        bo = _ladder(_BurnStub())
        assert bo.pipeline_depth_cap() > 100
        assert not bo.serial_only() and bo.bucket_degrade_steps() == 0
        assert not bo.defers(PriorityClass.BATCH)
        bo.level = BrownoutController.L1
        assert bo.pipeline_depth_cap() == 1 and not bo.serial_only()
        bo.level = BrownoutController.L2
        assert bo.serial_only() and bo.bucket_degrade_steps() == 1
        assert not bo.defers(PriorityClass.BATCH)
        bo.level = BrownoutController.L3
        assert bo.defers(PriorityClass.BATCH)
        assert bo.defers(PriorityClass.FREE)
        assert not bo.defers(PriorityClass.PROD)
        assert not bo.sheds(PriorityClass.FREE)
        bo.level = BrownoutController.L4
        assert bo.sheds(PriorityClass.FREE)
        assert not bo.sheds(PriorityClass.BATCH)

    def test_thresholds_must_ascend(self):
        with pytest.raises(ValueError):
            BrownoutController(thresholds=(2.0, 1.0, 4.0, 8.0))
        with pytest.raises(ValueError):
            BrownoutController(thresholds=(1.0, 2.0, 4.0))

    def test_transitions_journal_to_flight_recorder_and_health(self):
        from koordinator_tpu.obs.flightrecorder import FlightRecorder
        from koordinator_tpu.obs.health import HealthRegistry

        burn = _BurnStub()
        bo = _ladder(burn, sustain=1, cooldown=1)
        fr = FlightRecorder(capacity=8)
        health = HealthRegistry()
        bo.attach_flight(fr)
        bo.attach_health(health)
        assert health.get("brownout")["ok"] is True
        burn.burn = 1.5
        bo.tick(cycle=7)
        assert bo.level == 1
        rec = fr.last(1)[0]
        assert rec["cycle"] == 7
        assert rec["brownout"] == {"from": 0, "to": 1, "burn": 1.5}
        row = health.get("brownout")
        assert row["ok"] is False and "L1" in row["detail"]

    def test_debug_brownout_endpoint_and_gauge(self):
        import json as _json

        clock = _Clock()
        burn = _BurnStub()
        bo = BrownoutController(
            slo=burn, shards=lambda: [0], sustain=1, clock=clock
        )
        ov = AdmissionController(brownout=bo, clock=clock)
        sched = _sched()
        StreamScheduler(sched, overload=ov)
        services = sched.extender.services
        code, body = services.dispatch("GET", "/debug/brownout")
        assert code == 200
        doc = _json.loads(body)
        assert doc["level"] == 0 and doc["level_name"] == "L0"
        reg = sched.extender.registry
        assert reg.get("brownout_level").value() == 0.0
        burn.burn = 3.0
        bo.tick()
        assert reg.get("brownout_level").value() == 1.0
        assert (
            reg.get("brownout_transitions_total").value(
                direction="escalate"
            )
            == 1.0
        )
        doc = _json.loads(services.dispatch("GET", "/debug/brownout")[1])
        assert doc["level"] == 1 and len(doc["transitions"]) == 1

    def test_l2_closes_pipeline_gate_and_degrades_bucket(self):
        clock = _Clock()
        bo = BrownoutController(clock=clock)
        ov = AdmissionController(brownout=bo, clock=clock)
        sched = _sched()
        st = StreamScheduler(
            sched, max_batch=8, pipelined=True, pipeline_depth=2,
            overload=ov,
        )
        try:
            assert sched.brownout is bo
            bucket0 = sched.effective_batch_bucket()
            bo.level = BrownoutController.L2
            assert sched.effective_batch_bucket() == max(16, bucket0 >> 1)
            # the brownout gate keeps the cycle serial — and names itself
            for i in range(3):
                st.submit(_pod(f"p{i}"), now=float(i))
            st.pump()
            st.flush()
            report = st._pipe.last_gate_report
            assert report["gates"]["brownout"] is False
            assert "brownout" in report["closed"]
            bo.level = BrownoutController.L0
            for i in range(3):
                st.submit(_pod(f"q{i}"), now=float(i))
            st.pump()
            st.flush()
            assert st._pipe.last_gate_report["gates"]["brownout"] is True
        finally:
            st.close()

    def test_l1_caps_pipeline_depth_at_one(self):
        clock = _Clock()
        bo = BrownoutController(clock=clock)
        ov = AdmissionController(brownout=bo, clock=clock)
        sched = _sched()
        st = StreamScheduler(
            sched, max_batch=2, pipelined=True, pipeline_depth=2,
            overload=ov,
        )
        try:
            bo.level = BrownoutController.L1
            # depth 2 would hold TWO fed batches before returning the
            # first decision; the L1 cap forces the oldest trailing
            # commit every feed — one-pump lag, like depth 1
            st.submit(_pod("a0"), now=0.0)
            assert st.pump() == []
            st.submit(_pod("a1"), now=1.0)
            out = st.pump()
            assert [p.meta.uid for p, n, _l in out if n] == ["a0"]
            assert len(st._pipe._pending) == 1
        finally:
            st.close()


# ---------------------------------------------------------------------------
# the circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_trip_probe_reclose(self):
        clock = _Clock()
        b = CircuitBreaker(threshold=3, cooldown_s=10.0, clock=clock)
        for _ in range(2):
            b.record_failure()
        assert b.state == b.CLOSED and b.allow()
        b.record_failure()
        assert b.state == b.OPEN and not b.allow()
        clock.t = 10.0
        assert b.allow()  # the half-open probe
        assert b.state == b.HALF_OPEN
        assert not b.allow()  # only ONE probe at a time
        b.record_success()
        assert b.state == b.CLOSED and b.allow()
        assert b.stats == {"trips": 1, "probes": 1, "closes": 1}

    def test_probe_failure_reopens_with_fresh_window(self):
        clock = _Clock()
        b = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=clock)
        b.record_failure()
        assert b.state == b.OPEN
        clock.t = 5.0
        assert b.allow()
        b.record_failure()
        assert b.state == b.OPEN
        clock.t = 9.0
        assert not b.allow(), "the failed probe re-stamped the window"
        clock.t = 10.0
        assert b.allow()

    def test_success_resets_consecutive_failures(self):
        b = CircuitBreaker(threshold=3)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == b.CLOSED, "non-consecutive failures never trip"

    def test_gauge_tracks_state(self):
        from koordinator_tpu.scheduler.frameworkext import scheduler_registry

        reg = scheduler_registry()
        g = reg.get("solver_breaker_state")
        clock = _Clock()
        b = CircuitBreaker(threshold=1, cooldown_s=1.0, clock=clock, gauge=g)
        assert g.value() == float(b.CLOSED)
        b.record_failure()
        assert g.value() == float(b.OPEN)
        clock.t = 1.0
        b.allow()
        assert g.value() == float(b.HALF_OPEN)
        b.record_success()
        assert g.value() == float(b.CLOSED)


class TestSolverClientBreaker:
    def _serve(self):
        from koordinator_tpu.core.snapshot import ClusterSnapshot
        from koordinator_tpu.runtime.snapshot_channel import (
            SolverService,
            serve,
        )

        service = SolverService(ClusterSnapshot())
        service.scheduler.extender.monitor.stop_background()
        return serve(service)

    def test_breaker_storm_trips_and_fails_fast_then_probe_heals(self):
        from koordinator_tpu.chaos import FaultInjector
        from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
        from koordinator_tpu.runtime.snapshot_channel import (
            ChannelBreakerOpen,
            ChannelUnavailable,
            SolverClient,
        )

        server, port = self._serve()
        clock = _Clock()
        chaos = FaultInjector(seed=0)
        breaker = CircuitBreaker(threshold=2, cooldown_s=5.0, clock=clock)
        client = SolverClient(
            f"127.0.0.1:{port}", timeout_s=5.0, chaos=chaos,
            breaker=breaker,
        )
        try:
            assert client.sync(pb.SnapshotDelta()).applied_revision == 1
            assert breaker.state == breaker.CLOSED
            chaos.arm("channel.breaker_storm", times=2)
            for _ in range(2):
                with pytest.raises(ChannelUnavailable):
                    client.sync(pb.SnapshotDelta())
            assert breaker.state == breaker.OPEN
            # fail FAST while open: no wire, no retry grind
            with pytest.raises(ChannelBreakerOpen):
                client.sync(pb.SnapshotDelta())
            # cooldown admits ONE probe; the storm is over, it heals
            clock.t = 5.0
            ack = client.sync(pb.SnapshotDelta())
            assert ack.applied_revision == 2
            assert breaker.state == breaker.CLOSED
            assert breaker.stats["trips"] == 1
        finally:
            client.close()
            server.stop(None)

    def test_breaker_open_is_not_retried_by_policy(self):
        from koordinator_tpu.chaos import FaultInjector
        from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
        from koordinator_tpu.runtime.snapshot_channel import (
            ChannelBreakerOpen,
            SolverClient,
        )
        from koordinator_tpu.utils.retry import RetryPolicy

        server, port = self._serve()
        clock = _Clock()
        chaos = FaultInjector(seed=0)
        breaker = CircuitBreaker(threshold=1, cooldown_s=99.0, clock=clock)
        client = SolverClient(
            f"127.0.0.1:{port}",
            timeout_s=5.0,
            chaos=chaos,
            breaker=breaker,
            retry=RetryPolicy(
                max_attempts=4, base_delay_s=0.001, max_delay_s=0.002,
                jitter=0.0,
            ),
        )
        try:
            chaos.arm("channel.breaker_storm", times=1)
            # first attempt fails and trips (threshold 1); the retry
            # policy's SECOND attempt hits the open breaker — which is
            # NOT retryable, so the call surfaces it immediately
            with pytest.raises(ChannelBreakerOpen):
                client.sync(pb.SnapshotDelta())
            assert breaker.stats["trips"] == 1
        finally:
            client.close()
            server.stop(None)


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------


class TestRouterOverloadAwareness:
    def _router(self, **kw):
        from koordinator_tpu.runtime.shards import ShardMap, ShardRouter

        return ShardRouter(ShardMap(4), spill_backlog=10, **kw)

    def test_burning_primary_spills_earlier(self):
        burns = {0: 0.0, 1: 0.0, 2: 0.0, 3: 0.0}
        r = self._router(burn_of=lambda s: burns[s])
        pod = _pod("x")
        primary = r.route(pod)
        # below the raw threshold, healthy primary: no fan-out
        assert r.targets(pod, backlog_of=lambda s: 6) == [primary]
        # the same backlog on a BURNING primary fans out (engage point
        # halves at burn > 1)
        burns[primary] = 2.0
        t = r.targets(pod, backlog_of=lambda s: 6)
        assert len(t) == 2 and t[0] == primary

    def test_browning_fleet_stops_fanning_out_sheddable_bands(self):
        bo = BrownoutController(clock=_Clock())
        r = self._router(brownout=bo)
        batch = _pod("b", PriorityClass.BATCH)
        prod = _pod("p", PriorityClass.PROD)
        assert len(r.targets(batch, backlog_of=lambda s: 50)) == 2
        bo.level = BrownoutController.L3
        # BATCH would be deferred/shed at the spill shard — no claim
        assert len(r.targets(batch, backlog_of=lambda s: 50)) == 1
        # PROD still spills: it is never deferred
        assert len(r.targets(prod, backlog_of=lambda s: 50)) == 2


class TestValidateTimelineShedArm:
    def _ev(self, stage, t, shard=0):
        return LifecycleEvent(stage=stage, t=t, shard=shard)

    def test_terminal_shed_is_valid(self):
        evs = [
            self._ev("submit", 0.0, -1),
            self._ev("route", 0.0),
            self._ev("enqueue", 1.0),
            self._ev("shed", 5.0),
        ]
        assert validate_timeline(evs) == []

    def test_progress_after_shed_without_bridge_is_a_gap(self):
        evs = [
            self._ev("submit", 0.0, -1),
            self._ev("enqueue", 1.0),
            self._ev("shed", 2.0),
            self._ev("dispatch", 3.0),
            self._ev("decide", 4.0),
            self._ev("ack", 5.0),
        ]
        problems = validate_timeline(evs)
        assert any("without" in p and "bridge" in p for p in problems)

    def test_redeemed_ticket_bridges_shed(self):
        evs = [
            self._ev("submit", 0.0, -1),
            self._ev("enqueue", 1.0),
            self._ev("shed", 2.0),
            self._ev("route", 6.0),
            self._ev("enqueue", 6.0),
            self._ev("dispatch", 7.0),
            self._ev("decide", 8.0),
            self._ev("ack", 8.0),
        ]
        assert validate_timeline(evs) == []

    def test_shed_after_ack_is_a_problem(self):
        evs = [
            self._ev("submit", 0.0, -1),
            self._ev("enqueue", 1.0),
            self._ev("dispatch", 2.0),
            self._ev("decide", 3.0),
            self._ev("ack", 3.0),
            self._ev("shed", 4.0),
        ]
        problems = validate_timeline(evs)
        assert any("already-placed" in p for p in problems)


class TestLifecycleStormEviction:
    def test_storm_eviction_prefers_shed_timelines_over_open_ones(self):
        """PR 7's eviction fallback, storm-shaped (satellite): a fleet
        dominated by never-placed pods must evict SHED (completed)
        timelines first — open stories survive, the bound holds."""
        clock = _Clock()
        lc = PodLifecycle(clock=clock, max_pods=40)
        for i in range(20):
            lc.submitted(f"open{i}")
            lc.event(f"open{i}", "enqueue", shard=0)
        for i in range(20):
            lc.submitted(f"shed{i}")
            lc.event(f"shed{i}", "shed", shard=0)
        # the registry is full: the next arrivals evict — completed
        # (shed) timelines go first, ALL open ones survive
        for i in range(10):
            lc.submitted(f"new{i}")
        uids = set(lc.uids())
        assert len(uids) <= 40, "max_pods bound leaked"
        assert all(f"open{i}" in uids for i in range(20))
        assert sum(1 for u in uids if u.startswith("shed")) < 20

    def test_redeemed_shed_pod_leaves_the_completed_set(self):
        lc = PodLifecycle(clock=_Clock())
        lc.submitted("p")
        lc.event("p", "shed", shard=0)
        assert lc.is_done("p")
        lc.event("p", "resubmit", shard=1)
        assert not lc.is_done("p"), "a redeemed story is live again"
        lc.event("p", "decide", shard=1, detail="n0")
        lc.acked("p", 1, "n0")
        assert lc.is_done("p")

    def test_redeemed_pod_slo_clock_restarts_at_the_bridge(self):
        clock = _Clock()
        lc = PodLifecycle(clock=clock)
        lc.submitted("p", t=0.0)
        lc.event("p", "enqueue", shard=0, t=1.0)
        lc.event("p", "shed", shard=0, t=10.0)
        lc.event("p", "resubmit", shard=0, t=50.0)
        lc.event("p", "decide", shard=0, t=52.0, detail="n0")
        e2e = lc.acked("p", 0, "n0", t=53.0)
        # anchored at the redemption bridge, not the pre-shed submit
        assert e2e == pytest.approx(3.0)


class TestClaimVoid:
    def test_void_claims_drops_winner_without_tombstone(self):
        from koordinator_tpu.core.journal import (
            ClaimTable,
            MemoryJournalStore,
        )

        store = MemoryJournalStore()
        t = ClaimTable(store)
        assert t.claim("u1", 2, 1)
        t.void_claims(["u1", "unknown"])
        assert t.winner("u1") is None
        # NOT a tombstone: any shard may claim it afresh
        assert t.claim("u1", 0, 1)
        # the void is journaled: a reload replays the same state
        t2 = ClaimTable(MemoryJournalStore())
        assert t2.claim("a", 1, 1)
        t2.void_claims(["a"])
        reloaded = ClaimTable(store)
        assert reloaded.winner("u1") == 0

    def test_void_claims_noop_writes_no_record(self):
        from koordinator_tpu.core.journal import (
            ClaimTable,
            MemoryJournalStore,
        )

        store = MemoryJournalStore()
        t = ClaimTable(store)
        before = len(store.load())
        t.void_claims(["nobody"])
        assert len(store.load()) == before


class TestSloHorizons:
    def test_max_age_excludes_stale_samples_from_burn(self):
        clock = _Clock()
        slo = SloTracker(
            clock=clock,
            targets=(
                SloTarget(
                    "p99_latency", threshold_s=1.0, budget=0.1,
                    window=64, max_age_s=10.0,
                ),
            ),
        )
        for _ in range(10):
            slo.observe_latency(0, 5.0)  # all violations at t=0
        assert slo.burn_rate(0, "p99_latency") == pytest.approx(10.0)
        clock.t = 20.0  # every sample is now past the horizon
        assert slo.burn_rate(0, "p99_latency") == 0.0
        slo.observe_latency(0, 0.1)  # one fresh OK sample
        assert slo.burn_rate(0, "p99_latency") == 0.0
        ev = slo.evaluate()["0"]["p99_latency"]
        assert ev["burn_rate"] == 0.0 and ev["window_p99_s"] == 0.1

    def test_min_samples_floor_suppresses_straggler_burn(self):
        clock = _Clock()
        slo = SloTracker(
            clock=clock,
            targets=(
                SloTarget(
                    "p99_latency", threshold_s=1.0, budget=0.1,
                    window=64, min_samples=4,
                ),
            ),
        )
        slo.observe_latency(0, 99.0)
        slo.observe_latency(0, 99.0)
        assert slo.burn_rate(0, "p99_latency") == 0.0, (
            "two stragglers are not evidence"
        )
        slo.observe_latency(0, 99.0)
        slo.observe_latency(0, 99.0)
        assert slo.burn_rate(0, "p99_latency") == pytest.approx(10.0)

    def test_empty_queue_pump_samples_zero_age(self):
        clock = _Clock()
        slo = SloTracker(
            clock=clock,
            targets=(SloTarget("queue_age", threshold_s=1.0, budget=0.5),),
        )
        st = StreamScheduler(_sched(), slo=slo, shard=0)
        st.pump()  # empty queue: still one (healthy) sample
        ev = slo.evaluate()["0"]["queue_age"]
        assert ev["samples"] == 1 and ev["last_s"] == 0.0


class TestReviewHardening:
    """Review-round fixes: probe-slot wedge, yield-budget renewal,
    burn-stable spill hysteresis."""

    def test_fenced_probe_does_not_wedge_the_breaker(self):
        """A half-open probe that ends in a FENCING refusal (no channel
        verdict) must release the probe slot — not leave the breaker
        HALF_OPEN with its probe permanently in flight."""
        from koordinator_tpu.chaos import FaultInjector
        from koordinator_tpu.core.journal import EpochFence
        from koordinator_tpu.core.snapshot import ClusterSnapshot
        from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
        from koordinator_tpu.runtime.snapshot_channel import (
            SolverClient,
            SolverService,
            serve,
        )

        service = SolverService(ClusterSnapshot())
        service.scheduler.extender.monitor.stop_background()
        server, port = serve(service)
        clock = _Clock()
        chaos = FaultInjector(seed=0)
        breaker = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=clock)
        fence = EpochFence()
        client = SolverClient(
            f"127.0.0.1:{port}", timeout_s=5.0, chaos=chaos,
            breaker=breaker, fence=fence,
        )
        try:
            from koordinator_tpu.core.journal import StaleEpochError

            chaos.arm("channel.breaker_storm", times=1)
            with pytest.raises(Exception):
                client.sync(pb.SnapshotDelta())
            assert breaker.state == breaker.OPEN
            # depose the client, then let the cooldown admit a probe:
            # the probe dies at the LOCAL fence — uncounted
            fence.adopt(2)
            client.set_epoch(1)
            clock.t = 5.0
            with pytest.raises(StaleEpochError):
                client.sync(pb.SnapshotDelta())
            assert breaker.state == breaker.HALF_OPEN
            # the slot was released: a re-granted client can probe and
            # heal instead of fast-failing forever
            fence.adopt(3)
            client.set_epoch(3)
            ack = client.sync(pb.SnapshotDelta())
            assert ack.applied_revision >= 1
            assert breaker.state == breaker.CLOSED
        finally:
            client.close()
            server.stop(None)

    def test_yield_budget_renews_per_pressure_episode(self):
        """A storm fully relieved by a topology split (no ladder
        transition) must not consume the yield window for the NEXT
        storm."""
        burn = _BurnStub()
        topo = _TopoStub(can=True, cooling=False)
        bo = _ladder(burn, sustain=2, topology=topo)
        burn.burn = 100.0
        bo.tick()
        bo.tick()  # yield 1
        bo.tick()  # yield 2 — budget spent
        assert bo.stats["yielded_to_split"] == 2 and bo.level == 0
        burn.burn = 0.0  # the split relieved the pressure
        bo.tick()
        # storm 2: the budget renewed — the ladder yields again before
        # degrading, instead of escalating on the first sustained tick
        burn.burn = 100.0
        bo.tick()
        bo.tick()
        assert bo.level == 0
        assert bo.stats["yielded_to_split"] == 3

    def test_spill_release_threshold_is_burn_stable(self):
        """An oscillating burn signal must not move the RELEASE level
        of the spill hysteresis band — engage may come early on a burn,
        but disengage anchors at the burn floor, so a backlog sitting
        inside the band never flaps claims."""
        from koordinator_tpu.runtime.shards import ShardMap, ShardRouter

        burns = {"v": 0.0}
        r = ShardRouter(
            ShardMap(4),
            spill_backlog=8,
            burn_of=lambda s: burns["v"],
            burn_spill_frac=0.5,
            spill_resume_frac=0.5,
        )
        pod = _pod("x")
        primary = r.route(pod)
        burns["v"] = 2.0
        assert len(r.targets(pod, backlog_of=lambda s: 4)) == 2  # engaged
        flips = 0
        engaged = True
        # backlog holds at 3 (inside [floor*resume=2, engage=4..8]) while
        # the burn saws across 1.0 — the band must hold
        for i in range(12):
            burns["v"] = 2.0 if i % 2 else 0.0
            now = len(r.targets(pod, backlog_of=lambda s: 3)) == 2
            if now != engaged:
                flips += 1
                engaged = now
        assert flips == 0
        # a genuinely drained backlog still releases
        burns["v"] = 0.0
        assert len(r.targets(pod, backlog_of=lambda s: 1)) == 1
