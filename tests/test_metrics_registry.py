"""utils.metrics hardening (ISSUE 1 satellites): type-conflict detection
in the registry, label-name validation, interpolated histogram quantiles,
and valid Prometheus text exposition."""

import math
import re

import pytest

from koordinator_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)


class TestRegistryTypeConflicts:
    def test_same_name_different_type_raises(self):
        reg = Registry()
        reg.counter("x", "help")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x")
        with pytest.raises(ValueError, match="already registered"):
            reg.histogram("x")

    def test_gauge_is_not_a_counter(self):
        # Gauge subclasses Counter — an isinstance check would wrongly
        # hand a Gauge back to a counter() caller
        reg = Registry()
        reg.gauge("g", "help")
        with pytest.raises(ValueError):
            reg.counter("g")

    def test_same_type_is_idempotent(self):
        reg = Registry(namespace="ns")
        c1 = reg.counter("x", "help")
        c2 = reg.counter("x")
        assert c1 is c2


class TestLabelValidation:
    def test_unknown_label_raises_on_counter(self):
        c = Counter("c", "h", label_names=("a",))
        with pytest.raises(ValueError, match="unknown label"):
            c.labels(b="oops")
        with pytest.raises(ValueError, match="unknown label"):
            c.value(b="oops")

    def test_unknown_label_raises_on_gauge_and_histogram(self):
        g = Gauge("g", "h", label_names=("a",))
        with pytest.raises(ValueError):
            g.set(1.0, b="oops")
        h = Histogram("h", "h", label_names=("a",))
        with pytest.raises(ValueError):
            h.observe(0.1, b="oops")

    def test_declared_labels_still_work(self):
        c = Counter("c", "h", label_names=("a", "b"))
        c.labels(a="1", b="2").inc()
        # partial label sets keep the historic empty-string default
        c.labels(a="1").inc()
        assert c.value(a="1", b="2") == 1
        assert c.value(a="1") == 1


class TestQuantileInterpolation:
    def test_uniform_samples_interpolate_within_bucket(self):
        h = Histogram("h", "x", buckets=(1.0, 2.0, 4.0))
        # 100 uniform samples in (1, 2]: p50 should land near 1.5, not
        # snap to the bucket's upper bound 2.0
        for i in range(100):
            h.observe(1.0 + (i + 1) / 100.0)
        assert h.quantile(0.5) == pytest.approx(1.5, abs=0.05)
        assert h.quantile(0.0) == pytest.approx(1.0, abs=0.02)
        assert h.quantile(1.0) == pytest.approx(2.0, abs=0.02)

    def test_first_bucket_lower_edge_is_zero(self):
        h = Histogram("h", "x", buckets=(1.0, 2.0))
        for _ in range(4):
            h.observe(0.5)
        # all mass in (0, 1]: p50 interpolates from lower edge 0
        assert h.quantile(0.5) == pytest.approx(0.5)

    def test_inf_bucket_keeps_inf_semantics(self):
        h = Histogram("h", "x", buckets=(1.0,))
        h.observe(0.5)
        h.observe(10.0)  # lands in +Inf bucket
        assert math.isinf(h.quantile(0.99))
        assert h.quantile(0.5) <= 1.0

    def test_exact_test_vector_from_frameworkext(self):
        # the pre-existing expectation: target at the top of the winning
        # bucket returns the bucket bound
        h = Histogram("h", "x")
        for v in (0.002, 0.002, 0.2, 3.0):
            h.observe(v)
        assert h.quantile(0.5) == pytest.approx(0.0025)


# ---- Prometheus text exposition validity ----

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})? (?P<value>\S+)$"
)
_LABEL_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"$'
)


def _parse_exposition(text: str):
    """Minimal validating parser: HELP then TYPE precede each family's
    samples; sample names belong to the most recent family (plus the
    _bucket/_sum/_count suffixes for histograms); label syntax is valid."""
    families = {}
    current = None
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"type": None, "samples": []}
            current = name
        elif line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ", 3)
            assert name == current, "TYPE must follow its HELP"
            assert families[name]["type"] is None, f"duplicate TYPE {name}"
            assert mtype in ("counter", "gauge", "histogram")
            families[name]["type"] = mtype
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            name = m.group("name")
            assert current is not None
            if families[current]["type"] == "histogram":
                assert name in (
                    current,
                    f"{current}_bucket",
                    f"{current}_sum",
                    f"{current}_count",
                ), f"sample {name} outside family {current}"
            else:
                assert name == current, f"sample {name} outside {current}"
            labels = {}
            if m.group("labels"):
                for pair in m.group("labels").split(","):
                    assert _LABEL_RE.match(pair), f"bad label pair {pair!r}"
                    k, v = pair.split("=", 1)
                    labels[k] = v.strip('"')
            float(m.group("value").replace("+Inf", "inf"))
            families[current]["samples"].append((name, labels, m.group("value")))
    return families


class TestExpositionValidity:
    def _full_registry(self):
        reg = Registry(namespace="t")
        c = reg.counter("req_total", "requests", labels=("code",))
        c.labels(code="200").inc(3)
        c.labels(code="500").inc()
        g = reg.gauge("temp", "degrees")
        g.set(-4.5)
        h = reg.histogram("lat_seconds", "latency", labels=("op",))
        for v in (0.002, 0.02, 0.2, 2.0, 20.0):
            h.observe(v, op="read")
        return reg

    def test_help_type_ordering_and_sample_grouping(self):
        fams = _parse_exposition(self._full_registry().expose())
        assert fams["t_req_total"]["type"] == "counter"
        assert fams["t_temp"]["type"] == "gauge"
        assert fams["t_lat_seconds"]["type"] == "histogram"

    def test_histogram_bucket_monotonicity_and_inf(self):
        fams = _parse_exposition(self._full_registry().expose())
        samples = fams["t_lat_seconds"]["samples"]
        buckets = [
            (float(lab["le"].replace("+Inf", "inf")), float(val))
            for name, lab, val in samples
            if name == "t_lat_seconds_bucket"
        ]
        les = [le for le, _ in buckets]
        counts = [c for _, c in buckets]
        assert les == sorted(les)
        assert counts == sorted(counts), "cumulative counts must be monotone"
        assert math.isinf(les[-1])
        count = [
            float(val)
            for name, _, val in samples
            if name == "t_lat_seconds_count"
        ][0]
        assert counts[-1] == count  # +Inf bucket equals _count

    def test_label_value_escaping(self):
        reg = Registry()
        c = reg.counter("c", 'help with \\ and\nnewline', labels=("msg",))
        c.labels(msg='quote " backslash \\ newline \n done').inc()
        text = reg.expose()
        # the exposition must parse despite hostile label values/help
        fams = _parse_exposition(text)
        assert len(fams["c"]["samples"]) == 1
        # embedded newline in the help text stays on the HELP line, escaped
        assert text.split("\n")[0] == "# HELP c help with \\\\ and\\nnewline"

    def test_scheduler_registry_exposes_validly(self):
        from koordinator_tpu.scheduler.frameworkext import scheduler_registry

        reg = scheduler_registry()
        reg.get("rejections_total").labels(
            stage="filter", plugin="noderesources", reason="insufficient_resources"
        ).inc()
        reg.get("solver_batch_latency_seconds").observe(0.01)
        fams = _parse_exposition(reg.expose())
        assert "koord_scheduler_rejections_total" in fams
