"""Cross-cycle solve pipelining (perf PR 4): the pipelined stream pump
must be DECISION-IDENTICAL to the serial pump over a multi-cycle stream
— including retries and node churn mid-stream — while overlapping the
host prepare/commit stages with the device solve. Plus the satellites:
donated in-place resident refresh (zero fresh full-axis buffers),
resident PodBatch interning, and the ``pipeline.worker_stall`` failure
domain (degrade to serial + /healthz + recovery, never a wedge)."""

import warnings

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.stream import StreamScheduler


def _node(name, cpu=16000, mem=65536):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _build(n_nodes=32, **kw):
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(_node(f"n{i:03d}"))
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=kw.pop("batch_bucket", 64), **kw
    )
    sched.extender.monitor.stop_background()
    return sched


def _pods(n, cpu=1000, mem=2048, prefix="p", prio0=9000):
    return [
        Pod(
            meta=ObjectMeta(name=f"{prefix}{i:04d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem},
                priority=prio0 - (i % 7),
            ),
        )
        for i in range(n)
    ]


def _drive(sched, pipelined, pods, waves=8, churn_at=None, **stream_kw):
    """Stream ``pods`` in ``waves`` equal submissions, pumping after each;
    ``churn_at`` removes one node and adds a fresh one before that wave
    (mid-stream topology churn). Returns {pod name: node | None}."""
    st = StreamScheduler(sched, pipelined=pipelined, **stream_kw)
    per = max(1, len(pods) // waves)
    decided = {}
    i = 0
    wave = 0
    try:
        while i < len(pods) or st.backlog() or (
            pipelined and st._pipe.inflight
        ):
            if churn_at is not None and wave == churn_at:
                # apply churn at a pipeline-QUIESCENT boundary: flush the
                # in-flight cycle first so both modes see the topology
                # change between the same two decided batches (an
                # epoch-changing event mid-pipeline is the discard path —
                # covered by its own test below — and re-times the
                # affected batch's commit, which no lagged pump can make
                # bit-identical to an eager one)
                for pod, node, _lat in st.flush():
                    decided[pod.meta.name] = node
                snap = sched.snapshot
                snap.remove_node(snap.node_name(3))
                snap.upsert_node(_node("late-node"))
            wave += 1
            for _ in range(per):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
            if i >= len(pods) and not st.backlog() and (
                not pipelined or not st._pipe.inflight
            ):
                break
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    return decided


def test_pipelined_equals_serial_multi_cycle():
    """Bit-exact decision equivalence over a plain multi-cycle stream —
    and the speculative fast path must actually ENGAGE, or this verifies
    nothing."""
    a = _build()
    da = _drive(a, pipelined=False, pods=_pods(300), waves=8, max_batch=64)
    b = _build()
    db = _drive(b, pipelined=True, pods=_pods(300), waves=8, max_batch=64)
    kept = b.extender.registry.get("pipeline_speculation_total").value(
        outcome="kept"
    )
    assert kept > 0, "speculative chained dispatch never engaged"
    assert len(db) == len(da) == 300
    assert da == db


def test_pipelined_equals_serial_with_retries():
    """An overloaded cluster forces unschedulable pods back through the
    retry queue; decisions (including final give-ups) must still match
    the serial pump, and the retried re-lowering must hit the intern
    cache."""
    a = _build(n_nodes=4)
    pods_a = _pods(120, cpu=4000, mem=16384)
    da = _drive(a, pipelined=False, pods=pods_a, waves=4, max_batch=64)
    b = _build(n_nodes=4)
    pods_b = _pods(120, cpu=4000, mem=16384)
    db = _drive(b, pipelined=True, pods=pods_b, waves=4, max_batch=64)
    assert da == db
    assert any(v is None for v in db.values()), "fixture must overload"
    hits = b.extender.registry.get("pod_intern_hits_total").value()
    assert hits > 0, "retried pods must hit the interned rows"


def test_pipelined_equals_serial_node_churn_mid_stream():
    """Node churn mid-stream (applied at a pipeline-quiescent boundary,
    see _drive) — decisions before AND after the topology change must
    match the serial pump bit-exactly."""
    a = _build()
    da = _drive(
        a, pipelined=False, pods=_pods(240), waves=6, churn_at=3,
        max_batch=64,
    )
    b = _build()
    db = _drive(
        b, pipelined=True, pods=_pods(240), waves=6, churn_at=3,
        max_batch=64,
    )
    assert da == db
    assert "late-node" in set(db.values()), "churn must be load-bearing"


def test_speculation_discarded_on_mid_pipeline_churn():
    """Churn landing while a speculative solve is in flight must DISCARD
    it (node-epoch/version guard), re-dispatch serially, and still place
    every pod on a live node — never on the vanished one, never wedge."""
    sched = _build()
    st = StreamScheduler(sched, max_batch=64, pipelined=True)
    pods = _pods(240)
    decided = {}
    i = 0
    wave = 0
    pre_churn: set = set()
    try:
        while i < len(pods) or st.backlog() or st._pipe.inflight:
            if wave == 3:
                # no flush: the in-flight speculation is now stale
                pre_churn = set(decided)
                snap = sched.snapshot
                snap.remove_node(snap.node_name(3))
                snap.upsert_node(_node("late-node"))
            wave += 1
            for _ in range(40):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    discarded = sched.extender.registry.get(
        "pipeline_speculation_total"
    ).value(outcome="discarded")
    assert discarded > 0, "mid-pipeline churn must discard the spec"
    assert len(decided) == 240
    for name, node in decided.items():
        assert node is not None, f"{name} never placed"
        if name not in pre_churn:
            # a post-churn decision may never land on the vanished node
            # (Reserve revalidation catches the stale nomination); pods
            # bound BEFORE the churn legitimately sat on it, like any
            # bound pod whose node later dies
            assert node != "n003", name


def test_speculation_discarded_when_quota_tree_arrives_mid_pipeline():
    """A gated subsystem can arrive through an informer WITHOUT bumping
    snapshot.version (the first ElasticQuota CR only bumps the quota
    manager's own state_version): the in-flight speculation — whose rows
    carry no quota chains — must be DISCARDED at consume, and the
    re-dispatched serial cycle must charge the quota tree."""
    from koordinator_tpu.api.types import ElasticQuota

    sched = _build(n_nodes=16, batch_bucket=32)
    st = StreamScheduler(sched, max_batch=32, pipelined=True)
    decided = {}
    try:
        # pump 1: batch A in flight (speculation dispatched)
        for p in _pods(32, prefix="a"):
            st.submit(p)
        st.pump()
        # mid-pipeline: the first quota CR lands; snapshot.version is
        # untouched but the pipeline gates no longer hold
        sched.quotas.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="team-q"),
                min={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                max={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384},
            )
        )
        # pump 2: batch B (quota-labeled) — commits batch A, which must
        # NOT consume the pre-quota speculation
        for i in range(16):
            st.submit(
                Pod(
                    meta=ObjectMeta(
                        name=f"q{i:03d}",
                        labels={ext.LABEL_QUOTA_NAME: "team-q"},
                    ),
                    spec=PodSpec(
                        requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048},
                        priority=9000,
                    ),
                )
            )
        for pod, node, _lat in st.pump():
            decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    discarded = sched.extender.registry.get(
        "pipeline_speculation_total"
    ).value(outcome="discarded")
    assert discarded > 0, "pre-quota speculation must be discarded"
    # quota admission actually engaged: at most max/1000m = 8 of the 16
    # labeled pods admitted, and the manager's used ledger is charged
    q_bound = [
        n for k, n in decided.items() if k.startswith("q") and n is not None
    ]
    assert 0 < len(q_bound) <= 8, q_bound
    q_idx = sched.quotas.index_of("team-q")
    assert sched.quotas.used[q_idx][0] == 1000.0 * len(q_bound)


def test_worker_stall_degrades_to_serial_and_recovers():
    """A stalled/dead prepare worker must degrade the cycle to the serial
    path with counted attribution and a /healthz transition — and the
    pipeline must recover (worker respawn, health ok) instead of wedging
    the drain."""
    chaos = FaultInjector(seed=5)
    sched = _build(n_nodes=16, batch_bucket=32, chaos=chaos)
    chaos.arm("pipeline.worker_stall", at_hits=frozenset([2]))
    st = StreamScheduler(
        sched, max_batch=32, pipelined=True, prepare_timeout_s=0.3
    )
    pods = _pods(160, cpu=500, mem=512)
    decided = {}
    health_seen_bad = False
    i = 0
    try:
        while i < len(pods):
            for _ in range(32):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
            row = sched.extender.health.snapshot().get("pipeline")
            if row is not None and not row["ok"]:
                health_seen_bad = True
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    reg = sched.extender.registry
    assert chaos.fired_counts()["pipeline.worker_stall"] == 1
    assert reg.get("pipeline_prepare_stalls_total").value() == 1.0
    assert health_seen_bad, "the stall must surface on /healthz"
    row = sched.extender.health.snapshot()["pipeline"]
    assert row["ok"], "the pipeline must recover after the respawn"
    assert len(decided) == 160
    assert all(v is not None for v in decided.values())


def test_pipelined_smoke_three_cycles():
    """Tier-1 smoke (CI satellite): three pipelined cycles end to end
    under JAX_PLATFORMS=cpu — dispatch, trailing commit, flush."""
    sched = _build(n_nodes=8, batch_bucket=16)
    st = StreamScheduler(sched, max_batch=16, pipelined=True)
    pods = _pods(48, cpu=500, mem=512)
    decided = {}
    try:
        for c in range(3):
            for p in pods[c * 16 : (c + 1) * 16]:
                st.submit(p)
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    assert len(decided) == 48
    assert all(v is not None for v in decided.values())
    depth = sched.extender.registry.get("solver_pipeline_depth")
    assert depth is not None


def test_donated_refresh_reuses_resident_buffers():
    """Satellite (a): the steady-state dirty-row refresh donates the
    resident buffers to the scatter — ownership transfers (the old
    handles are DEAD, not copied), no donation warning fires, and the
    steady state allocates zero net full-axis arrays (live device-buffer
    count stays flat across many refreshes)."""
    sched = _build(n_nodes=32)
    snap = sched.snapshot
    ns0 = sched.node_state()
    jax.block_until_ready(ns0.requested)
    pod = Pod(
        meta=ObjectMeta(name="d0"),
        spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 512}),
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any donation warning fails
        assert snap.assume_pod(pod, snap.node_name(5))
        ns1 = sched.node_state()
        jax.block_until_ready(ns1.requested)
    assert ns1 is not ns0
    np.testing.assert_array_equal(
        np.asarray(ns1.requested), snap.nodes.requested
    )
    # the donated input is dead — re-reading it must raise, proving the
    # buffers changed hands (in-place update) instead of being copied
    with pytest.raises(Exception):
        np.asarray(ns0.requested)
    del ns0, ns1
    # steady state: many dirty-row refreshes leave the live device-array
    # population flat — each scatter consumes the old resident buffers
    # and hands back the updated ones, allocating nothing net
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for k in range(3):  # warm every shape/jit path first
            p = Pod(
                meta=ObjectMeta(name=f"warm{k}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 100, ext.RES_MEMORY: 64}
                ),
            )
            assert snap.assume_pod(p, snap.node_name(k))
            jax.block_until_ready(sched.node_state().requested)
        live0 = len(jax.live_arrays())
        for k in range(20):
            p = Pod(
                meta=ObjectMeta(name=f"s{k}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 100, ext.RES_MEMORY: 64}
                ),
            )
            assert snap.assume_pod(p, snap.node_name(k % 16))
            jax.block_until_ready(sched.node_state().requested)
        live1 = len(jax.live_arrays())
    assert live1 <= live0, (live0, live1)


def test_intern_cache_identity_and_eviction():
    """Interned lowering must be byte-identical to a cold parse, and a
    bound pod's entry must be evicted (bind/drop eviction contract)."""
    sched_cold = _build(n_nodes=8, intern_pods=False)
    sched_warm = _build(n_nodes=8, intern_pods=True)
    pods_c = _pods(40, cpu=3000, mem=4096)
    pods_w = _pods(40, cpu=3000, mem=4096)
    # two identical schedules: the second warm pass lowers from cache
    out_c1 = sched_cold.schedule(pods_c)
    out_w1 = sched_warm.schedule(pods_w)
    assert {p.meta.name: n for p, n in out_c1.bound} == {
        p.meta.name: n for p, n in out_w1.bound
    }
    # bound pods evicted from the cache
    cache = sched_warm._pod_intern
    for pod, _n in out_w1.bound:
        assert pod.meta.uid not in cache
    # still-pending pods stay interned and hit on the retry
    for pod in out_w1.unschedulable:
        assert pod.meta.uid in cache
    if out_w1.unschedulable:
        hits0 = sched_warm.extender.registry.get(
            "pod_intern_hits_total"
        ).value()
        out_c2 = sched_cold.schedule(out_c1.unschedulable)
        out_w2 = sched_warm.schedule(out_w1.unschedulable)
        assert {p.meta.name: n for p, n in out_c2.bound} == {
            p.meta.name: n for p, n in out_w2.bound
        }
        assert (
            sched_warm.extender.registry.get("pod_intern_hits_total").value()
            > hits0
        )


def test_intern_entry_invalidated_by_spec_change():
    """An in-place spec edit under the same uid must self-invalidate the
    interned row (fingerprint mismatch), never resurrect stale data."""
    sched = _build(n_nodes=8)
    pod = Pod(
        meta=ObjectMeta(name="mut0"),
        spec=PodSpec(
            requests={ext.RES_CPU: 64000, ext.RES_MEMORY: 512},
            priority=9000,
        ),
    )
    out = sched.schedule([pod])
    assert not out.bound  # 64 cores fits nowhere (16-core nodes)
    pod.spec.requests[ext.RES_CPU] = 1000.0
    out2 = sched.schedule([pod])
    assert len(out2.bound) == 1, "stale interned row blocked the re-lower"


def test_numa_device_dirty_row_scatter():
    """Satellite (b): an allocation delta on one node must refresh the
    resident NUMA zone / GPU slot tables via the dirty-row scatter (a
    handful of padded rows), not a full-axis re-upload — and stay
    bit-exact vs the managers' live host arrays."""
    from koordinator_tpu.api.types import Device, DeviceInfo
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
        NUMAPolicy,
    )

    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    dm = DeviceManager(snap)
    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=8
    )
    for i in range(24):
        name = f"n{i:03d}"
        snap.upsert_node(_node(name, cpu=32000, mem=131072))
        numa.register_node(
            name, topo, NUMAPolicy.SINGLE_NUMA_NODE,
            memory_per_zone_mib=65536,
        )
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g, numa_node=g % 2)
                    for g in range(4)
                ],
            )
        )
    sched = BatchScheduler(
        snap, LoadAwareArgs(), numa=numa, devices=dm, batch_bucket=32
    )
    sched.extender.monitor.stop_background()
    sched._constraint_states()  # initial full uploads
    reg = sched.extender.registry
    h2d0 = reg.get("solver_h2d_rows_total").value()
    # one pod's NUMA + GPU allocation dirties exactly one node's rows
    pod = Pod(
        meta=ObjectMeta(
            name="g0", labels={ext.LABEL_POD_QOS: "LSR"}
        ),
        spec=PodSpec(
            requests={
                ext.RES_CPU: 2000,
                ext.RES_MEMORY: 2048,
                ext.RES_GPU: 1,
            },
            priority=9000,
        ),
    )
    out = sched.schedule([pod])
    assert len(out.bound) == 1
    h2d1 = reg.get("solver_h2d_rows_total").value()
    numa_state, dev_state = sched._constraint_states()
    uploaded = reg.get("solver_h2d_rows_total").value() - h2d1
    n_bucket = snap.nodes.allocatable.shape[0]
    # the refresh must be a scatter of a few padded rows per table, far
    # below two full-axis re-uploads
    assert 0 < uploaded < n_bucket, uploaded
    zone_free, zone_cap, policy = numa.arrays()
    np.testing.assert_array_equal(
        np.asarray(numa_state.zone_free), zone_free
    )
    np.testing.assert_array_equal(
        np.asarray(dev_state.slot_free), dm.slot_array()
    )
