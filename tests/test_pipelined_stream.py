"""Cross-cycle solve pipelining (perf PR 4): the pipelined stream pump
must be DECISION-IDENTICAL to the serial pump over a multi-cycle stream
— including retries and node churn mid-stream — while overlapping the
host prepare/commit stages with the device solve. Plus the satellites:
donated in-place resident refresh (zero fresh full-axis buffers),
resident PodBatch interning, and the ``pipeline.worker_stall`` failure
domain (degrade to serial + /healthz + recovery, never a wedge).

Open-the-gates PR: one bit-exact EQUIVALENCE ARM per opened speculation
gate (quota/NUMA/device/warm-gang carries), declared in ``GATE_ARMS``
below and enforced by the koordlint ``gate-coverage`` pass — each arm
drives the same fixed batch sequence through the pipelined and serial
paths (with retries, mid-pipeline churn and a commit rollback) and
asserts identical decisions AND identical end-state manager tables,
with the speculative path proven ENGAGED. Depth>1 pipelining gets its
own chain-discard arms: node churn, fence revocation and
fallback-ladder demotion must each discard the ENTIRE pending chain."""

import warnings

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.stream import StreamScheduler


def _node(name, cpu=16000, mem=65536):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _build(n_nodes=32, **kw):
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(_node(f"n{i:03d}"))
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=kw.pop("batch_bucket", 64), **kw
    )
    sched.extender.monitor.stop_background()
    return sched


def _pods(n, cpu=1000, mem=2048, prefix="p", prio0=9000):
    return [
        Pod(
            meta=ObjectMeta(name=f"{prefix}{i:04d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem},
                priority=prio0 - (i % 7),
            ),
        )
        for i in range(n)
    ]


def _drive(sched, pipelined, pods, waves=8, churn_at=None, **stream_kw):
    """Stream ``pods`` in ``waves`` equal submissions, pumping after each;
    ``churn_at`` removes one node and adds a fresh one before that wave
    (mid-stream topology churn). Returns {pod name: node | None}."""
    st = StreamScheduler(sched, pipelined=pipelined, **stream_kw)
    per = max(1, len(pods) // waves)
    decided = {}
    i = 0
    wave = 0
    try:
        while i < len(pods) or st.backlog() or (
            pipelined and st._pipe.inflight
        ):
            if churn_at is not None and wave == churn_at:
                # apply churn at a pipeline-QUIESCENT boundary: flush the
                # in-flight cycle first so both modes see the topology
                # change between the same two decided batches (an
                # epoch-changing event mid-pipeline is the discard path —
                # covered by its own test below — and re-times the
                # affected batch's commit, which no lagged pump can make
                # bit-identical to an eager one)
                for pod, node, _lat in st.flush():
                    decided[pod.meta.name] = node
                snap = sched.snapshot
                snap.remove_node(snap.node_name(3))
                snap.upsert_node(_node("late-node"))
            wave += 1
            for _ in range(per):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
            if i >= len(pods) and not st.backlog() and (
                not pipelined or not st._pipe.inflight
            ):
                break
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    return decided


def test_pipelined_equals_serial_multi_cycle():
    """Bit-exact decision equivalence over a plain multi-cycle stream —
    and the speculative fast path must actually ENGAGE, or this verifies
    nothing."""
    a = _build()
    da = _drive(a, pipelined=False, pods=_pods(300), waves=8, max_batch=64)
    b = _build()
    db = _drive(b, pipelined=True, pods=_pods(300), waves=8, max_batch=64)
    kept = b.extender.registry.get("pipeline_speculation_total").value(
        outcome="kept"
    )
    assert kept > 0, "speculative chained dispatch never engaged"
    assert len(db) == len(da) == 300
    assert da == db


def test_pipelined_equals_serial_with_retries():
    """An overloaded cluster forces unschedulable pods back through the
    retry queue; decisions (including final give-ups) must still match
    the serial pump, and the retried re-lowering must hit the intern
    cache."""
    a = _build(n_nodes=4)
    pods_a = _pods(120, cpu=4000, mem=16384)
    da = _drive(a, pipelined=False, pods=pods_a, waves=4, max_batch=64)
    b = _build(n_nodes=4)
    pods_b = _pods(120, cpu=4000, mem=16384)
    db = _drive(b, pipelined=True, pods=pods_b, waves=4, max_batch=64)
    assert da == db
    assert any(v is None for v in db.values()), "fixture must overload"
    hits = b.extender.registry.get("pod_intern_hits_total").value()
    assert hits > 0, "retried pods must hit the interned rows"


def test_pipelined_equals_serial_node_churn_mid_stream():
    """Node churn mid-stream (applied at a pipeline-quiescent boundary,
    see _drive) — decisions before AND after the topology change must
    match the serial pump bit-exactly."""
    a = _build()
    da = _drive(
        a, pipelined=False, pods=_pods(240), waves=6, churn_at=3,
        max_batch=64,
    )
    b = _build()
    db = _drive(
        b, pipelined=True, pods=_pods(240), waves=6, churn_at=3,
        max_batch=64,
    )
    assert da == db
    assert "late-node" in set(db.values()), "churn must be load-bearing"


def test_speculation_discarded_on_mid_pipeline_churn():
    """Churn landing while a speculative solve is in flight must DISCARD
    it (node-epoch/version guard), re-dispatch serially, and still place
    every pod on a live node — never on the vanished one, never wedge."""
    sched = _build()
    st = StreamScheduler(sched, max_batch=64, pipelined=True)
    pods = _pods(240)
    decided = {}
    i = 0
    wave = 0
    pre_churn: set = set()
    try:
        while i < len(pods) or st.backlog() or st._pipe.inflight:
            if wave == 3:
                # no flush: the in-flight speculation is now stale
                pre_churn = set(decided)
                snap = sched.snapshot
                snap.remove_node(snap.node_name(3))
                snap.upsert_node(_node("late-node"))
            wave += 1
            for _ in range(40):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    discarded = sched.extender.registry.get(
        "pipeline_speculation_total"
    ).value(outcome="discarded")
    assert discarded > 0, "mid-pipeline churn must discard the spec"
    assert len(decided) == 240
    for name, node in decided.items():
        assert node is not None, f"{name} never placed"
        if name not in pre_churn:
            # a post-churn decision may never land on the vanished node
            # (Reserve revalidation catches the stale nomination); pods
            # bound BEFORE the churn legitimately sat on it, like any
            # bound pod whose node later dies
            assert node != "n003", name


def test_speculation_discarded_when_quota_tree_arrives_mid_pipeline():
    """A gated subsystem can arrive through an informer WITHOUT bumping
    snapshot.version (the first ElasticQuota CR only bumps the quota
    manager's own state_version): the in-flight speculation — whose rows
    carry no quota chains — must be DISCARDED at consume, and the
    re-dispatched serial cycle must charge the quota tree."""
    from koordinator_tpu.api.types import ElasticQuota

    sched = _build(n_nodes=16, batch_bucket=32)
    st = StreamScheduler(sched, max_batch=32, pipelined=True)
    decided = {}
    try:
        # pump 1: batch A in flight (speculation dispatched)
        for p in _pods(32, prefix="a"):
            st.submit(p)
        st.pump()
        # mid-pipeline: the first quota CR lands; snapshot.version is
        # untouched but the pipeline gates no longer hold
        sched.quotas.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="team-q"),
                min={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                max={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384},
            )
        )
        # pump 2: batch B (quota-labeled) — commits batch A, which must
        # NOT consume the pre-quota speculation
        for i in range(16):
            st.submit(
                Pod(
                    meta=ObjectMeta(
                        name=f"q{i:03d}",
                        labels={ext.LABEL_QUOTA_NAME: "team-q"},
                    ),
                    spec=PodSpec(
                        requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048},
                        priority=9000,
                    ),
                )
            )
        for pod, node, _lat in st.pump():
            decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    discarded = sched.extender.registry.get(
        "pipeline_speculation_total"
    ).value(outcome="discarded")
    assert discarded > 0, "pre-quota speculation must be discarded"
    # quota admission actually engaged: at most max/1000m = 8 of the 16
    # labeled pods admitted, and the manager's used ledger is charged
    q_bound = [
        n for k, n in decided.items() if k.startswith("q") and n is not None
    ]
    assert 0 < len(q_bound) <= 8, q_bound
    q_idx = sched.quotas.index_of("team-q")
    assert sched.quotas.used[q_idx][0] == 1000.0 * len(q_bound)


def test_worker_stall_degrades_to_serial_and_recovers():
    """A stalled/dead prepare worker must degrade the cycle to the serial
    path with counted attribution and a /healthz transition — and the
    pipeline must recover (worker respawn, health ok) instead of wedging
    the drain."""
    chaos = FaultInjector(seed=5)
    sched = _build(n_nodes=16, batch_bucket=32, chaos=chaos)
    chaos.arm("pipeline.worker_stall", at_hits=frozenset([2]))
    st = StreamScheduler(
        sched, max_batch=32, pipelined=True, prepare_timeout_s=0.3
    )
    pods = _pods(160, cpu=500, mem=512)
    decided = {}
    health_seen_bad = False
    i = 0
    try:
        while i < len(pods):
            for _ in range(32):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
            row = sched.extender.health.snapshot().get("pipeline")
            if row is not None and not row["ok"]:
                health_seen_bad = True
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    reg = sched.extender.registry
    assert chaos.fired_counts()["pipeline.worker_stall"] == 1
    assert reg.get("pipeline_prepare_stalls_total").value() == 1.0
    assert health_seen_bad, "the stall must surface on /healthz"
    row = sched.extender.health.snapshot()["pipeline"]
    assert row["ok"], "the pipeline must recover after the respawn"
    assert len(decided) == 160
    assert all(v is not None for v in decided.values())


def test_pipelined_smoke_three_cycles():
    """Tier-1 smoke (CI satellite): three pipelined cycles end to end
    under JAX_PLATFORMS=cpu — dispatch, trailing commit, flush."""
    sched = _build(n_nodes=8, batch_bucket=16)
    st = StreamScheduler(sched, max_batch=16, pipelined=True)
    pods = _pods(48, cpu=500, mem=512)
    decided = {}
    try:
        for c in range(3):
            for p in pods[c * 16 : (c + 1) * 16]:
                st.submit(p)
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    assert len(decided) == 48
    assert all(v is not None for v in decided.values())
    depth = sched.extender.registry.get("solver_pipeline_depth")
    assert depth is not None


def test_donated_refresh_reuses_resident_buffers():
    """Satellite (a): the steady-state dirty-row refresh donates the
    resident buffers to the scatter — ownership transfers (the old
    handles are DEAD, not copied), no donation warning fires, and the
    steady state allocates zero net full-axis arrays (live device-buffer
    count stays flat across many refreshes)."""
    sched = _build(n_nodes=32)
    snap = sched.snapshot
    ns0 = sched.node_state()
    jax.block_until_ready(ns0.requested)
    pod = Pod(
        meta=ObjectMeta(name="d0"),
        spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 512}),
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any donation warning fails
        assert snap.assume_pod(pod, snap.node_name(5))
        ns1 = sched.node_state()
        jax.block_until_ready(ns1.requested)
    assert ns1 is not ns0
    np.testing.assert_array_equal(
        np.asarray(ns1.requested), snap.nodes.requested
    )
    # the donated input is dead — re-reading it must raise, proving the
    # buffers changed hands (in-place update) instead of being copied
    with pytest.raises(Exception):
        np.asarray(ns0.requested)
    del ns0, ns1
    # steady state: many dirty-row refreshes leave the live device-array
    # population flat — each scatter consumes the old resident buffers
    # and hands back the updated ones, allocating nothing net
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for k in range(3):  # warm every shape/jit path first
            p = Pod(
                meta=ObjectMeta(name=f"warm{k}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 100, ext.RES_MEMORY: 64}
                ),
            )
            assert snap.assume_pod(p, snap.node_name(k))
            jax.block_until_ready(sched.node_state().requested)
        live0 = len(jax.live_arrays())
        for k in range(20):
            p = Pod(
                meta=ObjectMeta(name=f"s{k}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 100, ext.RES_MEMORY: 64}
                ),
            )
            assert snap.assume_pod(p, snap.node_name(k % 16))
            jax.block_until_ready(sched.node_state().requested)
        live1 = len(jax.live_arrays())
    assert live1 <= live0, (live0, live1)


def test_intern_cache_identity_and_eviction():
    """Interned lowering must be byte-identical to a cold parse, and a
    bound pod's entry must be evicted (bind/drop eviction contract)."""
    sched_cold = _build(n_nodes=8, intern_pods=False)
    sched_warm = _build(n_nodes=8, intern_pods=True)
    pods_c = _pods(40, cpu=3000, mem=4096)
    pods_w = _pods(40, cpu=3000, mem=4096)
    # two identical schedules: the second warm pass lowers from cache
    out_c1 = sched_cold.schedule(pods_c)
    out_w1 = sched_warm.schedule(pods_w)
    assert {p.meta.name: n for p, n in out_c1.bound} == {
        p.meta.name: n for p, n in out_w1.bound
    }
    # bound pods evicted from the cache
    cache = sched_warm._pod_intern
    for pod, _n in out_w1.bound:
        assert pod.meta.uid not in cache
    # still-pending pods stay interned and hit on the retry
    for pod in out_w1.unschedulable:
        assert pod.meta.uid in cache
    if out_w1.unschedulable:
        hits0 = sched_warm.extender.registry.get(
            "pod_intern_hits_total"
        ).value()
        out_c2 = sched_cold.schedule(out_c1.unschedulable)
        out_w2 = sched_warm.schedule(out_w1.unschedulable)
        assert {p.meta.name: n for p, n in out_c2.bound} == {
            p.meta.name: n for p, n in out_w2.bound
        }
        assert (
            sched_warm.extender.registry.get("pod_intern_hits_total").value()
            > hits0
        )


def test_intern_entry_invalidated_by_spec_change():
    """An in-place spec edit under the same uid must self-invalidate the
    interned row (fingerprint mismatch), never resurrect stale data."""
    sched = _build(n_nodes=8)
    pod = Pod(
        meta=ObjectMeta(name="mut0"),
        spec=PodSpec(
            requests={ext.RES_CPU: 64000, ext.RES_MEMORY: 512},
            priority=9000,
        ),
    )
    out = sched.schedule([pod])
    assert not out.bound  # 64 cores fits nowhere (16-core nodes)
    pod.spec.requests[ext.RES_CPU] = 1000.0
    out2 = sched.schedule([pod])
    assert len(out2.bound) == 1, "stale interned row blocked the re-lower"


def test_numa_device_dirty_row_scatter():
    """Satellite (b): an allocation delta on one node must refresh the
    resident NUMA zone / GPU slot tables via the dirty-row scatter (a
    handful of padded rows), not a full-axis re-upload — and stay
    bit-exact vs the managers' live host arrays."""
    from koordinator_tpu.api.types import Device, DeviceInfo
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
        NUMAPolicy,
    )

    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    dm = DeviceManager(snap)
    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=8
    )
    for i in range(24):
        name = f"n{i:03d}"
        snap.upsert_node(_node(name, cpu=32000, mem=131072))
        numa.register_node(
            name, topo, NUMAPolicy.SINGLE_NUMA_NODE,
            memory_per_zone_mib=65536,
        )
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g, numa_node=g % 2)
                    for g in range(4)
                ],
            )
        )
    sched = BatchScheduler(
        snap, LoadAwareArgs(), numa=numa, devices=dm, batch_bucket=32
    )
    sched.extender.monitor.stop_background()
    sched._constraint_states()  # initial full uploads
    reg = sched.extender.registry
    h2d0 = reg.get("solver_h2d_rows_total").value()
    # one pod's NUMA + GPU allocation dirties exactly one node's rows
    pod = Pod(
        meta=ObjectMeta(
            name="g0", labels={ext.LABEL_POD_QOS: "LSR"}
        ),
        spec=PodSpec(
            requests={
                ext.RES_CPU: 2000,
                ext.RES_MEMORY: 2048,
                ext.RES_GPU: 1,
            },
            priority=9000,
        ),
    )
    out = sched.schedule([pod])
    assert len(out.bound) == 1
    h2d1 = reg.get("solver_h2d_rows_total").value()
    numa_state, dev_state = sched._constraint_states()
    uploaded = reg.get("solver_h2d_rows_total").value() - h2d1
    n_bucket = snap.nodes.allocatable.shape[0]
    # the refresh must be a scatter of a few padded rows per table, far
    # below two full-axis re-uploads
    assert 0 < uploaded < n_bucket, uploaded
    zone_free, zone_cap, policy = numa.arrays()
    np.testing.assert_array_equal(
        np.asarray(numa_state.zone_free), zone_free
    )
    np.testing.assert_array_equal(
        np.asarray(dev_state.slot_free), dm.slot_array()
    )


# ---------------------------------------------------------------------------
# Open-the-gates PR: per-gate bit-exact equivalence arms (koordlint
# gate-coverage pass: every named gate must appear here or carry a
# written exemption in tools/koordlint/passes/gate_coverage.py)
# ---------------------------------------------------------------------------

#: gate name -> equivalence-arm test function(s) in THIS file
def test_gate_mesh_equivalence():
    """Opened ``mesh`` gate (first-class multi-chip PR): the pipelined
    speculative stream over a (dp, tp) mesh — resident tables sharded
    on tp, ChainCarry riding sharded solver outputs — must decide
    bit-exactly like the SERIAL single-device pump, and the speculative
    chained dispatch must actually ENGAGE on the sharded path (a mesh
    that silently re-closed the gate would pass the equality check
    while verifying nothing)."""
    from koordinator_tpu.parallel.sharded import make_mesh

    a = _build()
    da = _drive(a, pipelined=False, pods=_pods(300), waves=8, max_batch=64)
    b = _build(mesh=make_mesh(8))
    assert b.speculation_gate_report()["mesh"], "mesh gate must be OPEN"
    db = _drive(b, pipelined=True, pods=_pods(300), waves=8, max_batch=64)
    kept = b.extender.registry.get("pipeline_speculation_total").value(
        outcome="kept"
    )
    assert kept > 0, "speculative mesh dispatch never engaged"
    assert len(db) == len(da) == 300
    assert da == db


def test_gate_mesh_swap_discards_speculation():
    """A mesh attach mid-pipeline (no version bump anywhere) must flip
    ``_carry_modes`` and DISCARD the in-flight speculation at consume —
    the carried tables were lowered under a different placement."""
    from koordinator_tpu.parallel.sharded import make_mesh

    sched = _build()
    st = StreamScheduler(sched, max_batch=64, pipelined=True)
    decided = {}
    pods = _pods(192)
    i = 0
    wave = 0
    try:
        while i < len(pods) or st.backlog() or st._pipe.inflight:
            if wave == 2:
                # no flush: the in-flight speculation predates the mesh
                sched.mesh = make_mesh(8)
            wave += 1
            for _ in range(48):
                if i < len(pods):
                    st.submit(pods[i])
                    i += 1
            for pod, node, _lat in st.pump():
                decided[pod.meta.name] = node
        for pod, node, _lat in st.flush():
            decided[pod.meta.name] = node
    finally:
        st.close()
    mism = sched.extender.registry.get(
        "pipeline_carry_mismatch_total"
    ).value(table="modes")
    assert mism > 0, "mesh swap must discard via the modes comparison"
    assert len(decided) == 192
    assert all(v is not None for v in decided.values())


GATE_ARMS = {
    "quotas": "test_gate_quota_equivalence",
    "numa": "test_gate_numa_equivalence",
    "devices": "test_gate_device_equivalence",
    "gangs": "test_gate_gang_equivalence",
    # first-class multi-chip PR
    "mesh": (
        "test_gate_mesh_equivalence",
        "test_gate_mesh_swap_discards_speculation",
    ),
    "batch_gangs": (
        "test_gate_gang_equivalence",
        "test_cold_gang_batch_stays_serial",
    ),
    "ladder": "test_depth2_ladder_demotion_discards_chain",
    # open the last gates PR
    "reservations": (
        "test_gate_reservation_equivalence",
        "test_reservation_bind_flip_discards_speculation",
    ),
    "preemption": (
        "test_gate_preemption_eager_equivalence",
        "test_gate_preemption_defer_equivalence",
    ),
}


def _drive_fixed(
    sched,
    batches,
    pipelined,
    depth=1,
    churn_at=None,
    rollback_at_commit=None,
    chaos=None,
    refeed_unsched=True,
):
    """Drive the SAME fixed batch sequence through the pipelined or the
    serial path (the honest equivalence frame: the stream pump's retry
    re-queue timing legitimately shifts batch composition between modes,
    so equivalence is asserted cycle-for-cycle on identical batches).
    ``churn_at`` removes one node + adds a fresh one before that batch
    index WITHOUT flushing — in pipelined mode the in-flight speculation
    goes stale and must be discarded, re-dispatching serial-identically.
    ``rollback_at_commit`` arms ``commit.crash`` on that 1-based commit
    evaluation (both modes hit the same commit sequence, so the same
    chunk rolls back). Unschedulable pods are re-fed once at the end
    (deterministic retry). Returns {pod name: node | None}."""
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    decided = {}

    def absorb(out):
        if out is None:
            return
        for p, nd in out.bound:
            decided[p.meta.name] = nd
        for p in out.unschedulable:
            decided[p.meta.name] = None

    if rollback_at_commit is not None:
        chaos.arm(
            "commit.crash",
            error=RuntimeError,
            at_hits=frozenset([rollback_at_commit]),
            times=1,
        )
    pipe = CyclePipeline(sched, depth=depth) if pipelined else None
    try:
        for k, batch in enumerate(batches):
            if churn_at is not None and k == churn_at:
                snap = sched.snapshot
                snap.remove_node(snap.node_name(1))
                snap.upsert_node(_node("late-node"))
            if pipe is not None:
                absorb(pipe.feed(batch))
            else:
                absorb(sched.schedule(batch))
        if pipe is not None:
            while pipe.inflight:
                absorb(pipe.flush())
        if refeed_unsched:
            retry = [
                p
                for batch in batches
                for p in batch
                if decided.get(p.meta.name) is None
            ]
            if retry:
                if pipe is not None:
                    absorb(pipe.feed(retry))
                    while pipe.inflight:
                        absorb(pipe.flush())
                else:
                    absorb(sched.schedule(retry))
    finally:
        if pipe is not None:
            pipe.close()
    return decided


def _spec_counts(sched):
    reg = sched.extender.registry
    c = reg.get("pipeline_speculation_total")
    return c.value(outcome="kept"), c.value(outcome="discarded")


def _build_quota(n_nodes=32, chaos=None):
    from koordinator_tpu.api.types import ElasticQuota
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        GroupQuotaManager,
    )

    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(_node(f"n{i:03d}"))
    gqm = GroupQuotaManager(snap.config)
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="org"),
            min={ext.RES_CPU: 8000, ext.RES_MEMORY: 32768},
            max={ext.RES_CPU: 200000, ext.RES_MEMORY: 800000},
            is_parent=True,
        )
    )
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="team"),
            parent="org",
            min={ext.RES_CPU: 4000, ext.RES_MEMORY: 16384},
            max={ext.RES_CPU: 100000, ext.RES_MEMORY: 400000},
        )
    )
    kw = {"chaos": chaos} if chaos is not None else {}
    sched = BatchScheduler(
        snap, LoadAwareArgs(), quotas=gqm, batch_bucket=64, **kw
    )
    sched.extender.monitor.stop_background()
    return sched


def _quota_pods(n):
    return [
        Pod(
            meta=ObjectMeta(
                name=f"q{i:04d}", labels={ext.LABEL_QUOTA_NAME: "team"}
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048},
                priority=9000 - (i % 7),
            ),
        )
        for i in range(n)
    ]


def test_gate_quota_equivalence():
    """Quota-table chaining: quota-bearing batches take the speculative
    path (kept > 0, quotas gate never closed) and stay bit-exact vs
    serial — decisions, the used ledger and the runtime table — across
    saturation (admission rejections), mid-pipeline node churn and a
    Reserve-journal rollback."""
    from koordinator_tpu.chaos import FaultInjector

    batches = lambda: [  # noqa: E731
        _quota_pods(300)[i * 50 : (i + 1) * 50] for i in range(6)
    ]
    ca = FaultInjector(seed=3)
    a = _build_quota(chaos=ca)
    da = _drive_fixed(
        a, batches(), pipelined=False, churn_at=3,
        rollback_at_commit=4, chaos=ca,
    )
    cb = FaultInjector(seed=3)
    b = _build_quota(chaos=cb)
    db = _drive_fixed(
        b, batches(), pipelined=True, churn_at=3,
        rollback_at_commit=4, chaos=cb,
    )
    kept, _disc = _spec_counts(b)
    assert kept > 0, "quota-bearing speculation never engaged"
    assert da == db
    assert any(v is None for v in db.values()), (
        "fixture must saturate the quota (admission arm untested)"
    )
    assert np.array_equal(a.quotas.used, b.quotas.used)
    assert np.array_equal(
        a.quotas.quota_arrays()[0], b.quotas.quota_arrays()[0]
    )
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="quotas") == 0.0


def _build_numa(n_nodes=24, chaos=None):
    from koordinator_tpu.core.topology import CPUTopology
    from koordinator_tpu.scheduler.plugins.nodenumaresource import (
        NUMAManager,
        NUMAPolicy,
    )

    topo = CPUTopology.uniform(
        sockets=2, numa_per_socket=1, cores_per_numa=16
    )
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    for i in range(n_nodes):
        name = f"n{i:03d}"
        snap.upsert_node(_node(name, cpu=64000, mem=262144))
        numa.register_node(
            name, topo, NUMAPolicy.SINGLE_NUMA_NODE,
            memory_per_zone_mib=131072,
        )

    def register_late(node_name):
        numa.register_node(
            node_name, topo, NUMAPolicy.SINGLE_NUMA_NODE,
            memory_per_zone_mib=131072,
        )

    kw = {"chaos": chaos} if chaos is not None else {}
    sched = BatchScheduler(
        snap, LoadAwareArgs(), numa=numa, batch_bucket=32, **kw
    )
    sched.extender.monitor.stop_background()
    sched._register_late = register_late
    return sched


def _numa_pods(n):
    return [
        Pod(
            meta=ObjectMeta(
                name=f"m{i:04d}", labels={ext.LABEL_POD_QOS: "LSR"}
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                priority=9500 - (i % 5),
            ),
        )
        for i in range(n)
    ]


def test_gate_numa_equivalence():
    """NUMA cross-cycle carry: zone-bearing batches speculate (gate
    never closed) and stay bit-exact vs serial — decisions AND the
    managers' zone-free tables — including the exact cpuset host commit
    and a mid-stream rollback."""
    from koordinator_tpu.chaos import FaultInjector

    batches = lambda: [  # noqa: E731
        _numa_pods(192)[i * 32 : (i + 1) * 32] for i in range(6)
    ]
    ca = FaultInjector(seed=4)
    a = _build_numa(chaos=ca)
    da = _drive_fixed(
        a, batches(), pipelined=False, rollback_at_commit=3, chaos=ca
    )
    cb = FaultInjector(seed=4)
    b = _build_numa(chaos=cb)
    db = _drive_fixed(
        b, batches(), pipelined=True, rollback_at_commit=3, chaos=cb
    )
    kept, _disc = _spec_counts(b)
    assert kept > 0, "NUMA-bearing speculation never engaged"
    assert da == db
    np.testing.assert_array_equal(a.numa.arrays()[0], b.numa.arrays()[0])
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="numa") == 0.0


def _build_devices(n_nodes=24, chaos=None, gpus=8):
    from koordinator_tpu.api.types import Device, DeviceInfo
    from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager

    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    for i in range(n_nodes):
        name = f"g{i:03d}"
        snap.upsert_node(_node(name, cpu=128000, mem=1 << 20))
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g, numa_node=g // 4)
                    for g in range(gpus)
                ],
            )
        )
    kw = {"chaos": chaos} if chaos is not None else {}
    sched = BatchScheduler(
        snap, LoadAwareArgs(), devices=dm, batch_bucket=32, **kw
    )
    sched.extender.monitor.stop_background()
    return sched


def _device_pods(n):
    pods = []
    for i in range(n):
        req = {ext.RES_CPU: 4000, ext.RES_MEMORY: 16384}
        kind = i % 4
        if kind == 0:
            req[ext.RES_GPU] = 2
        elif kind == 1:
            req[ext.RES_GPU] = 1
        elif kind == 2:
            req[ext.RES_GPU_MEMORY_RATIO] = 50
        else:
            req[ext.RES_GPU_MEMORY_RATIO] = 30
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"d{i:04d}"),
                spec=PodSpec(requests=req, priority=9000 - (i % 3)),
            )
        )
    return pods


def test_gate_device_equivalence():
    """Device cross-cycle carry: GPU-bearing batches (whole AND
    fractional shares) speculate and stay bit-exact vs serial —
    decisions and the exact per-slot table — with a rollback arm."""
    from koordinator_tpu.chaos import FaultInjector

    batches = lambda: [  # noqa: E731
        _device_pods(160)[i * 32 : (i + 1) * 32] for i in range(5)
    ]
    ca = FaultInjector(seed=5)
    a = _build_devices(chaos=ca)
    da = _drive_fixed(
        a, batches(), pipelined=False, rollback_at_commit=2, chaos=ca
    )
    cb = FaultInjector(seed=5)
    b = _build_devices(chaos=cb)
    db = _drive_fixed(
        b, batches(), pipelined=True, rollback_at_commit=2, chaos=cb
    )
    kept, _disc = _spec_counts(b)
    assert kept > 0, "device-bearing speculation never engaged"
    assert da == db
    np.testing.assert_array_equal(
        a.devices.slot_array(), b.devices.slot_array()
    )
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="devices") == 0.0


def _gang_pods(n_gangs, members=2, gpu=4, start=0):
    pods = []
    for g in range(start, start + n_gangs):
        for m in range(members):
            pods.append(
                Pod(
                    meta=ObjectMeta(
                        name=f"gang{g:04d}-{m}",
                        labels={
                            ext.LABEL_GANG_NAME: f"gang-{g}",
                            ext.LABEL_GANG_MIN_AVAILABLE: str(members),
                        },
                    ),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: 16000,
                            ext.RES_MEMORY: 65536,
                            ext.RES_GPU: gpu,
                        },
                        priority=9000,
                    ),
                )
            )
    return pods


def test_gate_gang_equivalence():
    """Warm-gang carry: batches of complete gangs speculate
    (batch_gangs gate open) and stay bit-exact vs serial — all-or-
    nothing Permit included — with the exact device-slot state carried
    across the boundary."""
    batches = lambda: [  # noqa: E731
        _gang_pods(8, start=k * 8) for k in range(5)
    ]
    a = _build_devices()
    da = _drive_fixed(a, batches(), pipelined=False)
    b = _build_devices()
    db = _drive_fixed(b, batches(), pipelined=True)
    kept, _disc = _spec_counts(b)
    assert kept > 0, "warm-gang speculation never engaged"
    assert da == db
    np.testing.assert_array_equal(
        a.devices.slot_array(), b.devices.slot_array()
    )
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="gangs") == 0.0
    assert closed.value(gate="batch_gangs") == 0.0


def test_cold_gang_batch_stays_serial():
    """A batch carrying an INCOMPLETE gang (member missing) is cold: the
    ``batch_gangs`` gate closes, the cycle runs serial, and decisions
    still match the serial path (the missing member gates the gang
    whole)."""
    batches = lambda: [  # noqa: E731
        _gang_pods(4, start=0) + _gang_pods(1, members=3, start=100)[:2]
    ]
    a = _build_devices()
    da = _drive_fixed(a, batches(), pipelined=False, refeed_unsched=False)
    b = _build_devices()
    db = _drive_fixed(b, batches(), pipelined=True, refeed_unsched=False)
    assert da == db
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="batch_gangs") > 0.0
    kept, _ = _spec_counts(b)
    assert kept == 0.0


def test_carry_mismatch_chaos_forces_redispatch():
    """The ``pipeline.carry_mismatch`` chaos point corrupts a chained
    carry at consume: the speculation must be DISCARDED through the real
    validation comparison (counted in pipeline_carry_mismatch_total) and
    the redispatched cycle must stay decision-identical to serial."""
    from koordinator_tpu.chaos import FaultInjector

    batches = lambda: [  # noqa: E731
        _quota_pods(200)[i * 40 : (i + 1) * 40] for i in range(5)
    ]
    a = _build_quota()
    da = _drive_fixed(a, batches(), pipelined=False)
    chaos = FaultInjector(seed=9)
    b = _build_quota(chaos=chaos)
    # at_hits: fire on the 3rd consume evaluation — deterministic, and
    # (like probability-1 arms) consumes no rng stream draw
    chaos.arm("pipeline.carry_mismatch", at_hits=frozenset([3]), times=1)
    db = _drive_fixed(b, batches(), pipelined=True)
    assert chaos.fired_counts()["pipeline.carry_mismatch"] == 1
    mism = b.extender.registry.get("pipeline_carry_mismatch_total")
    assert mism.value(table="quota") >= 1.0
    _kept, disc = _spec_counts(b)
    assert disc > 0
    assert da == db


# ---------------------------------------------------------------------------
# depth>1 pipelining: validation chains
# ---------------------------------------------------------------------------


def test_depth2_equivalence_and_depth_gauge():
    """Two in-flight speculative solves (depth=2): decisions stay
    bit-exact vs serial and the solver_pipeline_depth gauge reports the
    deeper pipeline."""
    batches = lambda: [  # noqa: E731
        _pods(240)[i * 40 : (i + 1) * 40] for i in range(6)
    ]
    a = _build()
    da = _drive_fixed(a, batches(), pipelined=False)
    b = _build()
    seen_depth = 0.0
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    pipe = CyclePipeline(b, depth=2)
    decided = {}

    def absorb(out):
        if out is None:
            return
        for p, nd in out.bound:
            decided[p.meta.name] = nd
        for p in out.unschedulable:
            decided[p.meta.name] = None

    try:
        gauge = b.extender.registry.get("solver_pipeline_depth")
        for batch in batches():
            absorb(pipe.feed(batch))
            seen_depth = max(seen_depth, gauge.value())
        while pipe.inflight:
            absorb(pipe.flush())
    finally:
        pipe.close()
    kept, _ = _spec_counts(b)
    assert kept > 0
    assert seen_depth >= 3.0, seen_depth  # 2 batches + ≥1 spec in flight
    assert da == decided


def _feed_depth2(sched, batches, poison=None):
    """Feed ``batches`` through a depth-2 pipeline, invoking
    ``poison(sched)`` just before the LAST feed (with two speculative
    solves then in flight). Returns (decided, pipe_closed_stats)."""
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    pipe = CyclePipeline(sched, depth=2)
    decided = {}

    def absorb(out):
        if out is None:
            return
        for p, nd in out.bound:
            decided[p.meta.name] = nd
        for p in out.unschedulable:
            decided[p.meta.name] = None

    try:
        for k, batch in enumerate(batches):
            if poison is not None and k == len(batches) - 1:
                poison(sched)
            absorb(pipe.feed(batch))
        while pipe.inflight:
            absorb(pipe.flush())
    finally:
        pipe.close()
    return decided


def test_depth2_node_churn_discards_entire_chain():
    """Mid-pipeline node churn with TWO speculations in flight must
    discard the ENTIRE pending chain (both solves, not just the head)
    and re-dispatch decision-identically to serial. The serial frame
    applies the churn before the first UNCOMMITTED batch (the pipeline
    lags its commits by ``depth``), so both runs schedule the same
    batches against the same world."""

    def churn(sched):
        snap = sched.snapshot
        snap.remove_node(snap.node_name(2))
        snap.upsert_node(_node("late-node"))

    batches = lambda: [  # noqa: E731
        _pods(200)[i * 40 : (i + 1) * 40] for i in range(5)
    ]
    a = _build()
    serial = {}
    for k, batch in enumerate(batches()):
        if k == 2:
            # the pipelined run poisons before feed(4), when batches 2-4
            # are still uncommitted — serial-equivalent point: before
            # batch 2's own schedule
            churn(a)
        out = a.schedule(batch)
        for p, nd in out.bound:
            serial[p.meta.name] = nd
        for p in out.unschedulable:
            serial[p.meta.name] = None
    b = _build()
    decided = _feed_depth2(b, batches(), poison=churn)
    kept, disc = _spec_counts(b)
    assert kept > 0
    assert disc >= 2, (
        f"churn with two in-flight solves must discard BOTH, got {disc}"
    )
    assert serial == decided
    assert "late-node" in set(decided.values())


def test_depth2_fence_revocation_discards_entire_chain():
    """Fence revocation mid-pipeline (leadership lost with two
    speculations in flight): drain_for_handoff discards the WHOLE chain
    and every trailing commit is fenced — all pods come back
    unschedulable, none half-committed."""
    from koordinator_tpu.core.journal import EpochFence
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    fence = EpochFence()
    snap = ClusterSnapshot()
    for i in range(32):
        snap.upsert_node(_node(f"n{i:03d}"))
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=64, fence=fence
    )
    sched.extender.monitor.stop_background()
    sched.grant_leadership(fence.advance())
    pipe = CyclePipeline(sched, depth=2)
    batches = [_pods(120)[i * 40 : (i + 1) * 40] for i in range(3)]
    bound = {}
    try:
        for batch in batches:
            out = pipe.feed(batch)
            if out is not None:
                for p, nd in out.bound:
                    bound[p.meta.name] = nd
        assert len(pipe._pending) == 2
        assert sum(1 for e in pipe._pending if e.spec is not None) >= 1
        # a rival takes the lease: our grant is stale from here on
        fence.advance()
        drained = pipe.drain_for_handoff()
    finally:
        pipe.close()
    assert drained is not None
    assert not drained.bound, "a fenced commit must never bind"
    names = {p.meta.name for p in drained.unschedulable}
    expect = {p.meta.name for b in batches[1:] for p in b}
    assert names == expect, "both in-flight batches must come back whole"
    disc = sched.extender.registry.get(
        "pipeline_speculation_total"
    ).value(outcome="discarded")
    assert disc >= 1.0


def test_depth2_ladder_demotion_discards_chain():
    """A fallback-ladder demotion mid-pipeline poisons every pending
    speculation: with two solves in flight, the whole chain is discarded
    at its commits (consume guard: ladder != 0) and decisions remain
    identical to serial — demotion moves dispatches to the per-chunk
    level, which is decision-identical by the ladder's own contract, so
    the serial frame needs no matching fault. The demotion is injected
    through the REAL failure path (``_note_solver_failure``, what a
    dispatch exception calls)."""
    batches = lambda: [  # noqa: E731
        _pods(200)[i * 40 : (i + 1) * 40] for i in range(5)
    ]
    a = _build()
    serial = {}
    for batch in batches():
        out = a.schedule(batch)
        for p, nd in out.bound:
            serial[p.meta.name] = nd
        for p in out.unschedulable:
            serial[p.meta.name] = None
    b = _build()

    def demote(sched):
        sched._note_solver_failure(0, RuntimeError("injected demotion"))

    decided = _feed_depth2(b, batches(), poison=demote)
    assert (
        b.extender.registry.get("solver_fallback_total").value(level="1")
        > 0
    ), "the injected failure must demote the ladder"
    kept, disc = _spec_counts(b)
    assert kept > 0
    assert disc >= 2, (
        f"demotion with two in-flight solves must discard BOTH, got {disc}"
    )
    assert serial == decided


# ---------------------------------------------------------------------------
# Open the LAST gates PR: reservation + preemption carries, adaptive depth
# ---------------------------------------------------------------------------


def _build_resv(n_nodes=16, chaos=None, n_resv=6):
    """Scheduler with an attached ReservationManager (+quota tree): half
    the reservations are allocate-once (consumed whole), half partial
    (remainder ghost re-assumed) — the two snapshot-effect shapes the
    preview must predict. Ghosts are scheduled Available up front."""
    from koordinator_tpu.api.types import (
        ElasticQuota,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        GroupQuotaManager,
    )
    from koordinator_tpu.scheduler.plugins.reservation import (
        ReservationManager,
    )

    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(_node(f"n{i:03d}", cpu=32000, mem=131072))
    gqm = GroupQuotaManager(snap.config)
    # allow_lent_resource=False keeps the full min reserved regardless
    # of propagated demand — runtime ≥ min, so the fast path's quota
    # headroom check actually ADMITS labeled owners (a demand-driven
    # runtime trails the fast path by one cycle and would refuse every
    # one, leaving the reservation-consumption legs untested)
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="resv-team"),
            min={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536},
            max={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144},
            allow_lent_resource=False,
        )
    )
    kw = {"chaos": chaos} if chaos is not None else {}
    sched = BatchScheduler(
        snap, LoadAwareArgs(), quotas=gqm, batch_bucket=32, **kw
    )
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    for k in range(n_resv):
        rm.add(
            Reservation(
                meta=ObjectMeta(name=f"resv-{k}"),
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                owners=[
                    ReservationOwner(label_selector={"app": "resv-owner"})
                ],
                allocate_once=(k % 2 == 0),
            )
        )
    assert rm.schedule_pending() == n_resv
    return sched


def _resv_batches(n_batches=4, owners_per=2, plain_per=18):
    """Fixed batches mixing fast-path owner pods (quota-labeled, so the
    preview's headroom + charge legs run) with plain solver pods."""
    batches = []
    oi = pi = 0
    for _b in range(n_batches):
        batch = []
        for _ in range(owners_per):
            batch.append(
                Pod(
                    meta=ObjectMeta(
                        name=f"own{oi:03d}",
                        labels={
                            "app": "resv-owner",
                            ext.LABEL_QUOTA_NAME: "resv-team",
                        },
                    ),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: 2000,
                            ext.RES_MEMORY: 4096,
                        },
                        priority=9100,
                    ),
                )
            )
            oi += 1
        for _ in range(plain_per):
            batch.append(
                Pod(
                    meta=ObjectMeta(name=f"pl{pi:04d}"),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: 1000,
                            ext.RES_MEMORY: 2048,
                        },
                        priority=9000 - (pi % 5),
                    ),
                )
            )
            pi += 1
        batches.append(batch)
    return batches


def test_gate_reservation_equivalence():
    """Reservation carry (open the last gates PR): reservation-bearing
    batches SPECULATE — the fast path's binds are predicted at dispatch
    and validated by value at consume — and stay bit-exact vs serial
    across mid-pipeline node churn and a Reserve-journal rollback.
    End-state ReservationManager table (phase/allocated/owners/ledger),
    quota used ledger and snapshot node accounting are compared by
    value; engagement is proven (kept > 0, reservations gate closures
    0) and the fast path really fired under speculation."""
    from koordinator_tpu.chaos import FaultInjector

    ca = FaultInjector(seed=6)
    a = _build_resv(chaos=ca)
    da = _drive_fixed(
        a, _resv_batches(), pipelined=False, churn_at=2,
        rollback_at_commit=3, chaos=ca,
    )
    cb = FaultInjector(seed=6)
    b = _build_resv(chaos=cb)
    db = _drive_fixed(
        b, _resv_batches(), pipelined=True, churn_at=2,
        rollback_at_commit=3, chaos=cb,
    )
    kept, _disc = _spec_counts(b)
    assert kept > 0, "reservation-bearing speculation never engaged"
    assert da == db
    # the fast path really CONSUMED reservations (the carry carried
    # something), and no discard was ever attributed to a wrong
    # reservation prediction — together with kept>0 this pins
    # speculation running over genuinely fast-path-bearing cycles
    consumed = sum(
        1
        for r in b.reservations.list()
        if r.current_owners or r.phase.value == "Succeeded"
    )
    assert consumed > 0, "no reservation was ever consumed"
    mism = b.extender.registry.get("pipeline_carry_mismatch_total")
    assert mism.value(table="reservation") == 0.0
    assert a.reservations.table_view() == b.reservations.table_view()
    assert np.array_equal(a.quotas.used, b.quotas.used)
    np.testing.assert_array_equal(
        a.snapshot.nodes.requested, b.snapshot.nodes.requested
    )
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="reservations") == 0.0


def test_reservation_bind_flip_discards_speculation():
    """Reservation-ledger drift OUTSIDE the pipeline between dispatch
    and consume — an informer delivering a new reservation CR, which
    touches no snapshot version — flips the table the preview started
    from: the pre-table comparison must DISCARD the speculation
    (attributed to the ``reservation`` table) and the redispatched
    cycle must stay decision-identical. (Drift that releases holds,
    e.g. expiry, is caught earlier by the cheap version guard — this
    arm pins the BY-VALUE comparison itself.)"""
    from koordinator_tpu.api.types import Reservation, ReservationOwner

    def _late_resv():
        return Reservation(
            meta=ObjectMeta(name="resv-late"),
            requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
            owners=[
                ReservationOwner(label_selector={"app": "resv-owner"})
            ],
        )

    a = _build_resv()
    batches = _resv_batches()
    serial = {}
    for k, batch in enumerate(batches):
        if k == 2:
            a.reservations.add(_late_resv())  # PENDING: decision-inert
        out = a.schedule(batch)
        for p, nd in out.bound:
            serial[p.meta.name] = nd
        for p in out.unschedulable:
            serial[p.meta.name] = None
    b = _build_resv()
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    pipe = CyclePipeline(b, depth=1)
    decided = {}

    def absorb(out):
        if out is None:
            return
        for p, nd in out.bound:
            decided[p.meta.name] = nd
        for p in out.unschedulable:
            decided[p.meta.name] = None

    try:
        for k, batch in enumerate(batches):
            if k == 2:
                # mid-pipeline: batch 1's speculation is in flight and
                # its preview table does not know this reservation
                b.reservations.add(_late_resv())
            absorb(pipe.feed(batch))
        while pipe.inflight:
            absorb(pipe.flush())
    finally:
        pipe.close()
    _kept, disc = _spec_counts(b)
    assert disc > 0, "the late reservation must discard the spec"
    mism = b.extender.registry.get("pipeline_carry_mismatch_total")
    assert mism.value(table="reservation") >= 1.0
    assert serial == decided
    assert a.reservations.table_view() == b.reservations.table_view()


def _build_preempt(chaos=None, defer=False):
    snap = ClusterSnapshot()
    for i in range(4):
        snap.upsert_node(_node(f"n{i:03d}", cpu=16000, mem=65536))
    kw = {"chaos": chaos} if chaos is not None else {}
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(),
        batch_bucket=32,
        enable_priority_preemption=True,
        defer_preemption=defer,
        **kw,
    )
    sched.extender.monitor.stop_background()
    return sched


def _preempt_batches():
    """Low-priority filler first (binds, fills the cluster), then
    high-priority arrivals that can only place by evicting them."""
    low = [
        Pod(
            meta=ObjectMeta(name=f"low{i:03d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 16384},
                priority=4000 + (i % 3),
            ),
        )
        for i in range(16)
    ]
    high = [
        Pod(
            meta=ObjectMeta(name=f"high{i:03d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 32768},
                priority=9500,
            ),
        )
        for i in range(4)
    ]
    return [low[:8], low[8:], high[:2], high[2:]]


def test_gate_preemption_eager_equivalence():
    """Priority preemption OPEN (open the last gates PR): cycles with
    ``enable_priority_preemption`` speculate; an EAGER eviction+retry
    sets ``_cycle_preempted`` and discards the downstream chain at that
    commit, so decisions — including the evictions themselves and the
    preemptors' retried placements — stay bit-exact vs serial, with
    the victim ledgers compared by value."""
    a = _build_preempt()
    serial = {}
    serial_victims = []
    for batch in _preempt_batches():
        out = a.schedule(batch)
        for p, nd in out.bound:
            serial[p.meta.name] = nd
        for p in out.unschedulable:
            serial[p.meta.name] = None
        serial_victims.extend(p.meta.name for p in out.preempted)
    b = _build_preempt()
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    pipe = CyclePipeline(b, depth=1)
    decided = {}
    victims = []
    try:
        for batch in _preempt_batches():
            out = pipe.feed(batch)
            if out is not None:
                for p, nd in out.bound:
                    decided[p.meta.name] = nd
                for p in out.unschedulable:
                    decided[p.meta.name] = None
                victims.extend(p.meta.name for p in out.preempted)
        while pipe.inflight:
            out = pipe.flush()
            if out is not None:
                for p, nd in out.bound:
                    decided[p.meta.name] = nd
                for p in out.unschedulable:
                    decided[p.meta.name] = None
                victims.extend(p.meta.name for p in out.preempted)
    finally:
        pipe.close()
    kept, _disc = _spec_counts(b)
    assert kept > 0, "preemption-enabled speculation never engaged"
    assert serial_victims, "fixture must actually preempt"
    assert serial_victims == victims
    assert serial == decided
    # victim ledgers by value: the evicted uids are gone from both
    assert a._bound_nodes == b._bound_nodes
    np.testing.assert_array_equal(
        a.snapshot.nodes.requested, b.snapshot.nodes.requested
    )
    closed = b.extender.registry.get("pipeline_gate_closed_total")
    assert closed.value(gate="preemption") == 0.0


def test_gate_preemption_defer_equivalence():
    """defer_preemption (nominate-only) chains TRIVIALLY: the PostFilter
    pass is a pure read, so a nominating cycle keeps the speculative
    chain alive (zero discards) while the nominations stay bit-exact vs
    serial."""
    a = _build_preempt(defer=True)
    serial = {}
    serial_nom = []
    for batch in _preempt_batches():
        out = a.schedule(batch)
        for p, nd in out.bound:
            serial[p.meta.name] = nd
        for p in out.unschedulable:
            serial[p.meta.name] = None
        serial_nom.extend(p.meta.name for p in out.preempted)
    b = _build_preempt(defer=True)
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    pipe = CyclePipeline(b, depth=1)
    decided = {}
    nominated = []
    try:
        for batch in _preempt_batches():
            out = pipe.feed(batch)
            if out is not None:
                for p, nd in out.bound:
                    decided[p.meta.name] = nd
                for p in out.unschedulable:
                    decided[p.meta.name] = None
                nominated.extend(p.meta.name for p in out.preempted)
        while pipe.inflight:
            out = pipe.flush()
            if out is not None:
                for p, nd in out.bound:
                    decided[p.meta.name] = nd
                for p in out.unschedulable:
                    decided[p.meta.name] = None
                nominated.extend(p.meta.name for p in out.preempted)
    finally:
        pipe.close()
    kept, disc = _spec_counts(b)
    assert kept > 0
    assert disc == 0, (
        "nominate-only preemption must not discard the chain"
    )
    assert serial_nom, "fixture must actually nominate victims"
    assert serial_nom == nominated
    assert serial == decided
    # nominate-only: nothing was evicted anywhere
    assert a._bound_nodes == b._bound_nodes
    assert all(n in b._bound_nodes for n in [])  # ledger intact shape


# ---------------------------------------------------------------------------
# adaptive pipeline depth (open the last gates PR)
# ---------------------------------------------------------------------------


def _churn_version(sched):
    """Net-zero snapshot churn: bumps the version (discarding any
    in-flight speculation at its consume guard) without changing any
    decision-bearing state."""
    snap = sched.snapshot
    dummy = Pod(
        meta=ObjectMeta(name="churn-dummy"),
        spec=PodSpec(requests={ext.RES_CPU: 1, ext.RES_MEMORY: 1}),
    )
    assert snap.assume_pod(dummy, snap.node_name(0))
    snap.forget_pod(dummy.meta.uid)


def test_adaptive_depth_degrades_and_recovers():
    """The depth controller: sustained discards (version churn between
    every feed) degrade the effective depth to 1 before more deep
    dispatches are wasted; a quiet stretch restores the configured max.
    The per-cycle depth decision + discard-rate input land on the
    flight recorder (post-hoc explainability)."""
    from koordinator_tpu.obs.flightrecorder import FlightRecorder
    from koordinator_tpu.scheduler.pipeline import (
        CyclePipeline,
        _DepthController,
    )

    sched = _build(n_nodes=16, batch_bucket=32)
    fr = FlightRecorder(capacity=64, incarnation="adaptive-test")
    sched.attach_flight_recorder(fr)
    pipe = CyclePipeline(sched, depth=2)
    pods = _pods(400, cpu=200, mem=256)
    i = 0
    depth_trace = []
    try:
        assert pipe.last_adaptive_depth == 2
        for _ in range(12):
            batch = pods[i : i + 16]
            i += 16
            _churn_version(sched)   # every consume discards
            pipe.feed(batch)
            depth_trace.append(pipe.last_adaptive_depth)
        assert pipe.last_adaptive_depth == 1, depth_trace
        # quiet stretch: no churn, drain + idle feeds restore the max
        while pipe.inflight:
            pipe.flush()
        for _ in range(_DepthController.QUIET_FEEDS + 1):
            pipe.feed([])
        pipe.feed(pods[i : i + 16])
        assert pipe.last_adaptive_depth == 2
    finally:
        pipe.close()
    recs = fr.last()
    assert recs, "cycles must have recorded"
    assert all("depth" in r and "discard_rate" in r for r in recs)
    assert any(r["depth"] == 1 and r["discard_rate"] >= 0.5 for r in recs), (
        "the degraded window must be explainable from the recorder"
    )
    # /debug/pipeline serves the controller's state
    info = pipe.gate_info()
    dc = info["depth_controller"]
    assert dc["max_depth"] == 2 and dc["adaptive"] is True
    assert "discard_rate" in dc and "effective_cap" in dc


def test_brownout_cap_dominates_adaptive_depth():
    """Brownout interplay (satellite): while the ladder sits at L1+ its
    depth cap DOMINATES the adaptive controller — the effective cap
    never exceeds 1 even though the controller wants the max — and the
    controller's choice resumes as the effective cap at L0."""
    from koordinator_tpu.scheduler.pipeline import CyclePipeline

    class _Ladder:
        level = 1

        def pipeline_depth_cap(self):
            return 1 if self.level >= 1 else 1 << 30

        def serial_only(self):
            return False

        def bucket_degrade_steps(self):
            return 0

    sched = _build(n_nodes=16, batch_bucket=32)
    ladder = _Ladder()
    sched.brownout = ladder
    pipe = CyclePipeline(sched, depth=2)
    pods = _pods(200, cpu=200, mem=256)
    i = 0
    try:
        for _ in range(4):
            pipe.feed(pods[i : i + 16])
            i += 16
            # clean stream: the controller holds the max…
            assert pipe.last_adaptive_depth == 2
            # …but the ladder's cap dominates while browning
            assert pipe.last_depth_cap == 1
            assert len(pipe._pending) <= 1
        ladder.level = 0   # brownout recovers to L0
        for _ in range(3):
            pipe.feed(pods[i : i + 16])
            i += 16
        assert pipe.last_depth_cap == 2, (
            "the controller must resume as the effective cap at L0"
        )
        while pipe.inflight:
            pipe.flush()
    finally:
        pipe.close()
