"""Bench regression gate self-test (devprof tentpole satellite): the
noise-aware thresholds must flag a synthetic regression, pass a synthetic
no-regression, and run clean over the COMMITTED round pair — the tool
only ever compares committed JSON; no bench runs inside tier-1."""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import bench_regress  # noqa: E402

REPO = Path(__file__).resolve().parent.parent


def _entry(scenario, pps, passes=None, **extra):
    out = {"scenario": scenario, "pods_per_sec": pps}
    if passes is not None:
        out["passes"] = passes
    out.update(extra)
    return out


def _rows_by_scenario(rows):
    return {r["scenario"]: r for r in rows}


class TestCompare:
    def test_flags_regression_and_improvement(self):
        base = bench_regress.load_artifact(
            [
                _entry("a", 1000.0, [990.0, 1000.0, 1010.0]),
                _entry("b", 1000.0, [990.0, 1000.0, 1010.0]),
                _entry("c", 1000.0, [990.0, 1000.0, 1010.0]),
            ]
        )
        cur = bench_regress.load_artifact(
            [
                _entry("a", 700.0, [690.0, 700.0, 710.0]),   # -30%
                _entry("b", 1050.0, [1040.0, 1050.0, 1060.0]),  # +5%
                _entry("c", 1400.0, [1390.0, 1400.0, 1410.0]),  # +40%
            ]
        )
        rows = _rows_by_scenario(
            bench_regress.compare(base, cur, threshold=0.10)
        )
        assert rows["a"]["verdict"] == "REGRESSION"
        assert rows["b"]["verdict"] == "OK"
        assert rows["c"]["verdict"] == "IMPROVED"

    def test_noise_band_widens_with_pass_spread(self):
        # a scenario whose own passes disagree by ±30% cannot flag a
        # 20% delta as regression; a tight-passes scenario can
        noisy_base = bench_regress.load_artifact(
            [_entry("noisy", 1000.0, [700.0, 1000.0, 1300.0])]
        )
        noisy_cur = bench_regress.load_artifact(
            [_entry("noisy", 800.0, [790.0, 800.0, 810.0])]
        )
        rows = bench_regress.compare(
            noisy_base, noisy_cur, threshold=0.10
        )
        assert rows[0]["verdict"] == "OK"
        assert rows[0]["band_pct"] > 10.0
        tight_base = bench_regress.load_artifact(
            [_entry("tight", 1000.0, [995.0, 1000.0, 1005.0])]
        )
        tight_cur = bench_regress.load_artifact(
            [_entry("tight", 800.0, [795.0, 800.0, 805.0])]
        )
        rows = bench_regress.compare(
            tight_base, tight_cur, threshold=0.10
        )
        assert rows[0]["verdict"] == "REGRESSION"

    def test_new_missing_and_no_metric(self):
        base = bench_regress.load_artifact(
            [_entry("gone", 1000.0), {"scenario": "tableonly", "runs": []}]
        )
        cur = bench_regress.load_artifact(
            [_entry("fresh", 1000.0), {"scenario": "tableonly", "runs": []}]
        )
        rows = _rows_by_scenario(bench_regress.compare(base, cur))
        assert rows["gone"]["verdict"] == "MISSING"
        assert rows["fresh"]["verdict"] == "NEW"
        assert rows["tableonly"]["verdict"] == "NO_METRIC"

    def test_metric_ladder_covers_suite_entry_shapes(self):
        e = {"scenario": "s", "pipelined_pods_per_sec": 7644.8,
             "pipelined_passes": [7531.7, 7644.8, 8091.3]}
        m = bench_regress.extract_metric(e)
        assert m["metric"] == "pipelined_pods_per_sec"
        assert m["passes"] == [7531.7, 7644.8, 8091.3]
        m = bench_regress.extract_metric(
            {"scenario": "recovery", "takeover_speedup": 9.33}
        )
        assert m["metric"] == "takeover_speedup" and m["passes"] is None


class TestArtifactShapes:
    def test_round_file_and_headline_shapes(self):
        round_doc = {
            "n": 5,
            "parsed": {
                "metric": "sched_pods_per_sec_10k_nodes",
                "value": 407363.6,
                "passes": [407309.8, 407363.6, 407929.7],
            },
        }
        art = bench_regress.load_artifact(round_doc)
        assert "sched_pods_per_sec_10k_nodes" in art
        headline = {"metric": "m", "value": 10.0, "passes": [9.0, 10.0]}
        assert "m" in bench_regress.load_artifact(headline)
        with pytest.raises(ValueError):
            bench_regress.load_artifact({"nope": 1})


class TestCurveFamily:
    """Multichip artifact family: an entry with an embedded
    pods/s-vs-device-count ``curve`` fans out into per-arm
    pseudo-scenarios so every device count gets its own noise band."""

    def _curve_entry(self, pps_by_s, spread=10.0):
        return _entry(
            "loadaware_multichip",
            pps_by_s[max(pps_by_s)],
            curve=[
                {
                    "devices": s,
                    "pods_per_sec": pps,
                    "passes": [pps - spread, pps, pps + spread],
                }
                for s, pps in sorted(pps_by_s.items())
            ],
        )

    def test_curve_expands_to_per_arm_pseudo_scenarios(self):
        art = bench_regress.load_artifact(
            [self._curve_entry({1: 900.0, 2: 1000.0, 8: 1200.0})]
        )
        assert set(art) == {
            "loadaware_multichip",
            "loadaware_multichip[S=1]",
            "loadaware_multichip[S=2]",
            "loadaware_multichip[S=8]",
        }
        # parent keeps the headline (widest-arm) metric; each arm
        # carries its own value + passes
        assert bench_regress.extract_metric(
            art["loadaware_multichip"]
        )["value"] == 1200.0
        arm = bench_regress.extract_metric(art["loadaware_multichip[S=2]"])
        assert arm["value"] == 1000.0 and len(arm["passes"]) == 3
        # single-entry (MULTICHIP_rNN.json) shape expands the same way
        single = bench_regress.load_artifact(
            self._curve_entry({2: 1000.0})
        )
        assert "loadaware_multichip[S=2]" in single

    def test_per_device_count_noise_bands_are_independent(self):
        base = bench_regress.load_artifact(
            [
                _entry(
                    "loadaware_multichip",
                    1200.0,
                    curve=[
                        {"devices": 2, "pods_per_sec": 1000.0,
                         "passes": [700.0, 1000.0, 1300.0]},   # ±30% noisy
                        {"devices": 8, "pods_per_sec": 1200.0,
                         "passes": [1195.0, 1200.0, 1205.0]},  # tight
                    ],
                )
            ]
        )
        cur = bench_regress.load_artifact(
            [
                _entry(
                    "loadaware_multichip",
                    960.0,
                    curve=[
                        {"devices": 2, "pods_per_sec": 800.0,
                         "passes": [790.0, 800.0, 810.0]},     # -20%
                        {"devices": 8, "pods_per_sec": 960.0,
                         "passes": [955.0, 960.0, 965.0]},     # -20%
                    ],
                )
            ]
        )
        rows = _rows_by_scenario(
            bench_regress.compare(base, cur, threshold=0.10)
        )
        # same -20% delta: absorbed by the noisy S=2 arm's own band,
        # flagged by the tight S=8 arm (and the tight parent row)
        assert rows["loadaware_multichip[S=2]"]["verdict"] == "OK"
        assert rows["loadaware_multichip[S=8]"]["verdict"] == "REGRESSION"
        assert rows["loadaware_multichip"]["verdict"] == "REGRESSION"

    def test_committed_multichip_artifact_expands_and_self_compares(self):
        path = REPO / "MULTICHIP_r06.json"
        assert path.exists(), "committed multichip curve artifact missing"
        art = bench_regress.load_artifact(json.loads(path.read_text()))
        arms = [s for s in art if s.startswith("loadaware_multichip[S=")]
        assert len(arms) >= 4, arms
        for s in arms:
            m = bench_regress.extract_metric(art[s])
            assert m and m["value"] > 0 and m["passes"]
        # evidence discipline: the committed artifact's perf claims ride
        # a retrace-free steady state and an effective donation
        entry = art["loadaware_multichip"]
        assert entry["steady_retraces"] == 0
        assert entry["donation_misses"] == 0
        rows = bench_regress.compare(art, art)
        assert {r["verdict"] for r in rows} <= {"OK", "NO_METRIC"}


class TestCommittedArtifacts:
    def test_committed_round_pair_produces_verdict_table(self, capsys):
        """Acceptance: the gate runs over the committed BENCH round pair
        and the committed suite vs itself, emitting a verdict per
        scenario and exit code 0 (no self-regression)."""
        rc = bench_regress.main(
            [
                "--baseline", str(REPO / "BENCH_r04.json"),
                "--current", str(REPO / "BENCH_r05.json"),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "sched_pods_per_sec_10k_nodes" in out and "OK" in out
        rc = bench_regress.main(
            [
                "--baseline", str(REPO / "BENCH_SUITE.json"),
                "--current", str(REPO / "BENCH_SUITE.json"),
            ]
        )
        out = capsys.readouterr().out
        assert rc == 0
        for scenario in (
            "loadaware_10k_nodes",
            "numa_binpack_2socket",
            "device_gang_8gpu",
            "quota_tree_3level",
        ):
            assert scenario in out

    def test_synthetic_regression_exits_nonzero(self, tmp_path, capsys):
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        base.write_text(
            json.dumps([_entry("s", 1000.0, [990.0, 1000.0, 1010.0])])
        )
        cur.write_text(
            json.dumps([_entry("s", 500.0, [490.0, 500.0, 510.0])])
        )
        out_json = tmp_path / "rows.json"
        rc = bench_regress.main(
            [
                "--baseline", str(base),
                "--current", str(cur),
                "--json", str(out_json),
            ]
        )
        assert rc == 1
        artifact = json.loads(out_json.read_text())
        assert artifact["rows"][0]["verdict"] == "REGRESSION"
        assert artifact["counts"]["REGRESSION"] == 1
        assert artifact["regressions"] == ["s"] and artifact["exit"] == 1
        assert "regression(s)" in capsys.readouterr().err

    def test_scenario_filter_gates_one_entry_independently(
        self, tmp_path, capsys
    ):
        """--scenario NAME compares only the named entries — the
        fleet_day CPU artifact can be gated without dragging in
        cross-backend rows from the accelerator suite (elastic-topology
        PR satellite)."""
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        base.write_text(
            json.dumps(
                [
                    _entry("fleet_day", 1000.0, [990.0, 1000.0, 1010.0]),
                    _entry("axon_only", 9000.0, [8990.0, 9000.0, 9010.0]),
                ]
            )
        )
        cur.write_text(
            json.dumps([_entry("fleet_day", 1005.0, [995.0, 1005.0, 1015.0])])
        )
        out_json = tmp_path / "rows.json"
        rc = bench_regress.main(
            [
                "--baseline", str(base),
                "--current", str(cur),
                "--scenario", "fleet_day",
                "--json", str(out_json),
            ]
        )
        assert rc == 0
        artifact = json.loads(out_json.read_text())
        scenarios = [r["scenario"] for r in artifact["rows"]]
        assert scenarios == ["fleet_day"], (
            "the unfiltered axon_only row must not appear (it would "
            "read MISSING and pollute the verdict counts)"
        )
        assert artifact["rows"][0]["verdict"] == "OK"
        # an unknown scenario is a usage error, not a silent empty run
        with pytest.raises(SystemExit):
            bench_regress.main(
                [
                    "--baseline", str(base),
                    "--current", str(cur),
                    "--scenario", "no-such-scenario",
                ]
            )

    def test_json_to_stdout_is_one_artifact(self, tmp_path, capsys):
        """--json - replaces the text table with the machine artifact:
        CI and the verdict table consume ONE comparison."""
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        base.write_text(
            json.dumps([_entry("s", 1000.0, [990.0, 1000.0, 1010.0])])
        )
        cur.write_text(
            json.dumps([_entry("s", 1005.0, [995.0, 1005.0, 1015.0])])
        )
        rc = bench_regress.main(
            ["--baseline", str(base), "--current", str(cur), "--json", "-"]
        )
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["rows"][0]["verdict"] == "OK"
        assert set(doc["counts"]) == set(bench_regress.VERDICTS)
        assert doc["exit"] == 0
