"""Runtime proxy tests: CRI interposition, hook dispatch with failure
policies, response merging, checkpoint store restore, and the koordlet
hook server end of the protocol (SURVEY §2.6)."""

import json

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.koordlet import resourceexecutor as rex
from koordinator_tpu.runtimeproxy import (
    ContainerConfig,
    ContainerMetadata,
    ContainerResourceHookResponse,
    CRIProxy,
    Dispatcher,
    FailurePolicy,
    HookError,
    HookServerRegistration,
    KoordletHookServer,
    LinuxContainerResources,
    PodSandboxConfig,
    PodSandboxHookResponse,
    PodSandboxMetadata,
    RuntimeHookType,
    Store,
    parse_failure_policy,
)
from koordinator_tpu.runtimeproxy.hookserver import ANNOTATION_POD_REQUESTS


class FakeRuntime:
    """Backend CRI runtime double: records calls, mints ids."""

    def __init__(self):
        self.calls = []
        self.sandboxes = {}
        self.containers = {}

    def run_pod_sandbox(self, config):
        pod_id = f"sb-{len(self.sandboxes)}"
        self.sandboxes[pod_id] = config
        self.calls.append(("RunPodSandbox", pod_id))
        return pod_id

    def stop_pod_sandbox(self, pod_id):
        self.calls.append(("StopPodSandbox", pod_id))

    def create_container(self, pod_id, config):
        cid = f"c-{len(self.containers)}"
        self.containers[cid] = config
        self.calls.append(("CreateContainer", cid))
        return cid

    def start_container(self, container_id):
        self.calls.append(("StartContainer", container_id))

    def stop_container(self, container_id):
        self.calls.append(("StopContainer", container_id))

    def update_container_resources(self, container_id, resources):
        self.calls.append(("UpdateContainerResources", container_id, resources))


def sandbox_cfg(name="pod-a", labels=None, annotations=None):
    return PodSandboxConfig(
        metadata=PodSandboxMetadata(name=name, uid=f"uid-{name}"),
        labels=labels or {},
        annotations=annotations or {},
        cgroup_parent="kubepods/burstable",
    )


def test_proxy_forwards_and_checkpoints():
    rt = FakeRuntime()
    proxy = CRIProxy(rt)
    pod_id = proxy.run_pod_sandbox(sandbox_cfg())
    assert rt.calls[0] == ("RunPodSandbox", pod_id)
    assert proxy.store.get_pod(pod_id).request.pod_meta.name == "pod-a"
    cid = proxy.create_container(pod_id, ContainerConfig(ContainerMetadata("main")))
    assert proxy.store.get_container(cid).pod_id == pod_id
    proxy.stop_container(cid)
    assert proxy.store.get_container(cid) is None
    proxy.stop_pod_sandbox(pod_id)
    assert proxy.store.get_pod(pod_id) is None


def test_pre_hook_response_merges_into_request():
    rt = FakeRuntime()
    proxy = CRIProxy(rt)

    def handler(hook, request):
        if hook is RuntimeHookType.PRE_RUN_POD_SANDBOX:
            return PodSandboxHookResponse(
                labels={"injected": "yes"}, cgroup_parent="kubepods/besteffort"
            )
        if hook is RuntimeHookType.PRE_CREATE_CONTAINER:
            return ContainerResourceHookResponse(
                container_envs={"HOOKED": "1"},
                container_resources=LinuxContainerResources(cpu_shares=2),
            )
        return None

    proxy.dispatcher.register(
        HookServerRegistration.create("t", tuple(RuntimeHookType), handler)
    )
    pod_id = proxy.run_pod_sandbox(sandbox_cfg())
    fwd = rt.sandboxes[pod_id]
    assert fwd.labels["injected"] == "yes"
    assert fwd.cgroup_parent == "kubepods/besteffort"
    # container request inherits the *effective* cgroup parent
    cid = proxy.create_container(pod_id, ContainerConfig(ContainerMetadata("m")))
    assert rt.containers[cid].envs == {"HOOKED": "1"}
    assert rt.containers[cid].resources.cpu_shares == 2
    assert (
        proxy.store.get_container(cid).request.pod_cgroup_parent
        == "kubepods/besteffort"
    )


def test_failure_policy_fail_vs_ignore():
    def boom(hook, request):
        raise RuntimeError("down")

    rt = FakeRuntime()
    proxy = CRIProxy(rt)
    proxy.dispatcher.register(
        HookServerRegistration.create(
            "flaky", (RuntimeHookType.PRE_RUN_POD_SANDBOX,), boom,
            FailurePolicy.IGNORE,
        )
    )
    pod_id = proxy.run_pod_sandbox(sandbox_cfg())   # proceeds
    assert pod_id in rt.sandboxes
    proxy.dispatcher.register(
        HookServerRegistration.create(
            "strict", (RuntimeHookType.PRE_RUN_POD_SANDBOX,), boom,
            FailurePolicy.FAIL,
        )
    )
    with pytest.raises(HookError):
        proxy.run_pod_sandbox(sandbox_cfg(name="pod-b"))
    assert "sb-1" not in rt.sandboxes  # never reached the backend
    assert parse_failure_policy("Fail") is FailurePolicy.FAIL
    assert parse_failure_policy("whatever").fails_open


def test_update_container_resources_merge():
    rt = FakeRuntime()
    proxy = CRIProxy(rt)

    def handler(hook, request):
        if hook is RuntimeHookType.PRE_UPDATE_CONTAINER_RESOURCES:
            return ContainerResourceHookResponse(
                container_resources=LinuxContainerResources(cpu_quota=50_000)
            )
        return None

    proxy.dispatcher.register(
        HookServerRegistration.create("t", tuple(RuntimeHookType), handler)
    )
    pod_id = proxy.run_pod_sandbox(sandbox_cfg())
    cid = proxy.create_container(pod_id, ContainerConfig(ContainerMetadata("m")))
    res = LinuxContainerResources(cpu_period=100_000, cpu_quota=200_000)
    proxy.update_container_resources(cid, res)
    # hook's non-zero quota overrode kubelet's
    sent = rt.calls[-1][2]
    assert sent.cpu_quota == 50_000 and sent.cpu_period == 100_000


def test_store_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "proxy.json")
    rt = FakeRuntime()
    proxy = CRIProxy(rt, store=Store(checkpoint_path=path))
    pod_id = proxy.run_pod_sandbox(sandbox_cfg(labels={"a": "b"}))
    cid = proxy.create_container(
        pod_id,
        ContainerConfig(
            ContainerMetadata("m"),
            resources=LinuxContainerResources(cpu_shares=512),
        ),
    )
    # simulate proxy restart
    restored = Store(checkpoint_path=path)
    assert restored.get_pod(pod_id).request.labels == {"a": "b"}
    info = restored.get_container(cid)
    assert info.pod_id == pod_id
    assert info.request.container_resources.cpu_shares == 512


def test_koordlet_hookserver_end_to_end(tmp_path):
    """kubelet → proxy → koordlet hook server → cgroup writes + env."""
    executor = rex.ResourceExecutor(cgroup_root=str(tmp_path))
    hooks = KoordletHookServer(executor)
    rt = FakeRuntime()
    proxy = CRIProxy(rt)
    proxy.dispatcher.register(hooks.registration())

    alloc = {"gpu": [{"minor": 0}, {"minor": 1}]}
    cfg = sandbox_cfg(
        name="be-1",
        labels={ext.LABEL_POD_QOS: "BE"},
        annotations={
            ANNOTATION_POD_REQUESTS: json.dumps(
                {ext.RES_BATCH_CPU: 2000, ext.RES_BATCH_MEMORY: 1024}
            ),
            ext.ANNOTATION_DEVICE_ALLOCATED: json.dumps(alloc),
        },
    )
    pod_id = proxy.run_pod_sandbox(cfg)
    # bvt for BE was written before the sandbox started
    bvt = executor.read("kubepods/besteffort/pod-be-1", rex.CPU_BVT)
    assert bvt == "-1"
    shares = executor.read("kubepods/besteffort/pod-be-1", rex.CPU_SHARES)
    assert shares == str(int(2000 * 1024 / 1000))
    # container gets the device env via PreCreateContainer
    cid = proxy.create_container(pod_id, ContainerConfig(ContainerMetadata("m")))
    assert rt.containers[cid].envs["KOORD_VISIBLE_DEVICES"] == "0,1"
    # teardown GC clears the executor cache for the pod group
    proxy.stop_pod_sandbox(pod_id)
    events = executor.auditor.query(group_prefix="kubepods/besteffort/pod-be-1")
    assert any(e.new == "<gc>" for e in events)


def test_gc_group_is_path_boundary_aware(tmp_path):
    """pod-web-1 teardown must not drop pod-web-10's write cache."""
    executor = rex.ResourceExecutor(cgroup_root=str(tmp_path))
    executor.write("kubepods/pod-web-1", rex.CPU_SHARES, "512")
    executor.write("kubepods/pod-web-1/sub", rex.CPU_SHARES, "256")
    executor.write("kubepods/pod-web-10", rex.CPU_SHARES, "1024")
    executor.gc_group("kubepods/pod-web-1", reason="teardown")
    assert ("kubepods/pod-web-1", rex.CPU_SHARES) not in executor._cache
    assert ("kubepods/pod-web-1/sub", rex.CPU_SHARES) not in executor._cache
    assert ("kubepods/pod-web-10", rex.CPU_SHARES) in executor._cache


def test_grpc_hook_channel_end_to_end(tmp_path):
    """The reference topology over the real wire: kubelet → proxy →
    (gRPC, runtimehook.proto) → koordlet hook server → cgroup writes —
    the dispatcher can't tell a RemoteHookHandler from an in-process
    registration, and the merged response rides the wire back."""
    from koordinator_tpu.runtimeproxy.config import (
        FailurePolicy,
        HookServerRegistration,
    )
    from koordinator_tpu.runtimeproxy.grpc_channel import (
        RemoteHookHandler,
        serve_hooks,
    )
    from koordinator_tpu.runtimeproxy.proto import RuntimeHookType

    executor = rex.ResourceExecutor(cgroup_root=str(tmp_path))
    hooks = KoordletHookServer(executor)
    server, port = serve_hooks(hooks.handle)
    remote = RemoteHookHandler(f"127.0.0.1:{port}")
    try:
        rt = FakeRuntime()
        proxy = CRIProxy(rt)
        proxy.dispatcher.register(
            HookServerRegistration(
                name="koordlet-grpc",
                hook_types=frozenset(RuntimeHookType),
                handler=remote,
                failure_policy=FailurePolicy.FAIL,
            )
        )
        alloc = {"gpu": [{"minor": 3}]}
        cfg = sandbox_cfg(
            name="be-grpc",
            labels={ext.LABEL_POD_QOS: "BE"},
            annotations={
                ANNOTATION_POD_REQUESTS: json.dumps(
                    {ext.RES_BATCH_CPU: 1000, ext.RES_BATCH_MEMORY: 512}
                ),
                ext.ANNOTATION_DEVICE_ALLOCATED: json.dumps(alloc),
            },
        )
        pod_id = proxy.run_pod_sandbox(cfg)
        assert executor.read("kubepods/besteffort/pod-be-grpc", rex.CPU_BVT) == "-1"
        cid = proxy.create_container(
            pod_id, ContainerConfig(ContainerMetadata("main"))
        )
        assert rt.containers[cid].envs["KOORD_VISIBLE_DEVICES"] == "3"
        # server down + Fail policy → the CRI call aborts (reference
        # failure policy semantics over a real broken channel)
        server.stop(grace=None)
        import pytest as _pytest

        from koordinator_tpu.runtimeproxy.dispatcher import HookError

        with _pytest.raises(HookError):
            proxy.run_pod_sandbox(sandbox_cfg(name="after-down"))
    finally:
        remote.close()
        server.stop(grace=None)


def test_grpc_hook_channel_ignore_policy_survives_server_crash(tmp_path):
    """Ignore-policy over a REAL broken gRPC channel: the hook server
    dies mid-flight and the CRI calls keep succeeding (fails-open), with
    no hook effects applied — the reference's Ignore semantics
    (config.go:27-31) at the wire level, not just the dispatcher."""
    from koordinator_tpu.runtimeproxy.config import (
        FailurePolicy,
        HookServerRegistration,
    )
    from koordinator_tpu.runtimeproxy.grpc_channel import (
        RemoteHookHandler,
        serve_hooks,
    )
    from koordinator_tpu.runtimeproxy.proto import RuntimeHookType

    executor = rex.ResourceExecutor(cgroup_root=str(tmp_path))
    hooks = KoordletHookServer(executor)
    server, port = serve_hooks(hooks.handle)
    remote = RemoteHookHandler(f"127.0.0.1:{port}")
    try:
        rt = FakeRuntime()
        proxy = CRIProxy(rt)
        proxy.dispatcher.register(
            HookServerRegistration(
                name="koordlet-grpc",
                hook_types=frozenset(RuntimeHookType),
                handler=remote,
                failure_policy=FailurePolicy.IGNORE,
            )
        )
        # live server: hook effects land
        pod_id = proxy.run_pod_sandbox(
            sandbox_cfg(name="be-live", labels={ext.LABEL_POD_QOS: "BE"})
        )
        assert (
            executor.read("kubepods/besteffort/pod-be-live", rex.CPU_BVT)
            == "-1"
        )
        # kill the server: the SAME proxy keeps serving CRI traffic
        server.stop(grace=None)
        pod2 = proxy.run_pod_sandbox(
            sandbox_cfg(name="be-down", labels={ext.LABEL_POD_QOS: "BE"})
        )
        assert pod2 in rt.sandboxes
        # no hook ran, so no bvt write happened for the second pod
        assert (
            executor.read("kubepods/besteffort/pod-be-down", rex.CPU_BVT)
            is None
        )
        cid = proxy.create_container(
            pod2, ContainerConfig(ContainerMetadata("main"))
        )
        assert cid in rt.containers
    finally:
        remote.close()
        server.stop(grace=None)
