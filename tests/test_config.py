"""Versioned componentconfig + DefaultPreBind tests (reference
pkg/scheduler/apis/config/{v1,v1beta3,validation} + defaultprebind)."""

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import ObjectMeta, Pod
from koordinator_tpu.scheduler.config import (
    ConfigError,
    decode_plugin_args,
    decode_profile,
)
from koordinator_tpu.scheduler.prebind import DefaultPreBind


def test_load_aware_defaults_and_merge():
    args = decode_plugin_args("LoadAwareScheduling", {}, "v1beta3")
    assert args.usage_thresholds[ext.RES_CPU] == 65.0
    assert args.estimator_scales[ext.RES_CPU] == 0.85
    assert args.node_metric_expiration_s == 180.0
    # user scales merge key-wise over the defaults (defaults.go:106-115)
    args = decode_plugin_args(
        "LoadAwareScheduling",
        {"estimatedScalingFactors": {ext.RES_CPU: 0.5}},
    )
    assert args.estimator_scales[ext.RES_CPU] == 0.5
    assert args.estimator_scales[ext.RES_MEMORY] == 0.70


def test_load_aware_validation():
    with pytest.raises(ConfigError, match="nodeMetricExpirationSeconds"):
        decode_plugin_args(
            "LoadAwareScheduling", {"nodeMetricExpirationSeconds": -1}
        )
    with pytest.raises(ConfigError, match="usageThresholds"):
        decode_plugin_args(
            "LoadAwareScheduling", {"usageThresholds": {ext.RES_CPU: 120}}
        )
    with pytest.raises(ConfigError, match="resourceWeights"):
        decode_plugin_args(
            "LoadAwareScheduling", {"resourceWeights": {ext.RES_CPU: 0}}
        )
    with pytest.raises(ConfigError, match="usageAggregationType"):
        decode_plugin_args(
            "LoadAwareScheduling", {"usageAggregationType": "p42"}
        )


def test_explicit_empty_map_disables_checks():
    """usageThresholds: {} means 'no thresholds', not 'use defaults'
    (the reference only defaults nil maps)."""
    args = decode_plugin_args("LoadAwareScheduling", {"usageThresholds": {}})
    assert dict(args.usage_thresholds) == {}
    args = decode_plugin_args("LoadAwareScheduling", {"resourceWeights": {}})
    assert dict(args.resource_weights) == {}


def test_malformed_values_raise_config_error():
    with pytest.raises(ConfigError, match="nodeMetricExpirationSeconds"):
        decode_plugin_args(
            "LoadAwareScheduling", {"nodeMetricExpirationSeconds": None}
        )
    with pytest.raises(ConfigError, match="controllerWorkers"):
        decode_plugin_args("Coscheduling", {"controllerWorkers": "two"})
    with pytest.raises(ConfigError, match="usageThresholds"):
        decode_plugin_args(
            "LoadAwareScheduling", {"usageThresholds": {"cpu": "lots"}}
        )


def test_device_share_scoring_validated():
    with pytest.raises(ConfigError, match="scoringStrategy"):
        decode_plugin_args("DeviceShare", {"scoringStrategy": {"type": "Bogus"}})
    assert (
        decode_plugin_args("DeviceShare", {}).scoring_strategy == "LeastAllocated"
    )


def test_unknown_plugin_and_version():
    with pytest.raises(ConfigError, match="unknown plugin"):
        decode_plugin_args("Nope", {})
    with pytest.raises(ConfigError, match="unsupported version"):
        decode_plugin_args("LoadAwareScheduling", {}, "v1alpha1")


def test_numa_and_coscheduling_validation():
    args = decode_plugin_args("NodeNUMAResource", {})
    assert args.default_cpu_bind_policy == "FullPCPUs"
    with pytest.raises(ConfigError, match="defaultCPUBindPolicy"):
        decode_plugin_args(
            "NodeNUMAResource", {"defaultCPUBindPolicy": "Diagonal"}
        )
    with pytest.raises(ConfigError, match="controllerWorkers"):
        decode_plugin_args("Coscheduling", {"controllerWorkers": 0})
    args = decode_plugin_args("ElasticQuota", {})
    assert args.disable_default_quota_preemption is True


def test_low_node_load_cross_field():
    with pytest.raises(ConfigError, match="lowThresholds"):
        decode_plugin_args(
            "LowNodeLoad",
            {
                "highThresholds": {ext.RES_CPU: 50},
                "lowThresholds": {ext.RES_CPU: 60},
            },
        )


def test_decode_profile():
    profile = {
        "pluginConfig": [
            {"name": "LoadAwareScheduling", "args": {}},
            {"name": "Reservation", "args": {"enablePreemption": True}},
        ]
    }
    out = decode_profile(profile)
    assert out["Reservation"].enable_preemption is True
    assert out["LoadAwareScheduling"].aggregated_usage_type == "p95"


def test_default_prebind_single_patch():
    pb = DefaultPreBind()
    pod = Pod(meta=ObjectMeta(name="p"))
    pb.stage_annotations(pod, {"a": "1"})
    pb.stage_annotations(pod, {"b": "2"})
    pb.stage_labels(pod, {"l": "x"})
    assert pod.meta.annotations == {}          # staged, not applied
    assert pb.apply(pod) is True
    assert pod.meta.annotations == {"a": "1", "b": "2"}
    assert pod.meta.labels["l"] == "x"
    assert pb.apply(pod) is False              # one patch only
    # Permit rejection: staged mutations evaporate
    pod2 = Pod(meta=ObjectMeta(name="q"))
    pb.stage_annotations(pod2, {"stale": "claim"})
    pb.discard(pod2.meta.uid)
    assert pb.apply(pod2) is False
    assert pod2.meta.annotations == {}


def test_filter_expired_node_metrics_version_divergence():
    """v1beta3's hand-written conversion FORCES filterExpiredNodeMetrics
    true regardless of the configured value (conversion_plugin.go:25-33);
    v1 honors the field (default true when absent) — the same fixture
    must decode DIFFERENTLY per version."""
    from koordinator_tpu.scheduler.config import decode_plugin_args

    fixture = {"filterExpiredNodeMetrics": False}
    v1 = decode_plugin_args("LoadAwareScheduling", fixture, "v1")
    beta = decode_plugin_args("LoadAwareScheduling", fixture, "v1beta3")
    assert v1.filter_expired_node_metrics is False
    assert beta.filter_expired_node_metrics is True
    # absent key: both default true; the strict schedule-when-expired
    # default is false in both (defaults.go:91-95)
    for ver in ("v1", "v1beta3"):
        args = decode_plugin_args("LoadAwareScheduling", {}, ver)
        assert args.filter_expired_node_metrics is True
        assert args.enable_schedule_when_node_metrics_expired is False


def test_strict_expired_metric_filter_rejects_stale_nodes():
    """With the componentconfig defaults (filter on, schedule-when-expired
    off), a node whose NodeMetric went STALE is unschedulable while a
    never-reported node stays admitted (load_aware.go:143-149 +
    the nil-NodeMetric path)."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeMetric,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        ResourceMetric,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.config import decode_plugin_args

    args = decode_plugin_args("LoadAwareScheduling", {}, "v1")
    snap = ClusterSnapshot()
    for name in ("stale", "fresh", "silent"):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 8000, ext.RES_MEMORY: 16384}
                ),
            )
        )
    mk_metric = lambda n, t: NodeMetric(
        meta=ObjectMeta(name=n),
        node_usage=ResourceMetric(usage={ext.RES_CPU: 100.0}),
        update_time=t,
    )
    snap.set_node_metric(mk_metric("stale", 100.0), now=100.0 + 10_000)
    snap.set_node_metric(mk_metric("fresh", 100.0), now=101.0)
    sched = BatchScheduler(snap, args, batch_bucket=64)
    sched.extender.monitor.stop_background()

    def where(pod_name, node_name=None):
        out = sched.schedule(
            [
                Pod(
                    meta=ObjectMeta(name=pod_name),
                    spec=PodSpec(
                        requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1024},
                        node_name=node_name,
                    ),
                )
            ]
        )
        return out.bound[0][1] if out.bound else None

    assert where("p-stale", "stale") is None       # stale metric: rejected
    assert where("p-fresh", "fresh") == "fresh"    # fresh metric: fine
    assert where("p-silent", "silent") == "silent"  # never reported: fine
