"""Chaos soak: the full fault composition over the longrun loop.

The fast subset (tier-1) runs a shortened soak covering every fault
domain — RPC drops + generation-gap resync, watch disconnects, solver
dispatch failure, NaN quarantine, deadline deferral, one mid-commit
crash — and the determinism contract (same seed ⇒ same fault trace).
The ≥200-cycle acceptance soak is marked ``slow``.

Decision observatory (decision-observatory PR): every soak sweeps its
decision ledgers in-run (gap-free per-controller sequences across the
kill-restart's adopted tail, recompute-replay cleanliness) and stamps
the canonical ``decision_trace``. The same-seed pairs here run their
SECOND leg with an always-diverging shadow attached — same-seed ⇒
bit-identical decision traces AND a shadow can never perturb the
acting schedule, proved in one comparison."""

import pytest

from koordinator_tpu.sim.longrun import run_chaos_soak

pytestmark = pytest.mark.chaos


def _check(stats):
    # the invariants proper (duplicate placement, quota bound, resident
    # bit-exactness, accounting drift) are asserted INSIDE the soak every
    # cycle; here we check the outcome shape
    assert stats["placed"] == stats["arrived"] > 0
    assert stats["health_ok"], "every subsystem must recover to ok"
    assert stats["fault_trace"], "the schedule must have injected faults"


@pytest.mark.chaos
def test_chaos_soak_fast_subset():
    stats = run_chaos_soak(cycles=40, seed=7, n_nodes=12, max_arrivals=6)
    _check(stats)
    # the schedule must actually have exercised the channel + crash legs
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert "channel.sync.drop" in points
    assert "commit.crash" in points
    assert stats["metrics"]["commit_rollbacks_total"] == 1.0
    assert stats["sync_lost"] > 0 and stats["resyncs"] > 0
    # adaptive depth (open the last gates PR): the controller must
    # visibly FLEX under the existing fault schedule — start at the
    # configured max (2), degrade to 1 inside the fault window (the
    # completion churn + chaos discards), and return to 2 in the quiet
    # steady tail — deterministically (no rng-stream draws feed it)
    trace = stats["depth_trace"]
    assert trace and trace[0] == 2, trace
    assert 1 in trace, "depth never degraded under the fault schedule"
    assert trace[-1] == 2, "depth never recovered in the quiet tail"
    first_one = trace.index(1)
    assert all(d == 2 for d in trace[:first_one]), trace
    # decision observatory (decision-observatory PR): the gap-free and
    # recompute-replay sweeps ran INSIDE the soak; here the recorded
    # ledger must also replay clean through the OFFLINE tool — exit 0
    # means every recorded action reproduced bit-exactly from its
    # snapshot (the counterfactual-replay entry point works on real
    # soak output, not just synthetic ledgers)
    assert stats["decisions_total"] == len(stats["decision_trace"]) > 0
    import json as _json
    import os
    import tempfile

    from tools.decision_replay import main as replay_main

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "decisions.json")
        with open(path, "w", encoding="utf-8") as f:
            _json.dump({"records": stats["decision_trace"]}, f)
        assert replay_main(["--ledger", path]) == 0


@pytest.mark.chaos
def test_chaos_soak_same_seed_same_fault_trace():
    a = run_chaos_soak(cycles=25, seed=11, n_nodes=10, max_arrivals=5)
    # the second leg runs with an ALWAYS-diverging shadow consulting on
    # every depth record: same seed must still yield a bit-identical
    # schedule and decision trace (a shadow can never act), while the
    # shadow's own divergences prove it really was consulted
    b = run_chaos_soak(
        cycles=25, seed=11, n_nodes=10, max_arrivals=5, shadow=True
    )
    assert a["fault_trace"] == b["fault_trace"]
    assert a["faults"] == b["faults"]
    # the adaptive-depth trace is part of the deterministic contract
    assert a["depth_trace"] == b["depth_trace"]
    # decision observatory: same seed ⇒ bit-identical decision traces
    # (seq, cseq, tick, full input snapshots, actions, states)
    assert a["decision_trace"] == b["decision_trace"]
    assert a["shadow_divergences"] == 0
    assert b["shadow_divergences"] == b["decisions_total"] > 0
    c = run_chaos_soak(cycles=25, seed=12, n_nodes=10, max_arrivals=5)
    assert c["fault_trace"] != a["fault_trace"]


@pytest.mark.chaos
def test_chaos_soak_ha_failover_arm():
    """HA failure domain (failover PR): mid-commit crash-restart + leader
    flaps over the full fault composition. Zero duplicate placements,
    zero lost acknowledged bindings and per-takeover bit-exact
    resident-state reconvergence are asserted INSIDE the soak; here we
    pin the arm's shape: the crash really ran, the takeover gap really
    existed, journal-acknowledged bindings really were recovered rather
    than re-placed, and a deposed leader's commit really was fenced."""
    stats = run_chaos_soak(
        cycles=30, seed=7, n_nodes=12, max_arrivals=6, ha=True
    )
    _check(stats)
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert "scheduler.crash_restart" in points
    assert "leader.lost" in points
    assert "commit.crash" in points
    # state-integrity PR: the corruption fault domain fired and was
    # CONTAINED — a mid-stream corrupt record was quarantined with ZERO
    # acked binds lost (the zero-lost-ack sweep inside the soak runs
    # THROUGH the corruption), the injected write hole was counted, the
    # post-crash recovery rejected its checkpoint image (digest
    # mismatch) and fell back to full replay bit-exactly, and the
    # resident bit flip was detected + healed by the scrubber (end-state
    # bit-exactness is asserted inside the soak after the heal)
    assert {
        "journal.corrupt_record", "journal.seq_gap",
        "checkpoint.digest_mismatch", "resident.bit_flip",
    } <= points
    assert stats["journal_corrupt_quarantined"] == 1
    assert stats["journal_seq_gaps"] == 1
    assert stats["checkpoint_fallbacks"] >= 1
    assert stats["scrub_divergence"].get("nodes", 0) >= 1
    assert stats["crash_restarts"] == 1
    # decision observatory (decision-observatory PR): the fresh
    # incarnation's ledger ADOPTED the dead writer's decision tail from
    # the shared store — the trace shows both writers, and the in-soak
    # sweep asserted the depth controller's sequence is gap-free
    # THROUGH the kill
    assert len(
        {r["incarnation"] for r in stats["decision_trace"]}
    ) >= 2, "decision trace does not span the crash-restart"
    # journal_fsck round-trips the soak's POST-CORRUPTION journal: the
    # dump (quarantined records included) repairs to a clean file whose
    # replay reconstructs exactly the soak's acknowledged live set
    import json as _json
    import os
    import tempfile

    from koordinator_tpu.core.journal import BindJournal, FileJournalStore
    from tools.journal_fsck import check_file

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "soak.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            for rec in stats["journal_dump"]:
                f.write(_json.dumps(rec, separators=(",", ":")) + "\n")
        report = check_file(path, repair=True)
        assert report["repaired"] and not report["unrepairable"]
        assert report["corrupt"] == stats["journal_corrupt_quarantined"]
        clean = check_file(path)
        assert clean["ok"], clean
        rep = BindJournal(FileJournalStore(path)).replay()
        assert sorted(rep.live) == stats["journal_live"]
    assert stats["takeovers"] >= 2          # initial grant + post-crash
    assert stats["cycles_without_leader"] > 0   # the lease gap is real
    assert stats["recovered_bindings"] > 0  # journal acks survived
    assert stats["fenced_commits_total"] >= 1.0
    assert stats["journal_open_intents"] == 0
    assert stats["leader_epoch_final"] >= 2


@pytest.mark.chaos
def test_chaos_soak_ha_same_seed_same_trace():
    a = run_chaos_soak(
        cycles=20, seed=13, n_nodes=10, max_arrivals=5, ha=True
    )
    # shadow-attached second leg (decision-observatory PR): bit-exact
    # through the crash-restart + takeover too
    b = run_chaos_soak(
        cycles=20, seed=13, n_nodes=10, max_arrivals=5, ha=True,
        shadow=True,
    )
    assert a["fault_trace"] == b["fault_trace"]
    assert a["takeovers"] == b["takeovers"]
    assert a["placed"] == b["placed"]
    # decision observatory: the decision trace — including the dead
    # incarnation's adopted tail — is part of the deterministic contract
    assert a["decision_trace"] == b["decision_trace"]
    assert a["shadow_divergences"] == 0 and b["shadow_divergences"] > 0
    # the corruption arms are part of the deterministic contract too
    for key in (
        "journal_corrupt_quarantined", "journal_seq_gaps",
        "checkpoint_fallbacks", "scrub_divergence",
    ):
        assert a[key] == b[key], key


@pytest.mark.chaos
def test_chaos_soak_multi_shard_arm():
    """Multi-shard arm (PR 6): 3 concurrently-live incarnations over 3
    shards with per-shard fencing — shard handoffs and one kill-restart
    mid-schedule. Zero-duplicate / zero-lost-acknowledged / per-shard
    bit-exact asserts run INSIDE the soak; here we pin the arm's shape:
    the kill really happened, journal-acknowledged bindings of the dead
    incarnation were recovered per shard rather than re-placed, shards
    really went ownerless during the lease gap, ownership really moved
    (handoffs + takeovers beyond the initial grants), and deletions on
    ownerless shards were journaled fence-exempt by the observer."""
    stats = run_chaos_soak(
        cycles=18, seed=7, n_nodes=18, max_arrivals=6,
        shards=3, incarnations=3,
    )
    assert stats["placed"] == stats["arrived"] > 0
    assert stats["health_ok"]
    assert stats["crash_restarts"] == 1
    assert stats["recovered_bindings"] > 0
    assert stats["shard_cycles_without_owner"] > 0
    assert stats["takeovers"] > 3  # initial grants + post-kill takeovers
    assert stats["handoffs"] >= 1
    assert stats["driver_forgets"] >= 1
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert "commit.crash" in points
    # per-shard epochs all advanced past the initial grant somewhere
    assert max(stats["shard_epochs_final"].values()) >= 2
    # elastic topology (elastic-topology PR): one SPLIT and one MERGE
    # executed under live traffic mid-schedule, each preceded by a
    # crash-armed attempt that rolled back to the parent generation
    # (the zero-dup / zero-lost-ack / bit-exact / gap-free-timeline
    # invariants across the transitions are asserted INSIDE the soak)
    assert stats["splits"] == 1 and stats["merges"] == 1
    assert stats["topology_rollbacks"] == 2
    assert stats["generation_final"] == 2
    assert "shard.split_crash" in points
    assert "shard.merge_crash" in points
    # the cell count is back to the deploy-time base after the merge,
    # but the merged cell carries a FRESH shard id (ids never recycle)
    assert len(stats["active_shards_final"]) == 3
    assert stats["active_shards_final"] != [0, 1, 2]
    # cross-shard gang arm (overload-control PR satellite): one gang
    # COMMITTED through the placed-once ledger all-or-nothing, one
    # doomed gang ABORTED with its members returned claimable and
    # re-placed exactly once as plain pods (the abort/ledger asserts
    # run INSIDE the soak at finish time)
    assert stats["xs_gangs"]["committed"] >= 1
    assert stats["xs_gangs"]["aborted"] >= 1
    assert stats["xs_gangs"]["abort_resubmitted"] >= 3
    # state-integrity arms, per shard (same contract as the HA arm:
    # quarantined-not-truncated, write hole counted, checkpoint-digest
    # fallback on the post-kill takeover, bit flip healed in rotation)
    assert {
        "journal.corrupt_record", "journal.seq_gap",
        "checkpoint.digest_mismatch", "resident.bit_flip",
    } <= points
    assert stats["journal_corrupt_quarantined"] >= 1
    assert stats["journal_seq_gaps"] >= 1
    assert stats["checkpoint_fallbacks"] >= 1
    assert stats["scrub_divergence"].get("nodes", 0) >= 1
    # decision observatory (decision-observatory PR): at least one
    # shard's decision trace spans both the dead owner and its takeover
    # (the in-soak sweep asserted every shard's per-controller sequence
    # is gap-free THROUGH the ownership boundary)
    assert any(
        len({r["incarnation"] for r in recs}) >= 2
        for recs in stats["decision_trace"].values()
    ), "no shard's decision trace spans the kill-restart takeover"


@pytest.mark.chaos
def test_chaos_soak_multi_shard_same_seed_same_trace():
    kw = dict(
        cycles=14, seed=11, n_nodes=18, max_arrivals=5,
        shards=3, incarnations=3,
    )
    a = run_chaos_soak(**kw)
    # shadow-attached second leg (decision-observatory PR): bit-exact
    # across shard handoffs, the kill-restart and the split/merge
    b = run_chaos_soak(**kw, shadow=True)
    assert a["fault_trace"] == b["fault_trace"]
    assert a["placed"] == b["placed"]
    assert a["takeovers"] == b["takeovers"]
    assert a["recovered_bindings"] == b["recovered_bindings"]
    # decision observatory: per-shard decision traces bit-identical
    assert a["decision_trace"] == b["decision_trace"]
    assert a["shadow_divergences"] == 0 and b["shadow_divergences"] > 0
    c = run_chaos_soak(**{**kw, "seed": 12})
    assert c["fault_trace"] != a["fault_trace"]


@pytest.mark.slow
def test_chaos_soak_multi_shard_full_acceptance():
    """Acceptance (PR 6): 3+ incarnations, shard handoff and a
    kill-restart mid-schedule over 200+ cycles, all per-shard invariants
    held (asserted inside the soak)."""
    stats = run_chaos_soak(
        cycles=200, seed=0, n_nodes=36, max_arrivals=12,
        shards=4, incarnations=3,
    )
    assert stats["placed"] == stats["arrived"] > 0
    assert stats["crash_restarts"] == 1
    assert stats["recovered_bindings"] > 0
    assert stats["handoffs"] >= 1
    assert stats["health_ok"]


@pytest.mark.slow
def test_chaos_soak_ha_full_acceptance():
    """≥200-cycle acceptance soak for the HA arm: kill-restart + leader
    flaps on top of every prior fault domain, all invariants held."""
    stats = run_chaos_soak(
        cycles=200, seed=0, n_nodes=24, max_arrivals=12, ha=True
    )
    _check(stats)
    assert stats["crash_restarts"] == 1
    assert stats["recovered_bindings"] >= 0
    assert stats["takeovers"] >= 2
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert "scheduler.crash_restart" in points and "leader.lost" in points


@pytest.mark.slow
def test_chaos_soak_full_acceptance():
    """≥200 longrun cycles under the seeded random fault schedule: zero
    duplicate placements, zero quota violations, resident state bit-exact
    vs full re-lower, 100% of pods eventually placed (all asserted inside
    the soak)."""
    stats = run_chaos_soak(cycles=200, seed=0, n_nodes=24, max_arrivals=12)
    _check(stats)
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert {"channel.sync.drop", "commit.crash", "solver.dispatch"} <= points
    assert stats["metrics"]["commit_rollbacks_total"] == 1.0
    assert stats["resyncs"] > 0


@pytest.mark.chaos
def test_overload_storm_soak_fast_arm():
    """Overload-control acceptance arm (brownout PR): a 10x QoS-mixed
    arrival storm + channel brownout (breaker) + one mid-storm shard
    split. Zero-dup, PROD/MID-never-shed, gap-free shed-terminal
    timelines, ladder monotonic-with-hysteresis-and-recovery, breaker
    trip/fast-fail/reclose and mirror convergence are asserted INSIDE
    the soak; here we pin the arm's shape."""
    from koordinator_tpu.sim.longrun import run_overload_storm_soak

    stats = run_overload_storm_soak(cycles=40, seed=0)
    assert stats["placed"] + stats["shed_terminal"] == stats["arrived"] > 0
    assert stats["shed_terminal"] > 0 and stats["tickets_redeemed"] > 0
    assert set(stats["shed_counts"]) <= {"BATCH", "FREE"}
    assert stats["splits"] == 1
    assert stats["brownout"]["peak"] >= 3
    assert stats["brownout"]["final"] == 0
    assert stats["breaker"]["stats"]["trips"] >= 1
    assert stats["breaker"]["state"] == "closed"
    assert stats["breaker_fast_fails"] >= 1
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert "channel.breaker_storm" in points
    # decision observatory (decision-observatory PR): the whole storm
    # story is on the ledgers — every ladder move, admission verdict
    # and breaker transition on the fleet ledger, every depth choice on
    # the per-shard stores (swept gap-free + recompute-clean in-soak)
    fleet = {r["controller"] for r in stats["decision_trace"]["fleet"]}
    assert {"brownout", "admission", "breaker"} <= fleet
    assert any(
        r["controller"] == "depth"
        for recs in stats["decision_trace"]["shards"].values()
        for r in recs
    )


@pytest.mark.chaos
def test_overload_storm_soak_same_seed_same_trace():
    from koordinator_tpu.sim.longrun import run_overload_storm_soak

    kw = dict(cycles=32, seed=11, n_nodes=16, base_arrivals=3)
    a = run_overload_storm_soak(**kw)
    # shadow-attached second leg (decision-observatory PR): an
    # always-diverging shadow consults on every ladder move, admission
    # verdict, breaker transition and depth choice — the storm's
    # schedule and decision traces must stay bit-identical
    b = run_overload_storm_soak(**kw, shadow=True)
    for key in (
        "fault_trace", "level_trace", "shed_counts", "placed",
        "arrived", "shed_terminal", "tickets_redeemed",
        "decision_trace", "decisions_total",
    ):
        assert a[key] == b[key], key
    assert a["shadow_divergences"] == 0 and b["shadow_divergences"] > 0
    c = run_overload_storm_soak(**{**kw, "seed": 12})
    assert (
        c["fault_trace"] != a["fault_trace"]
        or c["arrived"] != a["arrived"]
    )
