"""Chaos soak: the full fault composition over the longrun loop.

The fast subset (tier-1) runs a shortened soak covering every fault
domain — RPC drops + generation-gap resync, watch disconnects, solver
dispatch failure, NaN quarantine, deadline deferral, one mid-commit
crash — and the determinism contract (same seed ⇒ same fault trace).
The ≥200-cycle acceptance soak is marked ``slow``."""

import pytest

from koordinator_tpu.sim.longrun import run_chaos_soak

pytestmark = pytest.mark.chaos


def _check(stats):
    # the invariants proper (duplicate placement, quota bound, resident
    # bit-exactness, accounting drift) are asserted INSIDE the soak every
    # cycle; here we check the outcome shape
    assert stats["placed"] == stats["arrived"] > 0
    assert stats["health_ok"], "every subsystem must recover to ok"
    assert stats["fault_trace"], "the schedule must have injected faults"


@pytest.mark.chaos
def test_chaos_soak_fast_subset():
    stats = run_chaos_soak(cycles=40, seed=7, n_nodes=12, max_arrivals=6)
    _check(stats)
    # the schedule must actually have exercised the channel + crash legs
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert "channel.sync.drop" in points
    assert "commit.crash" in points
    assert stats["metrics"]["commit_rollbacks_total"] == 1.0
    assert stats["sync_lost"] > 0 and stats["resyncs"] > 0


@pytest.mark.chaos
def test_chaos_soak_same_seed_same_fault_trace():
    a = run_chaos_soak(cycles=25, seed=11, n_nodes=10, max_arrivals=5)
    b = run_chaos_soak(cycles=25, seed=11, n_nodes=10, max_arrivals=5)
    assert a["fault_trace"] == b["fault_trace"]
    assert a["faults"] == b["faults"]
    c = run_chaos_soak(cycles=25, seed=12, n_nodes=10, max_arrivals=5)
    assert c["fault_trace"] != a["fault_trace"]


@pytest.mark.slow
def test_chaos_soak_full_acceptance():
    """≥200 longrun cycles under the seeded random fault schedule: zero
    duplicate placements, zero quota violations, resident state bit-exact
    vs full re-lower, 100% of pods eventually placed (all asserted inside
    the soak)."""
    stats = run_chaos_soak(cycles=200, seed=0, n_nodes=24, max_arrivals=12)
    _check(stats)
    points = {p for _s, p, _k in stats["fault_trace"]}
    assert {"channel.sync.drop", "commit.crash", "solver.dispatch"} <= points
    assert stats["metrics"]["commit_rollbacks_total"] == 1.0
    assert stats["resyncs"] > 0
