"""Reservation nominator parity tests.

Mirrors the reference nominator's selection behavior
(``pkg/scheduler/plugins/reservation/nominator_test.go`` TestNominateReservation
and ``scoring.go`` scoreReservation): an order-labeled reservation wins
outright; otherwise the MostAllocated fit score picks the tightest-fitting
reservation, with prior allocations counted toward the fill.
"""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Reservation,
    ReservationOwner,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.reservation import (
    ReservationManager,
    ReservationPhase,
    _score_reservation,
)


def make_rm(n_nodes=1):
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
                ),
            )
        )
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    return ReservationManager(sched)


def available(rm, name, requests, node="n0", labels=None, allocated=None):
    r = Reservation(
        meta=ObjectMeta(name=name, labels=labels or {}),
        requests=requests,
        owners=[ReservationOwner(label_selector={"app": "t"})],
    )
    r.phase = ReservationPhase.AVAILABLE
    r.node_name = node
    if allocated:
        r.allocated = dict(allocated)
    rm.add(r)
    return r


def owner_pod(cpu=2000, mem=4096):
    return Pod(
        meta=ObjectMeta(name="p", labels={"app": "t"}),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}),
    )


def test_order_label_wins_over_score():
    """'preferred reservation' case: reservation-order label beats any fit
    score; among ordered ones the smallest order wins."""
    rm = make_rm()
    available(rm, "normal-exact-fit", {ext.RES_CPU: 2000, ext.RES_MEMORY: 4096})
    preferred = available(
        rm,
        "preferred-reservation",
        {ext.RES_CPU: 64000, ext.RES_MEMORY: 262144},
        labels={ext.LABEL_RESERVATION_ORDER: "100"},
    )
    available(
        rm,
        "later-order",
        {ext.RES_CPU: 64000, ext.RES_MEMORY: 262144},
        labels={ext.LABEL_RESERVATION_ORDER: "200"},
    )
    assert rm.match(owner_pod()) is preferred


def test_order_label_zero_or_garbage_is_unordered():
    rm = make_rm()
    exact = available(
        rm, "exact", {ext.RES_CPU: 2000, ext.RES_MEMORY: 4096},
        labels={ext.LABEL_RESERVATION_ORDER: "0"},
    )
    available(
        rm, "big", {ext.RES_CPU: 64000, ext.RES_MEMORY: 262144},
        labels={ext.LABEL_RESERVATION_ORDER: "nan"},
    )
    # both degrade to score-based selection; the exact fit wins
    assert rm.match(owner_pod()) is exact


def test_matched_reservations_tightest_fit_wins():
    """'matched reservations' case: a 2C4G pod picks reservation2C4G (score
    100) over reservation4C8G (score 50)."""
    rm = make_rm()
    available(rm, "reservation4C8G", {ext.RES_CPU: 4000, ext.RES_MEMORY: 8192})
    r2 = available(
        rm, "reservation2C4G", {ext.RES_CPU: 2000, ext.RES_MEMORY: 4096}
    )
    assert rm.match(owner_pod()) is r2


def test_allocated_reservation_falls_back_to_free_one():
    """'allocated reservation' case: with reservation2C4G fully consumed,
    the pod nominates reservation4C8G."""
    rm = make_rm()
    r4 = available(rm, "reservation4C8G", {ext.RES_CPU: 4000, ext.RES_MEMORY: 8192})
    available(
        rm,
        "reservation2C4G",
        {ext.RES_CPU: 2000, ext.RES_MEMORY: 4096},
        allocated={ext.RES_CPU: 2000, ext.RES_MEMORY: 4096},
    )
    assert rm.match(owner_pod()) is r4


def test_partial_allocation_raises_fill_score():
    """scoreReservation counts prior allocations: a half-filled big
    reservation outscores an empty same-size one (MostAllocated packing)."""
    rm = make_rm()
    available(rm, "empty-8C", {ext.RES_CPU: 8000, ext.RES_MEMORY: 16384})
    half = available(
        rm,
        "half-8C",
        {ext.RES_CPU: 8000, ext.RES_MEMORY: 16384},
        allocated={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
    )
    assert rm.match(owner_pod()) is half


def test_score_reservation_reference_values():
    pod = owner_pod()  # 2C4G
    r4 = Reservation(
        meta=ObjectMeta(name="r4"),
        requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
    )
    r2 = Reservation(
        meta=ObjectMeta(name="r2"),
        requests={ext.RES_CPU: 2000, ext.RES_MEMORY: 4096},
    )
    assert _score_reservation(pod, r4) == 50.0
    assert _score_reservation(pod, r2) == 100.0
    # overflow dims contribute zero
    r_small = Reservation(
        meta=ObjectMeta(name="rs"),
        requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 8192},
    )
    assert _score_reservation(pod, r_small) == 25.0


def test_nomination_commits_through_fast_path():
    """End to end: the nominated (tightest) reservation takes the owner,
    leaving the big reservation untouched."""
    rm = make_rm()
    available(rm, "big", {ext.RES_CPU: 8000, ext.RES_MEMORY: 16384})
    small = available(rm, "small", {ext.RES_CPU: 2000, ext.RES_MEMORY: 4096})
    # charge their ghost holds so the fast-path accounting is real
    for r in rm.list():
        rm.scheduler.snapshot.assume_pod(
            Pod(meta=ObjectMeta(name=f"reserve-{r.meta.name}",
                                uid=f"reservation-ghost/{r.meta.name}"),
                spec=PodSpec(requests=dict(r.requests))),
            "n0",
        )
    out = rm.scheduler.schedule([owner_pod()])
    assert len(out.bound) == 1
    assert small.current_owners and not rm.get("big").current_owners


def test_exact_match_reservation_spec():
    """reservation.go:188-241: the exact-match annotation restricts
    nomination to reservations whose allocatable EXACTLY equals the
    pod's request on the listed names — including the reference's
    both-absent early-return quirk."""
    from koordinator_tpu.api import extension as ext

    em = ext.exact_match_reservation
    assert em({"cpu": 4.0}, {"cpu": 4.0}, ["cpu"])
    assert not em({"cpu": 4.0}, {"cpu": 8.0}, ["cpu"])
    assert not em({"cpu": 4.0}, {}, ["cpu"])       # one side only
    assert not em({}, {"cpu": 4.0}, ["cpu"])
    assert em({}, {}, ["cpu"])                     # absent on BOTH: matched
    assert em({"cpu": 4.0}, {"cpu": 8.0}, [])      # empty spec: no-op
    # the quirk: the FIRST both-absent name short-circuits the whole spec
    assert em({"cpu": 4.0}, {"cpu": 8.0}, ["gpu", "cpu"])

    # end to end through match(): only the exactly-sized reservation wins
    import jax

    jax.config.update("jax_platforms", "cpu")
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager

    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)
    for name, cpu in (("small", 4000), ("exact", 8000)):
        rm.add(
            Reservation(
                meta=ObjectMeta(name=name),
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 8192},
                owners=[ReservationOwner(label_selector={"app": "em"})],
                allocate_once=False,
            )
        )
    assert rm.schedule_pending() == 2
    pod = Pod(
        meta=ObjectMeta(
            name="p",
            labels={"app": "em"},
            annotations={
                ext.ANNOTATION_EXACT_MATCH_RESERVATION_SPEC: (
                    '{"resourceNames": ["%s"]}' % ext.RES_CPU
                )
            },
        ),
        spec=PodSpec(
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 2048},
            priority=9500,
        ),
    )
    got = rm.match(pod)
    assert got is not None and got.meta.name == "exact"


def test_reservation_restricted_options_narrow_binding_dims():
    """reservation.go:89-96: restricted-options limits WHICH reserved
    dims the Restricted policy binds — an over-remaining memory request
    is allowed to spill when only cpu is listed as restricted."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
        Reservation,
        ReservationOwner,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager

    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 65536}
            ),
        )
    )
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    rm = ReservationManager(sched)

    def reservation(name, options=None):
        meta = ObjectMeta(name=name)
        if options:
            meta.annotations[
                ext.ANNOTATION_RESERVATION_RESTRICTED_OPTIONS
            ] = options
        return Reservation(
            meta=meta,
            requests={ext.RES_CPU: 8000, ext.RES_MEMORY: 4096},
            owners=[ReservationOwner(label_selector={"app": name})],
            allocate_once=False,
            allocate_policy="Restricted",
        )

    rm.add(reservation("strict"))
    rm.add(
        reservation(
            "cpu-only", options='{"resources": ["%s"]}' % ext.RES_CPU
        )
    )
    assert rm.schedule_pending() == 2

    def owner(app):
        return Pod(
            meta=ObjectMeta(name=f"{app}-pod", labels={"app": app}),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 8192},
                priority=9500,
            ),
        )

    # memory 8192 > reserved 4096: fully-Restricted reservation refuses
    assert rm.match(owner("strict")) is None
    # cpu-only restriction: memory may spill to the node — matches
    got = rm.match(owner("cpu-only"))
    assert got is not None and got.meta.name == "cpu-only"


def test_vectorized_match_equivalent_to_scalar_randomized():
    """State-integrity PR satellite: the numpy-over-the-candidate-axis
    nomination must be DECISION-IDENTICAL to the reference per-candidate
    loop (kept as ``_match_scalar``) across randomized populations —
    mixed policies, partial allocations, order labels, owner selectors,
    per-node spill headroom and affinity annotations."""
    import json
    import random

    from koordinator_tpu.api.types import (
        RESERVATION_ALLOCATE_POLICY_ALIGNED,
        RESERVATION_ALLOCATE_POLICY_RESTRICTED,
    )

    rng = random.Random(20260804)
    for trial in range(8):
        rm = make_rm(n_nodes=6)
        snap = rm.scheduler.snapshot
        for c in range(rng.randint(4, 40)):
            labels = {}
            if rng.random() < 0.3:
                labels[ext.LABEL_RESERVATION_ORDER] = str(
                    rng.choice([0, 1, 5, 5, 100])
                )
            r = available(
                rm,
                f"r{trial}-{c:03d}",
                {
                    ext.RES_CPU: rng.choice([1000, 2000, 4000, 64000]),
                    ext.RES_MEMORY: rng.choice([2048, 4096, 262144]),
                },
                node=f"n{rng.randrange(6)}",
                labels=labels,
                allocated=(
                    {ext.RES_CPU: rng.choice([500, 1000, 2000])}
                    if rng.random() < 0.4
                    else None
                ),
            )
            if rng.random() < 0.3:
                r.allocate_policy = RESERVATION_ALLOCATE_POLICY_RESTRICTED
            elif rng.random() < 0.3:
                r.allocate_policy = RESERVATION_ALLOCATE_POLICY_ALIGNED
            if rng.random() < 0.2:
                r.allocate_once = False
            if rng.random() < 0.25:
                # second owner selector shape (sig de-dup must not merge)
                r.owners.append(
                    ReservationOwner(label_selector={"team": "x"})
                )
        # a couple of nodes near-full so spill-fit filtering matters
        for i in (1, 3):
            snap.nodes.requested[i] = snap.nodes.allocatable[i] - 10.0
        snap.touch_all()
        for p in range(12):
            pod = owner_pod(
                cpu=rng.choice([500, 2000, 8000, 70000]),
                mem=rng.choice([1024, 4096, 300000]),
            )
            if rng.random() < 0.2:
                pod.meta.labels["team"] = "x"
            if rng.random() < 0.15:
                pod.meta.annotations[
                    ext.ANNOTATION_RESERVATION_AFFINITY
                ] = json.dumps({"name": f"r{trial}-000"})
            want = rm._match_scalar(pod)
            got = rm.match(pod)
            assert got is want, (
                f"trial {trial} pod {p}: vector nominated "
                f"{got.meta.name if got else None}, scalar "
                f"{want.meta.name if want else None}"
            )
