"""On-device NUMA zone selection (VERDICT r4 #4).

The solver carries the exact zone table through its commit rounds and
hands each winner's strategy-ordered zone pick to the host allocator
(``zones_hint``), which fit-verifies and otherwise falls back to its own
scan — so hint and host must agree pick-for-pick on a clean run.
Reference: ``pkg/scheduler/plugins/nodenumaresource`` zone selection +
``cpu_accumulator.go:345-800``.
"""

import json

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import CPUTopology
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.plugins.nodenumaresource import (
    NUMAManager,
    NUMAPolicy,
)


def _cluster(n_nodes=8, policy=NUMAPolicy.SINGLE_NUMA_NODE, labels=None):
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    topo = CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=8)
    for i in range(n_nodes):
        name = f"n{i}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name, labels=dict(labels or {})),
                status=NodeStatus(
                    # uniform(2 sockets, 8 cores/numa) is SMT: 16 CPUs
                    # (16000m) per zone, 32000m per node
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 65536}
                ),
            )
        )
        numa.register_node(name, topo, policy, memory_per_zone_mib=32768)
    return snap, numa


def _lsr(name, cpu=4000, node_name=None):
    return Pod(
        meta=ObjectMeta(name=name, labels={ext.LABEL_POD_QOS: "LSR"}),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 4096},
            priority=9500,
            node_name=node_name,
        ),
    )


def _zone_of(pod):
    payload = json.loads(pod.meta.annotations[ext.ANNOTATION_RESOURCE_STATUS])
    return payload["numaNodeResources"][0]["node"]


def test_device_zone_picks_spread_least_allocated():
    """Successive winners on one node alternate zones (LeastAllocated
    spread), with exact cpusets and zone bookkeeping — all through the
    device-picked hint path."""
    snap, numa = _cluster(n_nodes=1)
    sched = BatchScheduler(snap, LoadAwareArgs(), numa=numa, batch_bucket=64)
    sched.extender.monitor.stop_background()
    pods = [_lsr(f"p{i}", node_name="n0") for i in range(4)]
    out = sched.schedule(pods)
    assert len(out.bound) == 4
    zones = [_zone_of(p) for p, _n in out.bound]
    # 2 zones × 16000m, 4000m pods: exactly two per zone
    assert sorted(zones) == [0, 0, 1, 1], zones
    st = numa.node("n0")
    assert st.zone_used[0][0] == 8000.0 and st.zone_used[1][0] == 8000.0
    # cpusets are exclusive and zone-local
    seen = set()
    for p, _n in out.bound:
        cpus = json.loads(
            p.meta.annotations[ext.ANNOTATION_RESOURCE_STATUS]
        )["cpuset"]
        ids = set()
        for part in cpus.split(","):
            if "-" in part:
                a, b = part.split("-")
                ids.update(range(int(a), int(b) + 1))
            else:
                ids.add(int(part))
        assert not (ids & seen), "overlapping cpusets"
        seen |= ids
    assert len(seen) == 16


def test_device_zone_picks_pack_most_allocated():
    """A node labeled MostAllocated packs winners into one zone before
    opening the next — the device pick must follow the node strategy."""
    snap, numa = _cluster(
        n_nodes=1,
        labels={
            ext.LABEL_NODE_NUMA_ALLOCATE_STRATEGY: "MostAllocated",
        },
    )
    st = numa.node("n0")
    st.numa_allocate_strategy = "MostAllocated"
    sched = BatchScheduler(snap, LoadAwareArgs(), numa=numa, batch_bucket=64)
    sched.extender.monitor.stop_background()
    pods = [_lsr(f"m{i}", cpu=6000, node_name="n0") for i in range(3)]
    out = sched.schedule(pods)
    assert len(out.bound) == 3
    zones = sorted(_zone_of(p) for p, _n in out.bound)
    # 16000m per zone, 6000m pods: two pack into zone 0, third opens 1
    assert zones == [0, 0, 1], zones


def test_zone_hints_match_host_scan():
    """Disable the hint path on an identical cluster/workload: host-scan
    zone assignments must equal the device-picked ones (the hint is an
    accelerator, not a semantic change)."""

    def run(disable_hints):
        snap, numa = _cluster(n_nodes=6)
        sched = BatchScheduler(
            snap, LoadAwareArgs(), numa=numa, batch_bucket=64
        )
        sched.extender.monitor.stop_background()
        if disable_hints:
            orig = sched._commit

            def no_hints(chunk, assignment, rows=None, pod_zone=None):
                return orig(chunk, assignment, rows, pod_zone=None)

            sched._commit = no_hints
        pods = [_lsr(f"h{i}") for i in range(18)]
        out = sched.schedule(pods)
        assert len(out.bound) == 18
        return {p.meta.name: (n, _zone_of(p)) for p, n in out.bound}

    assert run(False) == run(True)


def test_zone_pick_never_selects_padded_zone():
    """Zero-capacity (padded) zones must never win the pick, even for a
    near-zero request under MostAllocated where util=1.0 would otherwise
    attract it (code-review r5)."""
    import jax.numpy as jnp

    from koordinator_tpu.ops.numa import zone_pick

    zone_free = jnp.asarray(
        [[[1000.0, 100.0], [0.0, 0.0], [0.0, 0.0], [0.0, 0.0]]], jnp.float32
    )
    zone_cap = jnp.asarray(
        [[[16000.0, 32768.0], [0.0, 0.0], [0.0, 0.0], [0.0, 0.0]]],
        jnp.float32,
    )
    req = jnp.asarray([[0.0, 0.0]], jnp.float32)
    zone, fit = zone_pick(
        zone_free, zone_cap, req, jnp.asarray([True])  # MostAllocated
    )
    assert bool(fit[0]) and int(zone[0]) == 0


def test_strict_pod_rejected_when_no_zone_fits():
    """SINGLE_NUMA_NODE: a pod larger than any single zone must stay
    unschedulable (device-side strict rejection), while a splittable
    workload on a BestEffort node still binds zoneless."""
    snap, numa = _cluster(n_nodes=1)
    sched = BatchScheduler(snap, LoadAwareArgs(), numa=numa, batch_bucket=64)
    sched.extender.monitor.stop_background()
    big = _lsr("big", cpu=18000, node_name="n0")  # > one 16000m zone
    out = sched.schedule([big])
    assert len(out.bound) == 0 and len(out.unschedulable) == 1
