"""Long-lived cross-component loop (VERDICT r1 item 10): koordlet tick →
NodeMetric report → noderesource batch capacity → scheduler batch →
runtimehook plan, composed in ONE process for N simulated minutes, with
per-tick consistency invariants (accounting drift, batch-capacity bounds)
asserted inside the driver (koordinator_tpu/sim/longrun.py)."""

from koordinator_tpu.sim.longrun import run_loop


def test_longrun_feedback_loop_stays_consistent():
    stats = run_loop(minutes=10.0, n_nodes=6, seed=4)
    assert stats["ticks"] == 40
    assert stats["reports"] == 10 * 6
    # the loop actually moved pods through their lifecycle
    assert stats["bound"] > 30
    assert stats["completed"] > 20
    assert stats["live_at_end"] < stats["bound"]
    # batch capacity breathed with the prod sinusoid
    assert stats["max_batch_cap"] - stats["min_batch_cap"] > 10_000
    # suppression engaged during the load peaks
    assert stats["suppressions"] > 0
    # the reservation lifecycle ran end to end: created → consumed →
    # owner-drift reconciled → TTL-expired → garbage-collected
    assert stats["reservations_created"] >= 2
    assert stats["reservations_consumed"] >= 1
    assert stats["reservations_drifted"] >= 1
    assert stats["reservations_expired"] >= 1
    assert stats["reservations_gced"] >= 1
    # the descheduler soft-evicted BE pods from debounced-hot nodes
    assert stats["soft_evicted"] >= 1
    # preemption → descheduler integration (VERDICT r2 #7): each
    # high-priority arrival into the saturated quota nominated a victim,
    # the victim was evicted via a PodMigrationJob, and the preemptor
    # landed the NEXT cycle
    assert stats["preemption_nominations"] >= 2
    assert stats["preemption_jobs"] >= 2
    assert stats["preemptors_landed"] >= 2


def test_longrun_survives_watch_disconnects():
    """VERDICT r2 #3 chaos test: every open watch is severed twice
    mid-loop (apiserver restart); the informers must re-list and the
    scheduler's world must re-converge — every per-tick invariant
    (accounting drift, batch-capacity bounds, reservation ledger) is
    asserted INSIDE run_loop after each disconnect."""
    stats = run_loop(minutes=10.0, n_nodes=6, seed=4, chaos_ticks=(7, 23))
    assert stats["watch_disconnects"] == 2
    # each of the wired informers re-listed at least once beyond its
    # initial sync (initial = 1 per informer; 5 informers wired: nodes,
    # metrics, pods, reservations, pod groups)
    assert stats["relists"] >= 5 + 2
    # the loop kept scheduling and completing across the disconnects
    assert stats["bound"] > 30
    assert stats["completed"] > 20
    assert stats["reservations_consumed"] >= 1


def test_chaos_relist_converges_scheduler_state():
    """Direct convergence proof: bind + delete events land while the
    watch is DOWN; after re-list the snapshot charge matches the live
    world exactly (the dropped events were reconciled by diff)."""
    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.statehub import ClusterStateHub
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    snap = ClusterSnapshot()
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    hub = ClusterStateHub()
    hub.wire_scheduler(sched)
    hub.start()
    try:
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name="n0"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 262144}
                ),
            ),
        )
        p1 = Pod(
            meta=ObjectMeta(name="a"),
            spec=PodSpec(
                requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4096},
                node_name="n0",
            ),
        )
        hub.publish(hub.pods, p1)
        assert hub.wait_synced()
        idx = snap.node_id("n0")
        assert snap.nodes.requested[idx, 0] == 4000.0

        # sever every watch, THEN mutate: p1 deleted, p2 bound, and a
        # second node appears — all while nobody is watching
        hub.disconnect()
        hub.delete(hub.pods, p1)
        p2 = Pod(
            meta=ObjectMeta(name="b"),
            spec=PodSpec(
                requests={ext.RES_CPU: 6000, ext.RES_MEMORY: 4096},
                node_name="n0",
            ),
        )
        hub.publish(hub.pods, p2)
        hub.publish(
            hub.nodes,
            Node(
                meta=ObjectMeta(name="n1"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
                ),
            ),
        )
        # re-list convergence: the diff delivers p1's delete, p2's add,
        # and n1's add
        assert hub.wait_synced()
        assert not snap.is_assumed(p1.meta.uid)
        assert snap.is_assumed(p2.meta.uid)
        assert snap.nodes.requested[idx, 0] == 6000.0
        assert snap.node_id("n1") is not None
        assert hub.relists() > len(hub.informers)  # recovery re-lists ran
        # accounting invariant after recovery
        want = np.zeros_like(snap.nodes.requested)
        for _uid, ap in snap._assumed.items():
            want[ap.node_idx] += ap.request
        np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)
    finally:
        hub.stop()
