"""Long-lived cross-component loop (VERDICT r1 item 10): koordlet tick →
NodeMetric report → noderesource batch capacity → scheduler batch →
runtimehook plan, composed in ONE process for N simulated minutes, with
per-tick consistency invariants (accounting drift, batch-capacity bounds)
asserted inside the driver (koordinator_tpu/sim/longrun.py)."""

from koordinator_tpu.sim.longrun import run_loop


def test_longrun_feedback_loop_stays_consistent():
    stats = run_loop(minutes=10.0, n_nodes=6, seed=3)
    assert stats["ticks"] == 40
    assert stats["reports"] == 10 * 6
    # the loop actually moved pods through their lifecycle
    assert stats["bound"] > 30
    assert stats["completed"] > 20
    assert stats["live_at_end"] < stats["bound"]
    # batch capacity breathed with the prod sinusoid
    assert stats["max_batch_cap"] - stats["min_batch_cap"] > 10_000
    # suppression engaged during the load peaks
    assert stats["suppressions"] > 0
    # the reservation lifecycle ran end to end: created → consumed →
    # owner-drift reconciled → TTL-expired → garbage-collected
    assert stats["reservations_created"] >= 2
    assert stats["reservations_consumed"] >= 1
    assert stats["reservations_drifted"] >= 1
    assert stats["reservations_expired"] >= 1
    assert stats["reservations_gced"] >= 1
    # the descheduler soft-evicted BE pods from debounced-hot nodes
    assert stats["soft_evicted"] >= 1
