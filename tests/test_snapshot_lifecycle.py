"""ClusterSnapshot accounting lifecycle depth (reference scheduler cache
+ LoadAware podAssignCache, ``load_aware.go:315-358``): assume/absorb/
forget interplay with metric reports, CPU amplification charging, node
churn with slot reuse, and the has_metric/metric_fresh columns."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot


def _node(name, cpu=16000, annotations=None):
    return Node(
        meta=ObjectMeta(name=name, annotations=dict(annotations or {})),
        status=NodeStatus(allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: 32768}),
    )


def _pod(name, cpu=2000, qos=None):
    labels = {ext.LABEL_POD_QOS: qos} if qos else {}
    return Pod(
        meta=ObjectMeta(name=name, labels=labels),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 1024}),
    )


def _metric(name, t, cpu=0.0):
    return NodeMetric(
        meta=ObjectMeta(name=name),
        node_usage=ResourceMetric(usage={ext.RES_CPU: cpu}),
        update_time=t,
    )


def test_absorb_then_forget_does_not_double_refund():
    """A pod absorbed by a metric report must not have its pending
    estimate refunded AGAIN at forget (only the requested row is)."""
    snap = ClusterSnapshot()
    snap.upsert_node(_node("n1"))
    idx = snap.node_id("n1")
    pod = _pod("p1")
    snap.assume_pod(pod, "n1", now=100.0)
    pend0 = snap.nodes.assigned_pending[idx].copy()
    assert pend0.sum() > 0
    # report AFTER the assume: the usage reflects the pod → absorbed
    snap.set_node_metric(_metric("n1", 150.0, cpu=2000.0), now=151.0)
    assert snap.nodes.assigned_pending[idx].sum() == 0
    req_after_absorb = snap.nodes.requested[idx].copy()
    snap.forget_pod(pod.meta.uid)
    # requested refunded, pending must NOT go negative
    assert snap.nodes.requested[idx].sum() < req_after_absorb.sum()
    assert (snap.nodes.assigned_pending[idx] >= -1e-6).all()


def test_amplified_node_charges_bound_pods_scaled():
    """cpu-amplification: an LSR (cpuset-bound) pod's CPU charge scales
    by the node ratio; a plain LS pod's does not
    (``AmplifyResourceList``, plugin.go:430-438)."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        _node(
            "amp",
            annotations={ext.ANNOTATION_NODE_AMPLIFICATION: "cpu=2.0"},
        )
    )
    idx = snap.node_id("amp")
    cpu_dim = snap._cpu_dim
    base = snap.nodes.requested[idx, cpu_dim]
    snap.assume_pod(_pod("ls", cpu=1000), "amp", now=1.0)
    ls_charge = snap.nodes.requested[idx, cpu_dim] - base
    snap.assume_pod(_pod("lsr", cpu=1000, qos="LSR"), "amp", now=2.0)
    lsr_charge = snap.nodes.requested[idx, cpu_dim] - base - ls_charge
    assert ls_charge == 1000.0
    assert lsr_charge == 2000.0, "bound pod must charge ×ratio"


def test_node_slot_reuse_resets_all_columns():
    """Removing a node and upserting a different one may reuse the dense
    row: every column (metrics, freshness, has_metric, amplification,
    custom thresholds) must reset — stale state on a reused slot would
    haunt the new node."""
    snap = ClusterSnapshot()
    snap.upsert_node(
        _node(
            "old",
            annotations={ext.ANNOTATION_NODE_AMPLIFICATION: "cpu=3.0"},
        )
    )
    snap.set_node_metric(_metric("old", 10.0, cpu=5000.0), now=11.0)
    old_idx = snap.node_id("old")
    assert snap.nodes.has_metric[old_idx]
    snap.remove_node("old")
    snap.upsert_node(_node("new"))
    new_idx = snap.node_id("new")
    assert new_idx == old_idx, "test assumes slot reuse"
    assert not snap.nodes.has_metric[new_idx]
    assert not snap.nodes.metric_fresh[new_idx]
    assert snap.nodes.cpu_amp[new_idx] == 1.0
    assert snap.nodes.usage_avg[new_idx].sum() == 0


def test_expired_assume_refunds_everything():
    snap = ClusterSnapshot()
    snap.upsert_node(_node("n1"))
    idx = snap.node_id("n1")
    # optimistic assume (the scheduler's Reserve path) — confirmed=True
    # assumes are bind-observed and exempt from TTL expiry
    snap.assume_pod(_pod("ghost"), "n1", now=100.0, confirmed=False)
    assert snap.nodes.requested[idx].sum() > 0
    n = snap.expire_assumed(now=100.0 + 10_000, ttl=300.0)
    assert n == 1
    np.testing.assert_allclose(snap.nodes.requested[idx], 0.0, atol=1e-6)
    np.testing.assert_allclose(
        snap.nodes.assigned_pending[idx], 0.0, atol=1e-6
    )


def test_confirmed_assume_never_expires():
    snap = ClusterSnapshot()
    snap.upsert_node(_node("n1"))
    pod = _pod("keeper")
    snap.assume_pod(pod, "n1", now=100.0)
    assert snap.confirm_pod(pod.meta.uid)
    assert snap.expire_assumed(now=1e9, ttl=1.0) == 0
    assert snap.nodes.requested[snap.node_id("n1")].sum() > 0


def test_stale_then_fresh_metric_restores_freshness():
    snap = ClusterSnapshot()
    snap.upsert_node(_node("n1"))
    idx = snap.node_id("n1")
    snap.set_node_metric(_metric("n1", 100.0), now=100.0 + 10_000)
    assert snap.nodes.has_metric[idx] and not snap.nodes.metric_fresh[idx]
    snap.set_node_metric(_metric("n1", 20_000.0), now=20_001.0)
    assert snap.nodes.metric_fresh[idx]
