"""Fused Pallas nomination vs the XLA reference path (interpret mode on
CPU; the same kernel compiles for TPU — see bench notes in the module)."""

import numpy as np

import jax
import jax.numpy as jnp

from koordinator_tpu.ops import costs as cost_ops, masks as mask_ops
from koordinator_tpu.ops.pallas_nominate import nominate_fused

from test_solver import make_fixture


def reference_nomination(pods, nodes, params, topk, jitter):
    p = pods.requests.shape[0]
    n = nodes.allocatable.shape[0]
    free = nodes.allocatable - nodes.requested
    feas = mask_ops.fit_mask(pods.requests, free)
    feas &= mask_ops.usage_threshold_mask(
        pods.estimate, nodes.estimated_used, nodes.allocatable,
        params.usage_thresholds, nodes.metric_fresh,
    )
    feas &= nodes.schedulable[None, :]
    cost = cost_ops.load_aware_cost(
        pods.estimate, nodes.estimated_used, nodes.allocatable,
        params.score_weights, metric_fresh=nodes.metric_fresh,
    )
    if jitter > 0:
        pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
        ni = jnp.arange(n, dtype=jnp.uint32)[None, :]
        h = (pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)) & jnp.uint32(
            0xFFFF
        )
        cost = cost + h.astype(jnp.float32) * (jitter / 65536.0)
    cost = jnp.where(feas, cost, jnp.inf)
    return jax.lax.top_k(-cost, topk)


def run_both(p, n, seed, jitter=4.0, topk=4, **fixture_kw):
    pods, nodes, params, _ = make_fixture(p=p, n=n, seed=seed, **fixture_kw)
    want_neg, want_idx = reference_nomination(pods, nodes, params, topk, jitter)
    got_neg, got_idx = nominate_fused(
        pods.requests, pods.estimate,
        nodes.allocatable, nodes.requested, nodes.estimated_used,
        nodes.schedulable, nodes.metric_fresh,
        params.usage_thresholds, params.score_weights,
        topk=topk, nomination_jitter=jitter, interpret=True,
    )
    return (
        np.asarray(got_neg), np.asarray(got_idx),
        np.asarray(want_neg), np.asarray(want_idx),
    )


def test_matches_xla_nomination():
    got_neg, got_idx, want_neg, want_idx = run_both(
        p=48, n=640, seed=3, base_util=0.3, thresholds=(65.0, 95.0)
    )
    finite = np.isfinite(want_neg)
    np.testing.assert_allclose(
        got_neg[finite], want_neg[finite], rtol=1e-5, atol=1e-4
    )
    np.testing.assert_array_equal(got_idx[finite], want_idx[finite])
    # infeasible slots: kernel reports -1
    assert (got_idx[~finite] == -1).all()


def test_no_feasible_nodes_all_minus_one():
    got_neg, got_idx, want_neg, _ = run_both(
        p=16, n=512, seed=4, pod_scale=10_000.0
    )
    assert not np.isfinite(want_neg).any()
    assert (got_idx == -1).all()


def test_ragged_shapes_pad_correctly():
    # P and N not multiples of the tile sizes: padded nodes must never
    # be nominated, padded pods are sliced off
    got_neg, got_idx, want_neg, want_idx = run_both(
        p=33, n=700, seed=5, base_util=0.2
    )
    assert got_idx.shape == (33, 4)
    assert (got_idx < 700).all()
    finite = np.isfinite(want_neg)
    np.testing.assert_allclose(
        got_neg[finite], want_neg[finite], rtol=1e-5, atol=1e-4
    )
    np.testing.assert_array_equal(got_idx[finite], want_idx[finite])


def test_zero_jitter_strict_argmin():
    got_neg, got_idx, want_neg, want_idx = run_both(
        p=24, n=512, seed=6, jitter=0.0, topk=1, base_util=0.1
    )
    finite = np.isfinite(want_neg)
    np.testing.assert_array_equal(got_idx[finite], want_idx[finite])
