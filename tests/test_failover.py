"""Fenced leader failover + warm-standby recovery tests (HA PR tentpole).

The acceptance-criterion test drives TWO scheduler instances over one
statehub and one lease lock, forces a leadership change mid-cycle (solve
in flight in instance A's pipeline), and proves the deposed leader's
trailing commit is rejected with the named STALE_LEADER_EPOCH reason and
counted in ``leader_fenced_commits_total`` — never double-placed.
"""

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core.journal import (
    BindJournal,
    EpochFence,
    MemoryJournalStore,
)
from koordinator_tpu.runtime.ha import LeaderCoordinator
from koordinator_tpu.runtime.recovery import recover_scheduler
from koordinator_tpu.runtime.statehub import ClusterStateHub
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.pipeline import CyclePipeline
from koordinator_tpu.utils.leaderelection import InMemoryLeaseLock, LeaderElector


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def _node(name, cpu=32_000.0, mem=128 * 1024.0):
    return Node(
        meta=ObjectMeta(name=name),
        status=NodeStatus(
            allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}
        ),
    )


def _pod(name, cpu=2000.0, mem=4096.0, prio=9000):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: mem}, priority=prio
        ),
    )


def _sched(store=None, fence=None, chaos=None, **kw):
    sched = BatchScheduler(
        args=LoadAwareArgs(usage_thresholds={}),
        batch_bucket=16,
        chaos=chaos,
        journal=BindJournal(store) if store is not None else None,
        fence=fence,
        **kw,
    )
    sched.extender.monitor.stop_background()
    return sched


def _hub_with_nodes(scheds, n_nodes=4):
    hub = ClusterStateHub()
    for s in scheds:
        hub.wire_scheduler(s)
    hub.start()
    for i in range(n_nodes):
        hub.publish(hub.nodes, _node(f"n{i}"))
    assert hub.wait_synced()
    return hub


def _elector(lock, ident, clock):
    return LeaderElector(
        lock, ident, now_fn=clock.now, sleep_fn=clock.sleep
    )


# ---------------------------------------------------------------------------
# acceptance criterion: deposed leader's in-flight commit is fenced
# ---------------------------------------------------------------------------


def test_deposed_leader_inflight_pipeline_commit_is_fenced():
    store = MemoryJournalStore()
    fence = EpochFence()
    lock = InMemoryLeaseLock()
    clock = FakeClock()
    sched_a = _sched(store=store, fence=fence)
    sched_b = _sched(store=store, fence=fence)
    hub = _hub_with_nodes([sched_a, sched_b])
    try:
        pipe_a = CyclePipeline(sched_a)
        coord_a = LeaderCoordinator(
            sched_a,
            _elector(lock, "instance-a", clock),
            fence,
            sched_a.bind_journal,
            hub=hub,
            pipeline=pipe_a,
        )
        coord_b = LeaderCoordinator(
            sched_b,
            _elector(lock, "instance-b", clock),
            fence,
            sched_b.bind_journal,
            hub=hub,
        )
        leading, _ = coord_a.tick()
        assert leading and sched_a._fence_epoch == 1
        assert not coord_b.tick()[0]  # contender blocked inside the lease

        # A's cycle goes in flight: the batch is fed, its solve is
        # dispatched, the trailing commit has NOT run yet
        batch = [_pod(f"p{i}") for i in range(6)]
        assert pipe_a.feed(batch) is None

        # leadership changes MID-CYCLE: the lease expires and B takes
        # over under epoch 2 (running recovery before its grant)
        clock.t = 20.0
        leading_b, _ = coord_b.tick()
        assert leading_b and fence.current() == 2
        assert coord_b.last_recovery is not None
        assert coord_b.last_recovery.bitexact is True

        # A discovers the loss; its in-flight commit must drain through
        # the fence and be REJECTED — not double-placed
        leading_a, drained = coord_a.tick()
        assert not leading_a
        assert drained is not None
        assert drained.bound == []
        assert {p.meta.uid for p in drained.unschedulable} == {
            p.meta.uid for p in batch
        }
        # the rejection is attributed with the NAMED reason + metric
        recs = sched_a.extender.rejections.for_uid(batch[0].meta.uid)
        assert any(r.reason == "stale_leader_epoch" for r in recs), recs
        assert (
            sched_a.extender.registry.get(
                "leader_fenced_commits_total"
            ).value()
            >= 1.0
        )
        # the deposed leader charged nothing and journaled nothing
        assert all(
            not sched_a.snapshot.is_assumed(p.meta.uid) for p in batch
        )
        assert not any(
            r["op"] == "bind" for r in sched_a.bind_journal.records()
        )

        # the new leader places the same pods exactly once
        out_b = sched_b.schedule(batch)
        assert len(out_b.bound) == len(batch)
        pipe_a.close()
    finally:
        hub.stop()


# ---------------------------------------------------------------------------
# crash restart: journal replay + statehub resync rebuild the world
# ---------------------------------------------------------------------------


def test_crash_restart_recovers_acknowledged_bindings():
    store = MemoryJournalStore()
    fence = EpochFence()
    sched1 = _sched(store=store, fence=fence)
    hub = _hub_with_nodes([sched1])
    try:
        sched1.grant_leadership(fence.advance())
        published = [_pod(f"pub{i}") for i in range(4)]
        out1 = sched1.schedule(published)
        assert len(out1.bound) == 4
        for pod, node in out1.bound:
            pod.spec.node_name = node
            hub.publish(hub.pods, pod)  # the bind API write landed
        # a second batch is committed + journal-ACKNOWLEDGED, but the
        # process dies before the bind API writes go out
        unpublished = [_pod(f"lost{i}", prio=7000) for i in range(3)]
        out2 = sched1.schedule(unpublished)
        assert len(out2.bound) == 3
        assert hub.wait_synced()

        # ---- crash: the process (snapshot, scheduler, watches) dies ----
        hub.detach_consumers()
        sched2 = _sched(store=store, fence=fence)
        hub.wire_scheduler(sched2)
        hub.start()

        rep = recover_scheduler(
            sched2,
            sched2.bind_journal,
            hub=hub,
            epoch=fence.advance(),
            verify=True,
        )
        # published binds came back through the resync; the unpublished
        # (assumed-but-unbound) ones through restore_assumed replay
        assert rep.reconfirmed == 4
        assert rep.replayed == 3
        assert rep.bitexact is True
        assert rep.skipped_missing_node == 0
        # every acknowledged binding is recoverable — zero lost
        acked = {p.meta.uid for p, _ in out1.bound} | {
            p.meta.uid for p, _ in out2.bound
        }
        assert set(rep.bindings) == acked
        # the rebuilt charges equal the dead leader's, node by node
        for i in range(4):
            name = f"n{i}"
            i1 = sched1.snapshot.node_id(name)
            i2 = sched2.snapshot.node_id(name)
            np.testing.assert_allclose(
                sched2.snapshot.nodes.requested[i2],
                sched1.snapshot.nodes.requested[i1],
                atol=1e-3,
            )
        assert sched2._fence_epoch == 2
    finally:
        hub.stop()


def test_recovery_skips_entries_for_vanished_nodes():
    store = MemoryJournalStore()
    journal = BindJournal(store)
    journal.append_bind(
        1,
        0,
        [
            {
                "uid": "ghost",
                "node": "gone-node",
                "req": [1000.0, 2048.0] + [0.0] * 8,
                "est": [1000.0, 2048.0] + [0.0] * 8,
                "prod": False,
                "nom": 0.0,
                "conf": True,
                "quota": None,
            }
        ],
    )
    sched = _sched(store=store)
    hub = _hub_with_nodes([sched])
    try:
        rep = recover_scheduler(sched, journal, hub=hub, epoch=None)
        assert rep.skipped_missing_node == 1 and rep.replayed == 0
        assert not sched.snapshot.is_assumed("ghost")
    finally:
        hub.stop()


# ---------------------------------------------------------------------------
# commit-boundary failure domains: journal.write_fail + leader.stale_commit
# ---------------------------------------------------------------------------


def test_journal_write_fail_rejects_chunk_unmutated():
    chaos = FaultInjector(seed=0)
    store = MemoryJournalStore()
    sched = _sched(store=store, chaos=chaos)
    for i in range(3):
        sched.snapshot.upsert_node(_node(f"n{i}"))
    pods = [_pod(f"p{i}") for i in range(4)]
    chaos.arm("journal.write_fail", times=1)
    before = sched.snapshot.nodes.requested.copy()
    out = sched.schedule(pods)
    # journal before mutate: the refused intent rejected the chunk with
    # ZERO snapshot mutation and nothing in the log
    assert out.bound == [] and len(out.unschedulable) == 4
    np.testing.assert_array_equal(sched.snapshot.nodes.requested, before)
    assert sched.bind_journal.records() == []
    recs = sched.extender.rejections.for_uid(pods[0].meta.uid)
    assert any(r.reason == "journal_write_failed" for r in recs), recs
    assert not sched.extender.health.get("commit")["ok"]
    # fault exhausted: the retry cycle binds and journals normally
    out2 = sched.schedule(pods)
    assert len(out2.bound) == 4
    assert {r["op"] for r in sched.bind_journal.records()} == {
        "intent",
        "bind",
    }
    assert sched.extender.health.get("commit")["ok"]


def test_stale_commit_chaos_point_fences_deterministically():
    chaos = FaultInjector(seed=0)
    sched = _sched(chaos=chaos)
    for i in range(2):
        sched.snapshot.upsert_node(_node(f"n{i}"))
    pods = [_pod(f"p{i}") for i in range(2)]
    chaos.arm("leader.stale_commit", times=1)
    out = sched.schedule(pods)
    assert out.bound == []
    recs = sched.extender.rejections.for_uid(pods[0].meta.uid)
    assert any(r.reason == "stale_leader_epoch" for r in recs), recs
    assert (
        sched.extender.registry.get("leader_fenced_commits_total").value()
        == 1.0
    )
    assert chaos.fired_counts()["leader.stale_commit"] == 1
    # next cycle is clean
    assert len(sched.schedule(pods).bound) == 2


def test_commit_crash_writes_abort_record():
    chaos = FaultInjector(seed=0)
    store = MemoryJournalStore()
    sched = _sched(store=store, chaos=chaos)
    sched.snapshot.upsert_node(_node("n0"))
    chaos.arm("commit.crash", error=RuntimeError, times=1)
    out = sched.schedule([_pod("p0")])
    assert out.bound == []
    ops = [r["op"] for r in sched.bind_journal.records()]
    assert ops == ["intent", "abort"]
    # replay sees nothing applied — matching the rolled-back host state
    assert sched.bind_journal.replay().live == {}


# ---------------------------------------------------------------------------
# pipeline drain/handoff + leader.lost flap
# ---------------------------------------------------------------------------


def test_drain_for_handoff_fences_inflight_batch():
    fence = EpochFence()
    sched = _sched(store=MemoryJournalStore(), fence=fence)
    for i in range(3):
        sched.snapshot.upsert_node(_node(f"n{i}"))
    sched.grant_leadership(fence.advance())
    pipe = CyclePipeline(sched)
    try:
        batch1 = [_pod(f"a{i}") for i in range(3)]
        batch2 = [_pod(f"b{i}") for i in range(3)]
        assert pipe.feed(batch1) is None
        out1 = pipe.feed(batch2)
        assert out1 is not None and len(out1.bound) == 3
        # leadership lost with batch2 in flight
        sched.revoke_leadership()
        drained = pipe.drain_for_handoff()
        assert drained is not None and drained.bound == []
        assert {p.meta.uid for p in drained.unschedulable} == {
            p.meta.uid for p in batch2
        }
        assert sched.extender.health.get("pipeline")["ok"]
        assert pipe.drain_for_handoff() is None  # idempotent when idle
    finally:
        pipe.close()


def test_handoff_flaps_never_burn_retry_budget():
    """A fencing rejection is not a scheduling verdict: pods caught
    in flight by MORE leadership flaps than ``max_retries`` must still
    be queued for the next leader, never reported unschedulable."""
    from koordinator_tpu.scheduler.stream import StreamScheduler

    fence = EpochFence()
    sched = _sched(store=MemoryJournalStore(), fence=fence)
    for i in range(3):
        sched.snapshot.upsert_node(_node(f"n{i}"))
    stream = StreamScheduler(sched, pipelined=True, max_retries=2)
    try:
        pod = _pod("flappy")
        stream.submit(pod)
        for flap in range(4):  # > max_retries flaps
            sched.grant_leadership(fence.advance())
            sched.revoke_leadership()
            assert stream.pump() == []  # pod goes in flight
            decided = stream.drain_for_handoff()
            assert decided == [], f"flap {flap} decided {decided}"
            assert stream.backlog() == 1
        # a real leader finally places it
        sched.grant_leadership(fence.advance())
        results = stream.flush()
        assert len(results) == 1 and results[0][1] is not None
    finally:
        stream.close()


def test_fenceless_recovery_adopts_journal_epoch():
    """The CLI restart path (no election wired, epoch=None) over a
    journal written under coordinator epochs must ADOPT the journal's
    last epoch — otherwise every append from the recovered writer is
    refused as stale and the scheduler can never commit again."""
    store = MemoryJournalStore()
    BindJournal(store).append_bind(
        3,
        0,
        [
            {
                "uid": "old",
                "node": "n0",
                "req": [100.0, 128.0, 0.0, 0.0],
                "est": [100.0, 128.0, 0.0, 0.0],
                "prod": False,
                "nom": 0.0,
                "conf": True,
                "quota": None,
            }
        ],
    )
    sched = _sched(store=store)  # no fence — the CLI shape
    hub = _hub_with_nodes([sched])
    try:
        rep = recover_scheduler(
            sched, sched.bind_journal, hub=hub, epoch=None
        )
        assert rep.epoch == 3 and sched._fence_epoch == 3
        out = sched.schedule([_pod("fresh")])
        assert len(out.bound) == 1  # journal append accepted epoch 3
        assert any(
            r["op"] == "bind" and r["epoch"] == 3
            for r in sched.bind_journal.records()[1:]
        )
    finally:
        hub.stop()


def test_snapshot_channel_rejects_malformed_epoch_metadata():
    """A PRESENT but unparseable x-leader-epoch must be rejected
    (INVALID_ARGUMENT), not waved through unfenced."""
    import grpc

    from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
    from koordinator_tpu.runtime.snapshot_channel import (
        EPOCH_METADATA_KEY,
        SERVICE_NAME,
        SolverService,
        serve,
    )

    service = SolverService()
    service.scheduler.extender.monitor.stop_background()
    server, port = serve(service)
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    stub = channel.unary_unary(
        f"/{SERVICE_NAME}/Sync",
        request_serializer=pb.SnapshotDelta.SerializeToString,
        response_deserializer=pb.SyncAck.FromString,
    )
    try:
        with pytest.raises(grpc.RpcError) as err:
            stub(
                pb.SnapshotDelta(revision=1),
                metadata=((EPOCH_METADATA_KEY, "epoch-7"),),
            )
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert service.revision == 0  # nothing mutated
    finally:
        channel.close()
        server.stop(grace=None)


def test_snapshot_channel_fences_stale_epoch():
    """Channel-boundary fencing: once the new leader's epoch has spoken
    over the channel, a deposed leader's sync/nominate is refused
    server-side (ChannelFenced), and a locally-wired fence stops the
    call before it even reaches the wire (StaleEpochError)."""
    from koordinator_tpu.core.journal import StaleEpochError
    from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
    from koordinator_tpu.runtime.snapshot_channel import (
        ChannelFenced,
        SolverClient,
        SolverService,
        serve,
    )

    service = SolverService()
    service.scheduler.extender.monitor.stop_background()
    server, port = serve(service)
    new_leader = SolverClient(f"127.0.0.1:{port}")
    old_leader = SolverClient(f"127.0.0.1:{port}")
    try:
        new_leader.set_epoch(5)
        old_leader.set_epoch(4)
        delta = pb.SnapshotDelta(revision=1)
        delta.node_upserts.add(
            name="n0", allocatable=pb.ResourceVector(values=[32000.0])
        )
        ack = new_leader.sync(delta)
        assert ack.applied_revision == 1
        assert service.leader_epoch == 5
        with pytest.raises(ChannelFenced):
            old_leader.sync(pb.SnapshotDelta(revision=2))
        with pytest.raises(ChannelFenced):
            old_leader.nominate(pb.NominateRequest())
        # the refused delta mutated nothing
        assert service.revision == 1
        # local fence layer: the call never leaves the process
        fence = EpochFence()
        fence.adopt(5)
        local = SolverClient(f"127.0.0.1:{port}", fence=fence)
        local.set_epoch(4)
        with pytest.raises(StaleEpochError):
            local.sync(pb.SnapshotDelta(revision=3))
        local.close()
    finally:
        new_leader.close()
        old_leader.close()
        server.stop(grace=None)


def test_leader_lost_chaos_flap_reacquires_under_new_epoch():
    chaos = FaultInjector(seed=0)
    fence = EpochFence()
    store = MemoryJournalStore()
    sched = _sched(store=store, fence=fence, chaos=chaos)
    hub = _hub_with_nodes([sched])
    try:
        lock = InMemoryLeaseLock()
        clock = FakeClock()
        coord = LeaderCoordinator(
            sched,
            _elector(lock, "solo", clock),
            fence,
            sched.bind_journal,
            hub=hub,
        )
        assert coord.tick()[0] and sched._fence_epoch == 1
        chaos.arm("leader.lost", times=1)
        leading, _ = coord.tick()
        assert not leading and sched._fence_epoch == -1
        # commits are fenced while revoked
        out = sched.schedule([_pod("flap0")])
        assert out.bound == []
        # next tick re-acquires under a NEW epoch, through recovery
        leading, _ = coord.tick()
        assert leading and sched._fence_epoch == 2
        assert fence.current() == 2
        assert len(sched.schedule([_pod("flap1")]).bound) == 1
    finally:
        hub.stop()
