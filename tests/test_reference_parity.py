"""Golden parity against the reference's own unit-test tables.

Each case here reproduces an entry of the reference's table tests with the
same inputs and asserts the same expected value — the safety net SURVEY §7
hard part (d) calls for. Sources cited per case.
"""

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.ops.costs import load_aware_cost

GI = 1024.0                      # MiB per Gi (snapshot memory unit is MiB)
NODE_ALLOC = np.array([[96_000.0, 512 * GI]], np.float32)   # 96C / 512Gi
WEIGHTS = jnp.ones(2, jnp.float32)


def score_of(est_cpu_milli, est_mem_mib, used_cpu_milli, used_mem_mib, fresh=True):
    est = jnp.asarray([[est_cpu_milli, est_mem_mib]], jnp.float32)
    used = jnp.asarray([[used_cpu_milli, used_mem_mib]], jnp.float32)
    cost = load_aware_cost(
        est,
        used,
        jnp.asarray(NODE_ALLOC),
        WEIGHTS,
        metric_fresh=jnp.asarray([fresh]),
    )
    return -float(np.asarray(cost)[0, 0])


# pod requests 16C/32Gi; the default estimator scales cpu x0.85, mem x0.7
# (estimator/default_estimator.go) -> 13600m / 22.4Gi
EST_CPU = 16_000 * 0.85
EST_MEM = 32 * GI * 0.7


def test_score_empty_node_is_90():
    """load_aware_test.go TestScore "score empty node": wantScore 90."""
    assert score_of(EST_CPU, EST_MEM, 0.0, 0.0) == 90.0


def test_score_loaded_node_is_72():
    """"score load node": usage 32C/10Gi -> wantScore 72 (only reproduced
    under the reference's per-resource + final integer flooring:
    cpu 52.5 -> 52, mem 93.67 -> 93, (52+93)/2 -> 72)."""
    assert score_of(EST_CPU, EST_MEM, 32_000.0, 10 * GI) == 72.0


def test_score_expired_metric_is_0():
    """"score node with expired nodeMetric": wantScore 0 — still
    schedulable, ranked last."""
    assert score_of(EST_CPU, EST_MEM, 0.0, 0.0, fresh=False) == 0.0


def test_score_with_assigned_pod_estimate_is_81():
    """"score load node with p95 but have not reported usage and have
    assigned pods": zero reported usage + one assigned 16C/32Gi pod
    estimated at 13.6C/22.4Gi -> wantScore 81."""
    assert score_of(EST_CPU, EST_MEM, EST_CPU, EST_MEM) == 81.0


def test_score_usage_plus_assigned_is_63():
    """"score load node with just assigned pod": usage 32C/10Gi plus an
    assigned pod's estimate on top -> wantScore 63."""
    assert (
        score_of(EST_CPU, EST_MEM, 32_000.0 + EST_CPU, 10 * GI + EST_MEM)
        == 63.0
    )


# ---- Filter (load_aware_test.go TestFilterUsage; default thresholds
# cpu 65 / memory 95, node 96C/512Gi) ----

from koordinator_tpu.ops.masks import (
    prod_usage_threshold_mask,
    usage_threshold_mask,
)


def filter_ok(used_cpu_milli, used_mem_mib, thr=(65.0, 95.0), fresh=True,
              est=(0.0, 0.0)):
    mask = usage_threshold_mask(
        jnp.asarray([list(est)], jnp.float32),
        jnp.asarray([[used_cpu_milli, used_mem_mib]], jnp.float32),
        jnp.asarray(NODE_ALLOC),
        jnp.asarray(thr, jnp.float32),
        jnp.asarray([fresh]),
    )
    return bool(np.asarray(mask)[0, 0])


def test_filter_normal_usage_passes():
    """"filter normal usage": 60C (62.5%) / 256Gi (50%) -> schedulable."""
    assert filter_ok(60_000.0, 256 * GI)


def test_filter_exceed_cpu_usage_rejects():
    """"filter exceed cpu usage": 70C -> 72.9% -> round 73 > 65."""
    assert not filter_ok(70_000.0, 256 * GI)


def test_filter_rounded_percent_boundary():
    """The reference compares int64(round(pct)): 65.4% rounds to 65 and
    PASSES a 65 threshold; 65.6% rounds to 66 and fails."""
    assert filter_ok(0.654 * 96_000.0, 0.0)
    assert not filter_ok(0.656 * 96_000.0, 0.0)


def test_filter_zero_threshold_disables_dim():
    """"disable filter exceed memory usage": memory threshold 0 admits a
    97.6%-memory node."""
    assert filter_ok(10_000.0, 500 * GI, thr=(65.0, 0.0))


def test_filter_expired_metric_degrades_to_fit_only():
    assert filter_ok(95_000.0, 500 * GI, fresh=False)


def test_filter_prod_usage_only_gates_prod_pods():
    """"filter prod cpu usage": prod usage 33C (34.4% -> 34 > 30) rejects
    a prod pod under prodUsageThresholds cpu=30; a non-prod pod passes."""
    def prod_ok(is_prod):
        mask = prod_usage_threshold_mask(
            jnp.asarray([is_prod]),
            jnp.zeros((1, 2), jnp.float32),
            jnp.asarray([[33_000.0, 0.0]], jnp.float32),   # prod-tier usage
            jnp.asarray(NODE_ALLOC),
            jnp.asarray([30.0, 0.0], jnp.float32),
            jnp.asarray([True]),
        )
        return bool(np.asarray(mask)[0, 0])

    assert not prod_ok(True)
    assert prod_ok(False)


# ---- ElasticQuota runtime fair sharing
# (runtime_quota_calculator_test.go TestRuntimeQuotaCalculator_IterationAdjustQuota:
# weights 40/60/50/80, limited requests 5/20/40/70, mins 10/15/20/15,
# total 100; runtime starts at min(min, request), rounds of rounded-integer
# weighted deltas, capped excess redistributed among the unsatisfied) ----

from koordinator_tpu.scheduler.plugins.elasticquota import water_fill


def _fill(guaranteed, caps, weights, total=100.0):
    out = water_fill(
        np.asarray([total], np.float32),
        np.asarray([[g] for g in guaranteed], np.float32),
        np.asarray([[c] for c in caps], np.float32),
        np.asarray([[w] for w in weights], np.float32),
    )
    return out.ravel().tolist()


def test_quota_iteration_case1_no_guarantee():
    assert _fill([5, 15, 20, 15], [5, 20, 40, 70], [40, 60, 50, 80]) == [
        5, 20, 35, 40,
    ]


def test_quota_iteration_case2_zero_weight():
    """node4 sharedWeight=0: it keeps only its min; node3 reaches its
    full request."""
    assert _fill([5, 15, 20, 15], [5, 20, 40, 70], [40, 60, 50, 0]) == [
        5, 20, 40, 15,
    ]


def test_quota_iteration_case3_guarantee_over_min():
    """node4 guarantee 45 > min 15: starts at 45 and keeps it even with
    zero weight."""
    assert _fill([5, 15, 20, 45], [5, 20, 40, 70], [40, 60, 50, 0]) == [
        5, 20, 30, 45,
    ]


# ---- batchresource calculation policies
# (CalculateBatchResourceByPolicy, plugins/util/util.go:50-105) ----


def test_batch_resource_policies():
    from koordinator_tpu.api.types import (
        Node,
        NodeMetric,
        NodeStatus,
        ObjectMeta,
        ResourceMetric,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.manager.noderesource import (
        ColocationStrategy,
        NodeResourceController,
    )
    from koordinator_tpu.api import extension as ext2

    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext2.RES_CPU: 100_000, ext2.RES_MEMORY: 100_000}
            ),
        )
    )
    snap.set_node_metric(
        NodeMetric(
            meta=ObjectMeta(name="n0"),
            node_usage=ResourceMetric(
                usage={ext2.RES_CPU: 50_000, ext2.RES_MEMORY: 40_000}
            ),
            prod_usage=ResourceMetric(
                usage={ext2.RES_CPU: 40_000, ext2.RES_MEMORY: 30_000}
            ),
            sys_usage=ResourceMetric(
                usage={ext2.RES_CPU: 7_000, ext2.RES_MEMORY: 5_000}
            ),
            update_time=999.0,
        ),
        now=1000.0,
    )
    idx = snap.node_id("n0")
    # prod requests on the node: 60C/50G (assumed pods)
    from koordinator_tpu.api.types import Pod, PodSpec

    snap.assume_pod(
        Pod(
            meta=ObjectMeta(name="prod", uid="prod"),
            spec=PodSpec(
                requests={ext2.RES_CPU: 60_000, ext2.RES_MEMORY: 50_000},
                priority=9500,
            ),
        ),
        "n0",
        estimated=np.zeros(snap.config.dims, np.float32),
    )

    def calc(cpu_policy, mem_policy):
        ctrl = NodeResourceController(
            snap,
            ColocationStrategy(
                reserve_ratio=0.1,
                node_reserved={ext2.RES_CPU: 5_000, ext2.RES_MEMORY: 4_000},
                cpu_calculate_policy=cpu_policy,
                memory_calculate_policy=mem_policy,
            ),
        )
        batch, _mid = ctrl.calculate()
        return batch[idx]

    # usage: 100k - 10k(margin) - max(7k sys, 5k reserved) - 40k prodUsed = 43k
    # mem:   100k - 10k - max(5k, 4k) - 30k = 55k
    b = calc("usage", "usage")
    assert b[0] == 43_000 and b[1] == 55_000
    # request (memory): 100k - 10k - 4k(reserved) - 50k(prodReq) = 36k
    b = calc("usage", "request")
    assert b[1] == 36_000
    # maxUsageRequest (cpu): 100k - 10k - 7k - max(40k, 60k) = 23k
    b = calc("maxUsageRequest", "usage")
    assert b[0] == 23_000


# ---- BE CPU suppression (calculateBESuppressCPU, cpu_suppress.go:136-170) ----


def test_be_suppress_formula():
    from koordinator_tpu.koordlet.qosmanager import cpu_suppress

    # suppress = 64C*65% - podNonBE 20C - max(sys 4C, reserved 2C) = 17.6C
    dec = cpu_suppress(
        64_000, 30_000, 6_000, 65.0,
        sys_used_milli=4_000, node_reserved_milli=2_000,
    )
    assert dec.be_allowance_milli == 64_000 * 0.65 - 20_000 - 4_000
    # reserved floor wins over smaller system usage
    dec = cpu_suppress(
        64_000, 30_000, 6_000, 65.0,
        sys_used_milli=1_000, node_reserved_milli=2_000,
    )
    assert dec.be_allowance_milli == 64_000 * 0.65 - 23_000 - 2_000
    # beCPUMinThreshold percent floor
    dec = cpu_suppress(
        64_000, 64_000, 0.0, 65.0, min_threshold_percent=10.0,
    )
    assert dec.be_allowance_milli == 6_400.0


# ---- takeCPUs FullPCPUs flow
# (cpu_accumulator_test.go TestTakeFullPCPUs; topologies built like
# buildCPUTopologyForTest(sockets, nodesPerSocket, coresPerNode,
# cpusPerCore) with sequential cpu ids) ----

from koordinator_tpu.core.topology import (
    CPUAccumulator,
    CPUBindPolicy,
    CPUTopology,
)


def take_full(sockets, numa_per_socket, cores, threads, allocated, need):
    topo = CPUTopology.uniform(
        sockets=sockets,
        numa_per_socket=numa_per_socket,
        cores_per_numa=cores,
        threads_per_core=threads,
    )
    acc = CPUAccumulator(topo)
    if allocated:
        acc._allocated |= set(allocated)
    got = acc.take("p", need, policy=CPUBindPolicy.FULL_PCPUS)
    return sorted(got) if got is not None else None


def test_take_on_non_numa_node():
    assert take_full(1, 1, 4, 2, [], 2) == [0, 1]


def test_take_with_allocated_cpus():
    assert take_full(1, 1, 4, 2, [0, 1], 2) == [2, 3]


def test_take_whole_socket():
    assert take_full(2, 1, 4, 2, [], 8) == list(range(8))


def test_take_across_sockets():
    assert take_full(2, 1, 4, 2, [], 12) == list(range(12))


def test_take_whole_socket_skipping_partial():
    assert take_full(2, 1, 4, 2, [0, 1], 8) == list(range(8, 16))


def test_take_smallest_idle_socket():
    """allocated 0-5,16-23: socket1 (8 free) is tighter than socket0 (10
    free) — MostAllocated strategy bin-packs into it."""
    assert take_full(2, 2, 4, 2, list(range(6)) + list(range(16, 24)), 6) == [
        24, 25, 26, 27, 28, 29,
    ]


def test_take_most_cpus_on_same_socket():
    """need exceeds any one socket: drain the largest free socket whole
    (6-15), top up from the tightest remainder core-by-core (24-25)."""
    got = take_full(2, 2, 4, 2, list(range(6)) + list(range(16, 24)), 12)
    assert got == list(range(6, 16)) + [24, 25]


# ---- DefaultEstimator (default_estimator.go:59-123) ----


def test_estimator_semantics():
    from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
    from koordinator_tpu.core.snapshot import SnapshotConfig
    from koordinator_tpu.ops.estimator import estimate_pod, scale_vector

    cfg = SnapshotConfig()
    scales = scale_vector(cfg.resources)
    cpu_i = cfg.resources.index("cpu")
    mem_i = cfg.resources.index("memory")
    bcpu_i = cfg.resources.index("kubernetes.io/batch-cpu")

    # base = max(request, limit): limit 20C dominates request 10C
    pod = Pod(
        meta=ObjectMeta(name="p"),
        spec=PodSpec(
            requests={"cpu": 10_000, "memory": 1024},
            limits={"cpu": 20_000},
            priority=9500,
        ),
    )
    est = estimate_pod(cfg, pod, scales)
    assert est[cpu_i] == round(20_000 * 0.85)
    # scaled value capped at the limit (factor > 100 scenario is the
    # reference's cap case; with a tight limit, cap binds)
    pod.spec.limits = {"cpu": 10_500}
    est = estimate_pod(cfg, pod, scales)
    assert est[cpu_i] == round(10_500 * 0.85)  # below cap, unchanged

    # zero request+limit floors at 250m / 200Mi on the pod's own tier
    empty_prod = Pod(
        meta=ObjectMeta(name="e"), spec=PodSpec(priority=9500)
    )
    est = estimate_pod(cfg, empty_prod, scales)
    assert est[cpu_i] == 250.0 and est[mem_i] == 200.0
    assert est[bcpu_i] == 0.0
    empty_batch = Pod(
        meta=ObjectMeta(name="b"), spec=PodSpec(priority=5500)
    )
    est = estimate_pod(cfg, empty_batch, scales)
    assert est[bcpu_i] == 250.0 and est[cpu_i] == 0.0


# ---- topology-manager hint merge
# (policy_test.go commonPolicyMergeTestCases / policy.go mergeFilteredHints) ----

from koordinator_tpu.ops.numa import TopologyHint, merge_provider_hints


def test_hint_merge_same_mask_both_preferred():
    """"Two providers, 1 hint each, same mask, both preferred": merged =
    the shared mask, preferred."""
    for mask in (0b01, 0b10):
        got = merge_provider_hints(
            [
                [TopologyHint(affinity=mask, preferred=True)],
                [TopologyHint(affinity=mask, preferred=True)],
            ],
            n_zones=2,
        )
        assert got.affinity == mask and got.preferred


def test_hint_merge_no_preference_provider_passes_through():
    """"Two providers, 1 no hints, 1 single hint preferred": the silent
    provider contributes a preferred any-NUMA hint."""
    got = merge_provider_hints(
        [None, [TopologyHint(affinity=0b01, preferred=True)]], n_zones=2
    )
    assert got.affinity == 0b01 and got.preferred


def test_hint_merge_conflicting_masks_fall_back_to_default():
    """Disjoint single-zone hints AND to zero and are skipped; the best
    hint stays the non-preferred any-NUMA default (bestEffort admits it,
    restricted/single-numa reject non-preferred)."""
    got = merge_provider_hints(
        [
            [TopologyHint(affinity=0b01, preferred=True)],
            [TopologyHint(affinity=0b10, preferred=True)],
        ],
        n_zones=2,
    )
    assert got.affinity == 0b11 and not got.preferred


def test_hint_merge_narrowest_preferred_wins():
    """A provider offering {0} and {0,1} both preferred against an
    any-NUMA provider: the narrower {0} wins."""
    got = merge_provider_hints(
        [
            [
                TopologyHint(affinity=0b11, preferred=True),
                TopologyHint(affinity=0b01, preferred=True),
            ],
            None,
        ],
        n_zones=2,
    )
    assert got.affinity == 0b01 and got.preferred


def test_hint_merge_cross_mask_permutation_unpreferred():
    """{0} x {0,1}: the merged affinity {0} exists but mixes unequal
    affinities, so it is NOT preferred — yet it still beats the default
    when no preferred candidate exists (policy_best_effort admits it)."""
    got = merge_provider_hints(
        [
            [TopologyHint(affinity=0b01, preferred=True)],
            [TopologyHint(affinity=0b11, preferred=False)],
        ],
        n_zones=2,
    )
    assert got.affinity == 0b01 and not got.preferred


def test_policy_admission_rules():
    """canAdmitPodResult per policy: restricted/single-numa admit only
    preferred results; best-effort admits anything; single-numa filters
    multi-zone hints before merging and degrades an all-NUMA result to a
    nil affinity (policy_single_numa_node.go:47-84)."""
    from koordinator_tpu.core.topology import NUMAPolicy
    from koordinator_tpu.ops.numa import policy_merge

    conflicting = [
        [TopologyHint(affinity=0b01, preferred=True)],
        [TopologyHint(affinity=0b10, preferred=True)],
    ]
    aligned = [
        [TopologyHint(affinity=0b01, preferred=True)],
        [TopologyHint(affinity=0b01, preferred=True)],
    ]
    multi_zone = [[TopologyHint(affinity=0b11, preferred=True)]]

    best, admit = policy_merge(aligned, 2, NUMAPolicy.SINGLE_NUMA_NODE)
    assert admit and best.affinity == 0b01
    best, admit = policy_merge(conflicting, 2, NUMAPolicy.SINGLE_NUMA_NODE)
    assert not admit
    # multi-zone hint filtered out under single-numa: merge degrades to the
    # nil-affinity default and the pod is rejected
    best, admit = policy_merge(multi_zone, 2, NUMAPolicy.SINGLE_NUMA_NODE)
    assert not admit and best.affinity is None

    best, admit = policy_merge(conflicting, 2, NUMAPolicy.RESTRICTED)
    assert not admit
    best, admit = policy_merge(conflicting, 2, NUMAPolicy.BEST_EFFORT)
    assert admit and best.affinity == 0b11 and not best.preferred
    _best, admit = policy_merge(conflicting, 2, NUMAPolicy.NONE)
    assert admit


def test_cpu_evict_release_amount():
    """calculateResourceMilliToRelease: release = request x (upper% -
    satisfactionRate); skip when satisfaction is above the lower bound or
    the gap is non-positive."""
    from koordinator_tpu.koordlet.qosmanager import cpu_evict

    pods = [(f"p{i}", 2_000.0, 5000) for i in range(10)]
    # request 20C, realLimit 6C -> satisfaction 0.3 < lower 0.35;
    # release = 20C x (0.4 - 0.3) = 2C -> one 2C victim
    dec = cpu_evict(
        20_000, 5_900, 6_000, 0.35, 90.0, pods,
        satisfaction_upper_threshold=0.40,
    )
    assert dec.evict and len(dec.victims) == 1
    # satisfaction above lower bound: no eviction
    dec = cpu_evict(
        20_000, 5_900, 8_000, 0.35, 90.0, pods,
        satisfaction_upper_threshold=0.40,
    )
    assert not dec.evict
    # usage below the saturation gate: no eviction
    dec = cpu_evict(
        20_000, 1_000, 6_000, 0.35, 90.0, pods,
        satisfaction_upper_threshold=0.40,
    )
    assert not dec.evict


def test_burst_limiter_token_bucket():
    """burstLimiter (cpu_burst.go:112-163): capacity = period x (scale -
    100); overuse drains (usage - 100) x dt, usage < 60% refills
    (100 - usage) x dt, clamped to +-capacity; burst allowed while
    tokens > 0."""
    from koordinator_tpu.koordlet.qosmanager import BurstLimiter

    lim = BurstLimiter(
        burst_period_s=300, max_scale_percent=200, now=0.0, init_ratio=0.25
    )
    assert lim.capacity == 300 * 100
    assert lim.tokens == 7500
    # sustained 150% usage: drains 50 tokens/s; 7500/50 = 150s to empty
    ok, tokens = lim.allow(100.0, 150)     # -5000
    assert ok and tokens == 2500
    ok, tokens = lim.allow(160.0, 150)     # -3000 -> -500: burst denied
    assert not ok and tokens == -500
    # idle at 40%: refills 60 tokens/s
    ok, tokens = lim.allow(260.0, 40)      # +6000 -> 5500
    assert ok and tokens == 5500
    # clamped at capacity
    ok, tokens = lim.allow(5000.0, 0)
    assert tokens == lim.capacity
    # 80% usage neither drains nor saves (60 <= u < 100)
    ok, tokens = lim.allow(5010.0, 80)
    assert tokens == lim.capacity
    assert not lim.expired(5020.0)
    assert lim.expired(5011.0 + 600.0)


def test_device_request_conversion_parity():
    """Reference ``deviceshare/utils_test.go:323+`` TestConvertDeviceRequest
    — the request-normalization table, expressed through
    parse_gpu_request_vector's (whole, core%, memory-ratio%, bytes)
    vector: nvidia.com/gpu multiplies to core/ratio 100s per device,
    koordinator.sh/gpu mirrors into both percentage dims, and the
    explicit per-dim combinations pass through untouched."""
    from koordinator_tpu.api import extension as ext

    v = ext.parse_gpu_request_vector
    # "nvidiaGPU": 2 -> gpu-core 200 / memory-ratio 200 == 2 whole devices
    assert v({ext.RES_GPU: 2}) == (2, 0.0, 0.0, None)
    # "koordGPU": gpu 50 -> core 50 / ratio 50
    assert v({ext.RES_KOORD_GPU: 50}) == (0, 50.0, 50.0, None)
    # "gpuCore | gpuMemoryRatio": 50/50 passes through
    assert v({ext.RES_GPU_CORE: 50, ext.RES_GPU_MEMORY_RATIO: 50}) == (
        0, 50.0, 50.0, None,
    )
    # "gpuCore | gpuMemory": core 50 + 32Gi bytes passes through
    gib32 = 32 * 1024**3
    assert v({ext.RES_GPU_CORE: 50, ext.RES_GPU_MEMORY: gib32}) == (
        0, 50.0, 0.0, float(gib32),
    )
    # asymmetric dims stay independent (the r2 review's missing #3)
    assert v({ext.RES_GPU_CORE: 20, ext.RES_GPU_MEMORY_RATIO: 70}) == (
        0, 20.0, 70.0, None,
    )
    # whole-device split only on equal multiples of 100
    assert v({ext.RES_GPU_CORE: 200, ext.RES_GPU_MEMORY_RATIO: 200}) == (
        2, 0.0, 0.0, None,
    )
