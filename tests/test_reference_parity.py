"""Golden parity against the reference's own unit-test tables.

Each case here reproduces an entry of the reference's table tests with the
same inputs and asserts the same expected value — the safety net SURVEY §7
hard part (d) calls for. Sources cited per case.
"""

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.ops.costs import load_aware_cost

GI = 1024.0                      # MiB per Gi (snapshot memory unit is MiB)
NODE_ALLOC = np.array([[96_000.0, 512 * GI]], np.float32)   # 96C / 512Gi
WEIGHTS = jnp.ones(2, jnp.float32)


def score_of(est_cpu_milli, est_mem_mib, used_cpu_milli, used_mem_mib, fresh=True):
    est = jnp.asarray([[est_cpu_milli, est_mem_mib]], jnp.float32)
    used = jnp.asarray([[used_cpu_milli, used_mem_mib]], jnp.float32)
    cost = load_aware_cost(
        est,
        used,
        jnp.asarray(NODE_ALLOC),
        WEIGHTS,
        metric_fresh=jnp.asarray([fresh]),
    )
    return -float(np.asarray(cost)[0, 0])


# pod requests 16C/32Gi; the default estimator scales cpu x0.85, mem x0.7
# (estimator/default_estimator.go) -> 13600m / 22.4Gi
EST_CPU = 16_000 * 0.85
EST_MEM = 32 * GI * 0.7


def test_score_empty_node_is_90():
    """load_aware_test.go TestScore "score empty node": wantScore 90."""
    assert score_of(EST_CPU, EST_MEM, 0.0, 0.0) == 90.0


def test_score_loaded_node_is_72():
    """"score load node": usage 32C/10Gi -> wantScore 72 (only reproduced
    under the reference's per-resource + final integer flooring:
    cpu 52.5 -> 52, mem 93.67 -> 93, (52+93)/2 -> 72)."""
    assert score_of(EST_CPU, EST_MEM, 32_000.0, 10 * GI) == 72.0


def test_score_expired_metric_is_0():
    """"score node with expired nodeMetric": wantScore 0 — still
    schedulable, ranked last."""
    assert score_of(EST_CPU, EST_MEM, 0.0, 0.0, fresh=False) == 0.0


def test_score_with_assigned_pod_estimate_is_81():
    """"score load node with p95 but have not reported usage and have
    assigned pods": zero reported usage + one assigned 16C/32Gi pod
    estimated at 13.6C/22.4Gi -> wantScore 81."""
    assert score_of(EST_CPU, EST_MEM, EST_CPU, EST_MEM) == 81.0


def test_score_usage_plus_assigned_is_63():
    """"score load node with just assigned pod": usage 32C/10Gi plus an
    assigned pod's estimate on top -> wantScore 63."""
    assert (
        score_of(EST_CPU, EST_MEM, 32_000.0 + EST_CPU, 10 * GI + EST_MEM)
        == 63.0
    )
