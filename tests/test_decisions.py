"""Control-plane decision observatory tests (decision-ledger PR).

Covers: the crash-surviving :class:`obs.decisions.DecisionLedger`
(seq/cseq stamping, dead-writer tail adoption over a shared journal
store, the 2x-capacity store compaction bound, storage-failure
containment); the shadow-policy harness (proposals recorded + diffed,
``shadow_divergence_total``, a shadow can neither act nor perturb the
acting controller's evidence); the complete-input-snapshot contract for
all FIVE controllers — every recorded decision is recomputed from its
RECORDED inputs alone, after a JSON round-trip, and must reproduce
bit-exactly; the ``/debug/decisions`` surfaces (ServicesEngine + the
fleet's per-shard aggregation); and the ``tools/decision_replay.py``
offline counterfactual replay (self-replay exit 0, drift exit 1,
candidate-policy divergence reports, reward sums).
"""

import json
import sys
from pathlib import Path

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.extension import PriorityClass
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.journal import MemoryJournalStore
from koordinator_tpu.obs.decisions import (
    DecisionLedger,
    action_label,
    controller_gaps,
    decision_trace,
)
from koordinator_tpu.obs.shadow import (
    NO_PROPOSAL,
    AlwaysDivergeShadow,
    MirrorShadow,
    ShadowPolicy,
    ShadowRegistry,
)
from koordinator_tpu.runtime.elastic import TopologyController
from koordinator_tpu.runtime.overload import (
    AdmissionController,
    BrownoutController,
    CircuitBreaker,
    OverloadConfig,
)
from koordinator_tpu.runtime.shards import ShardFabric
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.pipeline import _DepthController
from koordinator_tpu.utils.metrics import Registry
from tools.decision_replay import deterministic_policies, load_records, replay


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


class FakeSlo:
    """Per-(shard, metric) burn rates, settable by the test."""

    def __init__(self):
        self.burns = {}

    def set_burn(self, shard, burn):
        self.burns[int(shard)] = float(burn)

    def burn_rate(self, shard, metric):
        return self.burns.get(int(shard), 0.0)

    def evaluate(self):
        return {s: {} for s in self.burns}


PRIO = {
    PriorityClass.PROD: 9000,
    PriorityClass.MID: 7500,
    PriorityClass.BATCH: 5500,
    PriorityClass.FREE: 3500,
}


def _pod(name, band=PriorityClass.BATCH):
    return Pod(
        meta=ObjectMeta(name=name, uid=name),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000.0, ext.RES_MEMORY: 2048.0},
            priority=PRIO[band],
        ),
    )


def _roundtrip(records):
    """The wire shape: what the journal store / replay tool sees."""
    return json.loads(json.dumps(records))


def _recompute_all(records):
    """The complete-input-snapshot contract: every recorded decision
    must be reproducible from its RECORDED inputs alone — through the
    acting controller's own pure decide(), after a JSON round-trip."""
    deciders = deterministic_policies()
    assert records, "no decisions recorded"
    for rec in _roundtrip(records):
        action, _state = deciders[rec["controller"]](rec["inputs"])
        assert action == rec["action"], (
            f"{rec['controller']} cseq={rec['cseq']}: recorded "
            f"{rec['action']} but inputs recompute to {action}"
        )


# ---------------------------------------------------------------------------
# the ledger core
# ---------------------------------------------------------------------------


class TestDecisionLedgerCore:
    def test_record_stamps_seq_cseq_shard_and_outcome(self):
        clk = FakeClock(5.0)
        dl = DecisionLedger(shard=3, incarnation="inc-a", clock=clk)
        r1 = dl.record("depth", 1, {"x": 1}, {"depth": 2}, {"depth": 2})
        r2 = dl.record(
            "brownout", 1, {"burn": 0.5}, {"op": "hold", "to": 0},
            {"level": 0}, outcome={"burn": 0.5},
        )
        r3 = dl.record(
            "depth", 2, {"x": 2}, {"depth": 1}, {"depth": 1}, shard=7
        )
        assert [r["seq"] for r in (r1, r2, r3)] == [1, 2, 3]
        assert (r1["cseq"], r2["cseq"], r3["cseq"]) == (1, 1, 2)
        assert r1["shard"] == 3 and r3["shard"] == 7  # explicit wins
        assert r1["incarnation"] == "inc-a" and r1["t"] == 5.0
        assert r2["outcome"] == {"burn": 0.5} and "outcome" not in r1
        assert dl.last(1) == [r3] and len(dl.last()) == 3
        assert controller_gaps(dl.last()) == {}

    def test_action_label_vocabulary(self):
        assert action_label({"op": "escalate", "to": 2}) == "escalate"
        assert action_label({"verdict": "shed"}) == "shed"
        assert action_label({"depth": 4}) == "depth=4"
        assert action_label({"weird": 1}) == "other"
        assert action_label("raw") == "raw"

    def test_metrics_count_decisions_per_controller_and_action(self):
        reg = Registry()
        dl = DecisionLedger()
        dl.bind_registry(reg)
        dl.bind_registry(Registry())  # first caller wins
        dl.record("depth", 1, {}, {"depth": 2}, {})
        dl.record("depth", 2, {}, {"depth": 2}, {})
        dl.record("brownout", 1, {}, {"op": "hold", "to": 0}, {})
        ct = reg.get("controller_decisions_total")
        assert ct.value(controller="depth", action="depth=2") == 2.0
        assert ct.value(controller="brownout", action="hold") == 1.0

    def test_takeover_adopts_tail_and_continues_cseq(self):
        store = MemoryJournalStore()
        a = DecisionLedger(store, incarnation="inc-a")
        for i in range(3):
            a.record("depth", i + 1, {"i": i}, {"depth": 1}, {})
        a.record("brownout", 1, {}, {"op": "hold", "to": 0}, {})
        # inc-a dies; inc-b adopts the shared store's tail
        b = DecisionLedger(store, incarnation="inc-b")
        assert len(b.last()) == 4
        rec = b.record("depth", 4, {"i": 3}, {"depth": 1}, {})
        assert rec["seq"] == 5 and rec["cseq"] == 4  # continues, no gap
        assert controller_gaps(b.last()) == {}
        adopted = b.recovered_records()
        assert len(adopted) == 4
        assert all(r["incarnation"] == "inc-a" for r in adopted)
        doc = json.loads(b.render())
        assert doc["decisions"] == 5 and doc["recovered"] == 4
        assert doc["records"][0]["recovered"] is True
        assert doc["records"][-1]["recovered"] is False

    def test_store_compaction_bounded_by_2x_capacity(self):
        store = MemoryJournalStore()
        dl = DecisionLedger(store, capacity=8)
        for i in range(100):
            dl.record("depth", i + 1, {"i": i}, {"depth": 1}, {})
        assert len(dl.last()) == 8  # ring holds the tail
        assert len(store.load()) <= 2 * 8  # compaction keeps the bound
        # and the survivors are the NEWEST records
        survived = sorted(r["seq"] for r in store.load())
        assert survived[-1] == 100

    def test_storage_failure_degrades_to_ring_only(self):
        class BadStore:
            def load(self):
                return []

            def append(self, rec):
                raise IOError("disk gone")

            def rewrite(self, recs):
                raise IOError("disk gone")

        dl = DecisionLedger(BadStore())
        rec = dl.record("depth", 1, {}, {"depth": 1}, {})
        assert rec["seq"] == 1 and dl.last() == [rec]

    def test_controller_gaps_flags_holes_and_duplicates(self):
        ok = [
            {"controller": "a", "cseq": 2},
            {"controller": "a", "cseq": 3},
            {"controller": "b", "cseq": 1},
        ]
        assert controller_gaps(ok) == {}
        hole = ok + [{"controller": "a", "cseq": 6}]
        assert controller_gaps(hole) == {"a": [4, 5]}
        dupe = ok + [{"controller": "b", "cseq": 1}]
        assert "b" in controller_gaps(dupe)

    def test_decision_trace_drops_only_wall_time_shadow_and_crc(self):
        dl = DecisionLedger(incarnation="inc-a")
        dl.attach_shadow(ShadowRegistry())
        dl.shadow.attach("depth", AlwaysDivergeShadow())
        dl.record("depth", 1, {"x": 1}, {"depth": 2}, {"depth": 2})
        (proj,) = decision_trace(dl.last())
        assert "t" not in proj and "shadow" not in proj
        assert proj["inputs"] == {"x": 1} and proj["cseq"] == 1
        assert proj["incarnation"] == "inc-a"
        # store-loaded records carry the codec's crc seal on top; the
        # trace drops it too (the crc covers t/shadow, so it inherits
        # their run-to-run variance) — the same record projects
        # identically from the ring and from the store
        (sproj,) = decision_trace(dl.store.load())
        assert "crc" not in sproj
        assert sproj == proj


# ---------------------------------------------------------------------------
# the shadow harness
# ---------------------------------------------------------------------------


class TestShadowHarness:
    def _ledger(self, reg=None):
        dl = DecisionLedger()
        if reg is not None:
            dl.bind_registry(reg)
        dl.attach_shadow(ShadowRegistry())
        return dl

    def test_divergence_recorded_and_counted(self):
        reg = Registry()
        dl = self._ledger(reg)
        dl.shadow.attach("depth", AlwaysDivergeShadow())
        rec = dl.record("depth", 1, {"x": 1}, {"depth": 2}, {})
        assert rec["shadow"]["diverged"] is True
        assert rec["shadow"]["proposal"] == {"op": "__shadow_diverge__"}
        assert reg.get("shadow_divergence_total").value(
            controller="depth"
        ) == 1.0

    def test_mirror_shadow_agrees(self):
        reg = Registry()
        dl = self._ledger(reg)
        dl.shadow.attach("depth", MirrorShadow(_DepthController.decide))
        inputs = {
            "max_depth": 4, "depth": 4, "window": [], "discard_rate": 0.0,
            "quiet_feeds": 0,
        }
        action, state = _DepthController.decide(inputs)
        rec = dl.record("depth", 1, inputs, action, state)
        assert rec["shadow"]["diverged"] is False
        assert rec["shadow"]["proposal"] == action
        assert reg.get("shadow_divergence_total").value(
            controller="depth"
        ) == 0.0

    def test_shadow_sees_a_copy_never_the_acting_evidence(self):
        class Mutator(ShadowPolicy):
            def propose(self, inputs):
                inputs["window"].append(False)  # vandalize the snapshot
                return {"depth": 1}

        dl = self._ledger()
        dl.shadow.attach("depth", Mutator())
        inputs = {"window": [True]}
        rec = dl.record("depth", 1, inputs, {"depth": 2}, {})
        assert inputs == {"window": [True]}  # acting evidence untouched
        assert rec["inputs"] is inputs

    def test_shadow_crash_is_contained(self):
        class Crasher(ShadowPolicy):
            def propose(self, inputs):
                raise RuntimeError("candidate policy bug")

        dl = self._ledger()
        dl.shadow.attach("depth", Crasher())
        rec = dl.record("depth", 1, {}, {"depth": 1}, {})
        assert "shadow" not in rec  # dropped, never raised

    def test_unregistered_controller_gets_no_shadow_annotation(self):
        dl = self._ledger()
        dl.shadow.attach("depth", AlwaysDivergeShadow())
        rec = dl.record("brownout", 1, {}, {"op": "hold", "to": 0}, {})
        assert "shadow" not in rec

    def test_registry_attach_detach(self):
        sr = ShadowRegistry()
        assert sr.propose("depth", {}) is NO_PROPOSAL
        sr.attach("depth", AlwaysDivergeShadow())
        assert "depth" in sr.policies()
        assert sr.propose("depth", {}) == {"op": "__shadow_diverge__"}
        sr.detach("depth")
        assert sr.propose("depth", {}) is NO_PROPOSAL


# ---------------------------------------------------------------------------
# the complete-input-snapshot contract, per controller
# ---------------------------------------------------------------------------


class TestControllersRecordCompleteInputs:
    def test_depth_controller(self):
        dc = _DepthController(max_depth=4)
        dc.decisions = DecisionLedger()
        # churn: degrade to 1; then a quiet stretch restores the ceiling
        for kept in (False, False, True, False, False, True):
            dc.note_outcome(kept)
            dc.choose()
            dc.note_feed(had_discard=not kept)
        assert dc.depth == 1
        for _ in range(_DepthController.QUIET_FEEDS):
            dc.note_feed(had_discard=False)
        assert dc.choose() == 4
        recs = dc.decisions.last()
        assert [r["tick"] for r in recs] == list(range(1, len(recs) + 1))
        assert {"max_depth", "depth", "window", "discard_rate",
                "quiet_feeds"} <= set(recs[0]["inputs"])
        _recompute_all(recs)

    def test_brownout_controller(self):
        slo = FakeSlo()
        bo = BrownoutController(
            slo, shards=lambda: [0], thresholds=(1.0, 2.0, 4.0, 8.0),
            sustain=2, cooldown=2, clock=FakeClock(),
        )
        bo.attach_decisions(DecisionLedger())
        burns = [0.0, 1.5, 1.5, 1.5, 2.5, 2.5, 0.1, 0.1, 0.1, 0.1, 0.0]
        for cycle, burn in enumerate(burns):
            slo.set_burn(0, burn)
            bo.tick(cycle=cycle)
        assert bo.stats["escalations"] >= 2
        assert bo.stats["deescalations"] >= 1
        recs = bo.decisions.last()
        assert len(recs) == len(burns)
        ops = [r["action"]["op"] for r in recs]
        assert "escalate" in ops and "deescalate" in ops
        # burns recorded RAW: the exact float the threshold compared
        assert recs[1]["inputs"]["burn"] == 1.5
        _recompute_all(recs)

    def test_admission_controller(self):
        clk = FakeClock()
        slo = FakeSlo()
        bo = BrownoutController(
            slo, shards=lambda: [0], sustain=1, clock=clk
        )
        ac = AdmissionController(
            OverloadConfig(
                band_budget={PriorityClass.BATCH: 2,
                             PriorityClass.FREE: 1},
            ),
            brownout=bo,
            clock=clk,
        )
        dl = DecisionLedger()
        ac.attach_decisions(dl)
        bo.attach_decisions(dl)
        assert ac.admit(_pod("p", PriorityClass.PROD), 99) == "admit"
        assert ac.admit(_pod("b0"), 0, shard=1) == "admit"
        assert ac.admit(_pod("b1"), 2) == "defer"  # budget breach
        # push the ladder to L4: FREE sheds, BATCH defers
        slo.set_burn(0, 100.0)
        for cycle in range(4):
            bo.tick(cycle=cycle)
        assert bo.level == BrownoutController.L4
        assert ac.admit(_pod("f0", PriorityClass.FREE), 0) == "shed"
        assert ac.admit(_pod("b2"), 0) == "defer"
        recs = dl.last()
        adm = [r for r in recs if r["controller"] == "admission"]
        assert [r["action"]["verdict"] for r in adm] == [
            "admit", "admit", "defer", "shed", "defer",
        ]
        assert adm[1]["shard"] == 1 and "shard" not in adm[0]
        assert controller_gaps(recs) == {}
        _recompute_all(recs)

    def test_circuit_breaker(self):
        clk = FakeClock()
        cb = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=clk)
        cb.attach_decisions(DecisionLedger())
        assert cb.allow()
        cb.record_failure()
        cb.record_failure()           # trips OPEN
        assert not cb.allow()         # fail fast
        clk.tick(10.0)
        assert cb.allow()             # the half-open probe
        assert not cb.allow()         # behind the probe: deny
        cb.record_success()           # probe heals: CLOSED
        assert cb.allow()
        recs = cb.decisions.last()
        ops = [r["action"]["op"] for r in recs]
        assert ops == [
            "allow", "count_failure", "trip", "deny", "allow", "deny",
            "close", "allow",
        ]
        probe = recs[4]
        assert probe["action"]["probe"] is True
        assert probe["inputs"]["cooldown_elapsed"] is True
        _recompute_all(recs)

    def test_topology_controller(self):
        clk = FakeClock()
        fabric = ShardFabric(2, clock=clk)
        slo = FakeSlo()
        # max_shards == active count: decide records the full streak
        # bookkeeping but never proposes a split this world can't take
        tc = TopologyController(
            fabric, slo, sustain=2, cooldown=2, max_shards=2,
            split_burn=1.0, merge_burn=0.05,
        )
        tc.attach_decisions(DecisionLedger())
        for burn0, burn1 in [(2.0, 0.0), (2.0, 0.0), (0.5, 0.5), (0.0, 0.0)]:
            slo.set_burn(0, burn0)
            slo.set_burn(1, burn1)
            tc.tick()
        recs = tc.decisions.last()
        assert len(recs) == 4
        # hot streak accumulated from the RECORDED burns
        assert recs[1]["state"]["hot"] == {0: 2}
        assert recs[0]["inputs"]["burns"] == {0: 2.0, 1: 0.0}
        _recompute_all(recs)

    def test_topology_decide_proposes_split_and_merge(self):
        # the pure policy over synthetic wire-shaped (string-keyed)
        # snapshots: capacity -> split hottest; all-cold siblings -> merge
        base = {
            "active": [0, 1], "hot": {}, "cold": {},
            "in_cooldown": False, "siblings": [[0, 1]],
            "max_shards": 8, "sustain": 1,
            "split_burn": 1.0, "merge_burn": 0.05,
        }
        action, _ = TopologyController.decide(
            dict(base, burns={"0": 3.0, "1": 9.0})
        )
        assert action == {"op": "split", "shard": 1}
        action, _ = TopologyController.decide(
            dict(base, burns={"0": 0.0, "1": 0.0})
        )
        assert action == {"op": "merge", "pair": [0, 1]}
        action, _ = TopologyController.decide(
            dict(base, burns={"0": 9.0, "1": 0.0}, in_cooldown=True)
        )
        assert action == {"op": "none"}


# ---------------------------------------------------------------------------
# /debug/decisions surfaces
# ---------------------------------------------------------------------------


class TestDebugEndpoints:
    def _sched(self):
        s = BatchScheduler(
            args=LoadAwareArgs(usage_thresholds={}), batch_bucket=16
        )
        s.extender.monitor.stop_background()
        for i in range(4):
            s.snapshot.upsert_node(
                Node(
                    meta=ObjectMeta(name=f"n{i}"),
                    status=NodeStatus(allocatable={
                        ext.RES_CPU: 16_000.0, ext.RES_MEMORY: 65_536.0,
                    }),
                )
            )
        return s

    def test_services_engine_endpoint(self):
        sched = self._sched()
        eng = sched.extender.services
        assert eng.dispatch("GET", "/debug/decisions")[0] == 404
        dl = DecisionLedger(incarnation="inc-a")
        sched.attach_decision_ledger(dl)
        assert dl._registry is sched.extender.registry  # counting wired
        dl.record("depth", 1, {"x": 1}, {"depth": 2}, {"depth": 2})
        code, body = eng.dispatch("GET", "/debug/decisions")
        assert code == 200
        doc = json.loads(body)
        assert doc["decisions"] == 1 and doc["incarnation"] == "inc-a"
        assert doc["records"][0]["action"] == {"depth": 2}

    def test_attach_wires_flight_recorder_through_ledger(self):
        from koordinator_tpu.obs.flightrecorder import FlightRecorder

        sched = self._sched()
        fr = FlightRecorder(capacity=8, incarnation="inc-a")
        sched.attach_flight_recorder(fr)
        dl = DecisionLedger(incarnation="inc-a")
        sched.attach_decision_ledger(dl)
        assert fr in dl._flights  # single attachment point
        dl.flight_record(cycle=7, brownout={"from": 0, "to": 1, "burn": 2.0})
        assert fr.last(1)[0]["brownout"]["to"] == 1

    def test_fleet_surface_serves_every_owned_shard(self):
        from koordinator_tpu.obs.lifecycle import PodLifecycle
        from koordinator_tpu.obs.slo import SloTracker
        from koordinator_tpu.runtime.shards import ShardedScheduler
        from koordinator_tpu.runtime.statehub import ClusterStateHub

        t = [0.0]
        fabric = ShardFabric(2, clock=lambda: t[0], membership_ttl_s=2.5)
        hub = ClusterStateHub()
        for i in range(8):
            hub.publish(hub.nodes, Node(
                meta=ObjectMeta(name=f"n{i:03d}"),
                status=NodeStatus(allocatable={
                    ext.RES_CPU: 16_000.0, ext.RES_MEMORY: 65_536.0,
                }),
            ))

        def factory(shard, snapshot, fence, journal):
            s = BatchScheduler(
                snapshot, LoadAwareArgs(usage_thresholds={}),
                batch_bucket=16, journal=journal, fence=fence,
            )
            s.extender.monitor.stop_background()
            return s

        inc = ShardedScheduler(
            "inc-a", hub, fabric, factory, max_batch=16,
            lease_duration=3.0, renew_deadline=2.0, retry_period=0.5,
            lifecycle=PodLifecycle(registry=Registry(),
                                   clock=lambda: t[0]),
            slo=SloTracker(clock=lambda: t[0]),
        )
        fabric.membership.heartbeat("inc-a")
        for _ in range(2):
            t[0] += 1.0
            inc.tick()
        try:
            assert set(inc.owned()) == {0, 1}
            for s in (0, 1):
                dl = inc._runtimes[s].sched.decision_ledger
                assert dl is not None and dl.shard == s
                assert dl.incarnation == "inc-a"
                # the per-shard ledger persists over the fabric's
                # decision store — the surface a takeover adopts from
                assert dl.store is fabric.decision_stores[s]
                dl.record("depth", 1, {"s": s}, {"depth": 1}, {})
            code, body = inc.fleet().dispatch("GET", "/debug/decisions")
            assert code == 200
            doc = json.loads(body)
            assert doc["incarnation"] == "inc-a"
            assert set(doc["shards"]) == {"0", "1"}
            for s in (0, 1):
                row = doc["shards"][str(s)]
                assert row["decisions"] == 1
                assert row["records"][0]["inputs"] == {"s": s}
            # disabled fleet: no ledgers, an empty (not erroring) doc
            inc2 = ShardedScheduler(
                "inc-b", hub, ShardFabric(1, clock=lambda: t[0]),
                factory, max_batch=16, decisions=False,
                lease_duration=3.0, renew_deadline=2.0,
                retry_period=0.5,
            )
            code, body = inc2.fleet().dispatch(
                "GET", "/debug/decisions"
            )
            assert code == 200 and json.loads(body)["shards"] == {}
        finally:
            inc.close()


# ---------------------------------------------------------------------------
# offline counterfactual replay (tools/decision_replay.py)
# ---------------------------------------------------------------------------


class TestDecisionReplay:
    def _recorded_ledger(self):
        """A real multi-controller trace: depth churn + a brownout
        episode, all on one ledger."""
        dl = DecisionLedger(incarnation="inc-a")
        dc = _DepthController(max_depth=4)
        dc.decisions = dl
        for kept in (False, False, True, False, True, True):
            dc.note_outcome(kept)
            dc.choose()
        slo = FakeSlo()
        bo = BrownoutController(
            slo, shards=lambda: [0], sustain=1, cooldown=1,
            clock=FakeClock(),
        )
        bo.attach_decisions(dl)
        for cycle, burn in enumerate([0.0, 3.0, 3.0, 0.0, 0.0]):
            slo.set_burn(0, burn)
            bo.tick(cycle=cycle)
        return dl

    def test_self_replay_exits_zero(self, tmp_path, capsys):
        from tools.decision_replay import main

        dl = self._recorded_ledger()
        path = tmp_path / "decisions.json"
        path.write_text(dl.render())
        assert main(["--ledger", str(path)]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["mode"] == "self" and doc["diverged"] == 0
        for row in doc["controllers"].values():
            assert row["agreement_pct"] == 100.0
        # the brownout outcome burns summed as reward inputs
        assert doc["reward"]["burn"] == pytest.approx(6.0)

    def test_tampered_action_is_determinism_drift_exit_1(
        self, tmp_path, capsys
    ):
        from tools.decision_replay import main

        dl = self._recorded_ledger()
        doc = json.loads(dl.render())
        doc["records"][2]["action"] = {"depth": 999}
        path = tmp_path / "tampered.json"
        path.write_text(json.dumps(doc))
        assert main(["--ledger", str(path)]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["diverged"] == 1

    def test_candidate_policy_divergence_report(self):
        dl = self._recorded_ledger()
        records = _roundtrip(dl.last())
        policies = dict(deterministic_policies())
        policies["depth"] = lambda inputs: {"depth": 999}  # bare action
        report = replay(records, policies)
        depth = report["controllers"]["depth"]
        assert depth["agreed"] == 0 and depth["agreement_pct"] == 0.0
        fd = depth["first_divergence"]
        assert fd["proposed"] == {"depth": 999} and fd["cseq"] == 1
        assert fd["inputs"]  # the full snapshot rides in the report
        # the acting brownout policy still agrees with itself
        assert report["controllers"]["brownout"]["agreement_pct"] == 100.0
        assert report["diverged"] == depth["total"]

    def test_load_records_accepts_all_three_shapes(self):
        recs = [{"controller": "depth", "cseq": 1}]
        assert load_records(recs) == recs
        assert load_records({"records": recs}) == recs
        fleet_doc = {
            "shards": {
                "0": {"records": recs},
                "1": {"records": recs},
            }
        }
        assert load_records(fleet_doc) == recs + recs
        with pytest.raises(ValueError):
            load_records({"what": 1})

    def test_unknown_controller_records_are_skipped_not_fatal(self):
        report = replay([
            {"controller": "mystery", "inputs": {}, "action": {}},
        ])
        assert report["skipped"] == 1 and report["diverged"] == 0
