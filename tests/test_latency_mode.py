"""Latency operating point: kube-scheduler node sampling
(PercentageOfNodesToScore — the reference passes it through at
``cmd/koord-scheduler/app/server.go:411``) + the StreamScheduler's
adaptive-batch continuous admission."""

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta, Pod, PodSpec
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.scheduler.batch_solver import (
    BatchScheduler,
    LoadAwareArgs,
    num_nodes_to_score,
)
from koordinator_tpu.scheduler.stream import StreamScheduler


def test_num_nodes_to_score_upstream_table():
    """Upstream numFeasibleNodesToFind semantics: ≤100 nodes always all
    scored; adaptive = 50 − n/125 floored at 5%; explicit percentage
    honored; result never below 100."""
    assert num_nodes_to_score(80, 0) == 80
    assert num_nodes_to_score(100, 0) == 100
    # adaptive: 1000 nodes → 50 − 8 = 42% → 420
    assert num_nodes_to_score(1000, 0) == 420
    # adaptive at 10k: 50 − 80 → floor 5% → 500
    assert num_nodes_to_score(10_000, 0) == 500
    # explicit percentage
    assert num_nodes_to_score(1000, 20) == 200
    assert num_nodes_to_score(1000, 100) == 1000
    # floor: 1% of 5000 = 50 → clamped to 100
    assert num_nodes_to_score(5000, 1) == 100


def _cluster(n_nodes, cpu=64000):
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i:04d}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}
                ),
            )
        )
    return snap


def _pod(name, cpu=1000):
    return Pod(
        meta=ObjectMeta(name=name),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}),
    )


def test_node_sampling_places_and_accounts_correctly():
    """With a sampled window the solver sees a node subset, but the
    committed assignment uses REAL snapshot indices and the accounting
    matches the assumes exactly. The rotating window visits different
    nodes across cycles."""
    snap = _cluster(400)
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=64,
        percentage_of_nodes_to_score=50,
    )
    sched.extender.monitor.stop_background()
    used_nodes = set()
    for cycle in range(4):
        pods = [_pod(f"c{cycle}-p{i}") for i in range(48)]
        out = sched.schedule(pods)
        assert len(out.bound) == 48
        for _p, node in out.bound:
            used_nodes.add(node)
    # accounting invariant: total requested equals sum of assumes
    want = np.zeros_like(snap.nodes.requested)
    for _uid, ap in snap._assumed.items():
        want[ap.node_idx] += ap.request
    np.testing.assert_allclose(snap.nodes.requested, want, atol=1e-3)
    # the rotating window spread placements beyond one 200-node window
    assert len(used_nodes) > 50


def test_node_sampling_respects_node_name_constraint():
    """A pod pinned via spec.nodeName always reaches its node: the
    sampled window unions hard-constraint node indices (advisor r4), so
    the pin binds EVERY cycle regardless of window rotation."""
    snap = _cluster(300)
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=64,
        percentage_of_nodes_to_score=40,
    )
    sched.extender.monitor.stop_background()
    for cycle in range(3):
        pinned = Pod(
            meta=ObjectMeta(name=f"pin{cycle}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1000},
                node_name="n0007",
            ),
        )
        out = sched.schedule([pinned])
        assert [node for _p, node in out.bound] == ["n0007"], (
            f"cycle {cycle}: {out.bound} {out.unschedulable}"
        )


def test_node_sampling_affinity_names_and_selector():
    """Required node-affinity names are unioned into the window; a label
    nodeSelector (which can match any node) disables sampling for the
    cycle — either way the constrained pod binds where it must."""
    snap = _cluster(300)
    # give one far node a label only selector pods can find
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0250", labels={"disk": "ssd"}),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000}
            ),
        )
    )
    sched = BatchScheduler(
        snap, LoadAwareArgs(), batch_bucket=64,
        percentage_of_nodes_to_score=20,
    )
    sched.extender.monitor.stop_background()
    aff = Pod(
        meta=ObjectMeta(name="aff"),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1000},
            affinity_required_nodes=["n0280"],
        ),
    )
    sel = Pod(
        meta=ObjectMeta(name="sel"),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 1000},
            node_selector={"disk": "ssd"},
        ),
    )
    out = sched.schedule([aff, sel])
    nodes = {p.meta.name: n for p, n in out.bound}
    assert nodes.get("aff") == "n0280", (out.bound, out.unschedulable)
    assert nodes.get("sel") == "n0250", (out.bound, out.unschedulable)


def test_stream_scheduler_latency_and_retry():
    """StreamScheduler decides every submitted pod: bound pods report
    enqueue→bind latency; an unschedulable pod is retried max_retries
    cycles before being surfaced, with its latency clock running from
    the ORIGINAL submit."""
    snap = _cluster(50)
    sched = BatchScheduler(snap, LoadAwareArgs(), batch_bucket=64)
    sched.extender.monitor.stop_background()
    stream = StreamScheduler(sched, max_batch=64, max_retries=2)
    for i in range(10):
        stream.submit(_pod(f"s{i}"))
    giant = _pod("giant", cpu=10**9)
    stream.submit(giant)
    decided = []
    for _ in range(4):
        decided.extend(stream.pump())
        if stream.backlog() == 0:
            break
    by_name = {p.meta.name: (node, lat) for p, node, lat in decided}
    assert all(by_name[f"s{i}"][0] is not None for i in range(10))
    assert all(lat >= 0 for _n, lat in by_name.values())
    # the giant was retried then surfaced unschedulable
    assert by_name["giant"][0] is None
    assert stream.backlog() == 0
