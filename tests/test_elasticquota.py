"""ElasticQuota tests: fair-share water-filling, quota tree runtime,
solver admission (reference ``pkg/scheduler/plugins/elasticquota``)."""

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    ElasticQuota,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot, SnapshotConfig
from koordinator_tpu.ops.solver import (
    NodeState,
    PodBatch,
    QuotaState,
    SolverParams,
    assign,
    assign_sequential,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.elasticquota import (
    GroupQuotaManager,
    water_fill,
)


def quota(name, minv=None, maxv=None, weight=None, parent=""):
    def rl(v):
        return {ext.RES_CPU: v[0], ext.RES_MEMORY: v[1]} if v else {}

    return ElasticQuota(
        meta=ObjectMeta(name=name),
        min=rl(minv),
        max=rl(maxv),
        shared_weight=rl(weight),
        parent=parent,
    )


def quota_pod(name, q, cpu=4.0, prio=9000):
    return Pod(
        meta=ObjectMeta(name=name, labels={ext.LABEL_QUOTA_NAME: q}),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}, priority=prio),
    )


# ---- water filling ----


def test_water_fill_min_guarantee_and_weight_share():
    total = np.array([100.0], np.float32)
    guaranteed = np.array([[20.0], [10.0], [0.0]], np.float32)
    caps = np.array([[100.0], [100.0], [100.0]], np.float32)
    weights = np.array([[1.0], [1.0], [2.0]], np.float32)
    rt = water_fill(total, guaranteed, caps, weights)
    # guarantees honored
    assert (rt >= guaranteed - 1e-4).all()
    # everything distributed — up to the reference's per-child integer
    # rounding (iterationForRedistribution rounds each delta with +0.5,
    # which may overdraw by at most one unit per child)
    n_children = rt.shape[0]
    assert (rt.sum(axis=0) >= total - 1e-4).all()
    assert (rt.sum(axis=0) <= total + n_children).all()
    # remainder 70 split 1:1:2 => +17.5, +17.5, +35, each delta rounded
    # half-up per the reference's iteration (+18, +18, +35)
    np.testing.assert_allclose(rt[:, 0], [38.0, 28.0, 35.0], rtol=1e-5)


def test_water_fill_cap_redistribution():
    total = np.array([90.0], np.float32)
    guaranteed = np.zeros((3, 1), np.float32)
    caps = np.array([[10.0], [100.0], [100.0]], np.float32)
    weights = np.ones((3, 1), np.float32)
    rt = water_fill(total, guaranteed, caps, weights)
    # child 0 saturates at 10; surplus goes to the others equally
    np.testing.assert_allclose(rt[:, 0], [10.0, 40.0, 40.0], rtol=1e-5)


def test_water_fill_total_smaller_than_guarantees():
    total = np.array([10.0], np.float32)
    guaranteed = np.array([[20.0], [10.0]], np.float32)
    caps = np.array([[50.0], [50.0]], np.float32)
    rt = water_fill(total, guaranteed, caps, np.ones((2, 1), np.float32))
    # guarantees kept (reference keeps min even when over-committed;
    # min scaling is a separate mechanism)
    np.testing.assert_allclose(rt[:, 0], [20.0, 10.0])


# ---- GroupQuotaManager ----


def make_tree():
    cfg = SnapshotConfig()
    mgr = GroupQuotaManager(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    mgr.upsert_quota(quota("root-a", minv=(40, 40), maxv=(100, 100), weight=(1, 1)))
    mgr.upsert_quota(quota("root-b", minv=(20, 20), maxv=(60, 60), weight=(1, 1)))
    mgr.upsert_quota(
        quota("a-child-1", minv=(10, 10), maxv=(50, 50), weight=(1, 1), parent="root-a")
    )
    mgr.upsert_quota(
        quota("a-child-2", minv=(0, 0), maxv=(50, 50), weight=(3, 3), parent="root-a")
    )
    return mgr


def test_chain_resolution():
    mgr = make_tree()
    chain = mgr.chain_of("a-child-2")
    assert chain == [mgr.index_of("a-child-2"), mgr.index_of("root-a")]
    assert mgr.chain_of("missing") == []


def test_runtime_respects_demand_and_hierarchy():
    mgr = make_tree()
    big = np.array([80.0, 80.0, 0, 0], np.float32)
    mgr.set_leaf_requests(
        {"a-child-1": big, "a-child-2": big, "root-b": np.array([80.0, 80.0, 0, 0], np.float32)}
    )
    rt = mgr.refresh_runtime()
    ia, ib = mgr.index_of("root-a"), mgr.index_of("root-b")
    i1, i2 = mgr.index_of("a-child-1"), mgr.index_of("a-child-2")
    # children never exceed parent's runtime beyond the reference's
    # per-child rounding unit
    assert rt[i1][0] + rt[i2][0] <= rt[ia][0] + 2.0
    # mins guaranteed
    assert rt[ia][0] >= 40 - 1e-3 and rt[ib][0] >= 20 - 1e-3
    # root-b capped by max
    assert rt[ib][0] <= 60 + 1e-3
    # total within cluster
    assert rt[ia][0] + rt[ib][0] <= 100 + 1e-3
    # weighted sharing: a-child-2 (w=3) gets more of the surplus than
    # a-child-1 (w=1) beyond its guarantee
    assert (rt[i2][0] - 0) > (rt[i1][0] - 10) - 1e-3


def test_charge_refund_roundtrip():
    mgr = make_tree()
    mgr.refresh_runtime()
    mgr.charge("a-child-1", {ext.RES_CPU: 5, ext.RES_MEMORY: 5})
    i1, ia = mgr.index_of("a-child-1"), mgr.index_of("root-a")
    assert mgr.used[i1][0] == 5 and mgr.used[ia][0] == 5
    mgr.refund("a-child-1", {ext.RES_CPU: 5, ext.RES_MEMORY: 5})
    assert mgr.used[i1][0] == 0 and mgr.used[ia][0] == 0


# ---- solver admission ----


def _quota_fixture(runtime, used, chains, reqs, prios=None):
    p, d = reqs.shape
    pods = PodBatch.create(
        requests=reqs,
        priority=np.full(p, 9000, np.int32) if prios is None else prios,
        quota_chain=chains,
    )
    nodes = NodeState.create(allocatable=np.full((4, d), 1e6, np.float32))
    params = SolverParams(
        usage_thresholds=jnp.zeros(d),
        prod_thresholds=jnp.zeros(d),
        score_weights=jnp.ones(d),
    )
    quotas = QuotaState(
        runtime=jnp.asarray(runtime, jnp.float32), used=jnp.asarray(used, jnp.float32)
    )
    return pods, nodes, params, quotas


def test_solver_quota_admission_caps_usage():
    """Quota 0 has runtime 10; four pods of 4 cpu each -> only 2 admitted."""
    d = 1
    reqs = np.full((4, d), 4.0, np.float32)
    chains = np.full((4, 4), -1, np.int32)
    chains[:, 0] = 0
    runtime = np.array([[10.0]], np.float32)
    used = np.zeros((1, d), np.float32)
    for solver in (assign, assign_sequential):
        pods, nodes, params, quotas = _quota_fixture(runtime, used, chains, reqs)
        out = solver(pods, nodes, params, quotas)
        a = np.asarray(out.assignment)
        assert (a >= 0).sum() == 2, a
        np.testing.assert_allclose(np.asarray(out.quota_used)[0], [8.0])


def test_solver_quota_priority_order():
    """Higher-priority pods win the contended quota."""
    d = 1
    reqs = np.full((3, d), 4.0, np.float32)
    chains = np.full((3, 4), -1, np.int32)
    chains[:, 0] = 0
    prios = np.array([5000, 9500, 7000], np.int32)
    runtime = np.array([[8.0]], np.float32)
    used = np.zeros((1, d), np.float32)
    for solver in (assign, assign_sequential):
        pods, nodes, params, quotas = _quota_fixture(
            runtime, used, chains, reqs, prios
        )
        a = np.asarray(solver(pods, nodes, params, quotas).assignment)
        assert a[1] >= 0 and a[2] >= 0 and a[0] == -1


def test_solver_quota_hierarchy_parent_cap():
    """Two leaves under one parent: parent runtime caps their sum."""
    d = 1
    reqs = np.full((4, d), 4.0, np.float32)
    chains = np.full((4, 4), -1, np.int32)
    chains[0:2, 0] = 0   # leaf A -> parent 2
    chains[2:4, 0] = 1   # leaf B -> parent 2
    chains[:, 1] = 2
    # leaves individually generous, parent tight (8 = two pods)
    runtime = np.array([[16.0], [16.0], [8.0]], np.float32)
    used = np.zeros((3, d), np.float32)
    for solver in (assign, assign_sequential):
        pods, nodes, params, quotas = _quota_fixture(runtime, used, chains, reqs)
        out = solver(pods, nodes, params, quotas)
        a = np.asarray(out.assignment)
        assert (a >= 0).sum() == 2
        qu = np.asarray(out.quota_used)
        assert qu[2][0] <= 8.0 + 1e-3


# ---- end to end ----


def test_end_to_end_quota_scheduling():
    snap = ClusterSnapshot()
    for i in range(4):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 100.0, ext.RES_MEMORY: 100.0}
                ),
            )
        )
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400}
    )
    mgr.upsert_quota(quota("tenant-a", minv=(8, 8), maxv=(12, 12), weight=(1, 1)))
    mgr.upsert_quota(quota("tenant-b", minv=(8, 8), maxv=(400, 400), weight=(1, 1)))
    sched = BatchScheduler(snap, quotas=mgr)
    pods = [quota_pod(f"a{i}", "tenant-a", cpu=4.0) for i in range(5)] + [
        quota_pod(f"b{i}", "tenant-b", cpu=4.0) for i in range(5)
    ]
    out = sched.schedule(pods)
    bound = {p.meta.name for p, _ in out.bound}
    a_bound = [n for n in bound if n.startswith("a")]
    b_bound = [n for n in bound if n.startswith("b")]
    # tenant-a capped at max 12 cpu -> 3 pods; tenant-b unconstrained -> all 5
    assert len(a_bound) == 3, sorted(bound)
    assert len(b_bound) == 5
    # durable accounting
    assert mgr.used[mgr.index_of("tenant-a")][0] == 12.0


# ---- min-quota scaling when over root resource ----


def test_scale_mins_noop_when_capacity_sufficient():
    from koordinator_tpu.scheduler.plugins.elasticquota import scale_mins_over_root

    mins = np.array([[30.0, 10.0], [40.0, 10.0]], np.float32)
    out = scale_mins_over_root(mins, np.array([True, True]), np.array([100.0, 100.0]))
    np.testing.assert_allclose(out, mins)


def test_scale_mins_proportional_shrink():
    from koordinator_tpu.scheduler.plugins.elasticquota import scale_mins_over_root

    # Σ min = 150 > 100: each enabled child scaled by 100/150
    mins = np.array([[100.0, 10.0], [50.0, 10.0]], np.float32)
    out = scale_mins_over_root(mins, np.array([True, True]), np.array([100.0, 100.0]))
    np.testing.assert_allclose(out[:, 0], [100.0 * 100 / 150, 50.0 * 100 / 150], rtol=1e-5)
    np.testing.assert_allclose(out[:, 1], [10.0, 10.0])  # mem dim not oversubscribed


def test_scale_mins_disabled_children_keep_full_min():
    from koordinator_tpu.scheduler.plugins.elasticquota import scale_mins_over_root

    # disabled child keeps 60; enabled children split 100-60=40 by min ratio
    mins = np.array([[60.0], [60.0], [20.0]], np.float32)
    out = scale_mins_over_root(
        mins, np.array([False, True, True]), np.array([100.0])
    )
    np.testing.assert_allclose(out[:, 0], [60.0, 40.0 * 60 / 80, 40.0 * 20 / 80], rtol=1e-5)


def test_manager_scale_min_enabled_shrinks_runtime():
    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    mgr = GroupQuotaManager(
        cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100},
        scale_min_enabled=True,
    )
    mgr.upsert_quota(quota("a", minv=(80, 10), maxv=(100, 100)))
    mgr.upsert_quota(quota("b", minv=(80, 10), maxv=(100, 100)))
    mgr.set_leaf_requests({
        "a": cfg.res_vector({ext.RES_CPU: 200, ext.RES_MEMORY: 5}),
        "b": cfg.res_vector({ext.RES_CPU: 200, ext.RES_MEMORY: 5}),
    })
    rt = mgr.refresh_runtime()
    # scaled min = 50 each; remainder shared evenly → 50/50 split of cpu
    ia, ib = mgr.index_of("a"), mgr.index_of("b")
    np.testing.assert_allclose(rt[ia][0], 50.0, atol=1e-3)
    np.testing.assert_allclose(rt[ib][0], 50.0, atol=1e-3)


# ---- overuse revoke controller ----


def _revoke_fixture():
    from koordinator_tpu.scheduler.plugins.elasticquota import (
        QuotaOverUsedRevokeController,
    )

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    mgr = GroupQuotaManager(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    mgr.upsert_quota(quota("q1", minv=(10, 10), maxv=(100, 100)))
    mgr.upsert_quota(quota("q2", minv=(10, 10), maxv=(100, 100)))
    evicted = []
    clock = {"t": 0.0}
    ctl = QuotaOverUsedRevokeController(
        managers_fn=lambda: [mgr],
        evict_fn=evicted.append,
        delay_evict_time=120.0,
        revoke_pod_interval=1.0,
        now_fn=lambda: clock["t"],
    )
    return cfg, mgr, ctl, evicted, clock


def test_overuse_revoke_waits_for_delay():
    cfg, mgr, ctl, evicted, clock = _revoke_fixture()
    # q1 runtime shrinks to its share once q2 requests arrive; make q1 overused
    for i in range(3):
        mgr.assign_pod("q1", quota_pod(f"p{i}", "q1", cpu=30.0, prio=5000 + i))
    mgr.set_leaf_requests({
        "q1": cfg.res_vector({ext.RES_CPU: 90, ext.RES_MEMORY: 90}),
        "q2": cfg.res_vector({ext.RES_CPU: 90, ext.RES_MEMORY: 90}),
    })
    assert ctl.step() == []          # overused but inside the debounce window
    clock["t"] = 60.0
    assert ctl.step() == []
    clock["t"] = 121.0
    revoked = ctl.step()
    assert revoked, "overuse persisted past delay_evict_time, expected evictions"
    assert evicted == revoked
    # victims are the lowest-priority pods, and only enough to fit runtime
    rt, used = mgr.runtime_and_used_of("q1")
    assert np.all(used <= rt + 1e-5)


def test_overuse_revoke_skips_non_preemptible():
    cfg, mgr, ctl, evicted, clock = _revoke_fixture()
    locked = quota_pod("locked", "q1", cpu=60.0, prio=5000)
    locked.meta.labels[ext.LABEL_PREEMPTIBLE] = "false"
    mgr.assign_pod("q1", locked)
    mgr.assign_pod("q1", quota_pod("soft", "q1", cpu=30.0, prio=9000))
    mgr.set_leaf_requests({
        "q1": cfg.res_vector({ext.RES_CPU: 90, ext.RES_MEMORY: 90}),
        "q2": cfg.res_vector({ext.RES_CPU: 90, ext.RES_MEMORY: 90}),
    })
    ctl.step()
    clock["t"] = 121.0
    revoked = ctl.step()
    assert revoked, "preemptible pod should have been revoked"
    assert all(p.meta.name != "locked" for p in revoked)


def test_overuse_revoke_assign_back_keeps_fitting_pods():
    cfg, mgr, ctl, evicted, clock = _revoke_fixture()
    # runtime will be 50 cpu; pods: 40 + 20 + 20. Walk least-important first
    # revokes p-low(20) then p-mid(20); assign-back readmits p-mid (40+20≤50? no)
    # → readmits whichever fits. Verify final used ≤ runtime and minimal set.
    mgr.assign_pod("q1", quota_pod("p-high", "q1", cpu=40.0, prio=9900))
    mgr.assign_pod("q1", quota_pod("p-mid", "q1", cpu=10.0, prio=9000))
    mgr.assign_pod("q1", quota_pod("p-low", "q1", cpu=20.0, prio=5000))
    mgr.set_leaf_requests({
        "q1": cfg.res_vector({ext.RES_CPU: 70, ext.RES_MEMORY: 70}),
        "q2": cfg.res_vector({ext.RES_CPU: 70, ext.RES_MEMORY: 70}),
    })
    ctl.step()  # registers monitors at t=0; debounce runs from here
    clock["t"] = 121.0
    revoked = ctl.step()
    names = {p.meta.name for p in revoked}
    assert "p-high" not in names      # most important survives
    rt, used = mgr.runtime_and_used_of("q1")
    assert np.all(used <= rt + 1e-5)
    # p-mid (10 cpu) fits back next to p-high (40) under runtime 50
    assert "p-mid" not in names


# ---- multi-tree handler ----


def test_quota_tree_handler_routes_and_rebalances_totals():
    from koordinator_tpu.scheduler.plugins.elasticquota import QuotaTreeHandler

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    h = QuotaTreeHandler(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    root = quota("tree-a-root", minv=(0, 0))
    root.tree_id = "tree-a"
    root.is_root = True
    root.total_resource = {ext.RES_CPU: 40, ext.RES_MEMORY: 40}
    h.on_quota_upsert(root)

    # tree root capacity moved out of the default tree
    np.testing.assert_allclose(h.default_manager.cluster_total, [60.0, 60.0])
    np.testing.assert_allclose(
        h.manager_for_tree("tree-a").cluster_total, [40.0, 40.0]
    )

    leaf = quota("team-x", minv=(10, 10), maxv=(40, 40))
    leaf.tree_id = "tree-a"
    leaf.parent = "tree-a-root"
    h.on_quota_upsert(leaf)
    assert h.manager_for_quota("team-x") is h.manager_for_tree("tree-a")

    # shrinking the root total gives capacity back to the default tree
    root2 = quota("tree-a-root", minv=(0, 0))
    root2.tree_id = "tree-a"
    root2.is_root = True
    root2.total_resource = {ext.RES_CPU: 30, ext.RES_MEMORY: 30}
    h.on_quota_upsert(root2)
    np.testing.assert_allclose(h.default_manager.cluster_total, [70.0, 70.0])

    # deleting the root returns everything
    h.on_quota_delete(root2)
    np.testing.assert_allclose(h.default_manager.cluster_total, [100.0, 100.0])


def test_quota_tree_handler_ignore_default_tree():
    from koordinator_tpu.scheduler.plugins.elasticquota import QuotaTreeHandler

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    h = QuotaTreeHandler(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    root = quota("iso-root", minv=(0, 0))
    root.tree_id = "iso"
    root.is_root = True
    root.ignore_default_tree = True
    root.total_resource = {ext.RES_CPU: 40, ext.RES_MEMORY: 40}
    h.on_quota_upsert(root)
    np.testing.assert_allclose(h.default_manager.cluster_total, [100.0, 100.0])


def _tree_root(name, tree, cpu, ignore=False):
    q = quota(name, minv=(0, 0))
    q.tree_id = tree
    q.is_root = True
    q.ignore_default_tree = ignore
    q.total_resource = {ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}
    return q


def test_tree_root_delete_keeps_children_and_accounting():
    from koordinator_tpu.scheduler.plugins.elasticquota import QuotaTreeHandler

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    h = QuotaTreeHandler(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    h.on_quota_upsert(_tree_root("a-root", "tree-a", 40))
    leaf = quota("team-x", minv=(10, 10), maxv=(40, 40))
    leaf.tree_id = "tree-a"
    leaf.parent = "a-root"
    h.on_quota_upsert(leaf)
    mgr = h.manager_for_tree("tree-a")
    mgr.assign_pod("team-x", quota_pod("p0", "team-x", cpu=5.0))

    h.on_quota_delete(_tree_root("a-root", "tree-a", 40))
    # children + their used accounting survive in the SAME manager
    assert h.manager_for_quota("team-x") is mgr
    assert "team-x" in mgr.all_quota_names()
    assert mgr.pods_assigned("team-x")
    # but the orphaned tree has no capacity, and default got its 40 back
    np.testing.assert_allclose(mgr.cluster_total, [0.0, 0.0])
    np.testing.assert_allclose(h.default_manager.cluster_total, [100.0, 100.0])


def test_tree_totals_conserved_when_oversubscribed():
    from koordinator_tpu.scheduler.plugins.elasticquota import QuotaTreeHandler

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    h = QuotaTreeHandler(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    h.on_quota_upsert(_tree_root("a-root", "tree-a", 80))
    np.testing.assert_allclose(h.default_manager.cluster_total, [20.0, 20.0])
    # tree-b wants 80 but only 20 remains: deduction clamps at 20
    h.on_quota_upsert(_tree_root("b-root", "tree-b", 80))
    np.testing.assert_allclose(h.default_manager.cluster_total, [0.0, 0.0])
    # deleting tree-b returns exactly the 20 it took, not its declared 80
    h.on_quota_delete(_tree_root("b-root", "tree-b", 80))
    np.testing.assert_allclose(h.default_manager.cluster_total, [20.0, 20.0])


def test_quota_tree_change_migrates_registration():
    from koordinator_tpu.scheduler.plugins.elasticquota import QuotaTreeHandler

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    h = QuotaTreeHandler(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    q = quota("mover", minv=(10, 10), maxv=(50, 50))
    h.on_quota_upsert(q)
    assert "mover" in h.default_manager.all_quota_names()
    q2 = quota("mover", minv=(10, 10), maxv=(50, 50))
    q2.tree_id = "tree-a"
    h.on_quota_upsert(q2)
    assert "mover" not in h.default_manager.all_quota_names()
    assert "mover" in h.manager_for_tree("tree-a").all_quota_names()
    assert h.manager_for_quota("mover") is h.manager_for_tree("tree-a")


def test_ignore_default_tree_flag_flips_reconcile():
    from koordinator_tpu.scheduler.plugins.elasticquota import QuotaTreeHandler

    cfg = SnapshotConfig(resources=(ext.RES_CPU, ext.RES_MEMORY))
    h = QuotaTreeHandler(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    h.on_quota_upsert(_tree_root("a-root", "tree-a", 40, ignore=False))
    np.testing.assert_allclose(h.default_manager.cluster_total, [60.0, 60.0])
    # flipping to ignore returns the deducted capacity
    h.on_quota_upsert(_tree_root("a-root", "tree-a", 40, ignore=True))
    np.testing.assert_allclose(h.default_manager.cluster_total, [100.0, 100.0])
    # flipping back deducts again, and delete with the flag set still
    # returns only what was actually taken
    h.on_quota_upsert(_tree_root("a-root", "tree-a", 40, ignore=False))
    np.testing.assert_allclose(h.default_manager.cluster_total, [60.0, 60.0])
    h.on_quota_delete(_tree_root("a-root", "tree-a", 40, ignore=True))
    np.testing.assert_allclose(h.default_manager.cluster_total, [100.0, 100.0])


def test_scheduler_runtime_expands_beyond_min_with_cluster_capacity():
    """The BatchScheduler path must feed cluster capacity into the
    fair-sharing budget: with ample free capacity, a quota whose demand
    exceeds its min gets runtime toward max, not min (reference
    group_quota_manager recomputing total from node events — without the
    sync, admission sticks at the guaranteed tier)."""
    import jax

    from koordinator_tpu.api.types import Node, NodeMetric, NodeStatus, ResourceMetric
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs

    snap = ClusterSnapshot()
    for i in range(8):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 64000, ext.RES_MEMORY: 1 << 18}
                ),
            )
        )
        snap.set_node_metric(
            NodeMetric(
                meta=ObjectMeta(name=f"n{i}"),
                node_usage=ResourceMetric(usage={ext.RES_CPU: 0, ext.RES_MEMORY: 0}),
                update_time=999.0,
            ),
            now=1000.0,
        )
    mgr = GroupQuotaManager(snap.config)
    mgr.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="team"),
            min={ext.RES_CPU: 8000, ext.RES_MEMORY: 1 << 14},
            max={ext.RES_CPU: 256000, ext.RES_MEMORY: 1 << 20},
        )
    )
    pods = [
        Pod(
            meta=ObjectMeta(
                name=f"p{i}", labels={ext.LABEL_QUOTA_NAME: "team"}
            ),
            spec=PodSpec(
                requests={ext.RES_CPU: 2000, ext.RES_MEMORY: 4096},
                priority=9000,
            ),
        )
        for i in range(32)   # 64000m demand >> 8000m min
    ]
    sched = BatchScheduler(snap, LoadAwareArgs(), quotas=mgr, batch_bucket=64)
    sched.extender.monitor.stop_background()
    out = sched.schedule(pods)
    # min admits only 4 pods; cluster-capacity fair sharing admits all 32
    assert len(out.bound) == 32, (len(out.bound), len(out.unschedulable))


# ---- batch-failure preemption (reference elasticquota/preempt.go) ----


def preempt_cluster(max_a=(12, 400)):
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 400.0, ext.RES_MEMORY: 400.0}
            ),
        )
    )
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400}
    )
    mgr.upsert_quota(quota("team-a", minv=(8, 8), maxv=max_a, weight=(1, 1)))
    mgr.upsert_quota(quota("team-b", minv=(8, 8), maxv=(400, 400), weight=(1, 1)))
    sched = BatchScheduler(snap, quotas=mgr)
    sched.extender.monitor.stop_background()
    return snap, mgr, sched


def test_preemption_admits_high_priority_over_quota():
    """Quota team-a full of low-priority pods: a high-priority pod evicts
    the least-important same-quota victim and binds in the same cycle."""
    snap, mgr, sched = preempt_cluster()
    low = [quota_pod(f"low{i}", "team-a", cpu=6.0, prio=5000) for i in range(2)]
    out0 = sched.schedule(low)
    assert len(out0.bound) == 2           # 12 cpu used = team-a max

    high = quota_pod("high", "team-a", cpu=6.0, prio=9500)
    out = sched.schedule([high])
    assert [p.meta.name for p, _ in out.bound] == ["high"]
    assert [p.meta.name for p in out.preempted] == ["low1"]  # stable order: later pod less important
    # accounting: quota used unchanged at max (one out, one in)
    assert mgr.used[mgr.index_of("team-a")][0] == 12.0
    # snapshot charge for the victim is gone
    assert sched.bound_node_of("default/low1") is None


def test_preemption_never_crosses_quota_boundaries():
    """canPreempt requires the same quota: team-b victims are untouchable
    for a team-a preemptor even when nothing else can free headroom."""
    snap, mgr, sched = preempt_cluster()
    victim = quota_pod("b-low", "team-b", cpu=6.0, prio=5000)
    filler = [quota_pod(f"a{i}", "team-a", cpu=6.0, prio=5000) for i in range(2)]
    sched.schedule([victim] + filler)
    high = quota_pod("a-high", "team-a", cpu=200.0, prio=9500)  # over max
    out = sched.schedule([high])
    assert out.bound == []
    assert out.preempted == []            # b-low never considered


def test_preemption_respects_non_preemptible_label():
    # 4-cpu victims: non-preemptible pods must ALSO fit the quota min
    # (8 cpu) under the r4 min-bounded admission (plugin.go:252-262)
    snap, mgr, sched = preempt_cluster()
    low = [quota_pod(f"low{i}", "team-a", cpu=4.0, prio=5000) for i in range(2)]
    for p in low:
        p.meta.labels[ext.LABEL_PREEMPTIBLE] = "false"
    out0 = sched.schedule(low)
    assert len(out0.bound) == 2
    high = quota_pod("high", "team-a", cpu=6.0, prio=9500)
    out = sched.schedule([high])
    assert out.bound == [] and out.preempted == []


def test_preemption_minimal_victim_set():
    """Remove-all-then-reprieve: only as many victims as the preemptor
    needs; more-important victims are reprieved first."""
    snap, mgr, sched = preempt_cluster(max_a=(18, 400))
    low = [
        quota_pod(f"low{i}", "team-a", cpu=6.0, prio=5000 + i * 100)
        for i in range(3)
    ]
    sched.schedule(low)                    # 18 cpu used = max
    high = quota_pod("high", "team-a", cpu=6.0, prio=9500)
    out = sched.schedule([high])
    assert [p.meta.name for p, _ in out.bound] == ["high"]
    # exactly one victim — the lowest-priority pod (low0 @ 5000)
    assert [p.meta.name for p in out.preempted] == ["low0"]


# ---- priority preemption (reservation/preemption.go) ----


def _prio_cluster(n_nodes=2, cpu=16000):
    from koordinator_tpu.api.types import Node, NodeStatus

    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}
                ),
            )
        )
    return snap


def _prio_pod(name, cpu, prio, labels=None):
    return Pod(
        meta=ObjectMeta(name=name, labels=labels or {}),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}, priority=prio
        ),
    )


def test_priority_preemption_evicts_lower_priority():
    """reservation/preemption.go:132-250 SelectVictimsOnNode: a
    high-priority pod failing scheduling evicts the minimal set of
    strictly-lower-priority preemptible pods (remove-all then reprieve
    most-important-first), then lands on retry."""
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    snap = _prio_cluster(n_nodes=2, cpu=16000)
    sched = BatchScheduler(
        snap, batch_bucket=64, enable_priority_preemption=True
    )
    sched.extender.monitor.stop_background()
    # fill both nodes with low-priority pods
    fillers = [_prio_pod(f"low-{i}", 8000, 5500) for i in range(4)]
    out = sched.schedule(fillers)
    assert len(out.bound) == 4
    # a high-priority pod arrives with nowhere to fit
    hi = _prio_pod("hi", 8000, 9500)
    out2 = sched.schedule([hi])
    assert [(p.meta.name) for p, _ in out2.bound] == ["hi"]
    assert len(out2.preempted) == 1          # minimal victim set
    assert out2.preempted[0].meta.name.startswith("low-")


def test_priority_preemption_respects_non_preemptible_and_gate():
    """Non-preemptible victims (label preemptible=false) are never
    selected, and the gate defaults OFF (v1beta3/defaults.go:52)."""
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    # gate off: no preemption even though victims exist
    snap = _prio_cluster(n_nodes=1, cpu=16000)
    sched = BatchScheduler(snap, batch_bucket=64)
    sched.extender.monitor.stop_background()
    assert len(sched.schedule([_prio_pod("low", 16000, 5500)]).bound) == 1
    out = sched.schedule([_prio_pod("hi", 8000, 9500)])
    assert out.bound == [] and out.preempted == []

    # gate on, but the only victim is marked non-preemptible
    snap2 = _prio_cluster(n_nodes=1, cpu=16000)
    sched2 = BatchScheduler(
        snap2, batch_bucket=64, enable_priority_preemption=True
    )
    sched2.extender.monitor.stop_background()
    protected = _prio_pod(
        "prot", 16000, 5500, labels={ext.LABEL_PREEMPTIBLE: "false"}
    )
    assert len(sched2.schedule([protected]).bound) == 1
    out2 = sched2.schedule([_prio_pod("hi", 8000, 9500)])
    assert out2.bound == [] and out2.preempted == []


def test_priority_preemption_reprieves_most_important():
    """Reprieve order: with three victims (5500, 5600, 5700) on one node
    and 8000m needed, the two MOST important victims are reprieved and
    only the least important is evicted."""
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    snap = _prio_cluster(n_nodes=1, cpu=24000)
    sched = BatchScheduler(
        snap, batch_bucket=64, enable_priority_preemption=True
    )
    sched.extender.monitor.stop_background()
    for name, prio in (("a", 5700), ("b", 5600), ("c", 5500)):
        assert len(sched.schedule([_prio_pod(name, 8000, prio)]).bound) == 1
    out = sched.schedule([_prio_pod("hi", 8000, 9500)])
    assert [(p.meta.name) for p, _ in out.bound] == ["hi"]
    assert [v.meta.name for v in out.preempted] == ["c"]


def test_allow_lent_resource_false_reserves_full_min():
    """quota.scheduling.koordinator.sh/allow-lent-resource=false: the
    quota's unused min is NEVER redistributed to siblings (reference
    quotaNode.AllowLentResource in the redistribution)."""
    from koordinator_tpu.core.snapshot import SnapshotConfig

    def build(lent: bool):
        gqm = GroupQuotaManager(
            SnapshotConfig(), cluster_total={ext.RES_CPU: 100}
        )
        gqm.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="hoarder"),
                min={ext.RES_CPU: 60},
                max={ext.RES_CPU: 100},
                allow_lent_resource=lent,
            )
        )
        gqm.upsert_quota(
            ElasticQuota(
                meta=ObjectMeta(name="hungry"),
                min={ext.RES_CPU: 10},
                max={ext.RES_CPU: 100},
            )
        )
        # hoarder demands almost nothing; hungry wants everything
        gqm.set_leaf_requests(
            {
                "hoarder": gqm.config.res_vector({ext.RES_CPU: 5}),
                "hungry": gqm.config.res_vector({ext.RES_CPU: 100}),
            }
        )
        gqm.refresh_runtime()
        cpu = gqm.config.resources.index(ext.RES_CPU)
        rt = {
            n: float(gqm.runtime_and_used_of(n)[0][cpu])
            for n in ("hoarder", "hungry")
        }
        return rt

    lending = build(lent=True)
    hoarding = build(lent=False)
    # with lending, hungry gets ~95 (hoarder keeps only its demand)
    assert lending["hungry"] >= 90.0
    # with lending disabled, hoarder's full 60 min stays reserved
    assert hoarding["hoarder"] >= 60.0
    assert hoarding["hungry"] <= 40.0


def test_quota_status_sync_stamps_annotations():
    """elasticquota/controller.go:160-180: the controller sync stamps
    runtime/request annotations onto every quota object and returns the
    summary; the allow-lent-resource LABEL is honored too."""
    import json as _json

    from koordinator_tpu.core.snapshot import SnapshotConfig

    gqm = GroupQuotaManager(SnapshotConfig(), cluster_total={ext.RES_CPU: 100})
    q = ElasticQuota(
        meta=ObjectMeta(
            name="team",
            labels={ext.LABEL_QUOTA_ALLOW_LENT: "false"},
        ),
        min={ext.RES_CPU: 40},
        max={ext.RES_CPU: 100},
    )
    gqm.upsert_quota(q)
    assert q.allow_lent_resource is False      # label parsed
    gqm.set_leaf_requests(
        {"team": gqm.config.res_vector({ext.RES_CPU: 10})}
    )
    report = gqm.sync_status()
    assert report["team"]["runtime"][ext.RES_CPU] >= 40.0  # full min kept
    stamped = _json.loads(q.meta.annotations[ext.ANNOTATION_QUOTA_RUNTIME])
    assert stamped[ext.RES_CPU] == report["team"]["runtime"][ext.RES_CPU]
    # allow-lent-resource=false pads the stamped request up to min — the
    # unlent guarantee is always demanded from the parent (reference
    # group_quota_manager.go:208-221); the raw demand survives as
    # childRequest
    assert _json.loads(q.meta.annotations[ext.ANNOTATION_QUOTA_REQUEST])[
        ext.RES_CPU
    ] == 40.0
    assert _json.loads(
        q.meta.annotations[ext.ANNOTATION_QUOTA_CHILD_REQUEST]
    )[ext.RES_CPU] == 10.0


def test_preemption_policy_never_blocks_both_preemptors():
    """preemption.go:22-41 LabelPodPreemptionPolicy=Never: preemption is
    never attempted on the pod's behalf — neither the quota preemptor
    nor the priority preemptor fires."""
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler

    snap = _prio_cluster(n_nodes=1, cpu=16000)
    sched = BatchScheduler(
        snap, batch_bucket=64, enable_priority_preemption=True
    )
    sched.extender.monitor.stop_background()
    assert len(sched.schedule([_prio_pod("low", 16000, 5500)]).bound) == 1
    never = _prio_pod(
        "hi-never", 8000, 9500,
        labels={ext.LABEL_POD_PREEMPTION_POLICY: "Never"},
    )
    out = sched.schedule([never])
    assert out.bound == [] and out.preempted == []
    # without the label the same pod preempts
    out2 = sched.schedule([_prio_pod("hi", 8000, 9500)])
    assert len(out2.bound) == 1 and len(out2.preempted) == 1
