"""ElasticQuota tests: fair-share water-filling, quota tree runtime,
solver admission (reference ``pkg/scheduler/plugins/elasticquota``)."""

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    ElasticQuota,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot, SnapshotConfig
from koordinator_tpu.ops.solver import (
    NodeState,
    PodBatch,
    QuotaState,
    SolverParams,
    assign,
    assign_sequential,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.elasticquota import (
    GroupQuotaManager,
    water_fill,
)


def quota(name, minv=None, maxv=None, weight=None, parent=""):
    def rl(v):
        return {ext.RES_CPU: v[0], ext.RES_MEMORY: v[1]} if v else {}

    return ElasticQuota(
        meta=ObjectMeta(name=name),
        min=rl(minv),
        max=rl(maxv),
        shared_weight=rl(weight),
        parent=parent,
    )


def quota_pod(name, q, cpu=4.0, prio=9000):
    return Pod(
        meta=ObjectMeta(name=name, labels={ext.LABEL_QUOTA_NAME: q}),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}, priority=prio),
    )


# ---- water filling ----


def test_water_fill_min_guarantee_and_weight_share():
    total = np.array([100.0], np.float32)
    guaranteed = np.array([[20.0], [10.0], [0.0]], np.float32)
    caps = np.array([[100.0], [100.0], [100.0]], np.float32)
    weights = np.array([[1.0], [1.0], [2.0]], np.float32)
    rt = water_fill(total, guaranteed, caps, weights)
    # guarantees honored
    assert (rt >= guaranteed - 1e-4).all()
    # everything distributed
    np.testing.assert_allclose(rt.sum(axis=0), total, rtol=1e-5)
    # remainder 70 split 1:1:2 => +17.5, +17.5, +35
    np.testing.assert_allclose(rt[:, 0], [37.5, 27.5, 35.0], rtol=1e-5)


def test_water_fill_cap_redistribution():
    total = np.array([90.0], np.float32)
    guaranteed = np.zeros((3, 1), np.float32)
    caps = np.array([[10.0], [100.0], [100.0]], np.float32)
    weights = np.ones((3, 1), np.float32)
    rt = water_fill(total, guaranteed, caps, weights)
    # child 0 saturates at 10; surplus goes to the others equally
    np.testing.assert_allclose(rt[:, 0], [10.0, 40.0, 40.0], rtol=1e-5)


def test_water_fill_total_smaller_than_guarantees():
    total = np.array([10.0], np.float32)
    guaranteed = np.array([[20.0], [10.0]], np.float32)
    caps = np.array([[50.0], [50.0]], np.float32)
    rt = water_fill(total, guaranteed, caps, np.ones((2, 1), np.float32))
    # guarantees kept (reference keeps min even when over-committed;
    # min scaling is a separate mechanism)
    np.testing.assert_allclose(rt[:, 0], [20.0, 10.0])


# ---- GroupQuotaManager ----


def make_tree():
    cfg = SnapshotConfig()
    mgr = GroupQuotaManager(cfg, cluster_total={ext.RES_CPU: 100, ext.RES_MEMORY: 100})
    mgr.upsert_quota(quota("root-a", minv=(40, 40), maxv=(100, 100), weight=(1, 1)))
    mgr.upsert_quota(quota("root-b", minv=(20, 20), maxv=(60, 60), weight=(1, 1)))
    mgr.upsert_quota(
        quota("a-child-1", minv=(10, 10), maxv=(50, 50), weight=(1, 1), parent="root-a")
    )
    mgr.upsert_quota(
        quota("a-child-2", minv=(0, 0), maxv=(50, 50), weight=(3, 3), parent="root-a")
    )
    return mgr


def test_chain_resolution():
    mgr = make_tree()
    chain = mgr.chain_of("a-child-2")
    assert chain == [mgr.index_of("a-child-2"), mgr.index_of("root-a")]
    assert mgr.chain_of("missing") == []


def test_runtime_respects_demand_and_hierarchy():
    mgr = make_tree()
    big = np.array([80.0, 80.0, 0, 0], np.float32)
    mgr.set_leaf_requests(
        {"a-child-1": big, "a-child-2": big, "root-b": np.array([80.0, 80.0, 0, 0], np.float32)}
    )
    rt = mgr.refresh_runtime()
    ia, ib = mgr.index_of("root-a"), mgr.index_of("root-b")
    i1, i2 = mgr.index_of("a-child-1"), mgr.index_of("a-child-2")
    # children never exceed parent's runtime
    assert rt[i1][0] + rt[i2][0] <= rt[ia][0] + 1e-3
    # mins guaranteed
    assert rt[ia][0] >= 40 - 1e-3 and rt[ib][0] >= 20 - 1e-3
    # root-b capped by max
    assert rt[ib][0] <= 60 + 1e-3
    # total within cluster
    assert rt[ia][0] + rt[ib][0] <= 100 + 1e-3
    # weighted sharing: a-child-2 (w=3) gets more of the surplus than
    # a-child-1 (w=1) beyond its guarantee
    assert (rt[i2][0] - 0) > (rt[i1][0] - 10) - 1e-3


def test_charge_refund_roundtrip():
    mgr = make_tree()
    mgr.refresh_runtime()
    mgr.charge("a-child-1", {ext.RES_CPU: 5, ext.RES_MEMORY: 5})
    i1, ia = mgr.index_of("a-child-1"), mgr.index_of("root-a")
    assert mgr.used[i1][0] == 5 and mgr.used[ia][0] == 5
    mgr.refund("a-child-1", {ext.RES_CPU: 5, ext.RES_MEMORY: 5})
    assert mgr.used[i1][0] == 0 and mgr.used[ia][0] == 0


# ---- solver admission ----


def _quota_fixture(runtime, used, chains, reqs, prios=None):
    p, d = reqs.shape
    pods = PodBatch.create(
        requests=reqs,
        priority=np.full(p, 9000, np.int32) if prios is None else prios,
        quota_chain=chains,
    )
    nodes = NodeState.create(allocatable=np.full((4, d), 1e6, np.float32))
    params = SolverParams(
        usage_thresholds=jnp.zeros(d),
        prod_thresholds=jnp.zeros(d),
        score_weights=jnp.ones(d),
    )
    quotas = QuotaState(
        runtime=jnp.asarray(runtime, jnp.float32), used=jnp.asarray(used, jnp.float32)
    )
    return pods, nodes, params, quotas


def test_solver_quota_admission_caps_usage():
    """Quota 0 has runtime 10; four pods of 4 cpu each -> only 2 admitted."""
    d = 1
    reqs = np.full((4, d), 4.0, np.float32)
    chains = np.full((4, 4), -1, np.int32)
    chains[:, 0] = 0
    runtime = np.array([[10.0]], np.float32)
    used = np.zeros((1, d), np.float32)
    for solver in (assign, assign_sequential):
        pods, nodes, params, quotas = _quota_fixture(runtime, used, chains, reqs)
        out = solver(pods, nodes, params, quotas)
        a = np.asarray(out.assignment)
        assert (a >= 0).sum() == 2, a
        np.testing.assert_allclose(np.asarray(out.quota_used)[0], [8.0])


def test_solver_quota_priority_order():
    """Higher-priority pods win the contended quota."""
    d = 1
    reqs = np.full((3, d), 4.0, np.float32)
    chains = np.full((3, 4), -1, np.int32)
    chains[:, 0] = 0
    prios = np.array([5000, 9500, 7000], np.int32)
    runtime = np.array([[8.0]], np.float32)
    used = np.zeros((1, d), np.float32)
    for solver in (assign, assign_sequential):
        pods, nodes, params, quotas = _quota_fixture(
            runtime, used, chains, reqs, prios
        )
        a = np.asarray(solver(pods, nodes, params, quotas).assignment)
        assert a[1] >= 0 and a[2] >= 0 and a[0] == -1


def test_solver_quota_hierarchy_parent_cap():
    """Two leaves under one parent: parent runtime caps their sum."""
    d = 1
    reqs = np.full((4, d), 4.0, np.float32)
    chains = np.full((4, 4), -1, np.int32)
    chains[0:2, 0] = 0   # leaf A -> parent 2
    chains[2:4, 0] = 1   # leaf B -> parent 2
    chains[:, 1] = 2
    # leaves individually generous, parent tight (8 = two pods)
    runtime = np.array([[16.0], [16.0], [8.0]], np.float32)
    used = np.zeros((3, d), np.float32)
    for solver in (assign, assign_sequential):
        pods, nodes, params, quotas = _quota_fixture(runtime, used, chains, reqs)
        out = solver(pods, nodes, params, quotas)
        a = np.asarray(out.assignment)
        assert (a >= 0).sum() == 2
        qu = np.asarray(out.quota_used)
        assert qu[2][0] <= 8.0 + 1e-3


# ---- end to end ----


def test_end_to_end_quota_scheduling():
    snap = ClusterSnapshot()
    for i in range(4):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 100.0, ext.RES_MEMORY: 100.0}
                ),
            )
        )
    mgr = GroupQuotaManager(
        snap.config, cluster_total={ext.RES_CPU: 400, ext.RES_MEMORY: 400}
    )
    mgr.upsert_quota(quota("tenant-a", minv=(8, 8), maxv=(12, 12), weight=(1, 1)))
    mgr.upsert_quota(quota("tenant-b", minv=(8, 8), maxv=(400, 400), weight=(1, 1)))
    sched = BatchScheduler(snap, quotas=mgr)
    pods = [quota_pod(f"a{i}", "tenant-a", cpu=4.0) for i in range(5)] + [
        quota_pod(f"b{i}", "tenant-b", cpu=4.0) for i in range(5)
    ]
    out = sched.schedule(pods)
    bound = {p.meta.name for p, _ in out.bound}
    a_bound = [n for n in bound if n.startswith("a")]
    b_bound = [n for n in bound if n.startswith("b")]
    # tenant-a capped at max 12 cpu -> 3 pods; tenant-b unconstrained -> all 5
    assert len(a_bound) == 3, sorted(bound)
    assert len(b_bound) == 5
    # durable accounting
    assert mgr.used[mgr.index_of("tenant-a")][0] == 12.0
