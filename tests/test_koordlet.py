"""Koordlet tests: metric cache, prediction, qos strategies, runtime hooks
against a fake cgroupfs (temp dir), native collector, daemon loop
(reference ``pkg/koordlet`` — fake-cgroupfs strategy per SURVEY §4)."""

import json
import os

import numpy as np
import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    NodeSLO,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceThresholdStrategy,
)
from koordinator_tpu.koordlet import collectors as col
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import qosmanager as qos
from koordinator_tpu.koordlet import resourceexecutor as rex
from koordinator_tpu.koordlet import runtimehooks as hooks
from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig
from koordinator_tpu.koordlet.prediction import PeakPredictor, PredictorConfig


# ---- metric cache ----


def test_metric_cache_window_and_percentiles():
    cache = mc.MetricCache(capacity_per_series=128)
    for i in range(100):
        cache.append(mc.NODE_CPU_USAGE, "node", float(i), float(i))
    agg = cache.aggregate(mc.NODE_CPU_USAGE, "node", 0.0, 99.0)
    assert agg.count == 100
    assert abs(agg.avg - 49.5) < 1e-6
    assert abs(agg.percentiles["p50"] - 49.5) < 1.0
    assert agg.percentiles["p99"] >= 97.0
    # window restriction
    agg2 = cache.aggregate(mc.NODE_CPU_USAGE, "node", 90.0, 99.0)
    assert agg2.count == 10
    assert cache.latest(mc.NODE_CPU_USAGE, "node") == (99.0, 99.0)


def test_metric_cache_ring_overwrite_and_gc():
    cache = mc.MetricCache(capacity_per_series=16)
    for i in range(40):
        cache.append("m", "s", float(i), float(i))
    agg = cache.aggregate("m", "s", 0.0, 100.0)
    assert agg.count == 16          # only the newest 16 survive
    assert agg.percentiles["p50"] >= 24
    cache.append("old", "s", 1.0, 1.0)
    assert cache.gc(before=10.0) == 1
    assert cache.aggregate("old", "s", 0.0, 100.0) is None


# ---- prediction ----


def test_predictor_peak_and_decay():
    pred = PeakPredictor(PredictorConfig(half_life_s=100.0))
    for i in range(200):
        pred.observe("pod-a", 1000.0, float(i))
    peak = pred.peak("pod-a", 95.0)
    assert peak is not None and 900 <= peak <= 1300
    # new regime at much higher usage: after decay, peak follows
    for i in range(200, 1200):
        pred.observe("pod-a", 4000.0, float(i))
    peak2 = pred.peak("pod-a", 95.0)
    assert peak2 > 3500
    assert pred.peak("missing") is None


def test_predictor_vectorized_peaks_and_checkpoint(tmp_path):
    pred = PeakPredictor()
    for i in range(50):
        pred.observe("a", 100.0, float(i))
        pred.observe("b", 2000.0, float(i))
    peaks = pred.peaks(95.0)
    assert set(peaks) == {"a", "b"}
    assert peaks["b"] > peaks["a"]
    path = str(tmp_path / "ckpt.npz")
    pred.checkpoint(path)
    restored = PeakPredictor.restore(path)
    assert restored.peaks(95.0) == pytest.approx(peaks)


# ---- qos strategies ----


def test_cpu_suppress_formula():
    # 64 cores, threshold 65% => budget 41.6 cores; non-BE uses 30 => BE gets 11.6
    dec = qos.cpu_suppress(64_000, 35_000, 5_000, 65.0)
    assert abs(dec.be_allowance_milli - (64_000 * 0.65 - 30_000)) < 1e-6
    assert dec.be_cpuset_cpus == 12
    assert dec.suppressed
    # min guarantee
    dec2 = qos.cpu_suppress(64_000, 64_000, 0.0, 65.0)
    assert dec2.be_allowance_milli == 1000.0
    assert dec2.be_cpuset_cpus == 1


def test_memory_evict_picks_lowest_priority_largest():
    pods = [("p-high", 1000.0, 6000), ("p-low-big", 4000.0, 5000), ("p-low-small", 500.0, 5000)]
    dec = qos.memory_evict(95_000, 100_000, 70.0, None, pods)
    assert dec.evict
    assert dec.victims[0] == "p-low-big"
    # below threshold: nothing
    assert not qos.memory_evict(50_000, 100_000, 70.0, None, pods).evict


def test_cpu_evict_on_satisfaction_collapse():
    pods = [("a", 4000.0, 5000), ("b", 4000.0, 5500)]
    dec = qos.cpu_evict(
        be_cpu_request_milli=8000,
        be_cpu_usage_milli=2900,
        be_cpu_limit_milli=3000,
        satisfaction_threshold=0.6,
        usage_threshold_percent=90.0,
        be_pods=pods,
    )
    assert dec.evict and dec.victims == ["a"]
    # healthy satisfaction: no evictions
    ok = qos.cpu_evict(8000, 6000, 7000, 0.6, 90.0, pods)
    assert not ok.evict


# ---- executor + hooks on fake cgroupfs ----


def be_pod(name, batch_cpu=4000, batch_mem=8192):
    return Pod(
        meta=ObjectMeta(name=name, labels={ext.LABEL_POD_QOS: "BE"}),
        spec=PodSpec(
            requests={
                ext.RES_BATCH_CPU: batch_cpu,
                ext.RES_BATCH_MEMORY: batch_mem,
            },
            priority=5500,
        ),
    )


def test_executor_writes_and_audit(tmp_path):
    ex = rex.ResourceExecutor(str(tmp_path))
    assert ex.write("kubepods/besteffort", rex.CPU_CFS_QUOTA, "10000", reason="t")
    # no-op suppressed
    assert not ex.write("kubepods/besteffort", rex.CPU_CFS_QUOTA, "10000")
    assert ex.read("kubepods/besteffort", rex.CPU_CFS_QUOTA) == "10000"
    events = ex.auditor.query(group_prefix="kubepods")
    assert len(events) == 1 and events[0].new == "10000"


def test_runtime_hooks_render_and_reconcile(tmp_path):
    ex = rex.ResourceExecutor(str(tmp_path))
    rec = hooks.Reconciler(ex)
    pod = be_pod("spark-exec")
    pod.meta.annotations[ext.ANNOTATION_RESOURCE_STATUS] = json.dumps(
        {"cpuset": "4-7"}
    )
    writes = rec.reconcile([pod])
    assert writes >= 5
    group = hooks.pod_cgroup(pod)
    assert ex.read(group, rex.CPU_BVT) == "-1"              # BE group identity
    assert ex.read(group, rex.CPU_SHARES) == str(4000 * 1024 // 1000)
    assert ex.read(group, rex.CPU_CFS_QUOTA) == str(int(4.0 * 100_000))
    assert ex.read(group, rex.MEMORY_LIMIT) == str(8192 * 1024 * 1024)
    assert ex.read(group, rex.CPUSET_CPUS) == "4-7"
    assert ex.read(group, rex.CORE_SCHED_COOKIE) == "2"
    # idempotent second pass: zero writes
    assert rec.reconcile([pod]) == 0


def test_qos_manager_tick_applies_suppression(tmp_path):
    ex = rex.ResourceExecutor(str(tmp_path))
    mgr = qos.QoSManager(
        ex, total_cpus=16, node_allocatable_milli=16_000,
        node_memory_capacity_mib=64_000,
    )
    slo = NodeSLO(
        meta=ObjectMeta(name="n"),
        threshold=ResourceThresholdStrategy(
            enable=True, cpu_suppress_threshold_percent=50.0
        ),
    )
    out = mgr.run_once(
        slo,
        node_used_milli=9_000,
        be_used_milli=1_000,
        node_memory_used_mib=10_000,
        be_pods_mem=[],
    )
    dec = out["cpu_suppress"]
    assert dec.suppressed
    # budget 8000 - non-be 8000 = min 1 cpu
    assert ex.read(qos.BE_GROUP, rex.CPUSET_CPUS) == "0"
    assert int(ex.read(qos.BE_GROUP, rex.CPU_CFS_QUOTA)) == 100_000


# ---- collectors (native + fallback) + daemon ----

#: environment probe, not a mock: sandboxed containers (gVisor-style)
#: serve an all-zero /proc/stat, so any test needing REAL jiffy counters
#: (absolute reads or deltas) can only skip there — the collectors'
#: parsing/fallback logic is covered by the fake-procfs tests either way
_PROC_STAT_LIVE = (lambda t: t is not None and t.total > 0)(
    col.read_cpu_times()
)
needs_live_procfs = pytest.mark.skipif(
    not _PROC_STAT_LIVE,
    reason="/proc/stat reports zero jiffies in this environment "
    "(sandboxed kernel); real-procfs probes cannot run",
)


@needs_live_procfs
def test_collectors_read_real_proc():
    times = col.read_cpu_times()
    assert times is not None and times.total > times.busy > 0
    mem = col.read_meminfo()
    assert mem is not None and mem[0] > mem[1] > 0


def test_daemon_collect_and_report(tmp_path):
    cfg = KoordletConfig(
        node_name="test-node",
        cgroup_root=str(tmp_path),
        report_interval_s=0.0,
        aggregate_window_s=1000.0,
    )
    agent = Koordlet(cfg)
    for t in range(5):
        agent.collect_tick(now=1000.0 + t)
    metric = agent.report_tick(now=1005.0)
    assert metric is not None
    assert metric.meta.name == "test-node"
    assert ext.RES_MEMORY in metric.node_usage.usage
    assert "p95" in metric.aggregated
    # feeds straight into the scheduler snapshot
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.api.types import Node, NodeStatus

    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="test-node"),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 64_000, ext.RES_MEMORY: 262_144}
            ),
        )
    )
    snap.set_node_metric(metric, now=1006.0)
    assert snap.nodes.metric_fresh[snap.node_id("test-node")]

    # qos tick runs against collected data without error
    agent.update_pods([be_pod("b1")])
    agent.qos_tick(now=1006.0)


def test_daemon_tick_chaos_points(tmp_path):
    """Dedicated fault test for the koordlet tick chaos points (the
    scheduler soak runs no koordlet daemon, so these cannot ride its
    fault schedule — the chaos-coverage lint's exemption names THIS
    test). Latency injection rides the injectable sleep; an armed error
    propagates to the wall-clock loop's retry/backoff, so a tick raise
    must surface, not wedge."""
    from koordinator_tpu.chaos import ChaosError, FaultInjector

    slept = []
    chaos = FaultInjector(seed=3, sleep=slept.append)
    cfg = KoordletConfig(
        node_name="test-node",
        cgroup_root=str(tmp_path),
        report_interval_s=0.0,
        aggregate_window_s=1000.0,
    )
    agent = Koordlet(cfg, chaos=chaos)
    chaos.arm("koordlet.collect_tick", latency_s=0.25, times=1)
    agent.collect_tick(now=1000.0)     # latency consumed, tick completes
    assert slept == [0.25]
    assert chaos.spec("koordlet.collect_tick").fired == 1
    agent.collect_tick(now=1001.0)     # budget spent: clean tick

    chaos.arm("koordlet.qos_tick", error=ChaosError, times=1)
    agent.update_pods([be_pod("b1")])
    with pytest.raises(ChaosError):
        agent.qos_tick(now=1002.0)
    out = agent.qos_tick(now=1003.0)   # next tick recovers
    assert isinstance(out, dict)
    # determinism contract: the injected faults land on the trace
    assert [(p, k) for _s, p, k in chaos.trace] == [
        ("koordlet.collect_tick", "latency"),
        ("koordlet.qos_tick", "error"),
    ]


def test_write_failure_does_not_crash(tmp_path):
    """A cgroup write rejection must be audited, not raised."""
    ex = rex.ResourceExecutor(str(tmp_path))
    # make the target a directory so open(..., 'w') fails
    os.makedirs(tmp_path / "g" / rex.CPU_CFS_QUOTA)
    assert ex.write("g", rex.CPU_CFS_QUOTA, "1") is False
    events = ex.auditor.query()
    assert any("WRITE-FAILED" in e.reason for e in events)


def test_memory_evict_dedup_and_callback(tmp_path):
    from koordinator_tpu.api.types import NodeSLO, ObjectMeta, ResourceThresholdStrategy

    calls = []
    ex = rex.ResourceExecutor(str(tmp_path))
    mgr = qos.QoSManager(
        ex, 16, 16_000, 100_000, evict_cb=lambda uid, reason: calls.append(uid)
    )
    slo = NodeSLO(
        meta=ObjectMeta(name="n"),
        threshold=ResourceThresholdStrategy(
            enable=True, memory_evict_threshold_percent=70.0
        ),
    )
    pods = [("victim", 30_000.0, 5000)]
    for _ in range(5):  # persistent pressure across ticks
        mgr.run_once(slo, 1000, 0, 95_000, be_pods_mem=pods)
    assert calls == ["victim"]          # evicted exactly once
    assert mgr.evicted == ["victim"]


def test_cpu_evict_wired_into_tick(tmp_path):
    from koordinator_tpu.api.types import NodeSLO, ObjectMeta, ResourceThresholdStrategy

    ex = rex.ResourceExecutor(str(tmp_path))
    mgr = qos.QoSManager(ex, 16, 16_000, 100_000)
    slo = NodeSLO(
        meta=ObjectMeta(name="n"),
        threshold=ResourceThresholdStrategy(
            enable=True,
            cpu_suppress_threshold_percent=30.0,
            cpu_evict_be_usage_threshold_percent=80.0,
        ),
    )
    # node busy with prod: suppress squeezes BE to the floor; BE requested
    # 10 cpus but runs at its 1-cpu floor fully saturated -> eviction
    out = mgr.run_once(
        slo,
        node_used_milli=15_000,
        be_used_milli=950,
        node_memory_used_mib=1000,
        be_pods_cpu=[("be-a", 5000.0, 5000), ("be-b", 5000.0, 5500)],
    )
    assert out["cpu_evict"].evict
    assert "be-a" in out["cpu_evict"].victims


def test_cpu_burst_wired_into_tick(tmp_path):
    from koordinator_tpu.api.types import (
        CPUBurstStrategy,
        NodeSLO,
        ObjectMeta,
    )

    ex = rex.ResourceExecutor(str(tmp_path))
    mgr = qos.QoSManager(ex, 16, 16_000, 100_000)
    slo = NodeSLO(
        meta=ObjectMeta(name="n"),
        cpu_burst=CPUBurstStrategy(policy="auto", cpu_burst_percent=200.0),
    )
    mgr.run_once(
        slo, 0, 0, 0, ls_pod_limits=[("kubepods/burstable/pod-x", 2000.0)]
    )
    assert ex.read("kubepods/burstable/pod-x", rex.CPU_BURST) == str(
        int(2.0 * 100_000 * 2.0)
    )


@needs_live_procfs
def test_be_tier_collector_and_prod_derivation(tmp_path):
    """BE cgroup usage feeds BE_CPU_USAGE; prod = node - BE (the prod
    derivation needs a real node-cpu jiffy delta from /proc/stat)."""
    cgroot = tmp_path / "cg"
    be_dir = cgroot / "kubepods" / "besteffort"
    os.makedirs(be_dir)
    (be_dir / "cpuacct.usage").write_text("0")
    (be_dir / "memory.usage_in_bytes").write_text(str(512 * 1024 * 1024))
    cfg = KoordletConfig(
        node_name="n", cgroup_root=str(cgroot), report_interval_s=0.0
    )
    agent = Koordlet(cfg)
    agent.collect_tick(now=1000.0)
    # 2 seconds of 1.5 BE cores; real /proc/stat needs wall time to pass
    # for the node-cpu jiffy delta to be nonzero
    import time as _t

    _t.sleep(0.2)
    (be_dir / "cpuacct.usage").write_text(str(int(3.0e9)))
    agent.collect_tick(now=1002.0)
    be = agent.metric_cache.latest(mc.BE_CPU_USAGE, "node")
    assert be is not None and abs(be[1] - 1500.0) < 1.0
    prod = agent.metric_cache.latest(mc.PROD_CPU_USAGE, "node")
    node = agent.metric_cache.latest(mc.NODE_CPU_USAGE, "node")
    assert prod is not None
    assert abs(prod[1] - max(node[1] - 1500.0, 0.0)) < 1.0
    metric = agent.report_tick(now=1002.0)
    assert metric.prod_usage.usage  # no longer empty


def test_reservation_on_removed_node_fails_safely():
    from koordinator_tpu.api.types import (
        Node, NodeStatus, Reservation, ReservationOwner, ReservationPhase,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import BatchScheduler
    from koordinator_tpu.scheduler.plugins.reservation import ReservationManager
    from koordinator_tpu.api.types import ObjectMeta as OM

    snap = ClusterSnapshot()
    snap.upsert_node(Node(meta=OM(name="n0"),
        status=NodeStatus(allocatable={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000})))
    snap.upsert_node(Node(meta=OM(name="n1"),
        status=NodeStatus(allocatable={ext.RES_CPU: 8000, ext.RES_MEMORY: 8000})))
    sched = BatchScheduler(snap)
    rm = ReservationManager(sched)
    rm.add(Reservation(meta=OM(name="r"), requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4000},
        owners=[ReservationOwner(label_selector={"a": "b"})]))
    rm.schedule_pending()
    node = rm.get("r").node_name
    snap.remove_node(node)
    owner = Pod(meta=OM(name="p", labels={"a": "b"}),
        spec=PodSpec(requests={ext.RES_CPU: 4000, ext.RES_MEMORY: 4000}, priority=9000))
    out = sched.schedule([owner])  # must not crash; falls back to solver
    assert rm.get("r").phase == ReservationPhase.FAILED
    assert len(out.bound) == 1  # placed on the surviving node


def test_metric_cache_checkpoint_restore(tmp_path):
    """Ring snapshots survive a koordlet restart (TSDB persistence analog,
    reference tsdb_storage.go): aggregates over the restored cache match
    the original, and a corrupt file restores to an empty cache."""
    cache = mc.MetricCache(capacity_per_series=64)
    for t in range(100):   # wraps the 64-slot ring
        cache.append(mc.NODE_CPU_USAGE, "node", float(t), float(t))
    path = str(tmp_path / "tsdb.npz")
    cache.checkpoint(path)

    back = mc.MetricCache.restore(path, capacity_per_series=64)
    want = cache.aggregate(mc.NODE_CPU_USAGE, "node", 0.0, 100.0)
    got = back.aggregate(mc.NODE_CPU_USAGE, "node", 0.0, 100.0)
    assert got.count == want.count == 64
    assert got.avg == want.avg
    assert back.latest(mc.NODE_CPU_USAGE, "node") == (99.0, 99.0)
    # appends continue at the right ring position
    back.append(mc.NODE_CPU_USAGE, "node", 100.0, 100.0)
    assert back.latest(mc.NODE_CPU_USAGE, "node") == (100.0, 100.0)

    (tmp_path / "bad.npz").write_bytes(b"not a checkpoint")
    empty = mc.MetricCache.restore(str(tmp_path / "bad.npz"))
    assert empty.latest(mc.NODE_CPU_USAGE, "node") is None


def test_daemon_checkpoint_restart_cycle(tmp_path):
    """A koordlet restart adopts the TSDB + prediction checkpoints written
    on report ticks (stateless-restartable agent, SURVEY §5)."""
    cfg = KoordletConfig(
        node_name="test-node",
        cgroup_root=str(tmp_path),
        report_interval_s=0.0,
        aggregate_window_s=1000.0,
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    agent = Koordlet(cfg)
    # seed history directly — collection timing (procfs jiffy deltas) is
    # not the subject here, persistence is
    for t in range(5):
        agent.metric_cache.append(
            mc.NODE_CPU_USAGE, "node", 1000.0 + t, 1000.0 + t
        )
    agent.predictor.observe("node/test-node", 1234.0, 1000.0)
    assert agent.report_tick(now=1005.0) is not None   # writes checkpoints

    agent2 = Koordlet(cfg)
    assert agent2.restore_checkpoints()
    # restored history answers aggregates without any new collection
    agg = agent2.metric_cache.aggregate(
        mc.NODE_CPU_USAGE, "node", 0.0, 3000.0
    )
    assert agg.count >= 1
    assert agent2.predictor.peak("node/test-node") is not None
