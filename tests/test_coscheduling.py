"""Gang / coscheduling tests: solver rollback + PodGroupManager semantics
(reference ``pkg/scheduler/plugins/coscheduling`` PreEnqueue/Permit)."""

import numpy as np

import jax.numpy as jnp

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.ops.solver import (
    NodeState,
    PodBatch,
    SolverParams,
    assign,
    enforce_gangs,
    SolveResult,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.coscheduling import PodGroupManager


def gang_pod(name, gang, cpu=4.0, prio=9000, ns="default", min_avail=None):
    labels = {ext.LABEL_GANG_NAME: gang}
    if min_avail is not None:
        labels[ext.LABEL_GANG_MIN_AVAILABLE] = str(min_avail)
    return Pod(
        meta=ObjectMeta(name=name, namespace=ns, labels=labels),
        spec=PodSpec(requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}, priority=prio),
    )


def test_enforce_gangs_rollback():
    # 4 pods: gang 0 = pods 0,1 (min 2, one unplaced), gang 1 = pods 2,3 ok
    assignment = jnp.asarray([0, -1, 1, 1], jnp.int32)
    req = jnp.full((4, 1), 2.0)
    node_req = jnp.asarray([[2.0], [4.0]])
    result = SolveResult(
        assignment=assignment,
        node_requested=node_req,
        node_estimated_used=node_req,
        node_prod_used=jnp.zeros_like(node_req),
        quota_used=jnp.zeros((1, 1)),
        rounds_used=jnp.array(1, jnp.int32),
    )
    pods = PodBatch.create(
        requests=req,
        estimate=req,
        priority=jnp.zeros(4, jnp.int32),
        is_prod=jnp.zeros(4, bool),
        gang_id=[0, 0, 1, 1],
        gang_min=[2, 2, 0, 0],
    )
    out = enforce_gangs(result, pods)
    got = np.asarray(out.assignment)
    assert got[0] == -1 and got[1] == -1          # gang 0 rolled back
    assert got[2] == 1 and got[3] == 1            # gang 1 kept
    np.testing.assert_allclose(np.asarray(out.node_requested), [[0.0], [4.0]])


def test_solver_all_or_nothing_gang():
    """A gang that cannot fully fit must not be partially placed."""
    d = 1
    alloc = jnp.asarray([[8.0]])
    # gang of 3, each 4 cpu -> only 2 fit on the single node
    req = jnp.full((3, d), 4.0)
    pods = PodBatch.create(
        requests=req,
        estimate=req * 0.85,
        priority=jnp.full(3, 9000, jnp.int32),
        gang_id=jnp.zeros(3, jnp.int32),
        gang_min=[3, 0, 0],
    )
    nodes = NodeState.create(allocatable=alloc)
    params = SolverParams(
        usage_thresholds=jnp.zeros(d),
        prod_thresholds=jnp.zeros(d),
        score_weights=jnp.ones(d),
    )
    out = assign(pods, nodes, params)
    assert (np.asarray(out.assignment) == -1).all()
    np.testing.assert_allclose(np.asarray(out.node_requested), [[0.0]])


def test_pre_enqueue_gating():
    mgr = PodGroupManager()
    mgr.upsert_pod_group(
        PodGroup(meta=ObjectMeta(name="g1"), min_member=3)
    )
    p1 = gang_pod("p1", "g1")
    p2 = gang_pod("p2", "g1")
    mgr.add_pending_pod(p1)
    ok, reason = mgr.pre_enqueue(p1)
    assert not ok and "1/3" in reason
    mgr.add_pending_pod(p2)
    p3 = gang_pod("p3", "g1")
    mgr.add_pending_pod(p3)
    ok, _ = mgr.pre_enqueue(p1)
    assert ok


def test_end_to_end_gang_scheduling():
    """Whole gang fits -> bound together; oversized gang -> nothing bound."""
    snap = ClusterSnapshot()
    for i in range(4):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 16.0, ext.RES_MEMORY: 16.0}
                ),
            )
        )
    sched = BatchScheduler(snap)
    # gang of 4 x 4cpu over 4 x 16cpu nodes: fits
    gang_ok = [gang_pod(f"a{i}", "ok-gang", cpu=4.0, min_avail=4) for i in range(4)]
    # gang of 3 x 16cpu pods: needs 3 whole nodes' remaining capacity; make
    # it impossible by requesting more than any node can offer twice over
    gang_big = [gang_pod(f"b{i}", "big-gang", cpu=40.0, min_avail=3) for i in range(3)]
    out = sched.schedule(gang_ok + gang_big)
    bound_names = {p.meta.name for p, _ in out.bound}
    assert bound_names == {"a0", "a1", "a2", "a3"}
    assert {p.meta.name for p in out.unschedulable} == {"b0", "b1", "b2"}


def test_gang_gated_until_min_members_pending():
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(allocatable={ext.RES_CPU: 64.0, ext.RES_MEMORY: 64.0}),
        )
    )
    sched = BatchScheduler(snap)
    sched.pod_groups.upsert_pod_group(
        PodGroup(meta=ObjectMeta(name="g"), min_member=2)
    )
    lone = gang_pod("solo", "g")
    out = sched.schedule([lone])
    assert out.bound == []
    assert [p.meta.name for p in out.unschedulable] == ["solo"]
    # second member arrives -> both go through
    mate = gang_pod("mate", "g")
    out2 = sched.schedule([lone, mate])
    assert {p.meta.name for p, _ in out2.bound} == {"solo", "mate"}


def _cluster(n_nodes=4, cpu=16.0):
    snap = ClusterSnapshot()
    for i in range(n_nodes):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu}
                ),
            )
        )
    return snap


def test_straggler_after_gang_satisfied_schedules():
    """A member arriving after the gang already met minMember schedules
    alone (bound members reduce the outstanding requirement)."""
    sched = BatchScheduler(_cluster())
    sched.pod_groups.upsert_pod_group(
        PodGroup(meta=ObjectMeta(name="g"), min_member=2)
    )
    first = [gang_pod("p1", "g"), gang_pod("p2", "g")]
    out1 = sched.schedule(first)
    assert len(out1.bound) == 2
    straggler = gang_pod("p3", "g")
    out2 = sched.schedule([straggler])
    assert [p.meta.name for p, _ in out2.bound] == ["p3"]


def test_gang_larger_than_batch_bucket_not_split():
    """Chunking must keep a gang whole even when it exceeds batch_bucket."""
    sched = BatchScheduler(_cluster(n_nodes=8, cpu=64.0), batch_bucket=2)
    gang = [gang_pod(f"p{i}", "wide", cpu=2.0, min_avail=5) for i in range(5)]
    out = sched.schedule(gang)
    assert len(out.bound) == 5, [p.meta.name for p in out.unschedulable]


def test_label_only_gang_all_or_nothing_by_member_count():
    """Gang labels without min-available: all-or-nothing over the members
    present (the build_pods member-count fallback)."""
    sched = BatchScheduler(_cluster(n_nodes=1, cpu=8.0))
    gang = [gang_pod(f"p{i}", "nolabel", cpu=4.0) for i in range(3)]  # 2 fit
    out = sched.schedule(gang)
    assert out.bound == []
    assert len(out.unschedulable) == 3


def test_ghost_members_pruned_between_cycles():
    """Members that vanish from the pending set stop counting toward the
    gang's PreEnqueue gate."""
    sched = BatchScheduler(_cluster(n_nodes=1, cpu=2.0))  # nothing fits
    sched.pod_groups.upsert_pod_group(
        PodGroup(meta=ObjectMeta(name="g"), min_member=3)
    )
    trio = [gang_pod(f"p{i}", "g", cpu=4.0) for i in range(3)]
    out1 = sched.schedule(trio)
    assert len(out1.unschedulable) == 3
    # two members deleted; the lone survivor must be gated, not solved
    lone = trio[0]
    out2 = sched.schedule([lone])
    assert out2.bound == []
    ok, reason = sched.pod_groups.pre_enqueue(lone)
    assert not ok and "1/3" in reason


def test_gang_timeout_backoff():
    mgr = PodGroupManager(default_timeout_s=10.0)
    pod = gang_pod("p", "g", min_avail=1)
    mgr.add_pending_pod(pod)
    ok, _ = mgr.pre_enqueue(pod, now=1000.0)
    # create_time is wall-clock; simulate passage beyond timeout
    state = mgr._gangs["default/g"]
    state.create_time = 0.0
    ok, reason = mgr.pre_enqueue(pod, now=1000.0)
    assert not ok and "timed out" in reason
    # clock reset -> next cycle eligible again
    ok, _ = mgr.pre_enqueue(pod, now=1001.0)
    assert ok


def test_gang_group_atomicity_at_permit():
    """AllowGangGroup (core/core.go:346-465): gangs linked by the
    gang-groups annotation pass Permit together or not at all — a failing
    member gang rejects the sibling gang's otherwise-complete placements."""
    import json

    mgr = PodGroupManager()
    group = json.dumps(["default/ga", "default/gb"])

    def member(gang, i, node):
        p = gang_pod(f"{gang}-{i}", gang, min_avail=2)
        p.meta.annotations[ext.ANNOTATION_GANG_GROUPS] = group
        return (p, node)

    # ga fully placed; gb placed only 1/2 -> the WHOLE group rejects
    results = [
        member("ga", 0, "n0"),
        member("ga", 1, "n1"),
        member("gb", 0, "n0"),
        member("gb", 1, None),
    ]
    allowed, rejected = mgr.permit(results)
    assert allowed == []
    assert len(rejected) == 4

    # both complete -> everything admits
    results_ok = [
        member("ga", 0, "n0"),
        member("ga", 1, "n1"),
        member("gb", 0, "n0"),
        member("gb", 1, "n1"),
    ]
    allowed, rejected = mgr.permit(results_ok)
    assert len(allowed) == 4 and rejected == []


def gang_pod_policy(name, gang, policy, cpu=4.0, min_avail=None):
    pod = gang_pod(name, gang, cpu=cpu, min_avail=min_avail)
    pod.meta.annotations[ext.ANNOTATION_GANG_MATCH_POLICY] = policy
    return pod


def test_match_policy_default_and_alias():
    from koordinator_tpu.scheduler.plugins.coscheduling import match_policy_of

    assert match_policy_of(gang_pod("p", "g")) == ext.GANG_MATCH_ONCE_SATISFIED
    p = gang_pod("p", "g")
    p.meta.annotations[ext.ANNOTATION_ALIAS_GANG_MATCH_POLICY] = (
        ext.GANG_MATCH_ONLY_WAITING
    )
    assert match_policy_of(p) == ext.GANG_MATCH_ONLY_WAITING
    p.meta.annotations[ext.ANNOTATION_ALIAS_GANG_MATCH_POLICY] = "bogus"
    assert match_policy_of(p) == ext.GANG_MATCH_ONCE_SATISFIED


def test_only_waiting_policy_regathers_min_members():
    """only-waiting (apis/extension/coscheduling.go:58): bound members do
    NOT count toward satisfaction — a straggler must re-gather minMember
    waiting members, unlike the once-satisfied default
    (test_straggler_after_gang_satisfied_schedules)."""
    sched = BatchScheduler(_cluster())
    sched.pod_groups.upsert_pod_group(
        PodGroup(meta=ObjectMeta(name="g"), min_member=2)
    )
    first = [
        gang_pod_policy("p1", "g", ext.GANG_MATCH_ONLY_WAITING),
        gang_pod_policy("p2", "g", ext.GANG_MATCH_ONLY_WAITING),
    ]
    out1 = sched.schedule(first)
    assert len(out1.bound) == 2
    straggler = gang_pod_policy("p3", "g", ext.GANG_MATCH_ONLY_WAITING)
    out2 = sched.schedule([straggler])
    assert out2.bound == []  # 1 waiting < minMember 2
    # two stragglers together re-satisfy the gang
    out3 = sched.schedule(
        [straggler, gang_pod_policy("p4", "g", ext.GANG_MATCH_ONLY_WAITING)]
    )
    assert len(out3.bound) == 2


def test_once_satisfied_sticky_flag_set_on_bind():
    sched = BatchScheduler(_cluster())
    sched.pod_groups.upsert_pod_group(
        PodGroup(meta=ObjectMeta(name="g"), min_member=2)
    )
    out = sched.schedule([gang_pod("p1", "g"), gang_pod("p2", "g")])
    assert len(out.bound) == 2
    state = sched.pod_groups._gangs["default/g"]
    assert state.satisfied and state.once_satisfied


def test_unannotated_member_does_not_reset_policy():
    """Code-review regression: a member without the match-policy annotation
    must not reset an only-waiting gang to the once-satisfied default; the
    PodGroup CRD's own annotation also declares the policy."""
    sched = BatchScheduler(_cluster())
    pg = PodGroup(meta=ObjectMeta(name="g"), min_member=2)
    pg.meta.annotations[ext.ANNOTATION_GANG_MATCH_POLICY] = (
        ext.GANG_MATCH_ONLY_WAITING
    )
    sched.pod_groups.upsert_pod_group(pg)
    # p1 annotated, p2 plain: the gang stays only-waiting
    out = sched.schedule(
        [
            gang_pod_policy("p1", "g", ext.GANG_MATCH_ONLY_WAITING),
            gang_pod("p2", "g"),
        ]
    )
    assert len(out.bound) == 2
    state = sched.pod_groups._gangs["default/g"]
    assert state.match_policy == ext.GANG_MATCH_ONLY_WAITING
    # a lone straggler still re-gathers minMember under only-waiting
    out2 = sched.schedule([gang_pod("p3", "g")])
    assert out2.bound == []


def test_straggler_cannot_flip_established_policy():
    """Advisor r2 regression: a differently-annotated straggler must not
    flip an established gang's match policy mid-lifecycle — the policy is
    parsed once at gang creation (reference parses from the CRD or the
    first pod)."""
    sched = BatchScheduler(_cluster())
    out = sched.schedule(
        [
            gang_pod_policy("p1", "g", ext.GANG_MATCH_ONLY_WAITING, min_avail=2),
            gang_pod_policy("p2", "g", ext.GANG_MATCH_ONLY_WAITING, min_avail=2),
        ]
    )
    assert len(out.bound) == 2
    state = sched.pod_groups._gangs["default/g"]
    assert state.match_policy == ext.GANG_MATCH_ONLY_WAITING
    # a straggler annotated once-satisfied does NOT flip the gang back
    straggler = gang_pod_policy(
        "p3", "g", ext.GANG_MATCH_ONCE_SATISFIED, min_avail=2
    )
    out2 = sched.schedule([straggler])
    assert state.match_policy == ext.GANG_MATCH_ONLY_WAITING
    assert out2.bound == []  # only-waiting: must re-gather minMember
    # the CRD annotation still has authority to change the policy
    pg = PodGroup(meta=ObjectMeta(name="g"), min_member=2)
    pg.meta.annotations[ext.ANNOTATION_GANG_MATCH_POLICY] = (
        ext.GANG_MATCH_ONCE_SATISFIED
    )
    sched.pod_groups.upsert_pod_group(pg)
    assert state.match_policy == ext.GANG_MATCH_ONCE_SATISFIED


def test_enforce_gangs_nonstrict_keeps_placed_members():
    """GangModeNonStrict (apis/extension/coscheduling.go:40-53): an
    under-filled NonStrict gang keeps its successfully-placed members —
    no rollback, capacity stays committed (core/gang.go branches on mode;
    rejectGangGroupById runs only in Strict, core/core.go:333)."""
    assignment = jnp.asarray([0, -1, 1, 1], jnp.int32)
    req = jnp.full((4, 1), 2.0)
    node_req = jnp.asarray([[2.0], [4.0]])
    result = SolveResult(
        assignment=assignment,
        node_requested=node_req,
        node_estimated_used=node_req,
        node_prod_used=jnp.zeros_like(node_req),
        quota_used=jnp.zeros((1, 1)),
        rounds_used=jnp.array(1, jnp.int32),
    )
    pods = PodBatch.create(
        requests=req,
        estimate=req,
        priority=jnp.zeros(4, jnp.int32),
        is_prod=jnp.zeros(4, bool),
        gang_id=[0, 0, 1, 1],
        gang_min=[2, 2, 0, 0],
        gang_nonstrict=[True, False, False, False],  # gang 0 NonStrict
    )
    out = enforce_gangs(result, pods)
    got = np.asarray(out.assignment)
    assert got[0] == 0 and got[1] == -1           # placed member survives
    assert got[2] == 1 and got[3] == 1
    np.testing.assert_allclose(np.asarray(out.node_requested), [[2.0], [4.0]])


def test_nonstrict_gang_e2e_partial_placement():
    """End-to-end parity for both modes on a cluster that fits only 2 of
    a 3-member gang: Strict binds nothing; NonStrict binds the 2 that fit
    (the third stays unschedulable and retries)."""
    def member(name, gang, nonstrict):
        p = gang_pod(name, gang, cpu=8.0, min_avail=3)
        if nonstrict:
            p.meta.annotations[ext.ANNOTATION_GANG_MODE] = (
                ext.GANG_MODE_NONSTRICT
            )
        return p

    # 2 nodes x 16 cpu, members want 8 cpu: only 2 of 3 members can ever
    # land with per-node estimated-usage headroom for exactly one each
    sched = BatchScheduler(_cluster(n_nodes=2, cpu=8.0))
    strict = [member(f"s{i}", "gs", False) for i in range(3)]
    out = sched.schedule(strict)
    assert out.bound == []                       # all-or-nothing
    assert len(out.unschedulable) == 3

    sched2 = BatchScheduler(_cluster(n_nodes=2, cpu=8.0))
    nonstrict = [member(f"n{i}", "gn", True) for i in range(3)]
    out2 = sched2.schedule(nonstrict)
    assert len(out2.bound) == 2                  # placed members kept
    assert len(out2.unschedulable) == 1
    state = sched2.pod_groups._gangs["default/gn"]
    assert state.mode == ext.GANG_MODE_NONSTRICT


def test_nonstrict_mode_from_podgroup_crd():
    """The PodGroup CRD's mode annotation declares NonStrict for the
    whole gang even when member pods carry no mode annotation."""
    sched = BatchScheduler(_cluster(n_nodes=2, cpu=8.0))
    pg = PodGroup(meta=ObjectMeta(name="g"), min_member=3)
    pg.meta.annotations[ext.ANNOTATION_GANG_MODE] = ext.GANG_MODE_NONSTRICT
    sched.pod_groups.upsert_pod_group(pg)
    pods = [gang_pod(f"p{i}", "g", cpu=8.0) for i in range(3)]
    out = sched.schedule(pods)
    assert len(out.bound) == 2
    # an illegal mode value degrades to Strict (gang.go:128-132)
    assert ext.gang_mode_of({ext.ANNOTATION_GANG_MODE: "bogus"}) == (
        ext.GANG_MODE_STRICT
    )


def test_native_gang_annotation_protocol():
    """The koordinator-native gang annotations (AnnotationGangPrefix,
    apis/extension/coscheduling.go:25-47) drive gang formation end to
    end: name, min-available, waiting-time (Go duration), total-number
    (clamped >= minMember); the deprecated lightweight labels remain a
    fallback."""
    def native_pod(name, cpu=4.0):
        p = Pod(
            meta=ObjectMeta(name=name),
            spec=PodSpec(
                requests={ext.RES_CPU: cpu, ext.RES_MEMORY: cpu},
                priority=9000,
            ),
        )
        p.meta.annotations.update(
            {
                ext.ANNOTATION_GANG_NAME: "native-g",
                ext.ANNOTATION_GANG_MIN_AVAILABLE: "2",
                ext.ANNOTATION_GANG_WAIT_TIME: "90s",
                ext.ANNOTATION_GANG_TOTAL_NUM: "1",  # illegal: < min
            }
        )
        return p

    sched = BatchScheduler(_cluster())
    # one member alone gates at PreEnqueue (minMember 2 from annotation)
    out1 = sched.schedule([native_pod("n1")])
    assert out1.bound == []
    state = sched.pod_groups._gangs["default/native-g"]
    assert state.min_member == 2
    assert state.schedule_timeout_s == 90.0
    assert state.total_num == 2        # clamped up to minMember
    # both members together schedule all-or-nothing
    out2 = sched.schedule([native_pod("n1"), native_pod("n2")])
    assert len(out2.bound) == 2


def test_parse_duration_s():
    from koordinator_tpu.api.extension import parse_duration_s

    assert parse_duration_s("90s") == 90.0
    assert parse_duration_s("1h30m") == 5400.0
    assert parse_duration_s("250ms") == 0.25
    assert parse_duration_s("2m3s") == 123.0
    assert parse_duration_s("") is None
    assert parse_duration_s("bogus") is None
    assert parse_duration_s("0s") is None   # non-positive -> default
