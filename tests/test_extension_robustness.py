"""Annotation-protocol parser robustness: every parse_* helper must
treat malformed operator input as absent (the reference's parsers return
zero values + error, and callers proceed without the feature — a bad
annotation must never crash an informer or a scheduling cycle)."""

import json

import pytest

from koordinator_tpu.api import extension as ext

#: (annotation key the parser reads, parser callable) — each is fed the
#: same battery of malformed payloads
_GARBAGE = [
    "",
    "not-json{{",
    "[]",                      # wrong JSON shape (list where dict expected)
    '{"unexpected": []}',
    '"quoted-string"',
    "\x00\xff",
    "9" * 10_000,              # absurd but parseable number
]


@pytest.mark.parametrize(
    "key, fn",
    [
        (ext.ANNOTATION_DEVICE_ALLOCATE_HINT, ext.parse_device_allocate_hints),
        (ext.ANNOTATION_GPU_PARTITION_SPEC, ext.parse_gpu_partition_table),
        (ext.ANNOTATION_DEVICE_JOINT_ALLOCATE, ext.parse_device_joint_allocate),
        (ext.ANNOTATION_RESERVATION_AFFINITY, ext.parse_reservation_affinity),
        (ext.ANNOTATION_CUSTOM_USAGE_THRESHOLDS, ext.parse_custom_usage_thresholds),
        (ext.ANNOTATION_QUOTA_SHARED_WEIGHT, ext.parse_quota_shared_weight),
        (ext.ANNOTATION_NUMA_TOPOLOGY_SPEC, ext.parse_numa_topology_spec),
        (ext.ANNOTATION_EXTENDED_RESOURCE_SPEC, ext.parse_extended_resource_spec),
    ],
)
@pytest.mark.parametrize("garbage", _GARBAGE)
def test_parsers_survive_garbage(key, fn, garbage):
    out = fn({key: garbage})
    # absent-equivalent: never an exception AND never truthy garbage
    # that could flow into a scheduling cycle as real config
    assert not out, (key, garbage, out)


def test_duration_parser_go_syntax_and_garbage():
    assert ext.parse_duration_s("90s") == 90.0
    assert ext.parse_duration_s("2m") == 120.0
    assert ext.parse_duration_s("1h30m") == 5400.0
    assert ext.parse_duration_s("1.5h") == 5400.0
    for bad in ("", "abc", "12", "h", "-5x", None):
        assert ext.parse_duration_s(bad) is None


def test_gpu_request_parser_edge_values():
    assert ext.parse_gpu_request({ext.RES_GPU: 0}) == (0, 0.0)
    # ratio exactly at a whole-GPU boundary
    assert ext.parse_gpu_request({ext.RES_GPU_MEMORY_RATIO: 100}) == (1, 0.0)
    assert ext.parse_gpu_request({ext.RES_GPU_MEMORY_RATIO: 350}) == (3, 50.0)
    # no device keys at all
    assert ext.parse_gpu_request({ext.RES_CPU: 4000}) == (0, 0.0)


def test_node_amplification_ignores_bad_ratios():
    # wire format is key=ratio pairs; malformed entries are skipped
    good = ext.parse_node_amplification(
        {ext.ANNOTATION_NODE_AMPLIFICATION: "cpu=1.5,memory=1.2"}
    )
    assert good["cpu"] == 1.5 and good["memory"] == 1.2
    for bad in ("cpu=x", "=1.5", ",,,", "cpu", "{json}"):
        out = ext.parse_node_amplification(
            {ext.ANNOTATION_NODE_AMPLIFICATION: bad}
        )
        assert all(isinstance(v, float) for v in out.values())
    mixed = ext.parse_node_amplification(
        {ext.ANNOTATION_NODE_AMPLIFICATION: "cpu=bogus,memory=2.0"}
    )
    assert mixed == {"memory": 2.0}


def test_shared_pools_parser_garbage():
    for bad in _GARBAGE:
        out = ext.parse_cpu_shared_pools(
            {ext.ANNOTATION_NODE_CPU_SHARED_POOLS: bad}
        )
        assert out is None or isinstance(out, (list, tuple))


def test_eviction_cost_clamps_and_defaults():
    assert ext.parse_eviction_cost({}) == 0
    assert (
        ext.parse_eviction_cost({ext.ANNOTATION_EVICTION_COST: "100"}) == 100
    )
    assert (
        ext.parse_eviction_cost({ext.ANNOTATION_EVICTION_COST: "junk"}) == 0
    )
