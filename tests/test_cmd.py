"""Entry-point tests: the five binaries (SURVEY §2.1) run end-to-end
against the simulator, and leader election actually gates the loops."""

import json

import pytest

from koordinator_tpu.cmd import (
    koord_descheduler,
    koord_manager,
    koord_runtime_proxy,
    koord_scheduler,
    koordlet,
)


def run_main(main, argv, capsys):
    rc = main(argv)
    out = capsys.readouterr().out.strip().splitlines()
    return rc, [json.loads(line) for line in out if line.startswith("{")]


def test_scheduler_main_binds_pods(capsys):
    rc, lines = run_main(
        koord_scheduler.main,
        ["--sim-nodes", "40", "--sim-pods", "150", "--rounds", "2"],
        capsys,
    )
    assert rc == 0
    assert lines[0]["bound"] > 0
    # round 2 only sees the leftovers
    assert lines[1]["bound"] + lines[1]["unschedulable"] <= lines[0]["unschedulable"]


def test_scheduler_latency_mode(capsys):
    """--latency runs the StreamScheduler operating point in the daemon
    (VERDICT r4 #2): rounds report per-pod enqueue→bind percentiles and
    the feed drains without a residual backlog."""
    rc, lines = run_main(
        koord_scheduler.main,
        [
            "--sim-nodes", "60", "--sim-pods", "120",
            "--latency", "5000", "--rounds", "8",
        ],
        capsys,
    )
    assert rc == 0
    assert all(line["mode"] == "latency" for line in lines)
    bound = sum(line["bound"] for line in lines)
    assert bound > 0
    decided = [line for line in lines if line["pod_p50_ms"] is not None]
    assert decided, lines
    assert all(line["pod_p50_ms"] >= 0 for line in decided)
    # the feed is finite: once drained the backlog stays empty
    assert lines[-1]["backlog"] == 0


def test_scheduler_main_with_config_file(tmp_path, capsys):
    cfg = tmp_path / "sched.json"
    cfg.write_text(json.dumps({"loadAware": {"cpuThreshold": 80.0}}))
    rc, lines = run_main(
        koord_scheduler.main,
        ["--sim-nodes", "20", "--sim-pods", "50", "--config", str(cfg)],
        capsys,
    )
    assert rc == 0 and lines


def test_descheduler_main_dry_run(capsys):
    rc, lines = run_main(
        koord_descheduler.main,
        ["--sim-nodes", "30", "--sim-pods", "100", "--dry-run"],
        capsys,
    )
    assert rc == 0
    assert "koord-descheduler" in lines[0]["profiles"]


def test_manager_main_reconciles(capsys):
    rc, lines = run_main(
        koord_manager.main, ["--sim-nodes", "25", "--rounds", "1"], capsys
    )
    assert rc == 0
    assert lines[0]["nodemetric_specs"] == 25
    assert lines[0]["batch_resources"] == 25


def test_runtime_proxy_main_hook_chain(capsys):
    rc, lines = run_main(koord_runtime_proxy.main, [], capsys)
    assert rc == 0
    fired = lines[0]["hooks_fired"]
    assert fired[0] == "PreRunPodSandbox" and "PostStopPodSandbox" in fired
    assert lines[0]["sandbox_checkpointed"]


def test_koordlet_main_short_run():
    assert koordlet.main(["--duration", "0.5", "--collect-interval", "0.2"]) == 0


def test_feature_gate_flag_rejects_unknown():
    with pytest.raises(KeyError):
        koord_manager.main(["--feature-gates", "NotAGate=true", "--rounds", "1"])


def test_leader_election_gates_second_instance(tmp_path, capsys):
    """Two scheduler instances on one lease file: the second must not run
    while the first holds the lease (we simulate by pre-creating a live
    lease record held by someone else)."""
    import time

    from koordinator_tpu.utils.leaderelection import FileLeaseLock, LeaseRecord

    lease = str(tmp_path / "lease.json")
    lock = FileLeaseLock(lease)
    now = time.time()  # electors use wall clock (leases survive reboots)
    assert lock.create(
        LeaseRecord(
            holder="other", acquire_time=now, renew_time=now, lease_duration=60.0
        )
    )

    import threading

    done = {}

    def run():
        done["rc"] = koord_scheduler.main(
            [
                "--sim-nodes",
                "10",
                "--sim-pods",
                "10",
                "--leader-elect",
                "--lease-file",
                lease,
                "--identity",
                "me",
            ]
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=3.0)
    # blocked waiting on the lease -> never scheduled, thread still alive
    assert t.is_alive()
    assert "rc" not in done


def test_koord_scheduler_serve_mode():
    """--serve runs the long-lived solver sidecar: a real gRPC client can
    sync a world and get nominations while the binary blocks."""
    import threading

    from koordinator_tpu.cmd import koord_scheduler
    from koordinator_tpu.runtime.proto import snapshot_pb2 as pb
    from koordinator_tpu.runtime.snapshot_channel import SolverClient

    stop = threading.Event()
    ready = threading.Event()
    state = {}

    def on_serve(server, port):
        state["port"] = port
        ready.set()

    t = threading.Thread(
        target=lambda: koord_scheduler.main(
            ["--serve", "127.0.0.1:0", "--batch-bucket", "64"],
            _stop_event=stop,
            _on_serve=on_serve,
        ),
    )
    t.start()
    assert ready.wait(timeout=30)

    client = SolverClient(f"127.0.0.1:{state['port']}")
    try:
        cfg_resp = client.get_config()
        res = list(cfg_resp.resources)
        d = pb.SnapshotDelta(revision=1, now=1000.0)
        d.node_upserts.add(
            name="n0",
            allocatable=pb.ResourceVector(
                values=[32000.0 if r == "cpu" else 131072.0 for r in res]
            ),
        )
        assert client.sync(d).node_count == 1
        req = pb.NominateRequest()
        req.pods.add(
            uid="p0",
            requests=pb.ResourceVector(
                values=[1000.0 if r == "cpu" else 1024.0 for r in res]
            ),
            priority=9000,
        )
        resp = client.nominate(req)
        assert resp.nominations[0].node == "n0"
    finally:
        client.close()
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive(), "--serve did not shut down on stop event"


def test_koord_sim_binary_runs_the_loop():
    from koordinator_tpu.cmd import koord_sim

    assert koord_sim.main(["--minutes", "2", "--nodes", "4", "--quiet"]) == 0


def test_scheduler_config_wires_device_scoring(tmp_path, capsys):
    """deviceShare.scoringStrategy in --config builds a DeviceManager and
    --sim-gpus gives sim nodes inventory (not a silent no-op)."""
    cfg = tmp_path / "sched.json"
    cfg.write_text(
        json.dumps(
            {
                "loadAware": {},
                "deviceShare": {"scoringStrategy": {"type": "MostAllocated"}},
            }
        )
    )
    rc, lines = run_main(
        koord_scheduler.main,
        [
            "--sim-nodes", "10", "--sim-pods", "20",
            "--sim-gpus", "4", "--config", str(cfg), "--rounds", "1",
        ],
        capsys,
    )
    assert rc == 0 and lines[0]["bound"] == 20


def test_descheduler_config_decodes_node_pools(tmp_path, capsys):
    """nodePools/resourceWeights/nodeFit reach the Balance plugin from the
    plugin-args JSON (decode_low_node_load_pools)."""
    cfg = tmp_path / "desched.json"
    cfg.write_text(
        json.dumps(
            {
                "lowNodeLoad": {
                    "highThresholds": {"cpu": 65},
                    "lowThresholds": {"cpu": 30},
                    "nodeFit": False,
                    "resourceWeights": {"cpu": 2},
                    "nodePools": [
                        {
                            "name": "batch",
                            "nodeSelector": {"matchLabels": {"pool": "batch"}},
                            "highThresholds": {"cpu": 90},
                            "lowThresholds": {"cpu": 10},
                        }
                    ],
                }
            }
        )
    )
    rc, lines = run_main(
        koord_descheduler.main,
        [
            "--sim-nodes", "20", "--sim-pods", "60",
            "--dry-run", "--config", str(cfg), "--rounds", "1",
        ],
        capsys,
    )
    assert rc == 0
    assert "koord-descheduler" in lines[0]["profiles"]


def test_scheduler_flight_file_survives_process_restart(tmp_path):
    """--flight-file (devprof PR satellite): the per-cycle flight
    recorder persists over a FileJournalStore beside --journal-file, so
    a REAL process restart adopts the dead incarnation's tail — two
    subprocess invocations against one file must leave records from two
    distinct incarnations, sequence-continuous."""
    import json as _json
    import os
    import subprocess
    import sys

    flight = tmp_path / "flight.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, "-m", "koordinator_tpu.cmd.koord_scheduler",
        "--sim-nodes", "12", "--sim-pods", "30", "--rounds", "2",
        "--flight-file", str(flight),
    ]
    for run in range(2):
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=180, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        if run == 1:
            assert "flight recorder adopted" in proc.stderr
    records = [
        _json.loads(line)
        for line in flight.read_text().splitlines()
        if line.strip()
    ]
    assert len(records) >= 4  # 2 rounds (cycles) per process
    incarnations = {r["incarnation"] for r in records}
    assert len(incarnations) == 2, incarnations
    seqs = [r["seq"] for r in records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # the black-box payload is real: stage timings + cycle verdicts
    assert all("stage_ms" in r and "bound" in r for r in records)
    assert any(r["bound"] > 0 for r in records)


def test_scheduler_flight_file_in_process(tmp_path, capsys):
    """In-process double invocation of main() (fast arm of the same
    smoke): the second CLI stack adopts the first's records."""
    flight = tmp_path / "flight.jsonl"
    argv = [
        "--sim-nodes", "10", "--sim-pods", "20", "--rounds", "1",
        "--flight-file", str(flight),
    ]
    assert koord_scheduler.main(argv) == 0
    n_first = len(flight.read_text().splitlines())
    assert n_first >= 1
    assert koord_scheduler.main(argv) == 0
    lines = flight.read_text().splitlines()
    assert len(lines) > n_first
    incs = {json.loads(line)["incarnation"] for line in lines}
    assert len(incs) == 2
