"""Leader election tests (reference: client-go leaderelection as used at
``cmd/koord-scheduler/app/server.go:247-281``)."""

import os
import threading

import pytest

from koordinator_tpu.utils.leaderelection import (
    FileLeaseLock,
    InMemoryLeaseLock,
    LeaderElector,
    LeaseRecord,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def elector(lock, ident, clock, **kw):
    kw.setdefault("lease_duration", 15.0)
    kw.setdefault("renew_deadline", 10.0)
    kw.setdefault("retry_period", 2.0)
    return LeaderElector(
        lock, ident, now_fn=clock.now, sleep_fn=clock.sleep, **kw
    )


def test_acquire_then_contender_blocked():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    assert a.is_leader()
    assert not b.try_acquire_or_renew()
    assert b.leader_identity() == "a"


def test_takeover_after_lease_expiry():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    clock.t = 14.0
    assert not b.try_acquire_or_renew()  # still inside a's lease
    clock.t = 15.1
    assert b.try_acquire_or_renew()      # expired -> takeover
    assert b.is_leader()
    rec = lock.get()
    assert rec.holder == "b" and rec.transitions == 1


def test_renew_preserves_acquire_time():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    assert a.try_acquire_or_renew()
    t0 = lock.get().acquire_time
    clock.t = 5.0
    assert a.try_acquire_or_renew()
    rec = lock.get()
    assert rec.acquire_time == t0 and rec.renew_time == 5.0


def test_release_lets_contender_in_immediately():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    a.release()
    assert b.try_acquire_or_renew()
    assert b.is_leader()


def test_renew_deadline_must_be_shorter_than_lease():
    with pytest.raises(ValueError):
        LeaderElector(InMemoryLeaseLock(), "x", lease_duration=5, renew_deadline=5)


def test_file_lock_cas_rejects_stale_update(tmp_path):
    path = os.fspath(tmp_path / "lease.json")
    lock = FileLeaseLock(path)
    rec = LeaseRecord(holder="a", acquire_time=0, renew_time=0, lease_duration=15)
    assert lock.create(rec)
    newer = LeaseRecord(holder="a", acquire_time=0, renew_time=5, lease_duration=15)
    assert lock.update(rec, newer)
    # an update based on the outdated snapshot must fail (CAS)
    stolen = LeaseRecord(holder="b", acquire_time=9, renew_time=9, lease_duration=15)
    assert not lock.update(rec, stolen)
    assert lock.get().holder == "a"


def test_file_lock_survives_corrupt_file(tmp_path):
    path = os.fspath(tmp_path / "lease.json")
    with open(path, "w") as f:
        f.write("{not json")
    lock = FileLeaseLock(path)
    assert lock.get() is None
    assert lock.create(
        LeaseRecord(holder="a", acquire_time=0, renew_time=0, lease_duration=15)
    ) is False or lock.get().holder == "a"


def test_run_acquire_renew_release_cycle():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    started, stopped = [], []
    a = elector(
        lock,
        "a",
        clock,
        on_started_leading=lambda: started.append(True),
        on_stopped_leading=lambda: stopped.append(True),
    )
    stop = threading.Event()

    orig_sleep = clock.sleep

    def sleeper(dt):
        orig_sleep(dt)
        if clock.t > 30:
            stop.set()

    a._sleep = sleeper
    a.run(stop)
    assert started and stopped
    # released: a fresh contender can take it at the current fake time
    b = elector(lock, "b", clock)
    assert b.try_acquire_or_renew()
