"""Leader election tests (reference: client-go leaderelection as used at
``cmd/koord-scheduler/app/server.go:247-281``)."""

import os
import threading

import pytest

from koordinator_tpu.utils.leaderelection import (
    FileLeaseLock,
    InMemoryLeaseLock,
    LeaderElector,
    LeaseRecord,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def elector(lock, ident, clock, **kw):
    kw.setdefault("lease_duration", 15.0)
    kw.setdefault("renew_deadline", 10.0)
    kw.setdefault("retry_period", 2.0)
    return LeaderElector(
        lock, ident, now_fn=clock.now, sleep_fn=clock.sleep, **kw
    )


def test_acquire_then_contender_blocked():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    assert a.is_leader()
    assert not b.try_acquire_or_renew()
    assert b.leader_identity() == "a"


def test_takeover_after_lease_expiry():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    clock.t = 14.0
    assert not b.try_acquire_or_renew()  # still inside a's lease
    clock.t = 15.1
    assert b.try_acquire_or_renew()      # expired -> takeover
    assert b.is_leader()
    rec = lock.get()
    assert rec.holder == "b" and rec.transitions == 1


def test_renew_preserves_acquire_time():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    assert a.try_acquire_or_renew()
    t0 = lock.get().acquire_time
    clock.t = 5.0
    assert a.try_acquire_or_renew()
    rec = lock.get()
    assert rec.acquire_time == t0 and rec.renew_time == 5.0


def test_release_lets_contender_in_immediately():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    a.release()
    assert b.try_acquire_or_renew()
    assert b.is_leader()


def test_renew_deadline_must_be_shorter_than_lease():
    with pytest.raises(ValueError):
        LeaderElector(InMemoryLeaseLock(), "x", lease_duration=5, renew_deadline=5)


def test_file_lock_cas_rejects_stale_update(tmp_path):
    path = os.fspath(tmp_path / "lease.json")
    lock = FileLeaseLock(path)
    rec = LeaseRecord(holder="a", acquire_time=0, renew_time=0, lease_duration=15)
    assert lock.create(rec)
    newer = LeaseRecord(holder="a", acquire_time=0, renew_time=5, lease_duration=15)
    assert lock.update(rec, newer)
    # an update based on the outdated snapshot must fail (CAS)
    stolen = LeaseRecord(holder="b", acquire_time=9, renew_time=9, lease_duration=15)
    assert not lock.update(rec, stolen)
    assert lock.get().holder == "a"


def test_file_lock_survives_corrupt_file(tmp_path):
    path = os.fspath(tmp_path / "lease.json")
    with open(path, "w") as f:
        f.write("{not json")
    lock = FileLeaseLock(path)
    assert lock.get() is None
    assert lock.create(
        LeaseRecord(holder="a", acquire_time=0, renew_time=0, lease_duration=15)
    ) is False or lock.get().holder == "a"


# ---------------------------------------------------------------------------
# failover-critical edges (HA PR satellite): renew-race at expiry, clock
# skew tolerance, re-election after force-release, fencing epochs
# ---------------------------------------------------------------------------


def test_renew_race_at_lease_expiry_admits_exactly_one():
    """At the expiry instant the holder's renew and a contender's
    takeover race on the CAS: whichever lands first wins, the loser's
    update (based on the now-stale record) MUST fail."""
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    clock.t = 15.1  # a's lease just expired; both see the same record
    stale = lock.get()
    # b's takeover lands first...
    assert b.try_acquire_or_renew()
    # ...so a's renew — CAS'd against the record it observed before b
    # moved it — must lose, not silently steal leadership back
    assert not a.try_acquire_or_renew()
    assert not a.is_leader() and b.is_leader()
    rec = lock.get()
    assert rec.holder == "b" and rec.epoch == 2
    # and the direct stale-CAS form: an update based on the pre-takeover
    # snapshot is rejected outright
    import dataclasses as _dc

    assert not lock.update(
        stale, _dc.replace(stale, renew_time=clock.t)
    )


def test_clock_skew_tolerance_delays_foreign_takeover():
    """With clock_skew_s=2 a contender waits 2 extra seconds past
    nominal expiry before stealing — a holder whose clock runs ahead of
    ours is not deposed while it still believes its lease is live."""
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock, clock_skew_s=2.0)
    c = elector(lock, "c", clock)  # no tolerance, for contrast
    assert a.try_acquire_or_renew()
    clock.t = 16.0  # nominally expired (15s lease)...
    assert not b.try_acquire_or_renew()  # ...but inside b's skew window
    clock.t = 17.5
    assert b.try_acquire_or_renew()      # past lease + skew: takeover
    assert b.is_leader()
    # the skew window never blocks taking a DEAD lease eventually, and
    # the no-tolerance contender would have taken it at 16.0 (sanity)
    a.release()
    b.release()
    assert c.try_acquire_or_renew()


def test_reelection_after_force_release_bumps_epoch():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    assert a.current_epoch() == 1
    a.release()  # force-release: the held lease is surrendered
    assert a.current_epoch() is None
    # the next grant — whoever wins it — is a NEW fencing epoch
    assert b.try_acquire_or_renew()
    assert b.current_epoch() == 2
    rec = lock.get()
    assert rec.transitions == 1
    # same for the original holder re-acquiring its OWN released lease:
    # that is a re-acquisition, not a renew
    b.release()
    assert a.try_acquire_or_renew()
    assert a.current_epoch() == 3


def test_renew_preserves_epoch_takeover_bumps_it():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    a = elector(lock, "a", clock)
    b = elector(lock, "b", clock)
    assert a.try_acquire_or_renew()
    clock.t = 5.0
    assert a.try_acquire_or_renew()  # renew
    assert lock.get().epoch == 1 and a.current_epoch() == 1
    clock.t = 30.0  # expired: b takes over
    assert b.try_acquire_or_renew()
    assert lock.get().epoch == 2
    assert a.current_epoch() is None or a.current_epoch() == 1
    # a's next protocol step observes the loss
    assert not a.try_acquire_or_renew()
    assert a.current_epoch() is None


def test_file_lock_roundtrips_epoch(tmp_path):
    path = os.fspath(tmp_path / "lease.json")
    lock = FileLeaseLock(path)
    rec = LeaseRecord(
        holder="a", acquire_time=0, renew_time=0, lease_duration=15, epoch=7
    )
    assert lock.create(rec)
    assert lock.get().epoch == 7


def test_run_acquire_renew_release_cycle():
    lock, clock = InMemoryLeaseLock(), FakeClock()
    started, stopped = [], []
    a = elector(
        lock,
        "a",
        clock,
        on_started_leading=lambda: started.append(True),
        on_stopped_leading=lambda: stopped.append(True),
    )
    stop = threading.Event()

    orig_sleep = clock.sleep

    def sleeper(dt):
        orig_sleep(dt)
        if clock.t > 30:
            stop.set()

    a._sleep = sleeper
    a.run(stop)
    assert started and stopped
    # released: a fresh contender can take it at the current fake time
    b = elector(lock, "b", clock)
    assert b.try_acquire_or_renew()
