"""Per-slot on-device GPU accounting (VERDICT r4 #1).

The solver carries the exact slot table through its commit rounds
(``ops/device.py`` slot_stats/slot_commit/slot_refund), mirroring the
reference's per-minor ``deviceResources`` state
(``pkg/scheduler/plugins/deviceshare/device_cache.go``) and its
allocator's best-fit rule (``allocator_gpu.go:1-451``).
"""

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Device,
    DeviceInfo,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.ops.device import (
    DeviceState,
    device_fit_mask,
    slot_commit,
    slot_refund,
    slot_stats,
)
from koordinator_tpu.scheduler.batch_solver import BatchScheduler
from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager


def test_slot_stats():
    slots = jnp.asarray(
        [[100.0, 100.0, 40.0], [70.0, 0.0, 0.0], [0.0, 0.0, 0.0]], jnp.float32
    )
    full, partial, smax, total = (np.asarray(a) for a in slot_stats(slots))
    assert full.tolist() == [2.0, 0.0, 0.0]
    assert partial.tolist() == [40.0, 70.0, 0.0]
    assert smax.tolist() == [100.0, 70.0, 0.0]
    assert total.tolist() == [240.0, 70.0, 0.0]


def test_fit_mask_exact_combined_whole_plus_share():
    # node 0: 2 full + a 40% partial; node 1: 2 full only
    state = DeviceState(
        slot_free=jnp.asarray(
            [[100.0, 100.0, 40.0], [100.0, 100.0, 0.0]], jnp.float32
        )
    )
    full, partial, smax, _ = slot_stats(state.slot_free)
    whole = jnp.asarray([2, 2, 1], jnp.int32)
    share = jnp.asarray([30.0, 50.0, 50.0], jnp.float32)
    mask = np.asarray(device_fit_mask(whole, share, full, partial, smax))
    # 2 whole + 30%: node 0 fits (partial 40 covers 30), node 1 cannot
    # (no 3rd slot). The old aggregate mask called node 1 feasible.
    assert mask[0].tolist() == [True, False]
    # 2 whole + 50%: neither (partial too small / missing)
    assert mask[1].tolist() == [False, False]
    # 1 whole + 50%: both (second full slot opens for the remainder)
    assert mask[2].tolist() == [True, True]


def test_slot_commit_whole_and_bestfit_partial():
    slots = jnp.asarray(
        [
            [100.0, 100.0, 60.0, 30.0],   # whole=1, frac 25 → best-fit 30-slot
            [100.0, 100.0, 0.0, 0.0],     # whole=1, frac 50 opens full slot
            [100.0, 50.0, 0.0, 0.0],      # untouched
        ],
        jnp.float32,
    )
    out = np.asarray(
        slot_commit(
            slots,
            whole_taken=jnp.asarray([1.0, 1.0, 0.0]),
            frac_share=jnp.asarray([25.0, 50.0, 0.0]),
            frac_opens_full=jnp.asarray([False, True, False]),
        )
    )
    # node 0: first full slot zeroed; 25 came out of the tightest
    # sufficient partial (30), NOT the 60 — the host best-fit rule
    assert out[0].tolist() == [0.0, 100.0, 60.0, 5.0]
    # node 1: slot 0 zeroed by the whole, slot 1 opened to 50
    assert out[1].tolist() == [0.0, 50.0, 0.0, 0.0]
    assert out[2].tolist() == [100.0, 50.0, 0.0, 0.0]


def test_slot_refund_waterfill():
    slots = jnp.asarray(
        [[0.0, 0.0, 40.0], [70.0, 100.0, 0.0]], jnp.float32
    )
    out = np.asarray(
        slot_refund(slots, jnp.asarray([200.0, 30.0], jnp.float32))
    )
    # node 0: two zeroed slots restored to full (a rolled-back 2-GPU member)
    assert out[0].tolist() == [100.0, 100.0, 40.0]
    # node 1: 30 lands on the emptiest slot
    assert out[1].tolist() == [70.0, 100.0, 30.0]
    # never beyond FULL
    assert (out <= 100.0 + 1e-6).all()


def test_slot_refund_skips_padding_slots():
    """Heterogeneous inventories pad the slot table with zero rows; a gang
    refund must land on the node's REAL slots, not fabricate capacity on
    padding (code-review r5 finding)."""
    # node with ONE real GPU in a G=4 table; a fractional bite of 40 was
    # rolled back
    slots = jnp.asarray([[60.0, 0.0, 0.0, 0.0]], jnp.float32)
    exists = jnp.asarray([[True, False, False, False]])
    out = np.asarray(
        slot_refund(slots, jnp.asarray([40.0], jnp.float32), exists)
    )
    assert out[0].tolist() == [100.0, 0.0, 0.0, 0.0]
    full, _, _, _ = (np.asarray(a) for a in slot_stats(jnp.asarray(out)))
    assert full[0] == 1.0


def _mixed_cluster(n_nodes=6, gpus=4):
    snap = ClusterSnapshot()
    dm = DeviceManager(snap)
    for i in range(n_nodes):
        name = f"n{i}"
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=name),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: 256000, ext.RES_MEMORY: 1 << 20}
                ),
            )
        )
        dm.upsert_device(
            Device(
                meta=ObjectMeta(name=name),
                devices=[
                    DeviceInfo(dev_type="gpu", minor=g) for g in range(gpus)
                ],
            )
        )
    return snap, dm


def test_mixed_whole_fractional_batch_places_fully():
    """A mixed whole+fractional batch that exactly fills the inventory
    places completely — the failure mode of the old conservative
    aggregates was burned rounds / host rejects on exactly this mix."""
    snap, dm = _mixed_cluster(n_nodes=6, gpus=4)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    pods = []
    # per node: one 2-GPU pod + one 1-GPU pod + two 50% pods = 4 GPUs
    for i in range(6):
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"w2-{i}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 4000, ext.RES_GPU: 2}, priority=9000
                ),
            )
        )
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"w1-{i}"),
                spec=PodSpec(
                    requests={ext.RES_CPU: 4000, ext.RES_GPU: 1}, priority=8000
                ),
            )
        )
        for j in range(2):
            pods.append(
                Pod(
                    meta=ObjectMeta(name=f"f-{i}-{j}"),
                    spec=PodSpec(
                        requests={
                            ext.RES_CPU: 1000,
                            ext.RES_GPU_MEMORY_RATIO: 50,
                        },
                        priority=7000,
                    ),
                )
            )
    out = sched.schedule(pods)
    assert len(out.bound) == len(pods), (
        f"only {len(out.bound)}/{len(pods)} placed; unschedulable: "
        f"{sorted(p.meta.name for p in out.unschedulable)}"
    )
    # the host DeviceManager accepted every winner: all slots consumed
    for i in range(6):
        st = dm.node(f"n{i}")
        assert sum(st.gpu_free) == 0.0, (f"n{i}", st.gpu_free)


def test_chunked_device_carry_is_exact():
    """Across solver chunks the carried slot table matches the host
    DeviceManager's post-commit state (chained dev_carry, no re-lowering
    between chunks)."""
    snap, dm = _mixed_cluster(n_nodes=4, gpus=2)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=4)
    pods = [
        Pod(
            meta=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000, ext.RES_GPU: 1}, priority=9000
            ),
        )
        for i in range(8)
    ]
    out = sched.schedule(pods)
    assert len(out.bound) == 8
    for i in range(4):
        assert sum(dm.node(f"n{i}").gpu_free) == 0.0


def test_uneven_chunks_scanned_and_pipelined_paths():
    """A drain whose last chunk has a smaller natural bucket must work
    through BOTH multi-chunk dispatch paths (code-review r5: the shared
    bucket override and the pair-packing both assumed equal shapes)."""
    snap, dm = _mixed_cluster(n_nodes=16, gpus=4)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=256)
    sched.extender.monitor.stop_background()

    def mk(i, node_name=None):
        return Pod(
            meta=ObjectMeta(name=f"u{i:03d}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 1000},
                priority=9000,
                node_name=node_name,
            ),
        )

    # 300 pods -> chunks of 256 + 44 (buckets 256 vs 128): scanned path
    out = sched.schedule([mk(i) for i in range(300)])
    assert len(out.bound) == 300, len(out.unschedulable)
    # a node-pinned pod forces the per-chunk pipelined fallback with the
    # same uneven chunking
    snap2, dm2 = _mixed_cluster(n_nodes=16, gpus=4)
    sched2 = BatchScheduler(snap2, devices=dm2, batch_bucket=256)
    sched2.extender.monitor.stop_background()
    pods2 = [mk(i) for i in range(299)] + [mk(299, node_name="n0")]
    out2 = sched2.schedule(pods2)
    assert len(out2.bound) == 300, len(out2.unschedulable)


def test_rdma_request_unschedulable_on_gpu_only_cluster():
    """No node carries RDMA: a pod requesting it must surface
    unschedulable (code-review r5: tracing the carry out must not turn
    into silent schedulability)."""
    snap, dm = _mixed_cluster(n_nodes=2, gpus=2)
    sched = BatchScheduler(snap, devices=dm, batch_bucket=64)
    sched.extender.monitor.stop_background()
    pod = Pod(
        meta=ObjectMeta(name="rdma-wanter"),
        spec=PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_RDMA: 100},
            priority=9000,
        ),
    )
    out = sched.schedule([pod])
    assert len(out.bound) == 0 and len(out.unschedulable) == 1
