"""State-integrity PR: checksummed journal codec, quarantine-not-
truncate semantics, verified checkpoints with bounded-RTO recovery,
the resident-state anti-entropy scrubber, and the journal_fsck CLI.

The chaos-soak arms prove the composition under load
(``tests/test_chaos_soak.py``); these are the deterministic unit edges:
crash-retried append dedup, stale-but-valid ``.tmp`` at open, empty
files, CRLF endings, corrupt-then-valid-tail quarantine ordering, and
the checkpoint-digest-mismatch fallback to full replay.
"""

import json
import os

import pytest

from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core import integrity
from koordinator_tpu.core.journal import (
    BindJournal,
    FileJournalStore,
    MemoryJournalStore,
)
from koordinator_tpu.obs.health import HealthRegistry


def _bind(uid, node, req=(1000.0, 2048.0)):
    return {
        "uid": uid,
        "node": node,
        "req": list(req),
        "est": list(req),
        "prod": False,
        "nom": 0.0,
        "conf": True,
        "quota": None,
    }


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def test_seal_verify_roundtrip_and_json_stability():
    rec = {"seq": 3, "op": "bind", "binds": [_bind("a", "n0")]}
    sealed = integrity.seal(rec)
    assert integrity.verify(sealed) is True
    # a JSON round-trip (what FileJournalStore load does) keeps the CRC
    reloaded = json.loads(json.dumps(sealed))
    assert integrity.verify(reloaded) is True
    # legacy records (no crc) are neither valid nor corrupt
    assert integrity.verify(rec) is None
    # any payload drift fails
    drifted = dict(sealed, op="forget")
    assert integrity.verify(drifted) is False
    # sealing is idempotent on an already-correct record
    assert integrity.seal(sealed) == sealed


def test_screen_distinguishes_torn_tail_from_midfile_corruption():
    good = [integrity.seal({"seq": i, "op": "x"}) for i in range(1, 4)]
    # torn FINAL entry: dropped silently, not corruption
    kept, quarantine, rep = integrity.screen_records(
        [(g, None) for g in good] + [(None, '{"seq": 4, "op"')],
    )
    assert len(kept) == 3 and not quarantine
    assert rep.torn_tail and rep.corrupt == 0 and rep.ok
    # the SAME unparseable entry mid-stream is corruption — quarantined,
    # and every verifiable record after it is KEPT
    kept, quarantine, rep = integrity.screen_records(
        [(good[0], None), (None, "garbage"), (good[1], None),
         (good[2], None)],
    )
    assert [r["seq"] for r in kept] == [1, 2, 3]
    assert len(quarantine) == 1 and rep.corrupt == 1 and not rep.ok


def test_screen_dedups_crash_retried_append():
    """A store-level append that landed but whose ack was lost is
    retried with the SAME seq and payload — load keeps exactly one."""
    rec = integrity.seal({"seq": 5, "op": "bind", "uid": "a"})
    kept, quarantine, rep = integrity.screen_records(
        [(dict(rec), None), (dict(rec), None)],
    )
    assert len(kept) == 1 and rep.dup_seq == 1 and rep.ok
    # same seq with DIVERGENT payload is corruption, first copy wins
    other = integrity.seal({"seq": 5, "op": "bind", "uid": "b"})
    kept, quarantine, rep = integrity.screen_records(
        [(dict(rec), None), (dict(other), None)],
    )
    assert len(kept) == 1 and kept[0]["uid"] == "a"
    assert rep.corrupt == 1 and len(quarantine) == 1


def test_screen_counts_interior_seq_gap_only():
    recs = [integrity.seal({"seq": s, "op": "x"}) for s in (4, 5, 8)]
    _kept, _q, rep = integrity.screen_records([(r, None) for r in recs])
    # 6 and 7 are write holes; the 1..3 prefix is a compacted head, not
    # a hole (a rewrite legitimately renumbers the start of the stream)
    assert rep.seq_gaps == 2 and not rep.ok


# ---------------------------------------------------------------------------
# FileJournalStore edges
# ---------------------------------------------------------------------------


def test_file_store_empty_file_and_missing_file(tmp_path):
    path = os.fspath(tmp_path / "j.jsonl")
    open(path, "w").close()
    store = FileJournalStore(path)
    assert store.load() == []
    assert store.integrity_total.ok
    j = BindJournal(store)
    assert j.replay().live == {}


def test_file_store_crlf_line_endings(tmp_path):
    """A journal copied through a CRLF-mangling transport still loads:
    the codec's canonical form is unaffected by the line terminator."""
    path = os.fspath(tmp_path / "j.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_bind(1, 1, [_bind("b", "n1")])
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data.replace(b"\n", b"\r\n"))
    store = FileJournalStore(path)
    rep = BindJournal(store).replay()
    assert set(rep.live) == {"a", "b"}
    assert store.integrity_total.ok


def test_file_store_stale_but_valid_tmp_at_open(tmp_path):
    """A crash AFTER the rewrite's tmp file was fully written but
    BEFORE the atomic rename: the tmp was never the journal — the open
    must drop it and serve the intact live log."""
    path = os.fspath(tmp_path / "j.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.append_bind(1, 1, [_bind("b", "n1")])
    # a COMPLETE, valid checkpoint in .tmp (not torn — the crash came
    # between fsync and rename)
    with open(path + ".tmp", "w", encoding="utf-8") as f:
        f.write(
            json.dumps(
                integrity.seal(
                    {"seq": 99, "op": "checkpoint", "live": {}}
                )
            )
            + "\n"
        )
    store = FileJournalStore(path)
    assert not os.path.exists(path + ".tmp")
    rep = BindJournal(store).replay()
    assert set(rep.live) == {"a", "b"}  # the tmp never shadowed the log


def test_file_store_corrupt_then_valid_tail_quarantine_order(tmp_path):
    """Mid-file corruption quarantines EXACTLY the rotted line into the
    sidecar — in stream order — and every verifiable record after it
    (including a torn-tail trim candidate) keeps its semantics."""
    path = os.fspath(tmp_path / "j.jsonl")
    j = BindJournal(FileJournalStore(path))
    for i in range(4):
        j.append_bind(1, i, [_bind(f"p{i}", "n0")])
    # rot line 1 (seq 2) in place
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    rotted = lines[1][:20] + "#" + lines[1][21:]
    lines[1] = rotted
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    store = FileJournalStore(path)
    rep = BindJournal(store).replay()
    # quarantined, NOT truncated: p0 and the post-corruption tail live
    assert set(rep.live) == {"p0", "p2", "p3"}
    assert rep.corrupt_records == 1
    with open(path + ".quarantine", encoding="utf-8") as f:
        side = f.read().splitlines()
    assert side == [rotted]
    # repeated loads do not double-count or re-append the sidecar
    store.load()
    store.load()
    assert store.integrity_total.corrupt == 1
    with open(path + ".quarantine", encoding="utf-8") as f:
        assert f.read().splitlines() == [rotted]


def test_journal_write_failure_leaves_no_seq_hole():
    chaos = FaultInjector(seed=0)
    j = BindJournal(MemoryJournalStore(), chaos=chaos)
    j.append_bind(1, 0, [_bind("a", "n0")])

    class _Boom(OSError):
        pass

    orig = j.store.append
    state = {"fail": True}

    def flaky(rec):
        if state["fail"]:
            state["fail"] = False
            raise _Boom("disk full")
        orig(rec)

    j.store.append = flaky
    from koordinator_tpu.core.journal import JournalWriteError

    with pytest.raises(JournalWriteError):
        j.append_bind(1, 1, [_bind("b", "n1")])
    j.append_bind(1, 1, [_bind("b", "n1")])  # the caller's retry
    rep = j.replay()
    assert set(rep.live) == {"a", "b"}
    assert rep.seq_gaps == 0  # the rolled-back seq left no hole


# ---------------------------------------------------------------------------
# verified checkpoints + recovery fallback
# ---------------------------------------------------------------------------


def test_append_checkpoint_bounds_replay_and_survives_digest_rot():
    store = MemoryJournalStore()
    j = BindJournal(store)
    for i in range(20):
        j.append_bind(1, i, [_bind(f"p{i}", "n0")])
    j.append_forget(1, 20, ["p0", "p1"])
    j.append_checkpoint(epoch=1)
    j.append_bind(1, 21, [_bind("tail", "n1")])
    fast = j.replay()
    assert fast.used_checkpoint and fast.applied == 2
    assert len(fast.live) == 19
    full = j.replay(use_checkpoint=False)
    assert not full.used_checkpoint and full.applied >= 22
    assert full.live == fast.live  # bit-identical either way
    # rot the checkpoint IMAGE (line CRC re-stamped: models a bad
    # writer / partial application rather than line-level media rot)
    for rec in store._records:
        if rec.get("op") == "checkpoint":
            rec["image_digest"] = "00000000"
            rec["crc"] = integrity.record_crc(rec)
    fb = j.replay()
    assert not fb.used_checkpoint and fb.checkpoint_fallbacks == 1
    assert fb.live == full.live  # fallback rebuilt the same world


def test_compact_checkpoint_carries_digest_and_extras():
    j = BindJournal(MemoryJournalStore())
    j.append_bind(3, 0, [_bind("a", "n0")])
    j.compact(extras={"claim_epoch_highs": {"0": 3}})
    recs = j.records()
    assert len(recs) == 1 and recs[0]["op"] == "checkpoint"
    assert recs[0]["extras"]["claim_epoch_highs"] == {"0": 3}
    assert recs[0]["extras"]["epoch_high"] == 3
    assert BindJournal._checkpoint_image_ok(recs[0])
    # the journal still replays through it after a reload
    assert set(BindJournal(j.store).replay().live) == {"a"}


def test_recover_scheduler_checkpoint_fallback_chaos(tmp_path):
    """``checkpoint.digest_mismatch`` forces recover_scheduler off the
    checkpoint fast path onto the full-history replay — same world,
    counted fallback, journal_integrity re-promoted."""
    import numpy as np

    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.recovery import recover_scheduler
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    def make(store, chaos=None):
        snap = ClusterSnapshot()
        for i in range(4):
            snap.upsert_node(
                Node(
                    meta=ObjectMeta(name=f"n{i}"),
                    status=NodeStatus(
                        allocatable={
                            ext.RES_CPU: 32000.0,
                            ext.RES_MEMORY: 131072.0,
                        }
                    ),
                )
            )
        s = BatchScheduler(
            snap,
            LoadAwareArgs(usage_thresholds={}),
            batch_bucket=8,
            journal=BindJournal(store),
            chaos=chaos,
        )
        s.extender.monitor.stop_background()
        return s

    store = MemoryJournalStore()
    leader = make(store)
    pods = [
        Pod(
            meta=ObjectMeta(name=f"p{k}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 500.0, ext.RES_MEMORY: 1024.0}
            ),
        )
        for k in range(6)
    ]
    out = leader.schedule(pods)
    assert len(out.bound) == 6
    leader.bind_journal.append_checkpoint()
    # normal path: checkpoint + (empty) tail
    warm = make(store)
    rep = recover_scheduler(warm, warm.bind_journal, hub=None)
    assert rep.used_checkpoint and not rep.checkpoint_fallback
    assert len(rep.bindings) == 6
    # chaos path: the digest verdict is forced bad -> full replay
    chaos = FaultInjector(seed=0)
    chaos.arm("checkpoint.digest_mismatch", times=1)
    cold = make(store, chaos=chaos)
    rep2 = recover_scheduler(cold, cold.bind_journal, hub=None)
    assert rep2.checkpoint_fallback and not rep2.used_checkpoint
    assert rep2.bindings == rep.bindings
    assert (
        cold.extender.registry.get(
            "recovery_checkpoint_fallback_total"
        ).value()
        == 1.0
    )
    np.testing.assert_array_equal(
        np.asarray(warm.snapshot.nodes.requested),
        np.asarray(cold.snapshot.nodes.requested),
    )


def test_corruption_flips_health_row_and_counts(tmp_path):
    """The journal_integrity /healthz row degrades on quarantine, the
    per-store counter counts it, and a verified recovery re-promotes."""
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import Node, NodeStatus, ObjectMeta
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.runtime.recovery import recover_scheduler
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    store = MemoryJournalStore(name="shard0")
    seed = BindJournal(store)
    seed.append_intent(1, 0, [("a", "n0")])
    # full-width request row (the snapshot's resource dims), as the
    # real commit path journals it
    seed.append_bind(
        1, 0, [_bind("a", "n0", req=(1000.0, 2048.0, 0.0, 0.0))]
    )
    store._records[0]["__bitrot__"] = 1  # rot the intent record
    snap = ClusterSnapshot()
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name="n0"),
            status=NodeStatus(
                allocatable={
                    ext.RES_CPU: 32000.0,
                    ext.RES_MEMORY: 131072.0,
                }
            ),
        )
    )
    sched = BatchScheduler(
        snap,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=8,
        journal=BindJournal(store),
    )
    sched.extender.monitor.stop_background()
    # wiring noted the corruption the journal's own init load found
    row = sched.extender.health.get("journal_integrity")
    assert row is not None and not row["ok"]
    assert (
        sched.extender.registry.get("journal_corrupt_records_total").value(
            store="shard0"
        )
        >= 1.0
    )
    rep = recover_scheduler(sched, sched.bind_journal, hub=None)
    assert rep.journal_corrupt_records == 1
    assert set(rep.bindings) == {"a"}  # the acked bind survived the rot
    row = sched.extender.health.get("journal_integrity")
    assert row["ok"] and "recovered past quarantine" in row["detail"]


# ---------------------------------------------------------------------------
# anti-entropy scrubber
# ---------------------------------------------------------------------------


def _mini_sched(scrub_rows=4, chaos=None):
    from koordinator_tpu.api import extension as ext
    from koordinator_tpu.api.types import (
        Node,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    snap = ClusterSnapshot()
    for i in range(6):
        snap.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={
                        ext.RES_CPU: 32000.0,
                        ext.RES_MEMORY: 131072.0,
                    }
                ),
            )
        )
    s = BatchScheduler(
        snap,
        LoadAwareArgs(usage_thresholds={}),
        batch_bucket=8,
        chaos=chaos,
        scrub_rows=scrub_rows,
    )
    s.extender.monitor.stop_background()
    pods = [
        Pod(
            meta=ObjectMeta(name=f"p{k}"),
            spec=PodSpec(
                requests={ext.RES_CPU: 500.0, ext.RES_MEMORY: 1024.0}
            ),
        )
        for k in range(3)
    ]
    s.schedule(pods)
    return s


def test_scrub_detects_and_heals_injected_bit_flip():
    import numpy as np

    chaos = FaultInjector(seed=0)
    s = _mini_sched(chaos=chaos)
    reg = s.extender.registry
    base_rows = reg.get("resident_scrub_rows_total").value()
    assert base_rows > 0  # the cycle tail already audited a window
    chaos.arm("resident.bit_flip", times=1)
    last = s.scrub_step()
    assert last["diverged"].get("nodes") == 1
    assert (
        reg.get("resident_scrub_divergence_total").value(table="nodes")
        == 1.0
    )
    # the heal is a dirty MARK; the next refresh scatters truth back
    from koordinator_tpu.runtime.recovery import assert_resident_bitexact

    s.node_state()
    assert_resident_bitexact(s)
    # a clean follow-up step finds nothing
    again = s.scrub_step()
    assert not again["diverged"]
    np.testing.assert_array_equal(
        np.asarray(s.node_state().requested),
        np.asarray(s.snapshot.nodes.requested),
    )


def test_scrub_skips_dirty_rows_not_divergence():
    """Rows the host legitimately mutated (pending dirty marks) are NOT
    divergence — the audit must never 'heal' un-scattered truth."""
    s = _mini_sched(scrub_rows=64)  # whole bucket per step
    s.snapshot.nodes.requested[0, 0] += 123.0
    s.snapshot.touch_rows([0])
    last = s.scrub_step()
    assert not last["diverged"]
    # once scattered, the same window is clean again
    s.node_state()
    last = s.scrub_step()
    assert not last["diverged"]


def test_scrub_debug_endpoint_and_report_shape():
    s = _mini_sched()
    code, body = s.extender.services.dispatch("GET", "/debug/scrub")
    assert code == 200
    doc = json.loads(body)
    assert doc["enabled"] and doc["rows_audited"] > 0
    assert set(doc) >= {
        "enabled", "window", "cursor", "steps", "rows_audited",
        "divergence", "last",
    }


def test_scrub_disabled_is_inert():
    from koordinator_tpu.core.snapshot import ClusterSnapshot
    from koordinator_tpu.scheduler.batch_solver import (
        BatchScheduler,
        LoadAwareArgs,
    )

    s = BatchScheduler(ClusterSnapshot(), LoadAwareArgs())
    s.extender.monitor.stop_background()
    code, body = s.extender.services.dispatch("GET", "/debug/scrub")
    assert code == 200 and not json.loads(body)["enabled"]
    assert s.extender.registry.get("resident_scrub_rows_total").value() == 0.0


# ---------------------------------------------------------------------------
# journal_fsck CLI
# ---------------------------------------------------------------------------


def _write_journal(tmp_path, name="j.jsonl"):
    path = os.fspath(tmp_path / name)
    j = BindJournal(FileJournalStore(path))
    for i in range(5):
        j.append_bind(1, i, [_bind(f"p{i}", "n0")])
    j.store.close()
    return path


def test_fsck_clean_file_exits_zero(tmp_path, capsys):
    from tools.journal_fsck import main

    path = _write_journal(tmp_path)
    assert main([path]) == 0
    out = capsys.readouterr().out
    assert "ok" in out and "OK" in out


def test_fsck_detects_and_repairs_corruption(tmp_path, capsys):
    from tools.journal_fsck import main

    path = _write_journal(tmp_path)
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    lines[2] = lines[2][:15] + "#" + lines[2][16:]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    # verify mode: corruption found, file untouched, exit 1
    assert main(["--json", "-", path]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["files"][0]["corrupt"] == 1
    assert not doc["files"][0]["unrepairable"]
    # repair mode: quarantined + rewritten clean, exit 0
    assert main(["--repair", path]) == 0
    capsys.readouterr()
    assert os.path.exists(path + ".quarantine")
    assert main([path]) == 0  # now verifies clean
    capsys.readouterr()
    rep = BindJournal(FileJournalStore(path)).replay()
    assert set(rep.live) == {"p0", "p1", "p3", "p4"}


def test_fsck_flags_unrepairable_head_checkpoint(tmp_path, capsys):
    from tools.journal_fsck import main

    path = os.fspath(tmp_path / "j.jsonl")
    j = BindJournal(FileJournalStore(path))
    j.append_bind(1, 0, [_bind("a", "n0")])
    j.compact()
    j.store.close()
    with open(path, encoding="utf-8") as f:
        line = f.read().splitlines()[0]
    rec = json.loads(line)
    rec["image_digest"] = "00000000"
    rec["crc"] = integrity.record_crc(rec)
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(rec) + "\n")
    # containment PR exit contract: unrepairable loss is code 2 (code 1
    # is reserved for repairable corruption found in verify mode)
    assert main(["--repair", "--json", "-", path]) == 2
    doc = json.loads(capsys.readouterr().out)
    assert doc["files"][0]["unrepairable"]


def test_fsck_directory_walk_skips_sidecars(tmp_path, capsys):
    from tools.journal_fsck import main

    _write_journal(tmp_path, "a.jsonl")
    _write_journal(tmp_path, "b.jsonl")
    (tmp_path / "c.quarantine").write_text("junk\n")
    (tmp_path / "d.tmp").write_text("junk\n")
    assert main(["--json", "-", os.fspath(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["files"]) == 2


def test_fsck_roundtrips_soak_style_corruption(tmp_path, capsys):
    """fsck over a journal carrying the soak's corruption signature
    (mid-stream rot + a seq write hole): verify flags both, repair
    quarantines the rot, and the repaired journal replays the same
    live set the screening load reconstructs."""
    from tools.journal_fsck import main

    path = os.fspath(tmp_path / "soak.jsonl")
    chaos = FaultInjector(seed=0)
    chaos.arm("journal.seq_gap", at_hits=[3])
    j = BindJournal(FileJournalStore(path), chaos=chaos)
    for i in range(6):
        j.append_intent(1, i, [(f"p{i}", "n0")])
        j.append_bind(1, i, [_bind(f"p{i}", "n0")])
    j.store.close()
    # rot one mid-file bind line
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    lines[5] = lines[5][:25] + "#" + lines[5][26:]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    assert main(["--json", "-", path]) == 1
    doc = json.loads(capsys.readouterr().out)
    f0 = doc["files"][0]
    assert f0["corrupt"] == 1 and f0["seq_gaps"] >= 1
    assert main(["--repair", path]) == 0
    capsys.readouterr()
    rep = BindJournal(FileJournalStore(path)).replay()
    assert len(rep.live) == 5  # one bind rotted; the rest survive
