"""Metrics-advisor collector inventory (reference
pkg/koordlet/metricsadvisor/collectors/* — 12 collectors + device
collectors), driven against a temp-dir fake cgroupfs like the reference's
fake cgroup helpers (SURVEY §4)."""

import os

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import ObjectMeta, Pod, PodSpec
from koordinator_tpu.koordlet import collectors as col
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.daemon import Koordlet, KoordletConfig
from koordinator_tpu.koordlet.runtimehooks import pod_cgroup


def mkpod(name, qos="LS"):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels={ext.LABEL_POD_QOS: qos}),
        spec=PodSpec(requests={ext.RES_CPU: 1000.0}),
    )


def write(root, group, fname, content):
    os.makedirs(os.path.join(root, group), exist_ok=True)
    with open(os.path.join(root, group, fname), "w") as f:
        f.write(content)


class TestPodResourceCollector:
    def test_per_pod_cpu_delta_and_memory(self, tmp_path):
        root = str(tmp_path)
        cache = mc.MetricCache()
        pod = mkpod("p1")
        group = pod_cgroup(pod)
        write(root, group, "cpuacct.usage", "1000000000")  # 1s of cpu
        write(root, group, "memory.usage_in_bytes", str(512 * 1024 * 1024))
        c = col.PodResourceCollector(cache, root, lambda: [pod])
        c.collect(now=100.0)
        write(root, group, "cpuacct.usage", "3000000000")  # +2s over 2s
        c.collect(now=102.0)
        ts, v = cache.latest(mc.POD_CPU_USAGE, "p1")
        assert v == pytest.approx(1000.0)  # 1 core
        assert cache.latest(mc.POD_MEMORY_USAGE, "p1")[1] == pytest.approx(512.0)

    def test_dead_pod_state_pruned(self, tmp_path):
        root = str(tmp_path)
        cache = mc.MetricCache()
        pod = mkpod("p1")
        write(root, pod_cgroup(pod), "cpuacct.usage", "1000000000")
        pods = [pod]
        c = col.PodResourceCollector(cache, root, lambda: pods)
        c.collect(now=100.0)
        assert "p1" in c._last
        pods.clear()
        c.collect(now=101.0)
        assert "p1" not in c._last


class TestSysResourceCollector:
    def test_sys_is_node_minus_kubepods(self, tmp_path):
        root = str(tmp_path)
        cache = mc.MetricCache()
        cache.append(mc.NODE_CPU_USAGE, "node", 102.0, 3000.0)
        write(root, "kubepods", "cpuacct.usage", "1000000000")
        c = col.SysResourceCollector(cache, root)
        assert not c.collect(now=100.0)   # needs a delta
        write(root, "kubepods", "cpuacct.usage", "5000000000")  # +4s / 2s = 2 cores
        assert c.collect(now=102.0)
        assert cache.latest(mc.SYS_CPU_USAGE, "node")[1] == pytest.approx(1000.0)


class TestResctrlCollector:
    def test_sums_domains(self, tmp_path):
        root = str(tmp_path)
        for dom, (llc, mbm) in {
            "mon_L3_00": (100.0, 5000.0),
            "mon_L3_01": (200.0, 7000.0),
        }.items():
            write(root, f"mon_data/{dom}", "llc_occupancy", str(llc))
            write(root, f"mon_data/{dom}", "mbm_total_bytes", str(mbm))
        cache = mc.MetricCache()
        c = col.ResctrlCollector(cache, resctrl_root=root)
        assert c.collect(now=1.0)
        assert cache.latest(mc.NODE_LLC_OCCUPANCY, "node")[1] == 300.0
        assert cache.latest(mc.NODE_MBM_TOTAL, "node")[1] == 12000.0

    def test_absent_resctrl_is_graceful(self, tmp_path):
        c = col.ResctrlCollector(mc.MetricCache(), resctrl_root=str(tmp_path / "no"))
        assert not c.collect(now=1.0)


class TestColdMemoryCollector:
    def test_kidled_stats(self, tmp_path):
        root = str(tmp_path)
        content = (
            "# version: 1.0\n"
            "csei 0 1048576 2097152\n"
            "dsei 0 1048576 0\n"
            "other 0 999 999\n"
        )
        with open(os.path.join(root, "memory.idle_page_stats"), "w") as f:
            f.write(content)
        cache = mc.MetricCache()
        c = col.ColdMemoryCollector(cache, root)
        assert c.collect(now=1.0)
        # (1+2+1) MiB of idle pages
        assert cache.latest(mc.NODE_COLD_MEMORY, "node")[1] == pytest.approx(4.0)


class TestPodThrottledCollector:
    def test_throttle_ratio_delta(self, tmp_path):
        root = str(tmp_path)
        cache = mc.MetricCache()
        pod = mkpod("p1")
        group = pod_cgroup(pod)
        write(root, group, "cpu.stat", "nr_periods 100\nnr_throttled 10\n")
        c = col.PodThrottledCollector(cache, root, lambda: [pod])
        c.collect(now=1.0)
        write(root, group, "cpu.stat", "nr_periods 200\nnr_throttled 60\n")
        assert c.collect(now=2.0)
        assert cache.latest(mc.POD_THROTTLED_RATIO, "p1")[1] == pytest.approx(0.5)


class TestHostApplicationCollector:
    def test_named_app_usage(self, tmp_path):
        root = str(tmp_path)
        cache = mc.MetricCache()
        write(root, "host-latency-sensitive/nginx", "cpuacct.usage", "0")
        write(
            root,
            "host-latency-sensitive/nginx",
            "memory.usage_in_bytes",
            str(256 * 1024 * 1024),
        )
        c = col.HostApplicationCollector(
            cache, root, lambda: [("nginx", "host-latency-sensitive/nginx")]
        )
        c.collect(now=1.0)
        write(root, "host-latency-sensitive/nginx", "cpuacct.usage", "500000000")
        assert c.collect(now=2.0)
        assert cache.latest(mc.HOST_APP_CPU_USAGE, "nginx")[1] == pytest.approx(500.0)
        assert cache.latest(mc.HOST_APP_MEMORY_USAGE, "nginx")[1] == pytest.approx(256.0)


class TestNodeInfoCollector:
    def test_kv_facts(self):
        cache = mc.MetricCache()
        c = col.NodeInfoCollector(cache, n_cpus=8)
        assert c.collect(now=5.0)
        assert cache.get_kv("node_info/num_cpus") == 8.0
        assert cache.get_kv("node_info/last_update") == 5.0


class TestNodeStorageInfoCollector:
    def test_real_diskstats_delta(self):
        # reads the real /proc/diskstats; two samples give a (possibly 0) rate
        cache = mc.MetricCache()
        c = col.NodeStorageInfoCollector(cache)
        first = c._read()
        if first is None:
            pytest.skip("no /proc/diskstats")
        c.collect(now=1.0)
        assert c.collect(now=2.0)
        assert cache.latest(mc.NODE_DISK_READ_BPS, "node")[1] >= 0.0


class TestDeviceCollector:
    def test_sample_stream(self):
        cache = mc.MetricCache()
        samples = [("gpu", 0, 55.0, 4096.0), ("rdma", 1, 10.0, 0.0)]
        c = col.DeviceCollector(cache, lambda: samples)
        assert c.collect(now=1.0)
        assert cache.latest(mc.DEVICE_UTIL, "gpu-0")[1] == 55.0
        assert cache.latest(mc.DEVICE_MEMORY_USED, "gpu-0")[1] == 4096.0
        assert cache.latest(mc.DEVICE_UTIL, "rdma-1")[1] == 10.0


class TestPagecacheCollector:
    def test_reads_meminfo(self):
        cache = mc.MetricCache()
        c = col.PagecacheCollector(cache)
        if not c.collect(now=1.0):
            pytest.skip("no /proc/meminfo")
        assert cache.latest(mc.NODE_PAGECACHE, "node")[1] > 0.0


class TestNativeParity:
    def test_native_lib_loads_and_has_new_symbols(self):
        if not col.native_available():
            pytest.skip("native telemetry not built")
        lib = col._NATIVE
        for sym in (
            "koord_cpi_open",
            "koord_cpi_read",
            "koord_read_pagecache_kib",
            "koord_read_cgroup_throttled",
            "koord_read_diskstats",
        ):
            assert hasattr(lib, sym)


class TestDaemonInventory:
    def test_all_collectors_constructed(self, tmp_path):
        agent = Koordlet(KoordletConfig(cgroup_root=str(tmp_path), n_cpus=4))
        names = {type(c).__name__ for c in agent.collectors}
        assert names == {
            "NodeResourceCollector",
            "PerformanceCollector",
            "BETierCollector",
            "PodResourceCollector",
            "SysResourceCollector",
            "ResctrlCollector",
            "ColdMemoryCollector",
            "PagecacheCollector",
            "PodThrottledCollector",
            "HostApplicationCollector",
            "NodeInfoCollector",
            "NodeStorageInfoCollector",
            "DeviceCollector",
        }
        # a tick over the fake root must not raise
        agent.collect_tick(now=1.0)
