"""Gray-failure containment PR: poison-batch bisection quarantine, the
crash-loop governor, the informer staleness watchdog, the ticketed
POISON_QUARANTINED shed path, the warm-call channel deadline, the
journal_fsck exit-code contract, and the composition soak
(``run_gray_failure_soak``) with its same-seed determinism pair."""

import json
import os

import pytest

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
)
from koordinator_tpu.chaos import FaultInjector
from koordinator_tpu.core import integrity
from koordinator_tpu.core.journal import (
    FileJournalStore,
    MemoryJournalStore,
)
from koordinator_tpu.runtime.containment import (
    POISON_LABEL,
    CrashLoopGovernor,
    QuarantineLedger,
    StalenessWatchdog,
    spec_fingerprint,
)
from koordinator_tpu.scheduler import frameworkext as fwext
from koordinator_tpu.scheduler.batch_solver import (
    BatchScheduler,
    LoadAwareArgs,
)

pytestmark = pytest.mark.chaos


def _mk_sched(n_nodes=4, cpu=32000.0, **kw):
    s = BatchScheduler(
        args=LoadAwareArgs(usage_thresholds={}), batch_bucket=8, **kw
    )
    s.extender.monitor.stop_background()
    for i in range(n_nodes):
        s.snapshot.upsert_node(
            Node(
                meta=ObjectMeta(name=f"n{i}"),
                status=NodeStatus(
                    allocatable={ext.RES_CPU: cpu, ext.RES_MEMORY: 65536.0}
                ),
            )
        )
    return s


def _pod(name, cpu=1000.0, labels=None, priority=9000):
    return Pod(
        meta=ObjectMeta(name=name, uid=name, labels=labels or {}),
        spec=PodSpec(
            requests={ext.RES_CPU: cpu, ext.RES_MEMORY: 256.0},
            priority=priority,
        ),
    )


def _poison_pod(name, cpu=1000.0):
    return _pod(name, cpu=cpu, labels={POISON_LABEL: "1"})


# ---------------------------------------------------------------------------
# spec fingerprints: the redemption key
# ---------------------------------------------------------------------------


class TestSpecFingerprint:
    def test_identical_specs_share_a_fingerprint(self):
        assert spec_fingerprint(_pod("a")) == spec_fingerprint(_pod("b"))

    def test_spec_change_changes_the_fingerprint(self):
        base = spec_fingerprint(_pod("a"))
        assert spec_fingerprint(_pod("a", cpu=2000.0)) != base
        assert spec_fingerprint(_pod("a", labels={"x": "1"})) != base
        assert spec_fingerprint(_pod("a", priority=1)) != base


# ---------------------------------------------------------------------------
# the quarantine ledger
# ---------------------------------------------------------------------------


class TestQuarantineLedger:
    def test_blame_is_idempotent_per_uid_and_fp(self):
        q = QuarantineLedger(incarnation="gen0")
        assert q.blame("ns/p", "fp1", evidence="boom", cycle=3)
        assert not q.blame("ns/p", "fp1", evidence="boom", cycle=4)
        assert q.active() and set(q.entries()) == {"ns/p"}
        recs = q.store.load()
        assert [r["op"] for r in recs] == ["blame"]
        assert recs[0]["incarnation"] == "gen0"
        assert recs[0]["cycle"] == 3

    def test_changed_fingerprint_redeems(self):
        q = QuarantineLedger()
        q.blame("ns/p", "fp1", evidence="boom")
        assert q.blamed("ns/p", "fp1"), "same bytes must stay out"
        # the redeemable ticket: a CHANGED spec re-admits and journals
        # the redeem decision
        assert not q.blamed("ns/p", "fp2")
        assert not q.active()
        assert [r["op"] for r in q.store.load()] == ["blame", "redeem"]
        # the fixed pod can be blamed afresh if it poisons again
        assert q.blame("ns/p", "fp2", evidence="again")

    def test_takeover_adopts_predecessor_blame(self):
        store = MemoryJournalStore(name="quarantine")
        a = QuarantineLedger(store=store, incarnation="gen0")
        a.blame("ns/p", "fp1", evidence="boom")
        b = QuarantineLedger(store=store, incarnation="gen1")
        assert b.blamed("ns/p", "fp1")
        assert b.adopt("gen2") == 1
        # the successor's appends continue the predecessor's numbering
        b.blame("ns/q", "fpX", evidence="other")
        seqs = [r["seq"] for r in store.load()]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert store.load()[-1]["incarnation"] == "gen2"

    def test_file_store_persistence_round_trip(self, tmp_path):
        path = str(tmp_path / "quarantine.journal")
        a = QuarantineLedger(store=FileJournalStore(path))
        a.blame("ns/p", "fp1", evidence="boom")
        b = QuarantineLedger(store=FileJournalStore(path))
        assert b.blamed("ns/p", "fp1")

    def test_corrupted_store_keeps_surviving_blames(self, tmp_path):
        path = str(tmp_path / "quarantine.journal")
        a = QuarantineLedger(store=FileJournalStore(path))
        a.blame("ns/p", "fp1", evidence="boom")
        a.blame("ns/q", "fp2", evidence="boom2")
        lines = open(path, encoding="utf-8").read().splitlines()
        lines[0] = lines[0][:-10] + "corrupted!"
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        b = QuarantineLedger(store=FileJournalStore(path))
        # the rotted blame is quarantined (PR 14 screening), the record
        # behind it survives — and loading never raises
        assert set(b.entries()) == {"ns/q"}


# ---------------------------------------------------------------------------
# the crash-loop governor
# ---------------------------------------------------------------------------


class _Recorder:
    """Captures DecisionLedger.record calls."""

    def __init__(self):
        self.records = []

    def record(self, controller, tick, inputs, action, state, outcome=None):
        self.records.append(
            {
                "controller": controller,
                "tick": tick,
                "inputs": inputs,
                "action": action,
                "state": state,
                "outcome": outcome,
            }
        )


class TestCrashLoopGovernor:
    def test_decide_is_pure(self):
        inputs = {
            "now": 10.0,
            "deaths": [8.0, 9.0, 10.0],
            "boots": 3,
            "k": 3,
            "horizon_s": 30.0,
            "base_backoff_s": 0.5,
            "max_backoff_s": 8.0,
            "brownout_cap": 2,
        }
        frozen = json.dumps(inputs, sort_keys=True)
        assert CrashLoopGovernor.decide(inputs) == CrashLoopGovernor.decide(
            inputs
        )
        assert json.dumps(inputs, sort_keys=True) == frozen

    def test_below_k_decides_nothing(self):
        action, state = CrashLoopGovernor.decide(
            {
                "now": 10.0, "deaths": [9.0, 10.0], "boots": 2, "k": 3,
                "horizon_s": 30.0, "base_backoff_s": 0.5,
                "max_backoff_s": 8.0, "brownout_cap": 2,
            }
        )
        assert action["op"] == "none" and action["backoff_s"] == 0.0
        assert not state["degraded"]

    def test_backoff_grows_exponentially_and_caps(self):
        def backoff(n_deaths):
            action, _state = CrashLoopGovernor.decide(
                {
                    "now": 0.0, "deaths": [0.0] * n_deaths, "boots": 0,
                    "k": 3, "horizon_s": 30.0, "base_backoff_s": 0.5,
                    "max_backoff_s": 8.0, "brownout_cap": 2,
                }
            )
            return action["backoff_s"]

        assert [backoff(n) for n in (3, 4, 5, 6, 7, 8)] == [
            0.5, 1.0, 2.0, 4.0, 8.0, 8.0,
        ]

    def test_old_deaths_age_out_of_the_horizon(self):
        action, _ = CrashLoopGovernor.decide(
            {
                "now": 100.0, "deaths": [1.0, 2.0, 99.0], "boots": 3,
                "k": 3, "horizon_s": 30.0, "base_backoff_s": 0.5,
                "max_backoff_s": 8.0, "brownout_cap": 2,
            }
        )
        assert action["op"] == "none", "ancient deaths are not a loop"

    def test_may_boot_gates_on_injected_clock(self):
        t = [0.0]
        gov = CrashLoopGovernor(
            k=3, horizon_s=30.0, base_backoff_s=2.0, max_backoff_s=8.0,
            clock=lambda: t[0],
        )
        for _ in range(2):
            assert gov.note_death(reason="crash").backoff_s == 0.0
        plan = gov.note_death(reason="crash")
        assert plan.degraded and plan.backoff_s == 2.0
        assert plan.pipeline_depth == 1 and plan.bisect_armed
        assert plan.brownout_cap == 2
        assert not gov.may_boot()
        t[0] = 1.9
        assert not gov.may_boot()
        t[0] = 2.0
        assert gov.may_boot()
        assert gov.boot_plan().degraded, "the NEXT boot stays degraded"

    def test_store_reload_adopts_history(self):
        store = MemoryJournalStore(name="crashloop")
        t = [0.0]
        a = CrashLoopGovernor(store=store, clock=lambda: t[0], k=3)
        a.note_boot("gen0")
        a.note_death("gen0", reason="kill")
        b = CrashLoopGovernor(store=store, clock=lambda: t[0], k=3)
        assert b.boots == 1 and b.deaths == 1
        b.note_death("gen1", reason="boot crash")
        b.note_death("gen1", reason="boot crash")
        assert b.boot_plan().degraded, (
            "the adopted death must count toward K"
        )
        seqs = [r["seq"] for r in store.load()]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_deaths_record_on_the_decision_ledger(self):
        dl = _Recorder()
        t = [0.0]
        gov = CrashLoopGovernor(clock=lambda: t[0], k=2, decisions=dl)
        gov.note_death(reason="first")
        gov.note_death(reason="second")
        assert [r["controller"] for r in dl.records] == [
            "crashloop", "crashloop",
        ]
        assert [r["tick"] for r in dl.records] == [1, 2]
        assert dl.records[-1]["action"]["op"] == "backoff"
        assert dl.records[-1]["outcome"] == {"reason": "second"}
        # the recorded snapshot is complete: replaying decide over it
        # reproduces the recorded action (PR 15 contract)
        for r in dl.records:
            action, state = CrashLoopGovernor.decide(r["inputs"])
            assert action == r["action"] and state == r["state"]


# ---------------------------------------------------------------------------
# the staleness watchdog
# ---------------------------------------------------------------------------


class _FakeTracker:
    def __init__(self):
        self.rv = 0

    def version(self):
        return self.rv


class _FakeInformer:
    def __init__(self, name):
        self.name = name
        self.tracker = _FakeTracker()
        self._observed = 0

    def observed_rv(self):
        return self._observed


class _FakeHub:
    def __init__(self, *informers):
        self.informers = list(informers)


class _FakeHealth:
    def __init__(self):
        self.rows = {}

    def set(self, name, ok, detail=""):
        self.rows[name] = (ok, detail)


class TestStalenessWatchdog:
    def _wd(self, horizon=2.0):
        t = [0.0]
        inf = _FakeInformer("pods")
        health = _FakeHealth()
        reg = fwext.scheduler_registry()
        wd = StalenessWatchdog(
            horizon_s=horizon, clock=lambda: t[0], health=health,
            registry=reg,
        ).watch_hub(_FakeHub(inf))
        return t, inf, health, reg, wd

    def test_caught_up_stream_is_fresh(self):
        t, inf, health, _reg, wd = self._wd()
        inf.tracker.rv = 5
        inf._observed = 5
        assert wd.check() == 0.0 and not wd.stale()
        assert health.rows["snapshot_freshness"][0]

    def test_quiet_stream_never_goes_stale(self):
        # rv-based, not wall-clock-based: silence with no published
        # events is health, not gray failure
        t, _inf, _health, _reg, wd = self._wd()
        t[0] = 1000.0
        assert wd.check() == 0.0 and not wd.stale()

    def test_persistent_lag_degrades_past_horizon(self):
        t, inf, health, reg, wd = self._wd(horizon=2.0)
        inf.tracker.rv = 7          # tracker moved, informer did not
        wd.check()
        assert not wd.stale(), "first sighting starts the age clock"
        t[0] = 2.5
        assert wd.check() == 2.5 and wd.stale()
        ok, detail = health.rows["snapshot_freshness"]
        assert not ok and "pods" in detail
        assert reg.get("snapshot_staleness_seconds").value() == 2.5
        assert wd.staleness_seconds == 2.5

    def test_catching_up_heals(self):
        t, inf, health, reg, wd = self._wd(horizon=2.0)
        inf.tracker.rv = 7
        wd.check()
        t[0] = 3.0
        wd.check()
        assert wd.stale()
        inf._observed = 7
        assert wd.check() == 0.0 and not wd.stale()
        assert health.rows["snapshot_freshness"][0]
        assert reg.get("snapshot_staleness_seconds").value() == 0.0

    def test_detached_informer_cannot_pin_staleness(self):
        t, inf, _health, _reg, wd = self._wd(horizon=2.0)
        inf.tracker.rv = 7
        wd.check()
        wd._hub.informers.remove(inf)
        t[0] = 10.0
        assert wd.check() == 0.0 and not wd.stale()


# ---------------------------------------------------------------------------
# poison bisection + the cycle gate (scheduler wiring)
# ---------------------------------------------------------------------------


class TestPoisonBisection:
    def test_bisection_isolates_the_poison_and_places_the_rest(self):
        chaos = FaultInjector()
        quar = QuarantineLedger(incarnation="gen0")
        s = _mk_sched(chaos=chaos)
        s.quarantine = quar
        chaos.arm("solver.poison_batch")
        pods = [_pod(f"h{i}") for i in range(5)] + [_poison_pod("bad")]
        out = s.schedule(pods)
        assert {p.meta.uid for p in out.unschedulable} == {"bad"}
        assert len(out.bound) == 5
        entries = quar.entries()
        assert set(entries) == {"bad"}
        rec = entries["bad"]
        assert rec["fp"] == spec_fingerprint(_poison_pod("bad"))
        assert "PoisonBatchError" in rec["evidence"]
        recs = s.extender.rejections.for_uid("bad")
        assert recs and recs[-1].reason == "poison_quarantined"

    def test_cycle_gate_rejects_resubmits_without_reprobing(self):
        chaos = FaultInjector()
        quar = QuarantineLedger()
        s = _mk_sched(chaos=chaos)
        s.quarantine = quar
        chaos.arm("solver.poison_batch")
        bad = _poison_pod("bad")
        s.schedule([bad, _pod("h0")])
        fires = len(chaos.trace)
        # the resubmitted same-bytes pod is gated at cycle START — the
        # poison never reaches a lowering again
        out = s.schedule([bad])
        assert {p.meta.uid for p in out.unschedulable} == {"bad"}
        assert len(chaos.trace) == fires

    def test_changed_spec_redeems_and_places(self):
        chaos = FaultInjector()
        quar = QuarantineLedger()
        s = _mk_sched(chaos=chaos)
        s.quarantine = quar
        chaos.arm("solver.poison_batch")
        s.schedule([_poison_pod("bad"), _pod("h0")])
        assert quar.active()
        chaos.disarm()
        fixed = _pod("bad")     # the poison label is gone: new spec
        out = s.schedule([fixed])
        assert [p.meta.uid for p, _n in out.bound] == ["bad"]
        assert not quar.active()


# ---------------------------------------------------------------------------
# stale evidence refuses evidence-hungry actions
# ---------------------------------------------------------------------------


class TestStaleEvidenceGates:
    def test_preemption_refused_on_stale_snapshot(self):
        s = _mk_sched(
            n_nodes=1, cpu=1000.0, enable_priority_preemption=True
        )
        stale = [True]
        s.staleness = lambda: stale[0]
        c = s.extender.registry.get("stale_evidence_refusals_total")
        v0 = c.value(action="preemption")
        low = _pod("low", cpu=800.0, priority=1)
        assert len(s.schedule([low]).bound) == 1
        big = _pod("big", cpu=900.0, priority=9000)
        out = s.schedule([big])
        # plain placement cannot fit it and preemption REFUSED to evict
        assert {p.meta.uid for p in out.unschedulable} == {"big"}
        assert c.value(action="preemption") == v0 + 1
        assert "low" in s.snapshot._assumed
        # events resume: the same pod preempts normally
        stale[0] = False
        out2 = s.schedule([big])
        assert [p.meta.uid for p, _n in out2.bound] == ["big"]
        assert c.value(action="preemption") == v0 + 1

    def test_descheduler_refuses_whole_pass_on_stale(self):
        from koordinator_tpu.descheduler.migration import (
            MigrationController,
        )
        from koordinator_tpu.scheduler.plugins.reservation import (
            ReservationManager,
        )

        s = _mk_sched()
        evicted = []
        stale = [True]
        reg = fwext.scheduler_registry()
        mig = MigrationController(
            ReservationManager(s),
            evict_fn=evicted.append,
            freshness=lambda: stale[0],
            registry=reg,
        )
        mig.reconcile(now=0.0)
        assert mig.refused_stale == 1 and not evicted
        assert (
            reg.get("stale_evidence_refusals_total").value(
                action="descheduler_eviction"
            )
            == 1.0
        )
        stale[0] = False
        mig.reconcile(now=1.0)
        assert mig.refused_stale == 1


# ---------------------------------------------------------------------------
# the ticketed POISON_QUARANTINED shed path
# ---------------------------------------------------------------------------


class TestQuarantineShedFunnel:
    def test_quarantined_pod_sheds_with_redeemable_ticket(self):
        from koordinator_tpu.obs.rejections import RejectReason
        from koordinator_tpu.runtime.overload import AdmissionController
        from koordinator_tpu.scheduler.stream import StreamScheduler

        chaos = FaultInjector()
        s = _mk_sched(chaos=chaos)
        s.quarantine = QuarantineLedger()
        ov = AdmissionController()
        st = StreamScheduler(s, max_batch=4, overload=ov)
        chaos.arm("solver.poison_batch")
        st.submit(_poison_pod("bad"), now=0.0)
        st.submit(_pod("h0"), now=0.0)
        results = st.pump()
        chaos.disarm()
        verdicts = {p.meta.uid: n for p, n, _l in results}
        assert verdicts.get("h0") is not None
        assert verdicts.get("bad", "queued") is None, (
            "the blamed pod must shed terminally, not burn retries"
        )
        tickets = ov.take_tickets()
        assert [t.reason for t in tickets] == [
            RejectReason.POISON_QUARANTINED.value
        ]
        assert tickets[0].pod.meta.uid == "bad"
        # redeem: the driver resubmits with a FIXED spec and it places
        st.submit(_pod("bad"), now=1.0)
        results2 = st.pump()
        assert [(p.meta.uid, n is not None) for p, n, _l in results2] == [
            ("bad", True)
        ]


# ---------------------------------------------------------------------------
# the warm-call channel deadline (timeout_warm_s)
# ---------------------------------------------------------------------------


class TestWarmCallDeadline:
    def _client(self, chaos=None, **kw):
        from koordinator_tpu.runtime.snapshot_channel import SolverClient

        cli = SolverClient("localhost:1", chaos=chaos, **kw)
        timeouts = []

        def stub(req, timeout=None, metadata=None):
            timeouts.append(timeout)
            return object()

        cli._sync = stub
        return cli, timeouts

    def test_cold_call_unbounded_then_warm_deadline(self):
        cli, timeouts = self._client(timeout_warm_s=2.5)
        cli.sync(object())
        cli.sync(object())
        cli.sync(object())
        # the FIRST call pays the JIT compile — no deadline; every call
        # after a success is steady-state and a hang is a gray failure
        assert timeouts == [None, 2.5, 2.5]

    def test_failed_cold_call_stays_cold(self):
        from koordinator_tpu.runtime.snapshot_channel import (
            ChannelUnavailable,
        )

        chaos = FaultInjector()
        chaos.arm("channel.sync.drop", times=1)
        cli, timeouts = self._client(chaos=chaos, timeout_warm_s=2.5)
        with pytest.raises(ChannelUnavailable):
            cli.sync(object())
        cli.sync(object())
        assert timeouts == [None], (
            "the channel never succeeded — the compile may still be "
            "ahead, so the deadline must not arm"
        )

    def test_explicit_timeout_wins_and_delay_rides_the_deadline(self):
        slept = []
        chaos = FaultInjector(sleep=slept.append)
        chaos.arm("channel.sync.delay", latency_s=0.8)
        cli, timeouts = self._client(
            chaos=chaos, timeout_s=1.0, timeout_warm_s=9.0
        )
        cli.sync(object())
        cli.sync(object())
        # an explicit per-call deadline always wins over the warm one,
        # and the injected delay fires BEFORE the wire — the stub still
        # sees the deadline it must enforce
        assert timeouts == [1.0, 1.0]
        assert slept == [0.8, 0.8]


# ---------------------------------------------------------------------------
# circuit breaker: half-open probe discipline under an injected clock
# ---------------------------------------------------------------------------


class TestBreakerHalfOpenProbe:
    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

    def _tripped(self, threshold=2, cooldown=10.0):
        from koordinator_tpu.runtime.overload import CircuitBreaker

        clock = self._Clock()
        b = CircuitBreaker(
            threshold=threshold, cooldown_s=cooldown, clock=clock
        )
        for _ in range(threshold):
            b.record_failure()
        assert b.state == b.OPEN
        return b, clock

    def test_denied_while_open(self):
        b, clock = self._tripped(cooldown=10.0)
        for t in (0.0, 3.0, 9.99):
            clock.t = t
            assert not b.allow(), f"admitted at t={t} inside cooldown"

    def test_exactly_one_probe_at_half_open(self):
        b, clock = self._tripped(cooldown=10.0)
        clock.t = 10.0
        assert b.allow()
        assert b.state == b.HALF_OPEN
        assert not b.allow() and not b.allow(), "probe slot is single"
        b.record_success()
        assert b.state == b.CLOSED and b.allow()

    def test_probe_failure_reopens_with_reset_backoff(self):
        b, clock = self._tripped(cooldown=10.0)
        clock.t = 10.0
        assert b.allow()
        b.record_failure()
        assert b.state == b.OPEN
        clock.t = 19.9
        assert not b.allow(), (
            "the cooldown must restart from the FAILED probe, not the "
            "original trip"
        )
        clock.t = 20.0
        assert b.allow() and b.state == b.HALF_OPEN


# ---------------------------------------------------------------------------
# journal_fsck exit-code contract + containment ledger coverage
# ---------------------------------------------------------------------------


def _fsck(argv):
    from tools.journal_fsck import main

    return main(argv)


class TestJournalFsckExitCodes:
    def test_exit_0_on_clean_ledger(self, tmp_path):
        path = str(tmp_path / "quarantine.journal")
        q = QuarantineLedger(store=FileJournalStore(path))
        q.blame("ns/p", "fp1", evidence="boom")
        assert _fsck([path]) == 0

    def test_exit_1_on_corruption(self, tmp_path, capsys):
        path = str(tmp_path / "quarantine.journal")
        q = QuarantineLedger(store=FileJournalStore(path))
        q.blame("ns/p", "fp1", evidence="boom")
        q.blame("ns/q", "fp2", evidence="boom2")
        lines = open(path, encoding="utf-8").read().splitlines()
        lines[0] = lines[0][:-8] + "rotted!!"
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        assert _fsck([path]) == 1
        assert "CORRUPTION FOUND" in capsys.readouterr().out
        # repair quarantines + rewrites clean: verify then exits 0
        assert _fsck([path, "--repair"]) == 0
        assert os.path.exists(path + ".quarantine")
        assert _fsck([path]) == 0

    def test_exit_2_on_unreadable_store(self, tmp_path):
        assert _fsck([str(tmp_path / "never_written.journal")]) == 2

    def test_containment_ops_tally(self, tmp_path, capsys):
        qpath = str(tmp_path / "quarantine.journal")
        cpath = str(tmp_path / "crashloop.journal")
        q = QuarantineLedger(store=FileJournalStore(qpath))
        q.blame("ns/p", "fp1", evidence="boom")
        assert not q.blamed("ns/p", "fp2")      # journals a redeem
        t = [0.0]
        gov = CrashLoopGovernor(
            store=FileJournalStore(cpath), clock=lambda: t[0]
        )
        gov.note_boot("gen0")
        gov.note_death("gen0", reason="kill")
        assert _fsck([str(tmp_path), "--json", "-"]) == 0
        doc = json.loads(capsys.readouterr().out)
        ops = {
            os.path.basename(f["path"]): f["containment_ops"]
            for f in doc["files"]
        }
        assert ops["quarantine.journal"] == {"blame": 1, "redeem": 1}
        assert ops["crashloop.journal"] == {"boot": 1, "death": 1}


# ---------------------------------------------------------------------------
# the composition soak
# ---------------------------------------------------------------------------


def _dump_sealed(path, records):
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(
                json.dumps(integrity.seal(dict(rec)), separators=(",", ":"))
                + "\n"
            )


class TestGrayFailureSoak:
    def test_soak_green_and_ledgers_fsck_clean(self, tmp_path):
        from koordinator_tpu.sim.longrun import run_gray_failure_soak

        stats = run_gray_failure_soak(seed=0)
        # the soak asserts the contract internally (exact quarantine
        # across the kill-restart, 100% placement of the rest, bounded
        # crash-loop boots, zero-dup/zero-lost-ack); spot-check the
        # headline numbers and that all three points actually fired
        assert stats["placed"] == stats["arrived"] - 2
        assert stats["takeovers"] >= 2
        assert stats["faults"]["solver.poison_batch"] >= 1
        assert stats["faults"]["scheduler.boot_crash"] == 2
        assert stats["faults"]["informer.silent_stall"] >= 1
        assert stats["poison_quarantined_total"] >= 2.0
        assert stats["bisect_probes_total"] >= 2.0
        assert stats["crash_backoffs_total"] >= 1.0
        assert stats["health_ok"], stats["health_detail"]
        # the end-state ledgers round-trip through journal_fsck clean
        qpath = str(tmp_path / "quarantine.journal")
        cpath = str(tmp_path / "crashloop.journal")
        _dump_sealed(qpath, stats["quarantine_dump"])
        _dump_sealed(cpath, stats["crashloop_dump"])
        assert _fsck([qpath, cpath]) == 0

    def test_same_seed_same_trace(self):
        from koordinator_tpu.sim.longrun import run_gray_failure_soak

        a = run_gray_failure_soak(seed=7)
        b = run_gray_failure_soak(seed=7)
        assert a["fault_trace"] == b["fault_trace"]
        assert a["decision_trace"] == b["decision_trace"]
        assert a["quarantine_dump"] == b["quarantine_dump"]
        assert a["crashloop_dump"] == b["crashloop_dump"]
        assert a["placed"] == b["placed"]
        assert a["bind_journal_live"] == b["bind_journal_live"]


# ---------------------------------------------------------------------------
# generated chaos catalog stays fresh
# ---------------------------------------------------------------------------


def test_readme_chaos_catalog_is_current():
    from tools.gen_chaos_catalog import main as catalog_main

    assert catalog_main(["--check"]) == 0, (
        "README chaos-point catalog is stale — run "
        "`python -m tools.gen_chaos_catalog`"
    )
