"""Incremental device-resident state correctness: a longrun-style loop
that mutates the cluster through every write path (assume/forget, metric
ingest, node churn, quota charges, NUMA/device allocations) and every K
cycles asserts the device-resident NodeState / quota table / zone and
slot tables are BIT-EXACTLY what a from-scratch re-lower of the host
snapshot would produce — a missed dirty mark anywhere shows up here as a
stale resident row."""

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from koordinator_tpu.api import extension as ext
from koordinator_tpu.api.types import (
    Device,
    DeviceInfo,
    ElasticQuota,
    Node,
    NodeMetric,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceMetric,
)
from koordinator_tpu.core.snapshot import ClusterSnapshot
from koordinator_tpu.core.topology import CPUTopology
from koordinator_tpu.scheduler.batch_solver import BatchScheduler, LoadAwareArgs
from koordinator_tpu.scheduler.plugins.deviceshare import DeviceManager
from koordinator_tpu.scheduler.plugins.elasticquota import GroupQuotaManager
from koordinator_tpu.scheduler.plugins.nodenumaresource import (
    NUMAManager,
    NUMAPolicy,
)


def _add_node(snap, numa, dm, topo, name):
    snap.upsert_node(
        Node(
            meta=ObjectMeta(name=name),
            status=NodeStatus(
                allocatable={ext.RES_CPU: 32000, ext.RES_MEMORY: 131072}
            ),
        )
    )
    numa.register_node(
        name, topo, NUMAPolicy.SINGLE_NUMA_NODE, memory_per_zone_mib=65536
    )
    dm.upsert_device(
        Device(
            meta=ObjectMeta(name=name),
            devices=[
                DeviceInfo(dev_type="gpu", minor=g, numa_node=g % 2)
                for g in range(4)
            ],
        )
    )


def _build():
    snap = ClusterSnapshot()
    numa = NUMAManager(snap)
    dm = DeviceManager(snap)
    topo = CPUTopology.uniform(sockets=2, numa_per_socket=1, cores_per_numa=8)
    for i in range(40):
        _add_node(snap, numa, dm, topo, f"n{i:03d}")
    gqm = GroupQuotaManager(
        snap.config,
        cluster_total={ext.RES_CPU: 32000 * 40, ext.RES_MEMORY: 131072 * 40},
    )
    gqm.upsert_quota(
        ElasticQuota(
            meta=ObjectMeta(name="team-a"),
            min={ext.RES_CPU: 100_000, ext.RES_MEMORY: 1 << 19},
            max={ext.RES_CPU: 600_000, ext.RES_MEMORY: 2 << 20},
        )
    )
    sched = BatchScheduler(
        snap, LoadAwareArgs(), quotas=gqm, numa=numa, devices=dm,
        batch_bucket=128,
    )
    sched.extender.monitor.stop_background()
    return sched, topo


def _wave(rng, cycle, n):
    pods = []
    for i in range(n):
        kind = rng.integers(0, 4)
        meta = ObjectMeta(name=f"c{cycle}-p{i:03d}")
        spec = PodSpec(
            requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 2048}, priority=9000
        )
        if kind == 0:
            meta.labels[ext.LABEL_POD_QOS] = "LSR"
            spec.requests[ext.RES_CPU] = 2000
        elif kind == 1:
            spec.requests[ext.RES_GPU] = 1
        elif kind == 2:
            meta.labels[ext.LABEL_QUOTA_NAME] = "team-a"
        pods.append(Pod(meta=meta, spec=spec))
    return pods


def _assert_resident_equals_full(sched):
    """Bit-exact: resident device state vs a from-scratch host lowering."""
    snap = sched.snapshot
    na = snap.nodes
    ns = sched.node_state()  # refreshes the resident state first
    est = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
    sched_rows = na.schedulable
    if (
        sched.args.filter_expired_node_metrics
        and not sched.args.enable_schedule_when_node_metrics_expired
    ):
        sched_rows = sched_rows & (na.metric_fresh | ~na.has_metric)
    for got, want in (
        (ns.allocatable, na.allocatable),
        (ns.requested, na.requested),
        (ns.estimated_used, est),
        (ns.prod_used, na.prod_usage + na.assigned_pending_prod),
        (ns.metric_fresh, na.metric_fresh),
        (ns.schedulable, sched_rows),
        (ns.cpu_amp, na.cpu_amp),
        (ns.custom_thresholds, na.custom_thresholds),
        (ns.custom_prod_thresholds, na.custom_prod_thresholds),
    ):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # quota used table (rows 0..Q-1 real, Q..2Q-1 non-preemptible shadow):
    # the resident copy refreshes at the next quota_state() call, so the
    # contract is versioned invalidation — an UNCHANGED state_version must
    # mean the resident table still equals the live one (a charge that
    # forgot to bump the version would fail here)
    if sched._quota_dev_cache is not None:
        key = sched._quota_dev_cache[0]
        _runtime, used = sched.quotas.quota_arrays_extended()
        if key[0] == sched.quotas.state_version:
            cached = np.asarray(sched._quota_dev_cache[1].used)
            np.testing.assert_array_equal(cached[: used.shape[0]], used)
    # NUMA zone table + GPU slot tables vs the managers' live host arrays
    numa_state, dev_state = sched._constraint_states()
    zone_free, zone_cap, policy = sched.numa.arrays()
    np.testing.assert_array_equal(np.asarray(numa_state.zone_free), zone_free)
    np.testing.assert_array_equal(np.asarray(numa_state.zone_cap), zone_cap)
    np.testing.assert_array_equal(np.asarray(numa_state.policy), policy)
    np.testing.assert_array_equal(
        np.asarray(dev_state.slot_free), sched.devices.slot_array()
    )
    np.testing.assert_array_equal(
        np.asarray(dev_state.cap_total), sched.devices.cap_array()
    )


def test_incremental_resident_state_matches_full_relower():
    rng = np.random.default_rng(42)
    sched, topo = _build()
    snap = sched.snapshot
    bound_pool = []
    for cycle in range(9):
        out = sched.schedule(_wave(rng, cycle, 48))
        bound_pool.extend(out.bound)
        # metric ingest for a random node subset (absorbs pending charges)
        import time as _t

        now = _t.time()
        for idx in rng.choice(snap.node_count, size=8, replace=False):
            name = snap.node_name(int(idx))
            if snap.node_id(name) is None:
                continue
            snap.set_node_metric(
                NodeMetric(
                    meta=ObjectMeta(name=name),
                    node_usage=ResourceMetric(
                        usage={
                            ext.RES_CPU: float(rng.integers(1000, 16000)),
                            ext.RES_MEMORY: float(rng.integers(4096, 65536)),
                        }
                    ),
                    update_time=now,
                ),
                now=now + 1,
            )
        # forget/evict a few bound pods (releases quota/NUMA/device holds)
        rng.shuffle(bound_pool)
        for _ in range(min(6, len(bound_pool))):
            pod, _node = bound_pool.pop()
            sched.evict_for_preemption(pod)
        if cycle == 4:
            # topology change mid-run: bucket-stable node add + a removal
            _add_node(snap, sched.numa, sched.devices, topo, f"late{cycle}")
            victim = snap.node_name(0)
            sched.numa.unregister_node(victim)
            sched.devices.remove_device(victim)
            snap.remove_node(victim)
        if cycle % 3 == 2:
            _assert_resident_equals_full(sched)
    _assert_resident_equals_full(sched)
    reg = sched.extender.registry
    hits = reg.get("solver_state_cache_hits_total")
    total_hits = sum(
        hits.value(table=t) for t in ("nodes", "quota", "numa", "device")
    )
    assert total_hits > 0, "resident-state cache never hit"
    # uploads must be FAR below one full node-axis re-lower per refresh
    n_bucket = snap.nodes.allocatable.shape[0]
    h2d = reg.get("solver_h2d_rows_total").value()
    assert h2d > 0


def test_dirty_scatter_uploads_only_touched_rows():
    """A small mutation between cycles must refresh the resident NodeState
    via the dirty-row scatter (a handful of padded rows), not a full
    node-axis re-lower."""
    sched, _topo = _build()
    snap = sched.snapshot
    reg = sched.extender.registry
    sched.node_state()  # initial full lower
    n_bucket = snap.nodes.allocatable.shape[0]
    h2d0 = reg.get("solver_h2d_rows_total").value()
    pod = Pod(
        meta=ObjectMeta(name="s0"),
        spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 512}),
    )
    assert snap.assume_pod(pod, snap.node_name(7))
    ns = sched.node_state()
    uploaded = reg.get("solver_h2d_rows_total").value() - h2d0
    assert 0 < uploaded < n_bucket, uploaded
    np.testing.assert_array_equal(
        np.asarray(ns.requested), snap.nodes.requested
    )


def test_node_state_window_memoized():
    sched, _topo = _build()
    snap = sched.snapshot
    sub = np.arange(16, dtype=np.int32)
    a = sched.node_state(sub)
    b = sched.node_state(sub)
    assert a is b, "unchanged (window, version) must re-use the gather"
    # the gathered window must equal the host-side pad-and-slice lowering
    na = snap.nodes
    est = np.maximum(na.usage_agg, na.usage_avg) + na.assigned_pending
    got = np.asarray(a.estimated_used)
    assert got.shape[0] >= len(sub)
    np.testing.assert_array_equal(got[: len(sub)], est[sub])
    assert not np.asarray(a.schedulable)[len(sub) :].any()
    # a mutation invalidates: the next call re-gathers fresh values
    pod = Pod(
        meta=ObjectMeta(name="w0"),
        spec=PodSpec(requests={ext.RES_CPU: 1000, ext.RES_MEMORY: 512}),
    )
    assert snap.assume_pod(pod, snap.node_name(3))
    c = sched.node_state(sub)
    assert c is not a
    np.testing.assert_array_equal(
        np.asarray(c.requested)[: len(sub)], na.requested[sub]
    )


def test_window_cache_invalidated_by_flag_change():
    """An args-flag change full-relowers the resident state WITHOUT a
    snapshot mutation — the memoized window gather must not outlive it."""
    sched, _topo = _build()
    sub = np.arange(16, dtype=np.int32)
    a = sched.node_state(sub)
    sched.args.filter_expired_node_metrics = True
    sched.args.enable_schedule_when_node_metrics_expired = False
    b = sched.node_state(sub)
    assert b is not a


def test_preempt_skip_trim_evicts_oldest_half():
    sched, _topo = _build()
    sched._preempt_skips = {f"uid-{i}": i for i in range(10)}
    # re-assignment keeps insertion order — uid-0..4 are oldest
    sched._preempt_skips["uid-2"] = 99
    sched._trim_preempt_skips()
    assert list(sched._preempt_skips) == [f"uid-{i}" for i in range(5, 10)]
    # rotation fairness state of the SURVIVORS is preserved, not reset
    assert sched._preempt_skips["uid-7"] == 7
