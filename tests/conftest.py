"""Test harness: force an 8-device virtual CPU mesh before any computation.

Mirrors the reference's strategy of testing multi-node behavior on fake
substrates (kind containers, fake cgroupfs — SURVEY §4): sharding tests run
against XLA's host-platform device partitioning instead of real TPU chips.

Note: the environment may pre-import jax with a TPU platform pinned via
JAX_PLATFORMS at interpreter startup (sitecustomize), so setting the env var
here is too late — update jax.config directly instead.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection robustness tests (fast subset runs in tier-1)",
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 run (-m 'not slow')",
    )
